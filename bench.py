"""Benchmark entry point (driver contract: prints ONE JSON line).

Measures the representative columnar pipeline of BASELINE.md milestone
config #1 — filter + project (arith + murmur3 hash) — with the projection
FORCED to materialize through a global aggregation of every projected
column, so neither engine can dead-code it away (column pruning would
otherwise reduce the old count()-based pipeline to a predicate scan for
both engines).

Methodology: each engine queries its own resident table — the CPU engine
over numpy-in-RAM, the TPU engine over the device-resident scan cache
(first action uploads once; steady-state queries run device-only with a
single host sync for the 3-scalar result).  This mirrors how the reference
is benchmarked: repeated SQL over a cached/parquet table, not per-query
reingestion (reference: integration_tests/ScaleTest.md).
"""

import json
import os
import sys
import time


def _build_data(n_rows: int):
    import numpy as np
    rng = np.random.default_rng(7)
    return {
        "k": rng.integers(0, 1 << 20, n_rows).astype(np.int64),
        "v": rng.standard_normal(n_rows),
        "w": rng.integers(-1000, 1000, n_rows).astype(np.int32),
    }


def _query(df):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.expressions import arithmetic as A
    from spark_rapids_tpu.expressions import hashing as H
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import Alias, col, lit
    return (df
            .filter(P.GreaterThan(col("w"), lit(0)))
            .select(Alias(A.Add(col("k"), lit(1)), "k1"),
                    Alias(A.Multiply(col("v"), lit(2.0)), "v2"),
                    Alias(H.Murmur3Hash(col("k"), col("w")), "h"))
            .agg(F.sum("k1").alias("sk"),
                 F.sum("v2").alias("sv"),
                 F.sum("h").alias("sh")))


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 8_000_000))
    parts = int(os.environ.get("BENCH_PARTS", 4))
    reps = int(os.environ.get("BENCH_REPS", 3))
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.session import TpuSession

    data = _build_data(n_rows)
    row_bytes = 8 + 8 + 4

    def measure(session, warmups, runs):
        table = session.create_dataframe(data, num_partitions=parts)
        for _ in range(warmups):
            _query(table).collect()
        best = float("inf")
        result = None
        for _ in range(runs):
            t0 = time.perf_counter()
            result = _query(table).collect()
            best = min(best, time.perf_counter() - t0)
        return best, result

    tpu = TpuSession(TpuConf({"spark.rapids.sql.enabled": "true"}))
    best_tpu, r_tpu = measure(tpu, warmups=2, runs=reps)

    cpu = TpuSession(TpuConf({"spark.rapids.sql.enabled": "false"}),
                     init_device=False)
    best_cpu, r_cpu = measure(cpu, warmups=1, runs=reps)

    # differential sanity: the two engines must agree or the number is void
    ok = (abs(r_tpu[0]["sk"] - r_cpu[0]["sk"]) == 0 and
          abs(r_tpu[0]["sv"] - r_cpu[0]["sv"]) < 1e-6 * abs(r_cpu[0]["sv"]))
    if not ok:
        print(json.dumps({
            "metric": "filter_project_hash_agg_rows_per_sec",
            "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
            "error": "TPU/CPU results diverge",
            "tpu": r_tpu[0], "cpu": r_cpu[0],
        }))
        return 1

    rows_per_sec = n_rows / best_tpu
    # honest device efficiency: effective bytes/s vs HBM bandwidth (v5e
    # ~819 GB/s; override for other chips).  The pipeline reads each row
    # once, so bytes/s ~ input traffic; hbm_frac near 0 = dispatch-bound.
    hbm_bw = float(os.environ.get("BENCH_HBM_GBPS", 819)) * 1e9
    bytes_per_sec = n_rows * row_bytes / best_tpu
    out = {
        "metric": "filter_project_hash_agg_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(best_cpu / best_tpu, 3),
        "bytes_per_sec": round(bytes_per_sec),
        "hbm_frac": round(bytes_per_sec / hbm_bw, 5),
        "tpu_s": round(best_tpu, 4),
        "cpu_s": round(best_cpu, 4),
        "results_match": True,
    }

    if os.environ.get("BENCH_SKIP_SCALING", "") != "1":
        # row-count scaling curve: dispatch-bound shows flat time (rising
        # rows/s); bandwidth-bound shows flat rows/s
        curve = {}
        for cn in (1_000_000, 2_000_000, 4_000_000, n_rows):
            if cn > n_rows:
                continue
            cdata = {k: v[:cn] for k, v in data.items()}
            ctable = tpu.create_dataframe(cdata, num_partitions=parts)
            _query(ctable).collect()
            t0 = time.perf_counter()
            _query(ctable).collect()
            dt = time.perf_counter() - t0
            curve[str(cn)] = round(cn / dt)
        out["scaling_rows_per_sec"] = curve

    if os.environ.get("BENCH_SKIP_TPCDS", "") != "1":
        try:
            out["tpcds"] = _tpcds_phase(tpu, cpu)
        except Exception as e:  # keep the primary metric reportable
            out["tpcds"] = {"error": f"{type(e).__name__}: {e}"}

    print(json.dumps(out))
    return 0


def _tpcds_phase(tpu, cpu):
    """BASELINE.md milestone #2: TPC-DS q1-q10 wall clock, TPU vs the CPU
    engine, geomean speedup.  Per-query oracle: row-LEVEL deep compare
    (sorted, float-tolerant — the same comparator the pytest differential
    tier uses), never just a count; an empty result set on both engines is
    flagged, not counted as a pass (reference:
    integration_tests/src/main/python/asserts.py:579)."""
    import math
    from spark_rapids_tpu.testing.rowcompare import rows_equal
    from spark_rapids_tpu.testing.tpcds import register_tables
    from spark_rapids_tpu.testing.tpcds_queries import QUERIES
    sf = float(os.environ.get("BENCH_TPCDS_SF", 1.0))
    per_query = {}
    speedups = []
    register_tables(tpu, sf=sf, num_partitions=4)
    register_tables(cpu, sf=sf, num_partitions=4)
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        t_rows = tpu.sql(sql).collect()       # warm (compile cache)
        t0 = time.perf_counter()
        t_rows = tpu.sql(sql).collect()
        t_tpu = time.perf_counter() - t0
        c_rows = cpu.sql(sql).collect()
        t0 = time.perf_counter()
        c_rows = cpu.sql(sql).collect()
        t_cpu = time.perf_counter() - t0
        diff = rows_equal(c_rows, t_rows, check_order=False,
                          approx_float=True)
        match = diff is None
        per_query[qname] = {"tpu_s": round(t_tpu, 4),
                            "cpu_s": round(t_cpu, 4),
                            "speedup": round(t_cpu / t_tpu, 3),
                            "rows": len(t_rows),
                            "match": match}
        if not match:
            per_query[qname]["diff"] = diff[:160]
        if len(t_rows) == 0:
            per_query[qname]["empty"] = True   # vacuous: flag loudly
        if match and t_rows:
            speedups.append(t_cpu / t_tpu)
    geomean = math.exp(sum(math.log(s) for s in speedups) /
                       len(speedups)) if speedups else 0.0
    return {"sf": sf, "geomean_speedup": round(geomean, 3),
            "queries_counted": len(speedups),
            "queries": per_query}


if __name__ == "__main__":
    sys.exit(main())
