"""Benchmark entry point (driver contract: prints ONE JSON line).

Measures the representative columnar pipeline of BASELINE.md milestone
config #1 — filter + project (arith + murmur3 hash) — with the projection
FORCED to materialize through a global aggregation of every projected
column, so neither engine can dead-code it away (column pruning would
otherwise reduce the old count()-based pipeline to a predicate scan for
both engines).

Methodology: each engine queries its own resident table — the CPU engine
over numpy-in-RAM, the TPU engine over the device-resident scan cache
(first action uploads once; steady-state queries run device-only with a
single host sync for the 3-scalar result).  This mirrors how the reference
is benchmarked: repeated SQL over a cached/parquet table, not per-query
reingestion (reference: integration_tests/ScaleTest.md).

Budget discipline (round-4 contract): the whole run is bounded by
``BENCH_BUDGET_S`` (default 240s).  The primary metric is computed first;
the moment it exists a SIGALRM failsafe guarantees its JSON line prints
even if a follow-on phase (scaling curve, TPC-DS) stalls.  Follow-on
phases check the remaining budget before starting and, for TPC-DS,
before every query — partial results are emitted for whatever finished.

Known limit: the failsafe relies on Python signal delivery, which cannot
preempt a native call that holds the GIL without returning (a truly hung
device runtime).  jax blocking waits release the GIL, so the realistic
stall modes (slow compiles, slow queries) are covered; a wedged PJRT
tunnel is not, and only the driver's outer timeout catches that.
"""

import json
import math
import os
import signal
import sys
import time

_T0 = time.perf_counter()
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 240))
#: failsafe payload; the SIGALRM handler prints this and exits
_PAYLOAD = {
    "metric": "filter_project_hash_agg_rows_per_sec",
    "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
    "error": "primary phase exceeded BENCH_BUDGET_S",
}
#: live progress the alarm handler reads (BENCH_r05 regression: a blown
#: budget printed value 0 with no metric and no culprit).  Phases update
#: it as they start; the primary phase adds rows as passes finish, so a
#: mid-phase alarm still reports a partial rows/s and WHAT was running.
_PROGRESS = {"phase": "init", "rows_done": 0}


def _set_phase(name: str):
    _PROGRESS["phase"] = name


def _remaining() -> float:
    return _BUDGET_S - (time.perf_counter() - _T0)


def _swap_payload(out: dict):
    """Updates the failsafe payload with the alarm quiesced: the handler
    must never observe (and print) a half-applied update (ADVICE r4)."""
    signal.alarm(0)
    _PAYLOAD.update(out)
    _arm(max(1.0, _remaining()))


def _on_alarm(signum, frame):
    _PAYLOAD.setdefault("budget_exceeded", True)
    if _PAYLOAD.get("error"):
        # the primary metric never landed: report the partial throughput
        # of whatever DID finish plus the phase that blew the budget,
        # never a bare value:0
        elapsed = max(time.perf_counter() - _T0, 1e-9)
        done = int(_PROGRESS["rows_done"])
        _PAYLOAD["phase"] = _PROGRESS["phase"]
        if done > 0:
            _PAYLOAD["value"] = round(done / elapsed)
            _PAYLOAD["partial"] = True
            _PAYLOAD["rows_processed"] = done
    else:
        # primary metric exists; still record where the budget died
        _PAYLOAD.setdefault("budget_phase", _PROGRESS["phase"])
    try:
        _PAYLOAD.setdefault("encoding", _encoding_payload())
    except Exception:  # noqa: BLE001 — the failsafe line must print
        pass
    sys.stdout.write(json.dumps(_PAYLOAD) + "\n")
    sys.stdout.flush()
    os._exit(0)


def _arm(seconds: float):
    signal.alarm(max(1, int(seconds)))


def _build_data(n_rows: int):
    import numpy as np
    rng = np.random.default_rng(7)
    return {
        "k": rng.integers(0, 1 << 20, n_rows).astype(np.int64),
        "v": rng.standard_normal(n_rows),
        "w": rng.integers(-1000, 1000, n_rows).astype(np.int32),
    }


def _query(df, threshold=0):
    # ``threshold`` rides a promoted literal slot: every threshold
    # variant shares ONE compiled program (the serving phase leans on
    # this — its mixed synthetic workload adds zero compiles)
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.expressions import arithmetic as A
    from spark_rapids_tpu.expressions import hashing as H
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import Alias, col, lit
    return (df
            .filter(P.GreaterThan(col("w"), lit(threshold)))
            .select(Alias(A.Add(col("k"), lit(1)), "k1"),
                    Alias(A.Multiply(col("v"), lit(2.0)), "v2"),
                    Alias(H.Murmur3Hash(col("k"), col("w")), "h"))
            .agg(F.sum("k1").alias("sk"),
                 F.sum("v2").alias("sv"),
                 F.sum("h").alias("sh")))


def main():
    signal.signal(signal.SIGALRM, _on_alarm)
    _arm(_remaining())

    # persistent XLA compilation cache: on a tunnel-attached chip each
    # remote compile costs tens of seconds; caching compiled programs on
    # local disk makes repeat bench runs measure the engine, not the
    # compiler (standard jax practice for exactly this setup)
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/jax_bench_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    # 128M rows (~2.5 GB working set) so the device-side number reflects
    # HBM traffic rather than tunnel dispatch latency: the engine's wall
    # time is flat in row count up to this size (see scaling curve), which
    # at 8M rows made the metric measure round-trips, not the engine.
    n_rows = int(os.environ.get("BENCH_ROWS", 128_000_000))
    parts = int(os.environ.get("BENCH_PARTS", 4))
    reps = int(os.environ.get("BENCH_REPS", 2))
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.session import TpuSession

    data = _build_data(n_rows)
    row_bytes = 8 + 8 + 4

    def measure(session, tbl_data, warmups, runs):
        # the table stays local: holding it past this function would pin
        # the full device-resident working set through the follow-on
        # phases (which compute out-of-core budgets from free HBM)
        from spark_rapids_tpu.exec.stage_compiler import stats as cstats
        tbl_rows = len(next(iter(tbl_data.values())))
        base = cstats()
        table = session.create_dataframe(tbl_data, num_partitions=parts)
        # uncounted compile warm-up pass: every stage program of the
        # query compiles here, so the timed runs below measure the
        # engine, never the compiler (warm/steady split reported in the
        # payload's "compile" field)
        for _ in range(warmups):
            _query(table).collect()
            _PROGRESS["rows_done"] += tbl_rows
        warm = cstats()
        best = float("inf")
        result = None
        for _ in range(runs):
            t0 = time.perf_counter()
            result = _query(table).collect()
            best = min(best, time.perf_counter() - t0)
            _PROGRESS["rows_done"] += tbl_rows
        steady = cstats()
        compile_info = {
            "warmup_compile_s": round(warm["compile_s"]
                                      - base["compile_s"], 4),
            "steady_compile_s": round(steady["compile_s"]
                                      - warm["compile_s"], 4),
            # MUST be 0 for a warm workload: any timed-run trace means
            # compilation leaked into the steady-state number
            "steady_traces": steady["traces"] - warm["traces"],
            "hits": steady["hits"] - base["hits"],
            "misses": steady["misses"] - base["misses"],
        }
        return best, result, compile_info

    # event log for offline attribution: every traced query of the run
    # appends here, and the payload records the path + a smoke parse via
    # the offline toolkit (tools profile must always read what bench wrote)
    ev_log = os.environ.get("BENCH_EVENT_LOG", "/tmp/bench_events.jsonl")
    try:
        # clear the base file AND its rotated siblings (the same set the
        # reader would ingest), or a previous run's queries leak into
        # this run's event_log payload
        from spark_rapids_tpu.tools.reader import log_file_set
        for stale in log_file_set(ev_log):
            os.remove(stale)
    except OSError:
        ev_log = ""
    tpu_conf = {"spark.rapids.sql.enabled": "true",
                # persistent executable tier (stage_compiler tier 2):
                # same dir the raw jax conf above primes, now owned by
                # the engine's conf so sessions re-apply it
                "spark.rapids.sql.compile.cacheDir": os.environ.get(
                    "JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache")}
    if ev_log:
        tpu_conf["spark.rapids.sql.eventLog.path"] = ev_log
    try:
        tpu = TpuSession(TpuConf(tpu_conf))
    except Exception as e:  # noqa: BLE001 — device backend unavailable
        # (tunnel down / misconfigured): record an honest error line
        # instead of dying output-less; only session INIT is wrapped so a
        # genuine engine failure during measurement keeps its own face
        signal.alarm(0)
        _PAYLOAD["error"] = \
            f"device backend unavailable: {type(e).__name__}: {e}"[:300]
        print(json.dumps(_PAYLOAD))
        return 1
    cpu = TpuSession(TpuConf({"spark.rapids.sql.enabled": "false"}),
                     init_device=False)

    def _match(r_tpu, r_cpu) -> bool:
        # differential sanity: the engines must agree or a number is void
        return (abs(r_tpu[0]["sk"] - r_cpu[0]["sk"]) == 0 and
                abs(r_tpu[0]["sv"] - r_cpu[0]["sv"])
                < 1e-6 * abs(r_cpu[0]["sv"]))

    # honest device efficiency: effective bytes/s vs HBM bandwidth (v5e
    # ~819 GB/s; override for other chips).  The pipeline reads each row
    # once, so bytes/s ~ input traffic; hbm_frac near 0 = dispatch-bound.
    hbm_bw = float(os.environ.get("BENCH_HBM_GBPS", 819)) * 1e9

    def _primary_out(n, best_tpu, best_cpu, tier):
        bps = n * row_bytes / best_tpu
        return {
            "metric": "filter_project_hash_agg_rows_per_sec",
            "value": round(n / best_tpu),
            "unit": "rows/s",
            "vs_baseline": round(best_cpu / best_tpu, 3),
            "rows": n,
            "tier": tier,
            "bytes_per_sec": round(bps),
            "hbm_frac": round(bps / hbm_bw, 5),
            "tpu_s": round(best_tpu, 4),
            "cpu_s": round(best_cpu, 4),
            "results_match": True,
        }

    # QUICK tier first (BENCH_r05 ended with value 0 after the full-size
    # primary blew the whole budget): a small slice lands a real metric
    # within minutes even when device compiles are slow, and the
    # full-size tier then only runs — and overwrites it — if the budget
    # provably still fits a linear projection of the measured pass times
    quick_rows = min(n_rows,
                     int(os.environ.get("BENCH_QUICK_ROWS", 8_000_000)))
    qdata = data if quick_rows == n_rows \
        else {k: v[:quick_rows] for k, v in data.items()}
    _set_phase("tpu_quick")
    # when the quick slice IS the full size, this pass is the full tier:
    # run the full protocol (2 warm-ups, best of reps), not the 1+1
    # quick probe — a 'full'-labeled number must mean the same thing
    # regardless of BENCH_ROWS
    full_now = quick_rows == n_rows
    best_tpu, r_tpu, tpu_compile = measure(
        tpu, qdata, warmups=2 if full_now else 1,
        runs=reps if full_now else 1)
    from spark_rapids_tpu.aux.tracing import last_query_summary
    tpu_query_metrics = _compact_summary(last_query_summary())
    _set_phase("cpu_quick")
    # warm reps, not one cold pass: at quick-tier row counts a cold CPU
    # pass is dominated by first-touch page faults and allocator growth,
    # which inflated vs_baseline (the TPU side always runs warm)
    best_cpu, r_cpu, _ = measure(cpu, qdata, warmups=1, runs=reps)
    if not _match(r_tpu, r_cpu):
        signal.alarm(0)
        print(json.dumps({
            "metric": "filter_project_hash_agg_rows_per_sec",
            "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
            "error": "TPU/CPU results diverge",
            "tpu": r_tpu[0], "cpu": r_cpu[0],
        }))
        return 1
    out = _primary_out(quick_rows, best_tpu, best_cpu,
                       "full" if quick_rows == n_rows else "quick")
    # a real metric exists NOW: the failsafe prints it from here on
    signal.alarm(0)
    _PAYLOAD.clear()
    _PAYLOAD.update(out)
    _PAYLOAD.pop("error", None)
    _arm(max(1.0, _remaining()))
    sys.stderr.write(json.dumps(out) + "\n")
    sys.stderr.flush()

    if quick_rows < n_rows:
        # full-size tier: 2 warm-up + reps timed TPU passes (device time
        # is near-flat in rows, so linear is conservative) + one
        # linear-scaling CPU pass
        scale = n_rows / quick_rows
        est = best_cpu * scale + (2 + reps) * best_tpu * scale
        if _remaining() > est + 45:
            _set_phase("tpu_primary")
            # full-tier results land in temporaries: a diverged full run
            # must leave the quick tier's compile/query_metrics payload
            # intact, not poison it with numbers from a run we rejected
            f_tpu, fr_tpu, f_compile = measure(tpu, data, warmups=2,
                                               runs=reps)
            f_query_metrics = _compact_summary(last_query_summary())
            _set_phase("cpu_primary")
            f_cpu, fr_cpu, _ = measure(cpu, data, warmups=0, runs=1)
            if _match(fr_tpu, fr_cpu):
                best_tpu, best_cpu = f_tpu, f_cpu
                tpu_compile = f_compile
                tpu_query_metrics = f_query_metrics
                out = _primary_out(n_rows, best_tpu, best_cpu, "full")
            else:   # keep the (matching) quick number, flag the full run
                out["full_tier_error"] = "TPU/CPU results diverge"
        else:
            out["full_tier_skipped"] = \
                f"projected {round(est)}s exceeds remaining budget"
    rows_per_sec = out["value"]
    # compile ledger (stage_compiler): warm-up compile seconds are
    # EXCLUDED from the primary metric and reported here; steady_traces
    # must be 0 or compilation leaked into the steady-state number
    from spark_rapids_tpu.exec.stage_compiler import stats as _cstats
    _cs = _cstats()
    out["compile"] = dict(tpu_compile,
                          programs=_cs["programs"],
                          evictions=_cs["evictions"],
                          disk_cache_dir=_cs["disk_cache_dir"])
    if tpu_query_metrics:
        out["query_metrics"] = tpu_query_metrics
    # offline-toolkit smoke assertion: the log this run just wrote must
    # parse through tools profile (reader + attribution) without error
    if ev_log:
        out["event_log"] = _event_log_payload(ev_log)
    # recovery-overhead ledger (PR-3 robustness layer): how many fetch
    # retries / failovers / task retries / breaker trips the run absorbed.
    # Zeros are the healthy baseline; a regression here means the engine
    # is paying recovery cost on the happy path.
    out["chaos"] = _chaos_payload()
    # pipelining ledger (PR-4 overlap layer): measured overlap ratio,
    # producer/consumer stall seconds and peak spool depth across the run
    # so BENCH_*.json tracks whether decode/transfer/compute actually
    # overlapped (overlap_ratio 0 = fully serial boundaries)
    out["pipeline"] = _pipeline_payload()
    # encoded-execution ledger (columnar/encoding.py): bytes the
    # encoding kept off the tunnel, bytes decoded late, fallback count
    out["encoding"] = _encoding_payload()
    # primary number exists: from here on the failsafe prints it verbatim
    signal.alarm(0)          # quiesce while the payload is swapped
    _PAYLOAD.clear()
    _PAYLOAD.update(out)
    # ALSO snapshot it NOW to STDERR: SIGALRM delivery can be starved by a
    # native call holding the GIL (a PJRT executable load); if the
    # driver's outer timeout then kills the process, the merged-stream
    # tail still carries this snapshot.  STDOUT keeps the one-line
    # contract: exactly one JSON line per successful run, printed last.
    sys.stderr.write(json.dumps(out) + "\n")
    sys.stderr.flush()
    _arm(_remaining())

    if os.environ.get("BENCH_SKIP_ENCODING", "") != "1" and _remaining() > 30:
        # encoded-vs-eager microbenchmark: filter+agg over a
        # dictionary-encoded parquet column, H2D/decode deltas from the
        # encoding ledger (ISSUE 11 acceptance evidence)
        _set_phase("encoding_microbench")
        try:
            out["encoding"]["microbench"] = _encoding_microbench(tpu)
        except Exception as e:  # keep the primary metric reportable
            out["encoding"]["microbench_error"] = \
                f"{type(e).__name__}: {e}"
        _swap_payload(out)

    if os.environ.get("BENCH_SKIP_PIPELINE", "") != "1" and _remaining() > 30:
        _set_phase("pipeline_microbench")
        # transfer-overlap microbenchmark: the primary pipeline with
        # prefetch spools on vs off, plus the overlap ratio measured over
        # the pipelined runs (stall time below the serial sum = win)
        try:
            out["pipeline"]["microbench"] = \
                _pipeline_microbench(tpu, data, parts)
        except Exception as e:  # keep the primary metric reportable
            out["pipeline"]["microbench_error"] = \
                f"{type(e).__name__}: {e}"
        _swap_payload(out)

    if os.environ.get("BENCH_SKIP_SERVING", "") != "1" and _remaining() > 30:
        # sustained-throughput serving payload (ISSUE 15 acceptance),
        # BEFORE the TPC-DS phase so a budget blowout there can never
        # leave it missing: 8 literal variants of the primary pipeline
        # at the quick tier's shape — shares its compiled programs, so
        # this round costs execution time only
        _set_phase("serving")
        serving: dict = {"partial": True}
        out["serving"] = serving
        _swap_payload(out)
        try:
            _serving_phase(tpu, serving, "synthetic",
                           data_slice=qdata, parts=parts)
            serving.pop("partial", None)
        except Exception as e:  # keep the primary metric reportable
            serving["error"] = f"{type(e).__name__}: {e}"
        _swap_payload(out)

    if os.environ.get("BENCH_SKIP_TPCDS", "") != "1" and _remaining() > 45:
        # TPC-DS before the scaling curve: per-query speedups are the
        # scarcer signal when the budget runs short
        _set_phase("tpcds")
        tpcds: dict = {"partial": True}
        out["tpcds"] = tpcds
        _swap_payload(out)
        try:
            _tpcds_phase(tpu, cpu, tpcds)
            tpcds.pop("partial", None)
        except Exception as e:  # keep the primary metric reportable
            tpcds["error"] = f"{type(e).__name__}: {e}"

    if os.environ.get("BENCH_SKIP_SERVING", "") != "1" and \
            _remaining() > 70 and "tpcds" in out:
        # opportunistic second serving round over the REAL mixed TPC-DS
        # workload the TPC-DS phase just warmed (the guaranteed
        # synthetic round above already landed the payload)
        _set_phase("serving_tpcds")
        serving2: dict = {"partial": True}
        out["serving_tpcds"] = serving2
        _swap_payload(out)
        try:
            _serving_phase(tpu, serving2, "tpcds")
            serving2.pop("partial", None)
        except Exception as e:  # keep the primary metric reportable
            serving2["error"] = f"{type(e).__name__}: {e}"
        _swap_payload(out)

    if os.environ.get("BENCH_SKIP_SCALING", "") != "1" and _remaining() > 30:
        # row-count scaling curve: dispatch-bound shows flat time (rising
        # rows/s); bandwidth-bound shows flat rows/s.  Each point gets its
        # own table at the SAME partition count as the primary phase (a
        # limit() slice would run single-partition and skew the diagnostic);
        # tables are dropped between points so device residency stays ~1x.
        _set_phase("scaling")
        try:
            # anchor at the rows the surviving metric actually measured
            # (the quick tier's count when the full tier was skipped)
            curve = {str(out["rows"]): round(rows_per_sec)}
            ctable = None
            for cn in (1_000_000, 2_000_000, 4_000_000):
                if cn > n_rows or _remaining() < 20:
                    continue
                ctable = None  # release the previous point's device columns
                cdata = {k: v[:cn] for k, v in data.items()}
                ctable = tpu.create_dataframe(cdata, num_partitions=parts)
                _query(ctable).collect()
                dt = float("inf")
                for _ in range(2):
                    t0 = time.perf_counter()
                    _query(ctable).collect()
                    dt = min(dt, time.perf_counter() - t0)
                curve[str(cn)] = round(cn / dt)
            out["scaling_rows_per_sec"] = curve
        except Exception as e:  # keep the primary metric reportable
            out["scaling_error"] = f"{type(e).__name__}: {e}"
        _swap_payload(out)

    # refresh the ledgers with anything the follow-on phases absorbed
    # (carrying the microbench result — or its failure marker — forward:
    # a persistently failing microbenchmark must stay visible)
    out["chaos"] = _chaos_payload()
    prev = out.get("pipeline", {})
    out["pipeline"] = _pipeline_payload()
    for k in ("microbench", "microbench_error"):
        if k in prev:
            out["pipeline"][k] = prev[k]
    if ev_log:
        # re-parse so the payload covers the follow-on phases' queries too
        out["event_log"] = _event_log_payload(ev_log)
    prev_enc = out.get("encoding", {})
    out["encoding"] = _encoding_payload()
    for k in ("microbench", "microbench_error"):
        if k in prev_enc:
            out["encoding"][k] = prev_enc[k]
    # trajectory warehouse auto-ingest (docs/history.md): when
    # BENCH_HISTORY_DB (or spark.rapids.history.path) names a database,
    # this run's payload + event log land there so `tools history
    # regress` can sentinel it against the accumulated baseline.  Never
    # changes bench's exit code or stdout contract.
    hist_db = os.environ.get("BENCH_HISTORY_DB", "") or \
        tpu_conf.get("spark.rapids.history.path", "")
    if hist_db:
        out["history"] = _history_ingest(hist_db, out, ev_log)
    signal.alarm(0)
    print(json.dumps(out))
    return 0


def _history_ingest(db: str, payload: dict, ev_log: str) -> dict:
    """Ingests this run into the history warehouse; failures are
    recorded in the payload, not raised."""
    try:
        from spark_rapids_tpu.tools.history import HistoryWarehouse
        with HistoryWarehouse(db) as wh:
            runs = [wh.ingest_payload(dict(payload), label="bench")]
            if ev_log and os.path.exists(ev_log):
                runs.append(wh.ingest_log(ev_log, label="bench"))
        return {"ok": True, "db": db,
                "runs": [r.get("run_id") for r in runs]}
    except Exception as e:  # noqa: BLE001 - ingest must never fail bench
        return {"ok": False, "db": db,
                "error": f"{type(e).__name__}: {e}"}


def _event_log_payload(path: str) -> dict:
    """Smoke-parses the run's event log through the offline toolkit
    (reader + per-query attribution) and records the verdict, so a
    schema drift between the sink and the tools surfaces in BENCH_*.json
    instead of months later on a real incident log."""
    try:
        from spark_rapids_tpu.tools.profile import attribute
        from spark_rapids_tpu.tools.reader import (profiles_from_events,
                                                   read_events)
        # ONE parse of the (possibly rotated/gzip'd) log serves the
        # profile smoke AND the audit below
        events, diag = read_events(path)
        profiles, _ = profiles_from_events(events, diag)
        for qp in profiles:
            attribute(qp)     # attribution must never raise on own logs
        out = {"path": path, "profile_ok": True,
               "queries": len(profiles),
               "events": diag.parsed,
               "truncated_lines": diag.truncated_lines}
        # per-query host-transition ledger (schema v4): BENCH_*.json
        # tracks boundary-crossing counts/bytes/sync seconds across PRs
        # the same way it tracks chaos/pipeline/encoding ledgers
        from spark_rapids_tpu.tools.profile import _transition_ledger
        out["transitions"] = {
            str(qp.query_id): _transition_ledger(qp) for qp in profiles}
    except Exception as e:  # noqa: BLE001 - keep the primary metric alive
        return {"path": path, "profile_ok": False,
                "error": f"{type(e).__name__}: {e}"[:200]}
    # compiled-program audit over the run's own stageProgram ledger
    # (schema v3): the bench payload carries the verdict so a forbidden
    # primitive / baked constant / recompile storm regression fails the
    # very next bench run, not a later incident review
    try:
        from spark_rapids_tpu.tools.audit import LedgerRow, run_audit
        rep = run_audit(
            rows=[LedgerRow.from_event(e) for e in events
                  if e.kind == "stageProgram"],
            profiles=profiles)
        out["audit"] = {
            "ok": rep.exit_code == 0,
            "programs": len(rep.rows),
            "structures": len({(r.kind, r.norm_sig) for r in rep.rows}),
            "errors": len(rep.active_errors),
            "warnings": len(rep.active) - len(rep.active_errors),
        }
    except Exception as e:  # noqa: BLE001 - keep the primary metric alive
        out["audit"] = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"[:200]}
    return out


def _chaos_payload() -> dict:
    """Recovery counters observed so far this process (aux/faults.py
    ledger): BENCH_*.json carries them so recovery overhead is tracked
    across PRs.  Fixed keys always present; extra recovery kinds ride
    along verbatim."""
    from spark_rapids_tpu.aux.faults import (RECOVERY_KINDS, fault_stats,
                                             recovery_stats)
    payload = {key: 0 for key in RECOVERY_KINDS.values()}
    payload.update(recovery_stats())
    payload["faults_injected"] = sum(fault_stats().values())
    return payload


def _encoding_payload() -> dict:
    """Encoded-execution counters observed so far this process
    (columnar/encoding.py ledger): encoded bytes in/out, decode-avoided
    bytes, late-decoded bytes and the dictionary fallback count."""
    from spark_rapids_tpu.columnar.encoding import encoding_stats
    return encoding_stats()


def _encoding_microbench(tpu) -> dict:
    """Filter+agg over a dictionary-encoded parquet string column with
    encoding ON vs OFF (eager decode): same query, same file — the
    ledger deltas show the avoided H2D bytes and the wall-clock the
    decode bucket gives back."""
    import tempfile
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.expressions.base import col, lit
    from spark_rapids_tpu.columnar.encoding import encoding_stats
    rng = np.random.default_rng(11)
    n = int(os.environ.get("BENCH_ENCODING_ROWS", 2_000_000))
    cats = np.array([f"cat{i:03d}" for i in range(64)])
    tbl = pa.table({"s": pa.array(cats[rng.integers(0, 64, n)]),
                    "v": rng.integers(0, 1000, n)})
    d = tempfile.mkdtemp(prefix="bench-enc-")
    path = os.path.join(d, "enc.parquet")
    pq.write_table(tbl, path)

    def q(session):
        return (session.read.parquet(path)
                .filter(col("s") == lit("cat007"))
                .groupBy("s")
                .agg(F.sum("v").alias("sv"), F.count("v").alias("c"))
                .collect())

    res = {"rows": n}
    try:
        for key, flag in (("eager_s", "false"), ("encoded_s", "true")):
            tpu.set_conf("spark.rapids.sql.encoding.enabled", flag)
            q(tpu)                    # warm (compile + any scan cache)
            s0 = encoding_stats()
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                rows = q(tpu)
                best = min(best, time.perf_counter() - t0)
            s1 = encoding_stats()
            res[key] = round(best, 4)
            if flag == "true":
                res["encoded_bytes_in"] = \
                    s1["encoded_bytes_in"] - s0["encoded_bytes_in"]
                res["decode_avoided_bytes"] = \
                    s1["decode_avoided_bytes"] - s0["decode_avoided_bytes"]
                res["dict_fallbacks"] = \
                    s1["dict_fallbacks"] - s0["dict_fallbacks"]
                res["groups"] = len(rows)
    finally:
        tpu.set_conf("spark.rapids.sql.encoding.enabled", "true")
        for f in (path,):
            try:
                os.remove(f)
            except OSError:
                pass
    if res.get("encoded_s"):
        res["speedup_vs_eager"] = round(res["eager_s"] / res["encoded_s"],
                                        3)
    return res


def _pipeline_payload() -> dict:
    """Pipelining counters observed so far this process (exec/pipeline.py
    ledger): spool count, batches/bytes staged, producer/consumer stall
    seconds, peak queue depth and the derived overlap ratio."""
    from spark_rapids_tpu.exec.pipeline import pipeline_stats
    return pipeline_stats()


def _pipeline_microbench(tpu, data, parts) -> dict:
    """Times the primary filter+project+agg pipeline with prefetch spools
    disabled (fully serial boundaries) vs enabled over a fresh moderate
    table, and reports the overlap ratio measured across the pipelined
    runs.  Fresh tables per mode keep the comparison honest: both sides
    pay the same upload/decode work the spools are meant to hide."""
    from spark_rapids_tpu.exec.pipeline import pipeline_stats
    n = min(2_000_000, len(next(iter(data.values()))))
    sub = {k: v[:n] for k, v in data.items()}
    res = {"rows": n}
    before = None
    try:
        for key, flag in (("serial_s", "false"), ("piped_s", "true")):
            tpu.set_conf("spark.rapids.pipeline.enabled", flag)
            table = tpu.create_dataframe(sub, num_partitions=parts)
            _query(table).collect()           # warm (compile + upload)
            if flag == "true":
                before = pipeline_stats()     # delta covers timed runs
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                _query(table).collect()
                best = min(best, time.perf_counter() - t0)
            res[key] = round(best, 4)
    finally:
        tpu.set_conf("spark.rapids.pipeline.enabled", "true")
    after = pipeline_stats()
    busy = after["producer_busy_s"] - before["producer_busy_s"]
    stall = after["consumer_stall_s"] - before["consumer_stall_s"]
    res["overlap_ratio"] = round(max(0.0, 1.0 - stall / busy), 4) \
        if busy > 0 else 0.0
    # (no peak_depth here: the ledger's peak is a run-wide max that can't
    # be delta'd to this window; the top-level pipeline payload carries it)
    if res["piped_s"] > 0:
        res["speedup_vs_serial"] = round(res["serial_s"] / res["piped_s"],
                                         3)
    return res


def _console_snapshot():
    """Mid-run live-console capture for the serving payload: when the
    engine console (aux/console.py) is up, fetch /queries and /server
    over its HTTP socket — the same path an external scraper takes — and
    keep the operational scalars (queue depth, cache hit rates).  None
    when the console is disabled or unreachable; never fails the bench."""
    try:
        from urllib.request import urlopen

        from spark_rapids_tpu.aux.console import active_console
        con = active_console()
        if con is None:
            return None
        with urlopen(con.url("/queries"), timeout=5) as r:
            queries = json.loads(r.read().decode("utf-8"))
        with urlopen(con.url("/server"), timeout=5) as r:
            server = json.loads(r.read().decode("utf-8"))
        srv_rows = server.get("servers", [])
        row = srv_rows[0] if srv_rows else {}
        return {
            "url": con.url(""),
            "live_queries": len(queries.get("live", [])),
            "recent_queries": len(queries.get("recent", [])),
            "queue_depth": row.get("queue_depth"),
            "admitted_now": row.get("admitted_now"),
            "plan_cache_hit_rate": row.get("plan_cache_hit_rate"),
            "result_cache_hit_rate": row.get("result_cache_hit_rate"),
        }
    except Exception:
        return None


def _serving_phase(tpu, res: dict, kind: str, data_slice=None, parts=2):
    """Sustained-throughput serving measurement (serving/server.py): the
    same mixed 8-query workload executed (a) serially through the plain
    session path and (b) concurrently through the QueryServer (admission
    + cross-query plan/result caches + the online AutoTuner), reporting
    queries/sec, p50/p99 submit-to-result latency, the plan-cache hit
    rate, and bit-identity of every served result against the serial
    reference.

    ``kind="synthetic"`` (runs BEFORE the TPC-DS phase, so a budget
    blowout there can never leave the payload missing): 8 threshold
    variants of the primary pipeline over ``data_slice`` at the primary
    phase's shape — literal promotion makes every variant share the
    already-compiled programs, so this round adds ZERO compiles.
    ``kind="tpcds"``: the 8 cheapest TPC-DS queries the TPC-DS phase
    just registered and compile-warmed."""
    from spark_rapids_tpu.serving import QueryServer
    reps = int(os.environ.get("BENCH_SERVING_REPS", 3))
    res["workload"] = kind
    if kind == "tpcds":
        from spark_rapids_tpu.testing.tpcds_queries import QUERIES
        # (q8 excluded: pathological native compile on some backends —
        # see the TPC-DS phase's slow tail)
        names = [q for q in ("q3", "q7", "q19", "q1", "q15", "q12",
                             "q13", "q20") if q in QUERIES]
        if len(names) < 4 or tpu.catalog_lookup("store_sales") is None:
            res["error"] = "tpcds tables/queries unavailable"
            return res
        workload = [(n, QUERIES[n]) for n in names]
    else:
        table = tpu.create_dataframe(data_slice, num_partitions=parts)

        def variant(threshold):
            def build(session):
                return _query(table, threshold)
            return build

        workload = [(f"w>{t}", variant(t))
                    for t in (-750, -500, -250, 0, 250, 500, 750, 900)]

    def run_serial(item):
        tag, q = item
        df = tpu.sql(q) if isinstance(q, str) else q(tpu)
        return df.collect()

    # every serving.* conf this phase touches on the SHARED session is
    # restored on exit (the first validation run leaked resultCache=0
    # into the follow-on round and silently disabled it)
    saved_conf = {}

    def set_conf(key, value):
        saved_conf.setdefault(key, tpu.conf.get(key))
        tpu.set_conf(key, value)

    # serial reference pass: one uncounted warm execution per distinct
    # query (compiles must not skew either side), TIMED so the sweep
    # cost is known before committing the budget to it
    reference = {}
    warm_s = 0.0
    for item in workload:
        if _remaining() < 25:
            res["error"] = "budget exhausted during serving warm-up"
            return res
        t0 = time.perf_counter()
        reference[item[0]] = run_serial(item)
        warm_s += time.perf_counter() - t0
    if warm_s * (reps + 1.5) > _remaining() - 20:
        # the warm sweep proved this workload too slow for a serial
        # baseline + concurrent pass within the remaining budget
        res["error"] = f"workload too slow for budget (warm {warm_s:.1f}s)"
        return res
    executions = workload * reps
    res.update({"queries": len(workload), "reps": reps,
                "executions": len(executions)})
    serial_s = 0.0
    for item in executions:
        if _remaining() < 20:
            res["error"] = "budget exhausted during serial baseline"
            return res
        t0 = time.perf_counter()
        run_serial(item)
        serial_s += time.perf_counter() - t0
    res["serial_s"] = round(serial_s, 4)

    try:
        # throughput pass: autotune stays OFF — an accepted delta
        # mid-measurement legitimately re-keys both caches (the conf
        # digest changed), which measures the tuner's transient, not
        # steady-state serving; the loop gets its own round below.
        # The live console rides this pass (results-neutral, pinned by
        # the trimodal console test) so the payload records a scrape of
        # the serving state taken over the console's own HTTP socket.
        tpu.set_conf("spark.rapids.console.enabled", "true")
        srv = QueryServer(session=tpu)
        try:
            t0 = time.perf_counter()
            subs = [(tag, srv.submit(q, tag=tag))
                    for tag, q in executions]
            # mid-run: the submissions are in flight while the console
            # scrape happens — queue depth / admitted counts are live
            snap = _console_snapshot()
            lat = []
            identical = True
            for tag, sub in subs:
                rows = sub.result(timeout=max(30.0, _remaining()))
                lat.append(sub.info.get("latency_s", 0.0))
                identical = identical and rows == reference[tag]
            wall = time.perf_counter() - t0
            lat.sort()
            st = srv.stats()
            pc = st["plan_cache"]
            looked = pc["hits"] + pc["misses"]
            res.update({
                "concurrent_s": round(wall, 4),
                "queries_per_sec": round(len(executions) / wall, 3),
                "serial_queries_per_sec":
                    round(len(executions) / serial_s, 3)
                    if serial_s else 0.0,
                "speedup_vs_serial": round(serial_s / wall, 3),
                "p50_latency_s": round(lat[len(lat) // 2], 4),
                "p99_latency_s":
                    round(lat[min(len(lat) - 1,
                                  math.ceil(0.99 * len(lat)) - 1)], 4),
                "bit_identical": identical,
                "plan_cache_hit_rate":
                    round(pc["hits"] / looked, 3) if looked else 0.0,
                "plan_cache": pc,
                "result_cache": st["result_cache"],
                "admission": st["admission"],
                "max_concurrent": srv.admission.max_concurrent,
            })
            if snap is not None:
                res["console_snapshot"] = snap
        finally:
            srv.stop()
            tpu.set_conf("spark.rapids.console.enabled", "false")

        if _remaining() > 20:
            # plan-cache round, result cache OFF: the mixed pass above
            # serves repeats from the RESULT cache, so the plan cache
            # never shows its exact-hit path there.  This round isolates
            # it — serial repeats of each query must hit the cached
            # physical plan and trace NOTHING (the ISSUE 15 acceptance
            # assertion, measured on the live bench workload, not only
            # in tier-1)
            from spark_rapids_tpu.exec.stage_compiler import \
                stats as cstats
            set_conf("spark.rapids.serving.resultCache.maxBytes", "0")
            srv2 = QueryServer(session=tpu)
            try:
                for tag, q in workload:          # insert sweep
                    srv2.execute(q, tag=tag,
                                 timeout=max(30.0, _remaining()))
                tr0 = cstats()["traces"]
                t0 = time.perf_counter()
                n_rep = 0
                for _ in range(max(1, reps - 1)):
                    if _remaining() < 15:
                        break
                    for tag, q in workload:      # repeat sweeps: hits
                        srv2.execute(q, tag=tag,
                                     timeout=max(30.0, _remaining()))
                        n_rep += 1
                pc2 = srv2.stats()["plan_cache"]
                looked2 = pc2["hits"] + pc2["misses"]
                res["plan_cache_round"] = {
                    "repeats": n_rep,
                    "repeat_s": round(time.perf_counter() - t0, 4),
                    "hits": pc2["hits"],
                    "misses": pc2["misses"],
                    "hit_rate": round(pc2["hits"] / looked2, 3)
                    if looked2 else 0.0,
                    # MUST be 0: a repeat that re-traces re-compiled
                    "new_traces_on_repeat": cstats()["traces"] - tr0,
                }
            finally:
                srv2.stop()

        if _remaining() > 15:
            # online-tuning round: the loop live on real executions
            # (result cache off so rules see executions, not cache
            # hits); the trail proves deltas apply between queries
            set_conf("spark.rapids.serving.autotune.enabled", "true")
            srv3 = QueryServer(session=tpu)
            try:
                for tag, q in workload:
                    if _remaining() < 10:
                        break
                    srv3.execute(q, tag=tag,
                                 timeout=max(30.0, _remaining()))
                res["autotune"] = {
                    "applied": len(srv3.autotune_applied),
                    "deltas": [
                        {"key": k, "old": str(o), "new": str(n)}
                        for k, o, n, _r, _q
                        in srv3.autotune_applied[:8]],
                }
            finally:
                srv3.stop()
    finally:
        for key, old in saved_conf.items():
            tpu.set_conf(key, str(old))
    return res


def _compact_summary(qm, max_nodes: int = 8):
    """Trims a tracing query summary for the one-line payload: the
    query-level counters plus the top-opTime nodes."""
    if not qm:
        return None
    out = {k: qm[k] for k in (
        "query_id", "duration_s", "tasks", "spill_count", "spill_bytes",
        "retry_count", "split_retry_count", "oom_count",
        "semaphore_wait_s", "max_device_bytes") if k in qm}
    nodes = sorted(qm.get("nodes", []),
                   key=lambda n: n.get("opTime", 0), reverse=True)
    out["nodes"] = [
        {k: n[k] for k in ("node", "numOutputRows", "numOutputBatches",
                           "opTime", "spill_bytes", "retry_count")
         if k in n}
        for n in nodes[:max_nodes]]
    return out


def _tpcds_phase(tpu, cpu, res: dict):
    """BASELINE.md milestone #2: TPC-DS wall clock, TPU vs the CPU engine,
    geomean speedup.  Per-query oracle: row-LEVEL deep compare (sorted,
    float-tolerant — the same comparator the pytest differential tier
    uses), never just a count; an empty result set on both engines is
    flagged, not counted as a pass (reference:
    integration_tests/src/main/python/asserts.py:579).

    Budget-aware: checks the remaining wall-clock before every query and
    streams each finished query into ``res`` (the failsafe payload holds a
    reference), so an alarm mid-query still reports the finished subset."""
    from spark_rapids_tpu.io.multifile import enable_scan_cache
    from spark_rapids_tpu.testing.rowcompare import rows_equal
    from spark_rapids_tpu.testing.tpcds import register_tables
    from spark_rapids_tpu.testing.tpcds_queries import QUERIES
    # SF 0.2: every implemented query returns rows here, and the persistent
    # compile cache covers these shapes (each REMOTE compile costs 30-900s
    # on the tunnel — a higher SF's fresh shapes would spend the whole
    # budget in the compiler; raise via BENCH_TPCDS_SF once primed)
    sf = float(os.environ.get("BENCH_TPCDS_SF", 0.2))
    storage = os.environ.get("BENCH_TPCDS_STORAGE", "parquet")
    per_query = {}
    speedups = []
    skipped = []
    res.update({"sf": sf, "storage": storage, "geomean_speedup": 0.0,
                "queries_counted": 0, "skipped": skipped,
                "queries": per_query})
    # steady-state scan cache: repeated queries over static parquet keep
    # decoded batches (CPU) / uploaded batches (TPU) resident — the
    # repeat-query methodology of the primary phase, now with the scan +
    # shuffle layers participating in every query
    from spark_rapids_tpu.exec.stage_compiler import stats as _cstats
    _c0 = _cstats()
    res["compile"] = {"compile_s": 0.0, "timed_traces": 0}
    enable_scan_cache(True)
    # ONE partition: a single chip parallelizes internally; partition
    # fan-out at this scale only multiplies per-op dispatches (and the
    # compile-cache shape count) for both engines equally
    register_tables(tpu, sf=sf, num_partitions=1, storage=storage)
    register_tables(cpu, sf=sf, num_partitions=1, storage=storage)
    # cheapest-first (by measured device wall time at SF 0.2): when the
    # budget runs short the expensive tail is skipped instead of eating
    # the cheap majority's slots; unmeasured queries run before the
    # known-slow tail
    order = ["q3", "q1", "q7", "q15", "q12", "q13", "q20", "q19",
             "q16", "q17", "q10", "q18", "q6", "q9", "q2", "q11", "q5",
             "q4"]
    # q8 rides the slow tail: its fused agg hits a pathological XLA
    # compile on some backends (minutes of native compile the SIGALRM
    # failsafe cannot preempt) — it must never starve the cheap majority
    slow_tail = ["q48", "q8", "q9", "q2", "q11", "q5", "q4"]
    fast_new = [q for q in sorted(QUERIES, key=lambda s: int(s[1:]))
                if q not in order and q not in slow_tail]
    names = [q for q in order if q in QUERIES and q not in slow_tail] + \
        fast_new + [q for q in slow_tail if q in QUERIES]
    # every query starts on the skip list and is removed when it FINISHES:
    # an alarm firing mid-loop then reports the whole untouched tail (and
    # the in-flight query) instead of a deceptively empty list (r4 bench
    # showed skipped:[] with 11 queries unreported)
    skipped.extend(names)
    for qname in names:
        if _remaining() < 25:
            continue
        sql = QUERIES[qname]
        t_rows = tpu.sql(sql).collect()       # warm (compile cache)
        _cw = _cstats()
        t0 = time.perf_counter()
        t_rows = tpu.sql(sql).collect()
        t_tpu = time.perf_counter() - t0
        _ct = _cstats()
        # compile cost stays out of the per-query number (warm pass paid
        # it); the ledger proves it: timed_traces must stay 0
        res["compile"]["compile_s"] = round(
            _ct["compile_s"] - _c0["compile_s"], 4)
        res["compile"]["timed_traces"] += _ct["traces"] - _cw["traces"]
        from spark_rapids_tpu.aux.tracing import last_query_summary
        qsum = last_query_summary() or {}
        t0 = time.perf_counter()              # one pass: result + timing
        c_rows = cpu.sql(sql).collect()
        t_cpu = time.perf_counter() - t0
        diff = rows_equal(c_rows, t_rows, check_order=False,
                          approx_float=True)
        match = diff is None
        per_query[qname] = {"tpu_s": round(t_tpu, 4),
                            "cpu_s": round(t_cpu, 4),
                            "speedup": round(t_cpu / t_tpu, 3),
                            "rows": len(t_rows),
                            "match": match}
        # attribution: only the nonzero pressure counters, kept compact
        attrib = {k: qsum[k] for k in (
            "tasks", "spill_count", "spill_bytes", "retry_count",
            "split_retry_count", "oom_count", "semaphore_wait_s")
            if qsum.get(k)}
        if attrib:
            per_query[qname]["metrics"] = attrib
        if not match:
            per_query[qname]["diff"] = diff[:160]
        if len(t_rows) == 0:
            per_query[qname]["empty"] = True   # vacuous: flag loudly
        if match and t_rows:
            speedups.append(t_cpu / t_tpu)
        skipped.remove(qname)
        geomean = math.exp(sum(math.log(s) for s in speedups) /
                           len(speedups)) if speedups else 0.0
        res["geomean_speedup"] = round(geomean, 3)
        res["queries_counted"] = len(speedups)
        # refresh the STDERR tail after every finished query: a hard
        # kill (outer timeout during a GIL-held compile/load) leaves the
        # most complete snapshot as the merged-stream tail, while stdout
        # keeps its one-line contract
        sys.stderr.write(json.dumps(_PAYLOAD) + "\n")
        sys.stderr.flush()
    return res


if __name__ == "__main__":
    sys.exit(main())
