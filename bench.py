"""Benchmark entry point (driver contract: prints ONE JSON line).

Measures the representative columnar pipeline of the minimum end-to-end
slice (BASELINE.md milestone config #1: single-node filter+project over
generated data): scan -> filter -> project(arith + hash) on the device
engine, against the CPU fallback engine as baseline (the reference's own
baseline is Spark-CPU; SURVEY.md §6).
"""

import json
import os
import sys
import time


def _build_data(n_rows: int):
    import numpy as np
    rng = np.random.default_rng(7)
    return {
        "k": rng.integers(0, 1 << 20, n_rows).astype(np.int64),
        "v": rng.standard_normal(n_rows),
        "w": rng.integers(-1000, 1000, n_rows).astype(np.int32),
    }


def _pipeline(s, data, parts):
    from spark_rapids_tpu.expressions import arithmetic as A
    from spark_rapids_tpu.expressions import hashing as H
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import Alias, col, lit
    return (s.create_dataframe(data, num_partitions=parts)
            .filter(P.GreaterThan(col("w"), lit(0)))
            .select(Alias(A.Add(col("k"), lit(1)), "k1"),
                    Alias(A.Multiply(col("v"), lit(2.0)), "v2"),
                    Alias(H.Murmur3Hash(col("k"), col("w")), "h")))


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 4_000_000))
    parts = 4
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.session import TpuSession

    data = _build_data(n_rows)

    def run(session):
        df = _pipeline(session, data, parts)
        t0 = time.perf_counter()
        total = df.count()
        dt = time.perf_counter() - t0
        return total, dt

    # warm + measure TPU engine
    tpu = TpuSession(TpuConf({"spark.rapids.sql.enabled": "true"}))
    run(tpu)  # warm-up: compile cache
    best_tpu = min(run(tpu)[1] for _ in range(3))

    cpu = TpuSession(TpuConf({"spark.rapids.sql.enabled": "false"}),
                     init_device=False)
    best_cpu = min(run(cpu)[1] for _ in range(2))

    rows_per_sec = n_rows / best_tpu
    print(json.dumps({
        "metric": "filter_project_hash_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(best_cpu / best_tpu, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
