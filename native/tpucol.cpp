// libtpucol: native host runtime for the TPU columnar engine.
//
// Reference counterparts (SURVEY.md §2.16): the reference consumes native
// C++/CUDA code for its host/device runtime — RMM host/pinned pools,
// JCudfSerialization's host wire layout, nvcomp LZ4 batch codecs, the
// spark-rapids-jni Hash kernels (murmur3/xxhash64) and RowConversion
// (row⇄column). This library provides the TPU-native equivalents for the
// *host* side of the engine: the device side is XLA/Pallas via JAX.
//
// Exposed via a C ABI consumed by ctypes (spark_rapids_tpu/native.py).
// Everything is thread-safe unless noted; the pool uses a mutex (shuffle
// writer threads allocate concurrently).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <mutex>
#include <new>

#if defined(_WIN32)
#define TPUCOL_API extern "C" __declspec(dllexport)
#else
#define TPUCOL_API extern "C" __attribute__((visibility("default")))
#endif

// ---------------------------------------------------------------------------
// Host memory pool with accounting (RMM analog).
//
// A tracking allocator: malloc-backed, but every allocation is accounted
// against a configurable limit so the Python retry layer (memory/retry.py)
// can observe pressure and spill — mirroring how RmmSpark's per-thread state
// machine turns allocator pressure into retry/split-retry signals.
// ---------------------------------------------------------------------------

namespace {

struct Pool {
    std::mutex mu;
    uint64_t limit = 0;        // 0 = unlimited
    uint64_t in_use = 0;
    uint64_t peak = 0;
    uint64_t total_allocs = 0;
    uint64_t failed_allocs = 0;
};

struct AllocHeader {
    Pool *pool;
    uint64_t size;
    uint64_t magic;
};
constexpr uint64_t kMagic = 0x747075636f6c5f31ULL;  // "tpucol_1"

}  // namespace

TPUCOL_API void *tpucol_pool_create(uint64_t limit_bytes) {
    Pool *p = new (std::nothrow) Pool();
    if (p) p->limit = limit_bytes;
    return p;
}

TPUCOL_API void tpucol_pool_destroy(void *pool) {
    delete static_cast<Pool *>(pool);
}

TPUCOL_API void *tpucol_pool_alloc(void *pool, uint64_t size) {
    Pool *p = static_cast<Pool *>(pool);
    {
        std::lock_guard<std::mutex> g(p->mu);
        if (p->limit && p->in_use + size > p->limit) {
            p->failed_allocs++;
            return nullptr;  // Python side raises RetryOOM -> spill/retry
        }
        p->in_use += size;
        if (p->in_use > p->peak) p->peak = p->in_use;
        p->total_allocs++;
    }
    void *raw = std::malloc(sizeof(AllocHeader) + size);
    if (!raw) {
        std::lock_guard<std::mutex> g(p->mu);
        p->in_use -= size;
        p->failed_allocs++;
        return nullptr;
    }
    AllocHeader *h = static_cast<AllocHeader *>(raw);
    h->pool = p;
    h->size = size;
    h->magic = kMagic;
    return h + 1;
}

TPUCOL_API int tpucol_pool_free(void *ptr) {
    if (!ptr) return 0;
    AllocHeader *h = static_cast<AllocHeader *>(ptr) - 1;
    if (h->magic != kMagic) return -1;
    h->magic = 0;
    {
        std::lock_guard<std::mutex> g(h->pool->mu);
        h->pool->in_use -= h->size;
    }
    std::free(h);
    return 0;
}

// stats: [in_use, peak, total_allocs, failed_allocs, limit]
TPUCOL_API void tpucol_pool_stats(void *pool, uint64_t *out5) {
    Pool *p = static_cast<Pool *>(pool);
    std::lock_guard<std::mutex> g(p->mu);
    out5[0] = p->in_use;
    out5[1] = p->peak;
    out5[2] = p->total_allocs;
    out5[3] = p->failed_allocs;
    out5[4] = p->limit;
}

TPUCOL_API void tpucol_pool_set_limit(void *pool, uint64_t limit_bytes) {
    Pool *p = static_cast<Pool *>(pool);
    std::lock_guard<std::mutex> g(p->mu);
    p->limit = limit_bytes;
}

// ---------------------------------------------------------------------------
// LZ4 block codec (nvcomp LZ4 analog, host-side).
//
// Standard LZ4 block format (token | literals | offset | matchlen...), so
// payloads are interoperable with any LZ4 implementation. Compressor uses a
// 16-bit hash chainless table (LZ4-fast equivalent); decompressor is fully
// bounds-checked (shuffle payloads cross trust boundaries between workers).
// ---------------------------------------------------------------------------

namespace {

constexpr int kMinMatch = 4;
constexpr int kHashLog = 16;
constexpr int kLastLiterals = 5;   // spec: last 5 bytes always literals
constexpr int kMfLimit = 12;       // spec: no match within 12 bytes of end

static inline uint32_t read32(const uint8_t *p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

static inline uint32_t hash4(uint32_t v) {
    return (v * 2654435761U) >> (32 - kHashLog);
}

}  // namespace

TPUCOL_API uint64_t tpucol_lz4_max_compressed(uint64_t n) {
    return n + n / 255 + 16;
}

// returns compressed size, or 0 if dst too small / input empty
TPUCOL_API uint64_t tpucol_lz4_compress(const uint8_t *src, uint64_t src_len,
                                        uint8_t *dst, uint64_t dst_cap) {
    if (src_len == 0 || dst_cap < tpucol_lz4_max_compressed(src_len))
        return 0;
    const uint8_t *ip = src;
    const uint8_t *const iend = src + src_len;
    const uint8_t *const mflimit = iend - kMfLimit;
    const uint8_t *anchor = src;
    uint8_t *op = dst;

    if (src_len >= kMfLimit) {
        // 32-bit positions: callers block the input at <= 4MB per frame
        uint32_t table[1 << kHashLog];
        std::memset(table, 0, sizeof(table));
        // position 0 sentinel: store pos+1, 0 means empty
        while (ip < mflimit) {
            uint32_t seq = read32(ip);
            uint32_t h = hash4(seq);
            const uint8_t *match = src + table[h] - 1;
            bool hit = table[h] != 0 && read32(match) == seq &&
                       (uint64_t)(ip - match) <= 0xFFFF && match < ip;
            table[h] = (uint32_t)(ip - src) + 1;
            if (!hit) {
                ip++;
                continue;
            }
            // extend match forward
            const uint8_t *mp = match + kMinMatch;
            const uint8_t *cp = ip + kMinMatch;
            while (cp < iend - kLastLiterals && *cp == *mp) { cp++; mp++; }
            uint64_t mlen = (uint64_t)(cp - ip) - kMinMatch;
            uint64_t litlen = (uint64_t)(ip - anchor);
            // token
            uint8_t *token = op++;
            if (litlen >= 15) {
                *token = (uint8_t)(15 << 4);
                uint64_t l = litlen - 15;
                while (l >= 255) { *op++ = 255; l -= 255; }
                *op++ = (uint8_t)l;
            } else {
                *token = (uint8_t)(litlen << 4);
            }
            std::memcpy(op, anchor, litlen);
            op += litlen;
            // offset (little-endian 16-bit)
            uint16_t off = (uint16_t)(ip - match);
            *op++ = (uint8_t)off;
            *op++ = (uint8_t)(off >> 8);
            // match length
            if (mlen >= 15) {
                *token |= 15;
                uint64_t m = mlen - 15;
                while (m >= 255) { *op++ = 255; m -= 255; }
                *op++ = (uint8_t)m;
            } else {
                *token |= (uint8_t)mlen;
            }
            ip = cp;
            anchor = ip;
        }
    }
    // trailing literals
    uint64_t litlen = (uint64_t)(iend - anchor);
    uint8_t *token = op++;
    if (litlen >= 15) {
        *token = (uint8_t)(15 << 4);
        uint64_t l = litlen - 15;
        while (l >= 255) { *op++ = 255; l -= 255; }
        *op++ = (uint8_t)l;
    } else {
        *token = (uint8_t)(litlen << 4);
    }
    std::memcpy(op, anchor, litlen);
    op += litlen;
    return (uint64_t)(op - dst);
}

// returns decompressed size, or 0 on malformed input / overflow
TPUCOL_API uint64_t tpucol_lz4_decompress(const uint8_t *src, uint64_t src_len,
                                          uint8_t *dst, uint64_t dst_cap) {
    const uint8_t *ip = src;
    const uint8_t *const iend = src + src_len;
    uint8_t *op = dst;
    uint8_t *const oend = dst + dst_cap;
    while (ip < iend) {
        uint8_t token = *ip++;
        // literals
        uint64_t litlen = token >> 4;
        if (litlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return 0;
                b = *ip++;
                litlen += b;
            } while (b == 255);
        }
        if ((uint64_t)(iend - ip) < litlen || (uint64_t)(oend - op) < litlen)
            return 0;
        std::memcpy(op, ip, litlen);
        ip += litlen;
        op += litlen;
        if (ip >= iend) break;  // last sequence has no match
        // offset
        if (iend - ip < 2) return 0;
        uint16_t off = (uint16_t)(ip[0] | (ip[1] << 8));
        ip += 2;
        if (off == 0 || (uint64_t)(op - dst) < off) return 0;
        // match length
        uint64_t mlen = (token & 15) + kMinMatch;
        if ((token & 15) == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return 0;
                b = *ip++;
                mlen += b;
            } while (b == 255);
        }
        if ((uint64_t)(oend - op) < mlen) return 0;
        const uint8_t *mp = op - off;
        // overlapping copy must be byte-wise
        for (uint64_t i = 0; i < mlen; i++) op[i] = mp[i];
        op += mlen;
    }
    return (uint64_t)(op - dst);
}

// ---------------------------------------------------------------------------
// Hash kernels (spark-rapids-jni Hash analog): murmur3_x86_32 with Spark's
// seed/tail handling, and xxhash64, both bulk over fixed-width column data.
// Used for host-side shuffle partitioning; the device path has its own JAX
// implementation (expressions/hashing.py) — these must agree bit-for-bit.
// ---------------------------------------------------------------------------

namespace {

static inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t mmh3_mix_k1(uint32_t k1) {
    k1 *= 0xcc9e2d51U;
    k1 = rotl32(k1, 15);
    k1 *= 0x1b873593U;
    return k1;
}

static inline uint32_t mmh3_mix_h1(uint32_t h1, uint32_t k1) {
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    return h1 * 5 + 0xe6546b64U;
}

static inline uint32_t mmh3_fmix(uint32_t h1, uint32_t len) {
    h1 ^= len;
    h1 ^= h1 >> 16;
    h1 *= 0x85ebca6bU;
    h1 ^= h1 >> 13;
    h1 *= 0xc2b2ae35U;
    h1 ^= h1 >> 16;
    return h1;
}

// Spark's Murmur3: ints/longs hashed as 4/8-byte ints (hashInt/hashLong),
// byte payloads hashed bytewise-as-ints (hashUnsafeBytes2 lenient mode).
static inline uint32_t mmh3_int(uint32_t v, uint32_t seed) {
    return mmh3_fmix(mmh3_mix_h1(seed, mmh3_mix_k1(v)), 4);
}

static inline uint32_t mmh3_long(uint64_t v, uint32_t seed) {
    uint32_t h1 = mmh3_mix_h1(seed, mmh3_mix_k1((uint32_t)v));
    h1 = mmh3_mix_h1(h1, mmh3_mix_k1((uint32_t)(v >> 32)));
    return mmh3_fmix(h1, 8);
}

static inline uint32_t mmh3_bytes(const uint8_t *data, uint32_t len,
                                  uint32_t seed) {
    // Spark hashUnsafeBytes: 4-byte blocks then per-byte tail mixing
    uint32_t h1 = seed;
    uint32_t nblocks = len / 4;
    for (uint32_t i = 0; i < nblocks; i++)
        h1 = mmh3_mix_h1(h1, mmh3_mix_k1(read32(data + i * 4)));
    for (uint32_t i = nblocks * 4; i < len; i++)
        h1 = mmh3_mix_h1(h1, mmh3_mix_k1((uint32_t)(int32_t)(int8_t)data[i]));
    return mmh3_fmix(h1, len);
}

constexpr uint64_t kXxPrime1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t kXxPrime2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t kXxPrime3 = 0x165667B19E3779F9ULL;
constexpr uint64_t kXxPrime4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t kXxPrime5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t xx64_long(uint64_t v, uint64_t seed) {
    // Spark XXH64.hashLong: one 8-byte chunk folded into seed+P5+len
    uint64_t h = seed + kXxPrime5 + 8;
    h ^= rotl64(v * kXxPrime2, 31) * kXxPrime1;
    h = rotl64(h, 27) * kXxPrime1 + kXxPrime4;
    h ^= h >> 33;
    h *= kXxPrime2;
    h ^= h >> 29;
    h *= kXxPrime3;
    h ^= h >> 32;
    return h;
}

}  // namespace

// hash n int64 values, combining into existing seeds[] (Spark chains column
// hashes: seed of column k+1 is the hash of column k)
TPUCOL_API void tpucol_murmur3_i64(const int64_t *vals, const uint8_t *valid,
                                   uint64_t n, uint32_t *seeds_io) {
    for (uint64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) continue;  // Spark: null leaves seed as-is
        seeds_io[i] = mmh3_long((uint64_t)vals[i], seeds_io[i]);
    }
}

TPUCOL_API void tpucol_murmur3_i32(const int32_t *vals, const uint8_t *valid,
                                   uint64_t n, uint32_t *seeds_io) {
    for (uint64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) continue;
        seeds_io[i] = mmh3_int((uint32_t)vals[i], seeds_io[i]);
    }
}

// strings: rectangular uint8[n, width] + int32 lengths (the engine's host
// string layout)
TPUCOL_API void tpucol_murmur3_bytes(const uint8_t *chars, const int32_t *lens,
                                     const uint8_t *valid, uint64_t n,
                                     uint64_t width, uint32_t *seeds_io) {
    for (uint64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) continue;
        seeds_io[i] = mmh3_bytes(chars + i * width, (uint32_t)lens[i],
                                 seeds_io[i]);
    }
}

TPUCOL_API void tpucol_xxhash64_i64(const int64_t *vals, const uint8_t *valid,
                                    uint64_t n, uint64_t *seeds_io) {
    for (uint64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) continue;
        seeds_io[i] = xx64_long((uint64_t)vals[i], seeds_io[i]);
    }
}

// ---------------------------------------------------------------------------
// Row ⇄ columnar conversion (RowConversion JNI analog).
//
// Fixed-width schema: rows are tightly packed records of the given column
// byte-widths (1/2/4/8) plus a leading null bitmap of ceil(ncols/8) bytes —
// the layout GpuRowToColumnarExec's generated code uses, minus padding.
// ---------------------------------------------------------------------------

TPUCOL_API int tpucol_rows_to_cols(const uint8_t *rows, uint64_t n_rows,
                                   const uint32_t *widths, uint32_t n_cols,
                                   uint8_t **col_data, uint8_t **col_valid) {
    uint64_t bitmap_bytes = (n_cols + 7) / 8;
    uint64_t row_size = bitmap_bytes;
    for (uint32_t c = 0; c < n_cols; c++) row_size += widths[c];
    for (uint64_t r = 0; r < n_rows; r++) {
        const uint8_t *rec = rows + r * row_size;
        const uint8_t *fld = rec + bitmap_bytes;
        for (uint32_t c = 0; c < n_cols; c++) {
            uint32_t w = widths[c];
            bool is_valid = (rec[c / 8] >> (c % 8)) & 1;
            col_valid[c][r] = is_valid ? 1 : 0;
            std::memcpy(col_data[c] + r * w, fld, w);
            fld += w;
        }
    }
    return 0;
}

TPUCOL_API int tpucol_cols_to_rows(uint8_t *rows, uint64_t n_rows,
                                   const uint32_t *widths, uint32_t n_cols,
                                   const uint8_t *const *col_data,
                                   const uint8_t *const *col_valid) {
    uint64_t bitmap_bytes = (n_cols + 7) / 8;
    uint64_t row_size = bitmap_bytes;
    for (uint32_t c = 0; c < n_cols; c++) row_size += widths[c];
    for (uint64_t r = 0; r < n_rows; r++) {
        uint8_t *rec = rows + r * row_size;
        std::memset(rec, 0, bitmap_bytes);
        uint8_t *fld = rec + bitmap_bytes;
        for (uint32_t c = 0; c < n_cols; c++) {
            uint32_t w = widths[c];
            if (!col_valid[c] || col_valid[c][r])
                rec[c / 8] |= (uint8_t)(1 << (c % 8));
            std::memcpy(fld, col_data[c] + r * w, w);
            fld += w;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// Shuffle split: scatter row indices into per-partition index lists in one
// pass (the host half of GpuPartitioning.sliceInternalOnGpu). Python computes
// partition ids (on device or via the hash kernels above); this builds the
// gather lists the serializer consumes.
// ---------------------------------------------------------------------------

TPUCOL_API int tpucol_partition_indices(const int32_t *pids, uint64_t n,
                                        uint32_t n_parts, uint32_t *offsets,
                                        uint32_t *indices) {
    // counting pass
    for (uint32_t p = 0; p <= n_parts; p++) offsets[p] = 0;
    for (uint64_t i = 0; i < n; i++) {
        int32_t p = pids[i];
        if (p < 0 || (uint32_t)p >= n_parts) return -1;
        offsets[p + 1]++;
    }
    for (uint32_t p = 0; p < n_parts; p++) offsets[p + 1] += offsets[p];
    // scatter pass (stable within partition)
    uint32_t *cursor = new (std::nothrow) uint32_t[n_parts];
    if (!cursor) return -2;
    std::memcpy(cursor, offsets, n_parts * sizeof(uint32_t));
    for (uint64_t i = 0; i < n; i++)
        indices[cursor[pids[i]]++] = (uint32_t)i;
    delete[] cursor;
    return 0;
}

// gather fixed-width column data by row indices (serializer hot loop)
TPUCOL_API void tpucol_gather(const uint8_t *src, const uint32_t *indices,
                              uint64_t n, uint32_t width, uint8_t *dst) {
    switch (width) {
    case 1:
        for (uint64_t i = 0; i < n; i++) dst[i] = src[indices[i]];
        break;
    case 2:
        for (uint64_t i = 0; i < n; i++)
            ((uint16_t *)dst)[i] = ((const uint16_t *)src)[indices[i]];
        break;
    case 4:
        for (uint64_t i = 0; i < n; i++)
            ((uint32_t *)dst)[i] = ((const uint32_t *)src)[indices[i]];
        break;
    case 8:
        for (uint64_t i = 0; i < n; i++)
            ((uint64_t *)dst)[i] = ((const uint64_t *)src)[indices[i]];
        break;
    default:
        for (uint64_t i = 0; i < n; i++)
            std::memcpy(dst + i * width, src + (uint64_t)indices[i] * width,
                        width);
    }
}

TPUCOL_API int tpucol_abi_version() { return 1; }
