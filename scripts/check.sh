#!/usr/bin/env bash
# Pre-merge / CI gate: static engine-invariant lint, then the smoke test
# tier.  Mirrors what tier-1 enforces (tests/test_lint.py runs the same
# linter as its gate test) but fails in seconds instead of minutes.
#
#   scripts/check.sh            # lint + smoke tests
#   scripts/check.sh --lint-only
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== 1/2 engine invariant lint =="
python -m spark_rapids_tpu.tools lint

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo "== 2/2 smoke test tier =="
python -m pytest tests/ -q -m smoke -p no:cacheprovider
