#!/usr/bin/env bash
# Pre-merge / CI gate: static engine-invariant lint, a compiled-program
# audit smoke (run a small query, audit its stageProgram ledger), then
# the smoke test tier.  Mirrors what tier-1 enforces (tests/test_lint.py
# and tests/test_audit.py run the same linter/auditor as their gate
# tests) but fails in seconds instead of minutes.
#
#   scripts/check.sh            # lint + audit smoke + trace round-trip + history round-trip + serving smoke + smoke tests
#   scripts/check.sh --lint-only
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== 1/7 engine invariant lint =="
python -m spark_rapids_tpu.tools lint

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo "== 2/7 compiled-program audit smoke =="
AUDIT_LOG="$(mktemp -d)/audit_smoke.jsonl"
python - "$AUDIT_LOG" <<'PY'
import sys
import numpy as np
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.expressions.base import Alias, col, lit
from spark_rapids_tpu.session import TpuSession

log = sys.argv[1]
s = TpuSession({"spark.rapids.sql.test.enabled": "false",
                "spark.rapids.sql.eventLog.path": log,
                "spark.rapids.debug.planCheck": "true"})
rng = np.random.default_rng(3)
df = s.create_dataframe(
    {"k": rng.integers(0, 20, 50_000).astype(np.int64),
     "v": rng.standard_normal(50_000)}, num_partitions=2)
out = (df.filter(col("k") > lit(2))
         .group_by("k").agg(Alias(F.sum(col("v")), "sv"))).collect()
assert out, "audit smoke query returned nothing"
PY
# error-severity ledger findings fail the gate; the roofline table is
# report-only here (no peak floor configured)
python -m spark_rapids_tpu.tools audit "$AUDIT_LOG" --no-roofline

echo "== 3/7 transition-ledger trace round-trip =="
# the audit smoke's own log round-trips through the Perfetto exporter:
# --check fails on any hostTransition/deviceSync the gateway saw that
# no query owns (unattributed = invisible latency), and the rendered
# JSON must be loadable trace-event format with a transitions track
TRACE_JSON="$(dirname "$AUDIT_LOG")/trace.json"
python -m spark_rapids_tpu.tools trace "$AUDIT_LOG" -o "$TRACE_JSON" --check
python - "$TRACE_JSON" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
evs = trace["traceEvents"]
assert evs and all(e["ph"] in ("M", "X", "C") for e in evs)
slices = [e for e in evs if e["ph"] == "X"]
assert any(e["cat"] == "plan" for e in slices), "plan track missing"
assert any(e["cat"] == "hostTransition" for e in slices), \
    "the smoke query crossed the boundary but no transition slice rendered"
print(f"trace round-trip ok: {len(evs)} events, "
      f"{sum(1 for e in slices if e['cat'] == 'hostTransition')} transition slice(s)")
PY

echo "== 4/7 history warehouse round-trip =="
# the audit smoke's log ingests (twice, as two labeled runs) into a
# fresh warehouse, calibrates a machine profile whose own residual
# bound must cover >=80% of observations, and the trajectory sentinel
# must stay quiet on a healthy (identical) repeat
HIST_DB="$(dirname "$AUDIT_LOG")/history.db"
MACHINE_JSON="$(dirname "$AUDIT_LOG")/machine.json"
python -m spark_rapids_tpu.tools history ingest "$AUDIT_LOG" --db "$HIST_DB" --label run1
# same path + same content: ingest is idempotent by content digest and
# would UPDATE run1 in place — --force inserts the second labeled run
# the calibrate/regress steps below need
python -m spark_rapids_tpu.tools history ingest "$AUDIT_LOG" --db "$HIST_DB" --label run2 --force
python -m spark_rapids_tpu.tools history calibrate --db "$HIST_DB" -o "$MACHINE_JSON"
python - "$MACHINE_JSON" <<'PY'
import json
import sys

prof = json.load(open(sys.argv[1]))
assert prof["schema"] == "spark-rapids-tpu-machine-profile", prof["schema"]
assert prof["stage_kinds"], "calibration produced no stage kinds"
assert prof["within_bound_frac"] >= 0.8, prof
print(f"machine profile ok: {len(prof['stage_kinds'])} stage kind(s), "
      f"{prof['observations']} observation(s), "
      f"{prof['within_bound_frac'] * 100:.0f}% within "
      f"+/-{prof['residual_bound'] * 100:.1f}%")
PY
python -m spark_rapids_tpu.tools history regress --db "$HIST_DB" --min-runs 1
python -m spark_rapids_tpu.tools history report --db "$HIST_DB"
rm -rf "$(dirname "$AUDIT_LOG")"

echo "== 5/7 concurrent-serving smoke =="
# two queries racing through the QueryServer: both admitted, results
# bit-identical to a serial run, and the exact repeat skips planning
python - <<'PY'
import numpy as np
from spark_rapids_tpu.serving import QueryServer
from spark_rapids_tpu.session import TpuSession

s = TpuSession({"spark.rapids.sql.test.enabled": "false",
                "spark.rapids.serving.maxConcurrentQueries": "2",
                "spark.rapids.serving.resultCache.maxBytes": "0"})
rng = np.random.default_rng(9)
df = s.create_dataframe(
    {"k": rng.integers(0, 10, 20_000).astype(np.int64),
     "v": rng.standard_normal(20_000)}, num_partitions=2)
s.create_or_replace_temp_view("t", df)
q = "SELECT k, SUM(v) AS sv, COUNT(*) AS c FROM t GROUP BY k ORDER BY k"
srv = QueryServer(session=s)
try:
    serial = srv.execute(q)
    a, b = srv.submit(q), srv.submit(q)
    assert a.result(120) == serial and b.result(120) == serial, \
        "concurrent serving results diverge from serial"
    st = srv.stats()
    assert st["admission"]["admitted"] == 3, st
    assert st["plan_cache"]["hits"] >= 1, \
        f"repeat query did not hit the plan cache: {st}"
finally:
    srv.stop()
print("serving smoke ok:", st["admission"], st["plan_cache"])
PY

echo "== 6/7 live console smoke =="
# the embedded console serves the engine live: start a session with the
# console enabled, race queries through the QueryServer, and scrape
# /metrics, /queries, and /server over its HTTP socket MID-RUN —
# Prometheus exposition shape, progress fields, and admission/cache
# state must all validate while work is in flight
python - <<'PY'
import json
import urllib.request

import numpy as np
from spark_rapids_tpu.aux.console import active_console
from spark_rapids_tpu.serving import QueryServer
from spark_rapids_tpu.session import TpuSession

s = TpuSession({"spark.rapids.sql.test.enabled": "false",
                "spark.rapids.console.enabled": "true",
                "spark.rapids.console.port": "0"})
con = active_console()
assert con is not None and con.running, "console did not start from conf"
rng = np.random.default_rng(11)
df = s.create_dataframe(
    {"k": rng.integers(0, 10, 20_000).astype(np.int64),
     "v": rng.standard_normal(20_000)}, num_partitions=2)
s.create_or_replace_temp_view("t", df)
q = "SELECT k, SUM(v) AS sv FROM t GROUP BY k ORDER BY k"


def get(path):
    with urllib.request.urlopen(con.url(path), timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


srv = QueryServer(session=s)
try:
    subs = [srv.submit(q) for _ in range(3)]
    # mid-run: the submissions are in flight while we scrape
    code, ctype, body = get("/metrics")
    assert code == 200 and ctype.startswith("text/plain; version=0.0.4"), \
        (code, ctype)
    text = body.decode("utf-8")
    assert "# TYPE" in text and "# HELP" in text, "not an exposition"
    queries = json.loads(get("/queries")[2])
    assert set(queries) == {"live", "recent"}, queries.keys()
    server = json.loads(get("/server")[2])
    assert server["servers"], "live QueryServer missing from /server"
    row = server["servers"][0]
    for key in ("queue_depth", "admitted_now", "plan_cache",
                "result_cache", "plan_cache_hit_rate"):
        assert key in row, f"/server row missing {key}"
    results = [sub.result(120) for sub in subs]
    assert all(r == results[0] for r in results), "results diverge"
    # completed serves populate the per-stage latency histograms
    server = json.loads(get("/server")[2])
    assert server["latency_histograms"], "latency histograms missing"
    for snap in server["latency_histograms"].values():
        assert snap["buckets"][-1][0] == "+Inf", snap
finally:
    srv.stop()
# the finished queries surface in the recent tail with progress 1.0
queries = json.loads(get("/queries")[2])
assert queries["recent"] and all(r["progress"] == 1.0
                                 for r in queries["recent"]), queries
s.stop()
assert active_console() is None, "session stop left the console running"
print(f"console smoke ok: {len(queries['recent'])} recent quer(ies), "
      f"queue_depth={row['queue_depth']}, "
      f"plan_cache_hit_rate={row['plan_cache_hit_rate']}")
PY

echo "== 7/7 smoke test tier =="
python -m pytest tests/ -q -m smoke -p no:cacheprovider
