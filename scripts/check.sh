#!/usr/bin/env bash
# Pre-merge / CI gate: static engine-invariant lint, a compiled-program
# audit smoke (run a small query, audit its stageProgram ledger), then
# the smoke test tier.  Mirrors what tier-1 enforces (tests/test_lint.py
# and tests/test_audit.py run the same linter/auditor as their gate
# tests) but fails in seconds instead of minutes.
#
#   scripts/check.sh            # lint + audit smoke + smoke tests
#   scripts/check.sh --lint-only
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== 1/3 engine invariant lint =="
python -m spark_rapids_tpu.tools lint

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo "== 2/3 compiled-program audit smoke =="
AUDIT_LOG="$(mktemp -d)/audit_smoke.jsonl"
python - "$AUDIT_LOG" <<'PY'
import sys
import numpy as np
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.expressions.base import Alias, col, lit
from spark_rapids_tpu.session import TpuSession

log = sys.argv[1]
s = TpuSession({"spark.rapids.sql.test.enabled": "false",
                "spark.rapids.sql.eventLog.path": log,
                "spark.rapids.debug.planCheck": "true"})
rng = np.random.default_rng(3)
df = s.create_dataframe(
    {"k": rng.integers(0, 20, 50_000).astype(np.int64),
     "v": rng.standard_normal(50_000)}, num_partitions=2)
out = (df.filter(col("k") > lit(2))
         .group_by("k").agg(Alias(F.sum(col("v")), "sv"))).collect()
assert out, "audit smoke query returned nothing"
PY
# error-severity ledger findings fail the gate; the roofline table is
# report-only here (no peak floor configured)
python -m spark_rapids_tpu.tools audit "$AUDIT_LOG" --no-roofline
rm -rf "$(dirname "$AUDIT_LOG")"

echo "== 3/3 smoke test tier =="
python -m pytest tests/ -q -m smoke -p no:cacheprovider
