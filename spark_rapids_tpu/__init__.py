"""spark-rapids-tpu: a TPU-native columnar SQL execution framework.

A ground-up rebuild of the capabilities of the RAPIDS Accelerator for Apache
Spark (reference: /root/reference, spark-rapids ~v24.08) designed TPU-first:

- Physical plans execute as columnar batches on TPU via JAX/XLA (whole-stage
  expression fusion into single XLA programs, vs the reference's
  kernel-at-a-time cuDF calls — see ``spark_rapids_tpu.exec``).
- A plan-rewrite framework tags every operator/expression for TPU support and
  falls back to a CPU columnar backend otherwise (reference:
  sql-plugin/.../GpuOverrides.scala, RapidsMeta.scala).
- Tiered HBM -> host-DRAM -> disk buffer catalog with spill, and a
  retry/split-retry discipline with deterministic OOM injection (reference:
  RapidsBufferCatalog.scala, RmmRapidsRetryIterator.scala).
- Shuffle via hash/range/round-robin partitioning with a multithreaded local
  transport and a mesh/ICI all-to-all device transport (reference:
  RapidsShuffleInternalManagerBase.scala, shuffle-plugin/).
- Differential CPU-vs-TPU testing as the correctness oracle (reference:
  integration_tests/src/main/python/asserts.py).

The package intentionally has no Spark/JVM dependency: it includes its own
Catalyst-equivalent DataFrame/expression layer so the whole stack is
self-contained and testable on a single host with a virtual device mesh.
"""

__version__ = "26.08.0"

from spark_rapids_tpu.config import TpuConf  # noqa: F401
from spark_rapids_tpu import types  # noqa: F401


def connect(conf=None):
    """Creates a TpuSession (SparkSession + plugin-init analog).

    Named ``connect`` (not ``session``) because the ``session`` submodule
    would shadow a package-level function of the same name after import.
    """
    from spark_rapids_tpu.session import TpuSession
    return TpuSession(conf)


__all__ = ["TpuConf", "types", "connect", "__version__"]
