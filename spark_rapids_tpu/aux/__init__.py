"""Auxiliary subsystems (SURVEY.md §2.13/§5): op metrics with verbosity
levels, the query-scoped tracing/event subsystem (span tree, event log,
Prometheus exposition), profiler trace ranges, debug batch dumps,
execution-plan capture, and the cost-based optimizer's helpers."""

from spark_rapids_tpu.aux.events import (  # noqa: F401
    Event, EventSink, JsonlEventLogSink, RingBufferSink, emit,
    parse_event_line, render_prometheus)
from spark_rapids_tpu.aux.faults import (  # noqa: F401
    CircuitBreaker, InjectedFault, arm_fault, arm_from_conf, disarm,
    disarm_all, fault_stats, maybe_fire, recovery_stats)
from spark_rapids_tpu.aux.profiler import (  # noqa: F401
    Profiler, op_range)
from spark_rapids_tpu.aux.metrics import (  # noqa: F401
    MetricLevel, OpMetric, collect_metrics, instrument_plan, reset_metrics)
from spark_rapids_tpu.aux.tracing import (  # noqa: F401
    QueryExecution, Span, last_query_summary, query_scope)
from spark_rapids_tpu.aux.capture import (  # noqa: F401
    ExecutionPlanCaptureCallback)
