"""Auxiliary subsystems (SURVEY.md §2.13/§5): op metrics with verbosity
levels, profiler trace ranges, debug batch dumps, execution-plan capture,
and the cost-based optimizer's helpers."""

from spark_rapids_tpu.aux.capture import (  # noqa: F401
    ExecutionPlanCaptureCallback)
from spark_rapids_tpu.aux.metrics import (  # noqa: F401
    MetricLevel, OpMetric, collect_metrics, instrument_plan)
from spark_rapids_tpu.aux.profiler import (  # noqa: F401
    Profiler, op_range)
