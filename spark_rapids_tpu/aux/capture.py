"""Execution-plan capture + debug batch dumps.

Reference: ExecutionPlanCaptureCallback (test/debug plan capture, used by
integration-test fallback assertions) and DumpUtils.scala (writes offending
input batches to parquet for kernel-bug reproduction)."""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional


class ExecutionPlanCaptureCallback:
    """Captures every plan that flows through TpuOverrides.apply.

    ``start_capture()`` then run queries; ``get_captured_plans()`` returns
    (input_plan, final_plan, meta) triples; assert helpers mirror the
    reference's assertContains/assertDidFallBack."""

    _lock = threading.Lock()
    _capturing = False
    _captured: List[tuple] = []

    @classmethod
    def start_capture(cls) -> None:
        with cls._lock:
            cls._captured = []
            cls._capturing = True

    @classmethod
    def end_capture(cls) -> List[tuple]:
        with cls._lock:
            cls._capturing = False
            return list(cls._captured)

    @classmethod
    def capture_if_needed(cls, input_plan, final_plan, meta) -> None:
        with cls._lock:
            if cls._capturing:
                cls._captured.append((input_plan, final_plan, meta))

    @classmethod
    def get_captured_plans(cls) -> List[tuple]:
        with cls._lock:
            return list(cls._captured)

    # -- assertion helpers ---------------------------------------------------
    @classmethod
    def assert_contains(cls, exec_name: str) -> None:
        for _, final, _ in cls.get_captured_plans():
            if any(n.name == exec_name for n in final.collect_nodes()):
                return
        raise AssertionError(
            f"no captured plan contains {exec_name}; captured: "
            + "; ".join(f.tree_string() for _, f, _ in
                        cls.get_captured_plans()))

    @classmethod
    def assert_did_fall_back(cls, exec_name: str) -> None:
        """The named CPU exec must appear NON-converted in a final plan
        (reference: assert_gpu_fallback_collect's plan check)."""
        cls.assert_contains(exec_name)


def dump_batch(hb, path_prefix: str) -> str:
    """Writes a host batch to a parquet file for offline repro (reference:
    DumpUtils.dumpToParquetFile — used when a kernel fails on an input)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    path = f"{path_prefix}-{int(time.time() * 1000)}.parquet"
    pq.write_table(pa.Table.from_batches([hb.to_arrow()]), path)
    return path


def dump_on_error(batch_iter, path_prefix: Optional[str]):
    """Wraps a host-batch iterator: on an exception mid-stream, dumps the
    LAST successfully produced batch to parquet and re-raises with the dump
    path in the message (the reference dumps the failing operator input)."""
    last = None
    try:
        for b in batch_iter:
            last = b
            yield b
    except Exception as e:
        if path_prefix and last is not None:
            hb = last.to_host() if hasattr(last, "to_host") and \
                not hasattr(last, "arrow_schema") else last
            try:
                p = dump_batch(hb, path_prefix)
                raise type(e)(f"{e} [last good batch dumped to {p}]") from e
            except TypeError:
                pass
        raise
