"""Live engine console: the runtime's state served over HTTP.

Reference: the Spark UI + the ``PrometheusServlet`` metrics sink — the
reference engine exposes operators, tasks, memory and shuffle state
live, while everything this engine had before was post-hoc
(``render_prometheus()`` was a function nobody served; running queries
were invisible until ``queryEnd``).  A stdlib-only
``ThreadingHTTPServer`` (no dependencies) serves:

- ``/metrics``  — ``aux.events.render_prometheus()`` verbatim, with the
  Prometheus exposition content-type;
- ``/queries``  — live QueryExecution span trees (aux/tracing.py) with
  per-operator rows/batches so far plus a progress fraction and ETA
  joined against the PR 17 machine-profile cost predictions (the cost
  model's first LIVE consumer);
- ``/memory``   — catalog pool gauges + the per-query/per-operator byte
  attribution threaded through BufferCatalog registration tags;
- ``/server``   — QueryServer admission/cache/latency state
  (serving/console_routes.py);
- ``/debug/dump`` — the PR 7 watchdog ladder on demand: arbiter
  registry, semaphore holders, live stacks — without waiting for a
  hang;
- ``/events``   — process-wide ring-buffer tail with kind filtering.

Every handler reads lock-protected SNAPSHOTS only (catalog stats,
arbiter stats/dump, histogram snapshots, per-query span locks) — a
scrape never takes an engine lock an executing query holds, which the
lock-order validator armed in the console tests proves.

Lifecycle mirrors the resource sampler singleton: ``TpuSession`` calls
``sync_from_conf`` at construction and on ``set_conf`` of any
``spark.rapids.console.*`` key; one console per process regardless of
session count; ``session.stop()`` stops it.  Off by default
(``spark.rapids.console.enabled``) with zero overhead when disabled —
no socket, no tap, one module-global read on the emit hot path.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from spark_rapids_tpu.aux import events as EV

#: Prometheus text exposition format version 0.0.4
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: /events default tail length
DEFAULT_EVENT_TAIL = 256


# ---------------------------------------------------------------------------
# endpoint payload builders (everything reads snapshots only)
# ---------------------------------------------------------------------------

def queries_payload(params: Optional[Dict] = None) -> dict:
    """Live span trees + a bounded tail of finished summaries."""
    from spark_rapids_tpu.aux import tracing as TR
    live = [q.live_snapshot() for q in TR.live_queries()]
    recent = [{"query_id": s.get("query_id"),
               "description": s.get("description"),
               "status": s.get("status"),
               "duration_s": s.get("duration_s"),
               "progress": 1.0}
              for s in TR.recent_summaries()]
    return {"live": live, "recent": recent}


def memory_payload(params: Optional[Dict] = None) -> dict:
    """Catalog pool gauges + per-(query, operator) byte attribution.
    Attribution rows resolve their span id to the operator name through
    the live-query registry; buffers registered outside any query
    (caches, exchange stores) aggregate under query_id -1."""
    from spark_rapids_tpu.memory.device_manager import get_runtime
    rt = get_runtime()
    if rt is None:
        return {"pool": None, "attribution": []}
    from spark_rapids_tpu.aux import tracing as TR
    names: Dict[int, str] = {}
    for q in TR.live_queries():
        names.update(q.span_names())
    rows = []
    for row in rt.catalog.attribution():
        row = dict(row)
        node = names.get(row["span_id"])
        if node is not None:
            row["node"] = node
        rows.append(row)
    return {"pool": rt.catalog.stats(), "attribution": rows}


def debug_dump_payload(params: Optional[Dict] = None) -> dict:
    """The watchdog's thread-state ladder, on demand: arbiter registry
    stats + serving view + live stacks, semaphore holders."""
    from spark_rapids_tpu.memory.arbiter import get_arbiter
    from spark_rapids_tpu.memory.device_manager import get_runtime
    arb = get_arbiter()
    payload = {
        "arbiter": arb.stats(),
        "serving": arb.serving_view(),
        "dump": arb.dump().splitlines(),
    }
    rt = get_runtime()
    if rt is not None:
        payload["semaphore"] = rt.semaphore.stats()
        payload["catalog"] = rt.catalog.stats()
    EV.emit("consoleLifecycle", op="dump")
    return payload


def events_payload(params: Optional[Dict] = None) -> dict:
    """Tail of the console's process-wide event tap, optionally
    filtered by ``?kind=`` and bounded by ``?n=``."""
    params = params or {}
    tap = EV.console_tap()
    if tap is None:
        return {"events": [], "dropped": 0}
    kind = params.get("kind") or None
    try:
        n = max(1, int(params.get("n", DEFAULT_EVENT_TAIL)))
    except ValueError:
        n = DEFAULT_EVENT_TAIL
    rows = [{"event": e.kind, "query_id": e.query_id,
             "span_id": e.span_id, "ts": e.ts, "payload": e.payload}
            for e in tap.events() if kind is None or e.kind == kind]
    return {"events": rows[-n:], "dropped": tap.dropped}


def _server_payload(params: Optional[Dict] = None) -> dict:
    from spark_rapids_tpu.serving.console_routes import server_payload
    return server_payload()


def _index_payload(params: Optional[Dict] = None) -> dict:
    return {"service": "spark-rapids-tpu console",
            "endpoints": sorted(list(_JSON_ROUTES) + ["/metrics"])}


_JSON_ROUTES = {
    "/": _index_payload,
    "/queries": queries_payload,
    "/memory": memory_payload,
    "/server": _server_payload,
    "/debug/dump": debug_dump_payload,
    "/events": events_payload,
}


class _ConsoleHandler(BaseHTTPRequestHandler):
    server_version = "SparkRapidsTpuConsole/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):     # noqa: A003 - BaseHTTPRequest API
        pass    # diagnostics endpoint; stderr chatter helps nobody

    def _send(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:              # noqa: N802 - BaseHTTPRequest API
        try:
            parsed = urllib.parse.urlsplit(self.path)
            path = parsed.path
            if len(path) > 1:
                path = path.rstrip("/")
            if path == "/metrics":
                self._send(200, PROMETHEUS_CONTENT_TYPE,
                           EV.render_prometheus().encode("utf-8"))
                return
            params = dict(urllib.parse.parse_qsl(parsed.query))
            fn = _JSON_ROUTES.get(path)
            if fn is None:
                self._send(404, "application/json",
                           json.dumps({"error": f"unknown path {path}",
                                       **_index_payload()}).encode("utf-8"))
                return
            body = json.dumps(fn(params), default=str).encode("utf-8")
            self._send(200, "application/json", body)
        except Exception as e:  # noqa: BLE001 - a scrape must never crash
            try:                # the server thread
                self._send(500, "application/json",
                           json.dumps({"error": repr(e)}).encode("utf-8"))
            except Exception:   # noqa: BLE001 - client went away
                pass


class EngineConsole:
    """One bound HTTP server + its serve thread + the event tap."""

    def __init__(self, port: int = 0, bind_address: str = "127.0.0.1",
                 ring_size: int = 2048):
        self.conf_port = int(port)          # as configured (0 = ephemeral)
        self.bind_address = bind_address
        self.tap = EV.RingBufferSink(ring_size)
        self._httpd = ThreadingHTTPServer((bind_address, self.conf_port),
                                          _ConsoleHandler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])  # as bound
        self._thread: Optional[threading.Thread] = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def url(self, path: str = "/") -> str:
        host = self.bind_address if self.bind_address not in (
            "", "0.0.0.0", "::") else "127.0.0.1"
        return f"http://{host}:{self.port}{path}"

    def start(self) -> None:
        if self.running:
            return
        EV.set_console_tap(self.tap)
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="tpu-console", daemon=True)
        self._thread = t
        t.start()
        EV.emit("consoleLifecycle", op="start", port=self.port,
                bind=self.bind_address)

    def stop(self) -> None:
        if EV.console_tap() is self.tap:
            EV.set_console_tap(None)
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
        EV.emit("consoleLifecycle", op="stop", port=self.port)


# ---------------------------------------------------------------------------
# process-wide singleton, synced from conf (the sampler pattern)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_CONSOLE: Optional[EngineConsole] = None


def active_console() -> Optional[EngineConsole]:
    with _LOCK:
        return _CONSOLE


def stop_console() -> None:
    global _CONSOLE
    with _LOCK:
        cur, _CONSOLE = _CONSOLE, None
    if cur is not None:
        cur.stop()


def sync_from_conf(conf) -> Optional[EngineConsole]:
    """Reconciles the singleton with ``spark.rapids.console.*``:
    enabling binds + starts it, disabling stops it, a changed
    port/bind address rebinds.  Idempotent — safe on every session
    init / set_conf."""
    global _CONSOLE
    from spark_rapids_tpu import config as C
    enabled = conf.get(C.CONSOLE_ENABLED.key, False)
    port = int(conf.get(C.CONSOLE_PORT.key, 0))
    bind = conf.get(C.CONSOLE_BIND_ADDRESS.key, "127.0.0.1")
    stale = None
    with _LOCK:
        cur = _CONSOLE
        if not enabled:
            _CONSOLE, stale = None, cur
        elif cur is not None and cur.running and \
                cur.conf_port == port and cur.bind_address == bind:
            return cur
        else:
            stale = cur
            _CONSOLE = EngineConsole(port, bind)
            _CONSOLE.start()
        out = _CONSOLE
    if stale is not None:
        stale.stop()
    return out
