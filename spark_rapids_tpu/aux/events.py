"""Query event bus + pluggable sinks.

Reference: the Spark event log (SparkListenerEvent JSON lines consumed by
the history server) crossed with the plugin's accumulators — the reference
surfaces semaphore/retry/spill via GpuTaskMetrics and NVTX; here every
layer emits a typed ``Event`` through one process-wide bus:

- ``emit(kind, **payload)`` is the single hook the memory / shuffle /
  task layers call.  It is zero-cost when nothing listens: one contextvar
  read when no ``QueryExecution`` is active and no global sink is
  registered.
- Events route to the active query's ring buffer + sinks (the query id
  and span id are stamped there), or to process-global sinks for
  daemon-thread emitters that run outside any query (heartbeats,
  shuffle workers).

Sinks: ``JsonlEventLogSink`` (the event-log file analog, conf
``spark.rapids.sql.eventLog.path``), ``RingBufferSink`` (in-memory, for
tests and ``explain(analyze=True)``), and ``render_prometheus()`` — a
text exposition of the registry's gauges/counters for scrapers.
"""

from __future__ import annotations

import collections
import contextvars
import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional

EVENT_SCHEMA_VERSION = 1

#: stamped on events emitted outside any query / span scope
NO_QUERY = -1
NO_SPAN = -1


@dataclasses.dataclass
class Event:
    """One observability record.  ``ts`` is ``time.monotonic()`` — event
    ordering within a query is meaningful, wall-clock is not."""
    kind: str
    query_id: int
    span_id: int
    ts: float
    payload: Dict

    def to_json(self) -> str:
        return json.dumps({"event": self.kind, "query_id": self.query_id,
                           "span_id": self.span_id, "ts": self.ts,
                           "v": EVENT_SCHEMA_VERSION, **self.payload},
                          default=str)


def parse_event_line(line: str) -> Event:
    """Inverse of ``Event.to_json`` (the round-trip contract the event-log
    schema test pins): raises on lines missing the required envelope."""
    d = json.loads(line)
    kind = d.pop("event")
    query_id = d.pop("query_id")
    span_id = d.pop("span_id")
    ts = d.pop("ts")
    d.pop("v", None)
    return Event(kind, query_id, span_id, ts, d)


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

class EventSink:
    def emit(self, event: Event) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class RingBufferSink(EventSink):
    """Bounded in-memory sink (tests / explain(analyze)); drops oldest
    beyond ``capacity`` and counts the drops — a truncated buffer must
    never read as complete."""

    def __init__(self, capacity: int = 2048):
        self._buf = collections.deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self.dropped = 0

    def emit(self, event: Event) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(event)

    def events(self) -> List[Event]:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


class JsonlEventLogSink(EventSink):
    """Appends one JSON object per event to ``path`` (Spark event-log
    analog; multiple queries interleave lines, keyed by ``query_id``).

    Line-atomic under concurrency: pending lines batch in memory and hit
    the O_APPEND fd in ONE unbuffered write per batch — a second query's
    sink on the same path can interleave between batches but never split
    a line (a torn line would break the ``parse_event_line`` contract).
    A stdio buffer would instead flush at SIZE boundaries, tearing lines
    mid-JSON."""

    #: events between writes; emitters (which may hold the query or
    #: catalog lock) only pay disk latency once per batch
    FLUSH_EVERY = 64

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "ab", buffering=0)
        self._pending: List[str] = []

    def emit(self, event: Event) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._pending.append(event.to_json() + "\n")
            if len(self._pending) >= self.FLUSH_EVERY:
                self._write_pending()

    def _write_pending(self) -> None:
        if self._pending:
            self._f.write("".join(self._pending).encode("utf-8"))
            self._pending = []

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._write_pending()
                self._f.close()


# ---------------------------------------------------------------------------
# routing: active query (contextvar) + per-thread span stack + global sinks
# ---------------------------------------------------------------------------

_ACTIVE: "contextvars.ContextVar[Optional[object]]" = contextvars.ContextVar(
    "srt_active_query", default=None)


def active_query():
    """The QueryExecution the calling context runs under, or None.
    Task-pool threads see the right query because iter_partition_tasks
    copies the submitting thread's context (plan/base.py)."""
    return _ACTIVE.get()


def _activate(query):
    return _ACTIVE.set(query)


def _deactivate(token) -> None:
    _ACTIVE.reset(token)


class _SpanStack(threading.local):
    def __init__(self):
        self.stack: List[int] = []


_SPANS = _SpanStack()


def push_span(span_id: int) -> None:
    """Marks the calling thread as executing inside ``span_id`` — events
    emitted deeper in the call stack (a spill inside a kernel staging
    alloc) attribute to the operator that triggered them."""
    _SPANS.stack.append(span_id)


def pop_span() -> None:
    if _SPANS.stack:
        _SPANS.stack.pop()


def current_span_id() -> Optional[int]:
    st = _SPANS.stack
    return st[-1] if st else None


_GLOBAL_SINKS: List[EventSink] = []
_GLOBAL_LOCK = threading.Lock()


def add_global_sink(sink: EventSink) -> None:
    """Receives events emitted OUTSIDE any query context (heartbeat
    threads, shuffle worker processes)."""
    with _GLOBAL_LOCK:
        _GLOBAL_SINKS.append(sink)


def remove_global_sink(sink: EventSink) -> None:
    with _GLOBAL_LOCK:
        if sink in _GLOBAL_SINKS:
            _GLOBAL_SINKS.remove(sink)


def emit(kind: str, **payload) -> None:
    """The one hook every layer calls.  No active query and no global
    sink = no allocation, no lock."""
    q = _ACTIVE.get()
    if q is not None:
        q.record_event(kind, payload)
        return
    if _GLOBAL_SINKS:
        ev = Event(kind, NO_QUERY, current_span_id() or NO_SPAN,
                   time.monotonic(), payload)
        with _GLOBAL_LOCK:
            sinks = list(_GLOBAL_SINKS)
        for s in sinks:
            s.emit(ev)


# ---------------------------------------------------------------------------
# Prometheus-style exposition of the process-wide registries
# ---------------------------------------------------------------------------

def render_prometheus() -> str:
    """Text exposition of the runtime's gauges/counters (catalog tiers,
    task-metric accumulators, semaphore, operator ranges) in the
    Prometheus format a scraper or test can parse."""
    lines: List[str] = []

    def add(name: str, mtype: str, value, help_text: str) -> None:
        full = f"spark_rapids_tpu_{name}"
        lines.append(f"# HELP {full} {help_text}")
        lines.append(f"# TYPE {full} {mtype}")
        lines.append(f"{full} {value}")

    from spark_rapids_tpu.memory.device_manager import get_runtime
    rt = get_runtime()
    if rt is not None:
        st = rt.catalog.stats()
        add("device_pool_bytes", "gauge", st["device_bytes"],
            "Catalog-tracked device bytes")
        add("device_pool_limit_bytes", "gauge", st["device_limit"],
            "Device pool budget")
        add("host_spill_bytes", "gauge", st["host_bytes"],
            "Catalog-tracked host-tier bytes")
        add("disk_spill_bytes", "gauge", st["disk_bytes"],
            "Catalog-tracked disk-tier bytes")
        add("catalog_buffers", "gauge", st["buffers"],
            "Live buffers in the catalog")
        add("spill_total", "counter", st["spill_count"],
            "Buffers pushed down a storage tier")
        total, finished = rt.metrics.snapshot()
        add("tasks_finished_total", "counter", finished,
            "Tasks reported to the metrics registry")
        add("retry_total", "counter", total.retry_count,
            "RetryOOM attempts across tasks")
        add("split_retry_total", "counter", total.split_retry_count,
            "SplitAndRetryOOM splits across tasks")
        add("oom_total", "counter", total.oom_count,
            "Device pool exhaustions signalled to tasks")
        add("task_spill_bytes_total", "counter", total.spill_bytes,
            "Bytes spilled attributed to tasks")
        add("semaphore_wait_seconds_total", "counter",
            round(total.semaphore_wait_seconds, 6),
            "Seconds tasks blocked on device admission")
        add("semaphore_max_concurrent", "gauge",
            rt.semaphore.max_concurrent,
            "Device admission permits (concurrentGpuTasks)")
    from spark_rapids_tpu.aux import profiler as _prof
    for op, s in sorted(_prof.range_stats().items()):
        full = "spark_rapids_tpu_op_range_seconds_total"
        if f"# TYPE {full} counter" not in lines:
            lines.append(f"# HELP {full} Wall seconds inside operator "
                         "ranges")
            lines.append(f"# TYPE {full} counter")
        lines.append(f'{full}{{op="{op}"}} {s["total_s"]}')
    return "\n".join(lines) + "\n"
