"""Query event bus + pluggable sinks.

Reference: the Spark event log (SparkListenerEvent JSON lines consumed by
the history server) crossed with the plugin's accumulators — the reference
surfaces semaphore/retry/spill via GpuTaskMetrics and NVTX; here every
layer emits a typed ``Event`` through one process-wide bus:

- ``emit(kind, **payload)`` is the single hook the memory / shuffle /
  task layers call.  It is zero-cost when nothing listens: one contextvar
  read when no ``QueryExecution`` is active and no global sink is
  registered.
- Events route to the active query's ring buffer + sinks (the query id
  and span id are stamped there), or to process-global sinks for
  daemon-thread emitters that run outside any query (heartbeats,
  shuffle workers, the resource sampler).

Sinks: ``JsonlEventLogSink`` (the event-log file analog, conf
``spark.rapids.sql.eventLog.path``, with size-based rotation and optional
gzip compression), ``RingBufferSink`` (in-memory, for tests and
``explain(analyze=True)``), and ``render_prometheus()`` — a text
exposition of the registry's gauges/counters for scrapers.

Every ``emit(kind=...)`` call site in the package must use a kind from
``EVENT_KINDS`` (pinned by a tier-1 ast test) so the offline reader
(``spark_rapids_tpu.tools``) can rely on known schemas.
"""

from __future__ import annotations

import atexit
import collections
import contextvars
import dataclasses
import gzip
import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

#: v1 = PR 1 envelope (event/query_id/span_id/ts).  v2 adds the offline
#: reader's structural fields: spanMetrics rows carry parent_id / depth /
#: start_s / end_s / partitions, queryStart carries the non-default conf
#: snapshot, and files open with an ``eventLogHeader`` line.  v3 adds the
#: compiled-program audit ledger: ``stageProgram`` rows (one per built
#: executable — jaxpr signatures, const shapes/fingerprints, arg
#: signature, flops/bytes, key provenance) and ``planInvariantViolation``
#: rows from the runtime plan verifier.  v4 adds the host-transition
#: ledger: ``hostTransition`` rows (one per packed H2D/D2H batch
#: transfer — direction, bytes, encoding kinds, duration) and
#: ``deviceSync`` rows (one per non-transfer blocking sync — site,
#: duration) from aux/transitions.py.  The reader (tools/reader.py)
#: accepts all four.
EVENT_SCHEMA_VERSION = 4

#: stamped on events emitted outside any query / span scope
NO_QUERY = -1
NO_SPAN = -1

#: THE event-kind catalog: every ``emit(kind=...)`` / ``record_event``
#: call site in the package uses one of these (tier-1 ast test), so the
#: offline reader can rely on a closed vocabulary.  Grouped by emitter.
EVENT_KINDS = frozenset({
    # tracing lifecycle (aux/tracing.py)
    "queryStart", "queryEnd", "spanMetrics",
    # event-log file framing (this module)
    "eventLogHeader",
    # memory layer (memory/catalog.py, retry.py, semaphore.py, metrics.py)
    "spill", "unspill", "oom", "retryOOM", "splitRetry",
    "semaphoreAcquired", "taskEnd",
    # cooperative memory arbitration + hung-query watchdog
    # (memory/arbiter.py)
    "threadBlocked", "deadlockBreak", "watchdogDump", "taskCancelled",
    # task runner (plan/base.py)
    "taskRetry", "taskDegraded",
    # pipelined execution (exec/pipeline.py)
    "pipelineSpool",
    # stage compiler (exec/stage_compiler.py); stageProgram is the
    # per-executable audit ledger row (schema v3, tools/audit)
    "stageCompile", "stageProgram",
    # runtime plan-invariant verifier (plan/verify.py)
    "planInvariantViolation",
    # encoded columnar execution (columnar/encoding.py, transfer.py)
    "encodedBatch", "encodingFallback",
    # host-transition & device-sync ledger (aux/transitions.py, schema
    # v4): one hostTransition per packed H2D/D2H transfer, one
    # deviceSync per non-transfer blocking sync
    "hostTransition", "deviceSync",
    # shuffle layer (shuffle/*.py, exec/exchange.py)
    "shuffleSend", "shuffleFetch", "fetchRetry", "fetchFailover",
    "shuffleBlockLoaded", "shuffleWorkerFetch", "shuffleBlocksInvalidated",
    "executorRegistered", "executorLost", "workerExpired", "mapRerun",
    "collectiveFallback",
    # SPMD partitioned execution (parallel/mesh.py, parallel/spmd.py,
    # plan/distribution.py, exec/adaptive.py)
    "meshTopology", "iciExchange", "exchangeElided", "aqeCoalesce",
    # chaos / resilience (aux/faults.py)
    "faultInjected", "breakerTrip",
    # runtime lock-order validator (aux/lockorder.py)
    "lockOrderViolation",
    # live resource sampler (aux/sampler.py)
    "resourceSample",
    # live engine console (aux/console.py): start/stop/dump lifecycle
    "consoleLifecycle",
    # concurrent query serving (serving/server.py, serving/caches.py):
    # admission lifecycle, the two cross-query caches, and the online
    # AutoTuner's applied conf deltas
    "servingAdmission", "planCache", "resultCache", "autotuneApplied",
    # calibrated cost-model cross-check (aux/tracing.py): predicted vs
    # measured wall time from the tools/history machine profile
    "costModel",
})


@dataclasses.dataclass
class Event:
    """One observability record.  ``ts`` is ``time.monotonic()`` — event
    ordering within a query is meaningful, wall-clock is not."""
    kind: str
    query_id: int
    span_id: int
    ts: float
    payload: Dict

    def to_json(self) -> str:
        return json.dumps({"event": self.kind, "query_id": self.query_id,
                           "span_id": self.span_id, "ts": self.ts,
                           "v": EVENT_SCHEMA_VERSION, **self.payload},
                          default=str)


def parse_event_line(line: str) -> Event:
    """Inverse of ``Event.to_json`` (the round-trip contract the event-log
    schema test pins): raises on lines missing the required envelope."""
    d = json.loads(line)
    kind = d.pop("event")
    query_id = d.pop("query_id")
    span_id = d.pop("span_id")
    ts = d.pop("ts")
    d.pop("v", None)
    return Event(kind, query_id, span_id, ts, d)


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

class EventSink:
    def emit(self, event: Event) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class _DropCell:
    """One ring's drop count, kept alive past the ring itself: at ring
    GC a finalizer retires the cell's value into the process total, so
    ``ring_dropped_total()`` stays monotonic without the hot emit path
    ever touching a process-global lock."""

    __slots__ = ("n",)

    def __init__(self):
        self.n = 0


_DROP_LOCK = threading.Lock()
_RETIRED_DROPS = 0
_LIVE_DROP_CELLS: set = set()


def _retire_drop_cell(cell: _DropCell) -> None:
    global _RETIRED_DROPS
    with _DROP_LOCK:
        _LIVE_DROP_CELLS.discard(cell)
        _RETIRED_DROPS += cell.n


def ring_dropped_total() -> int:
    """Process-lifetime count of events dropped by ring-buffer sinks —
    the truncation marker ``render_prometheus()`` and the offline
    profiler surface so a silently-trimmed buffer is never mistaken for
    'nothing happened'."""
    with _DROP_LOCK:
        return _RETIRED_DROPS + sum(c.n for c in _LIVE_DROP_CELLS)


class RingBufferSink(EventSink):
    """Bounded in-memory sink (tests / explain(analyze)); drops oldest
    beyond ``capacity`` and counts the drops — a truncated buffer must
    never read as complete.  Drops also tally into the process-wide
    ``ring_dropped_total()`` counter (via a per-ring cell: the emit path
    only touches this ring's lock)."""

    def __init__(self, capacity: int = 2048):
        self._buf = collections.deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._drop_cell = _DropCell()
        with _DROP_LOCK:
            _LIVE_DROP_CELLS.add(self._drop_cell)
        weakref.finalize(self, _retire_drop_cell, self._drop_cell)

    @property
    def dropped(self) -> int:
        return self._drop_cell.n

    def emit(self, event: Event) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._drop_cell.n += 1
            self._buf.append(event)

    def events(self) -> List[Event]:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


#: live event-log sinks, flushed at interpreter exit so short-lived
#: processes (bench runs, scripts) don't lose the sub-batch tail
_LIVE_EVENTLOG_SINKS: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_ARMED = False


def _flush_eventlog_sinks() -> None:
    """atexit hook (also directly testable): flush every live sink's
    pending lines without closing it."""
    for sink in list(_LIVE_EVENTLOG_SINKS):
        try:
            sink.flush()
        except Exception:   # noqa: BLE001 - exit path must not raise
            pass


def _register_eventlog_sink(sink: "JsonlEventLogSink") -> None:
    global _ATEXIT_ARMED
    _LIVE_EVENTLOG_SINKS.add(sink)
    if not _ATEXIT_ARMED:
        _ATEXIT_ARMED = True
        atexit.register(_flush_eventlog_sinks)


class JsonlEventLogSink(EventSink):
    """Appends one JSON object per event to ``path`` (Spark event-log
    analog; multiple queries interleave lines, keyed by ``query_id``).

    Line-atomic under concurrency: pending lines batch in memory and hit
    the O_APPEND fd in ONE unbuffered write per batch — a second query's
    sink on the same path can interleave between batches but never split
    a line (a torn line would break the ``parse_event_line`` contract).
    A stdio buffer would instead flush at SIZE boundaries, tearing lines
    mid-JSON.

    Hardening (conf ``spark.rapids.sql.eventLog.*``):

    - a fresh (empty) file opens with an ``eventLogHeader`` line carrying
      the schema version, so the offline reader knows what it is parsing;
    - ``max_bytes`` > 0 rotates the file once it crosses the budget: the
      current file renames to ``path.N`` (N increasing, oldest smallest)
      and a fresh file (with header) takes its place — the reader walks
      the rotated set in order;
    - ``compress=True`` writes each batch as ONE complete gzip member
      (``gzip.compress`` of the batch) to the O_APPEND fd, so the
      one-write-per-batch atomicity survives compression and readers see
      a standard multi-member gzip stream (sniffed by magic, not
      extension);
    - pending lines flush via ``atexit`` so short-lived processes don't
      lose the tail.
    """

    #: events between writes; emitters (which may hold the query or
    #: catalog lock) only pay disk latency once per batch
    FLUSH_EVERY = 64

    def __init__(self, path: str, max_bytes: int = 0,
                 compress: bool = False,
                 flush_every: Optional[int] = None):
        self.path = path
        self.max_bytes = max(0, int(max_bytes or 0))
        self.compress = bool(compress)
        self._flush_every = max(1, int(flush_every or self.FLUSH_EVERY))
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._pending: List[str] = []
        self._f = None
        self._open_file()
        _register_eventlog_sink(self)

    # -- file lifecycle ------------------------------------------------------
    def _open_file(self) -> None:
        self._f = open(self.path, "ab", buffering=0)
        if os.fstat(self._f.fileno()).st_size == 0:
            header = Event("eventLogHeader", NO_QUERY, NO_SPAN,
                           time.monotonic(),
                           {"format": "spark-rapids-tpu-eventlog",
                            "compress": self.compress})
            self._write_raw(header.to_json() + "\n")

    def _write_raw(self, text: str) -> None:
        data = text.encode("utf-8")
        if self.compress:
            data = gzip.compress(data)
        self._f.write(data)

    def _rotate_locked(self) -> None:
        self._f.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        os.replace(self.path, f"{self.path}.{n}")
        self._open_file()

    # -- sink API ------------------------------------------------------------
    def emit(self, event: Event) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._pending.append(event.to_json() + "\n")
            if len(self._pending) >= self._flush_every:
                self._write_pending()

    def _write_pending(self) -> None:
        if self._pending:
            self._write_raw("".join(self._pending))
            self._pending = []
        if not self.max_bytes:
            return
        # several sinks may share this path (per-query sinks + the
        # sampler's): judge the budget by the REAL file size, not this
        # sink's private write count, and never rename a file another
        # sink already rotated us away from — migrate to the fresh file
        # instead
        try:
            st_fd = os.fstat(self._f.fileno())
            st_path = os.stat(self.path)
        except OSError:
            return      # mid-rotation window elsewhere; re-check next batch
        if st_path.st_ino != st_fd.st_ino:
            self._f.close()
            self._open_file()
            return
        if st_fd.st_size >= self.max_bytes:
            self._rotate_locked()

    def flush(self) -> None:
        """Pushes pending lines to disk without closing (atexit hook)."""
        with self._lock:
            if not self._f.closed:
                self._write_pending()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._write_pending()
                self._f.close()


# ---------------------------------------------------------------------------
# routing: active query (contextvar) + per-thread span stack + global sinks
# ---------------------------------------------------------------------------

_ACTIVE: "contextvars.ContextVar[Optional[object]]" = contextvars.ContextVar(
    "srt_active_query", default=None)


def active_query():
    """The QueryExecution the calling context runs under, or None.
    Task-pool threads see the right query because iter_partition_tasks
    copies the submitting thread's context (plan/base.py)."""
    return _ACTIVE.get()


def _activate(query):
    return _ACTIVE.set(query)


def _deactivate(token) -> None:
    _ACTIVE.reset(token)


class _SpanStack(threading.local):
    def __init__(self):
        self.stack: List[int] = []


_SPANS = _SpanStack()


def push_span(span_id: int) -> None:
    """Marks the calling thread as executing inside ``span_id`` — events
    emitted deeper in the call stack (a spill inside a kernel staging
    alloc) attribute to the operator that triggered them."""
    _SPANS.stack.append(span_id)


def pop_span() -> None:
    if _SPANS.stack:
        _SPANS.stack.pop()


def current_span_id() -> Optional[int]:
    st = _SPANS.stack
    return st[-1] if st else None


_GLOBAL_SINKS: List[EventSink] = []
_GLOBAL_LOCK = threading.Lock()


def add_global_sink(sink: EventSink) -> None:
    """Receives events emitted OUTSIDE any query context (heartbeat
    threads, shuffle worker processes, the resource sampler)."""
    with _GLOBAL_LOCK:
        _GLOBAL_SINKS.append(sink)


def remove_global_sink(sink: EventSink) -> None:
    with _GLOBAL_LOCK:
        if sink in _GLOBAL_SINKS:
            _GLOBAL_SINKS.remove(sink)


#: the console's process-wide event tail (aux/console.py /events): a
#: RingBufferSink mirror of BOTH routing paths — query-scoped events
#: (mirrored by QueryExecution.record_event) and global-scope events
#: (mirrored here).  None when the console is off: the emit hot path
#: pays one module-global read, nothing else.
_CONSOLE_TAP: Optional[RingBufferSink] = None


def set_console_tap(sink: Optional[RingBufferSink]) -> None:
    global _CONSOLE_TAP
    _CONSOLE_TAP = sink


def console_tap() -> Optional[RingBufferSink]:
    return _CONSOLE_TAP


def emit(kind: str, **payload) -> None:
    """The one hook every layer calls.  No active query, no global
    sink and no console tap = no allocation, no lock."""
    q = _ACTIVE.get()
    if q is not None:
        q.record_event(kind, payload)
        return
    tap = _CONSOLE_TAP
    if _GLOBAL_SINKS or tap is not None:
        ev = Event(kind, NO_QUERY, current_span_id() or NO_SPAN,
                   time.monotonic(), payload)
        with _GLOBAL_LOCK:
            sinks = list(_GLOBAL_SINKS)
        for s in sinks:
            s.emit(ev)
        if tap is not None:
            tap.emit(ev)


# ---------------------------------------------------------------------------
# Prometheus-style exposition of the process-wide registries
# ---------------------------------------------------------------------------

def escape_label_value(v: str) -> str:
    """Prometheus exposition-format label escaping: backslash, double
    quote and newline must be escaped inside label values."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_prometheus() -> str:
    """Text exposition of the runtime's gauges/counters (catalog tiers,
    task-metric accumulators, semaphore, operator ranges) in the
    Prometheus format a scraper or test can parse."""
    lines: List[str] = []

    def add(name: str, mtype: str, value, help_text: str) -> None:
        full = f"spark_rapids_tpu_{name}"
        lines.append(f"# HELP {full} {help_text}")
        lines.append(f"# TYPE {full} {mtype}")
        lines.append(f"{full} {value}")

    from spark_rapids_tpu.memory.device_manager import get_runtime
    rt = get_runtime()
    if rt is not None:
        st = rt.catalog.stats()
        add("device_pool_bytes", "gauge", st["device_bytes"],
            "Catalog-tracked device bytes")
        add("device_pool_limit_bytes", "gauge", st["device_limit"],
            "Device pool budget")
        add("device_pool_peak_bytes", "gauge", st["device_peak_bytes"],
            "High-watermark of catalog-tracked device bytes")
        add("device_spillable_bytes", "gauge", st["spillable_bytes"],
            "Device-tier bytes the spill framework may evict")
        add("host_spill_bytes", "gauge", st["host_bytes"],
            "Catalog-tracked host-tier bytes")
        add("disk_spill_bytes", "gauge", st["disk_bytes"],
            "Catalog-tracked disk-tier bytes")
        add("catalog_buffers", "gauge", st["buffers"],
            "Live buffers in the catalog")
        add("spill_total", "counter", st["spill_count"],
            "Buffers pushed down a storage tier")
        total, finished = rt.metrics.snapshot()
        add("tasks_finished_total", "counter", finished,
            "Tasks reported to the metrics registry")
        add("retry_total", "counter", total.retry_count,
            "RetryOOM attempts across tasks")
        add("split_retry_total", "counter", total.split_retry_count,
            "SplitAndRetryOOM splits across tasks")
        add("oom_total", "counter", total.oom_count,
            "Device pool exhaustions signalled to tasks")
        add("task_spill_bytes_total", "counter", total.spill_bytes,
            "Bytes spilled attributed to tasks")
        add("semaphore_wait_seconds_total", "counter",
            round(total.semaphore_wait_seconds, 6),
            "Seconds tasks blocked on device admission")
        add("alloc_wait_seconds_total", "counter",
            round(total.alloc_wait_seconds, 6),
            "Seconds tasks parked in BLOCKED_ON_ALLOC awaiting releases")
        add("semaphore_max_concurrent", "gauge",
            rt.semaphore.max_concurrent,
            "Device admission permits (concurrentGpuTasks)")
    from spark_rapids_tpu.memory.arbiter import get_arbiter
    ast = get_arbiter().stats()
    add("arbiter_blocked_threads", "gauge", ast["blocked_threads"],
        "Task threads currently in a blocked arbiter state")
    add("arbiter_blocked_on_alloc_total", "counter",
        ast["blocked_on_alloc_total"],
        "Allocation parks taken by the cooperative arbiter")
    add("deadlock_breaks_total", "counter", ast["deadlock_breaks"],
        "Forced victim wakes by the deadlock detector")
    add("forced_splits_total", "counter", ast["forced_splits"],
        "Deadlock breaks escalated to SplitAndRetryOOM")
    add("tasks_cancelled_total", "counter", ast["tasks_cancelled"],
        "Wedged tasks cancelled by the hung-query watchdog")
    add("watchdog_dumps_total", "counter", ast["watchdog_dumps"],
        "Hung-query watchdog thread-state dumps")
    add("serving_queries", "gauge", ast["serving_queries"],
        "Queries currently admitted to or queued in the serving layer")
    add("serving_admission_queued", "gauge", ast["serving_queued"],
        "Submissions currently blocked on serving admission "
        "(BLOCKED_ON_ADMISSION)")
    add("events_ring_dropped_total", "counter", ring_dropped_total(),
        "Events dropped by bounded ring-buffer sinks (truncation marker)")
    from spark_rapids_tpu.aux import lockorder as _lo
    add("lock_order_violations_total", "counter", _lo.violations_total(),
        "Lock acquisitions that went backward against the canonical "
        "order (spark.rapids.debug.lockOrder validator; 0 when disarmed)")
    from spark_rapids_tpu.plan import verify as _pv
    add("plan_invariant_violations_total", "counter",
        _pv.violations_total(),
        "Structural plan-contract violations found by the runtime plan "
        "verifier (spark.rapids.debug.planCheck; 0 when disarmed)")
    from spark_rapids_tpu.exec import stage_compiler as _sc
    scs = _sc.stats()
    add("stage_programs", "gauge", scs["programs"],
        "Live compiled stage programs in the executable cache")
    add("stage_cache_hits_total", "counter", scs["hits"],
        "Executable-cache hits (program reused without rebuild)")
    add("stage_cache_misses_total", "counter", scs["misses"],
        "Executable-cache misses (program built)")
    add("stage_cache_evictions_total", "counter", scs["evictions"],
        "Programs dropped by the executable-cache LRU bound")
    add("stage_traces_total", "counter", scs["traces"],
        "JAX traces of stage programs (retrace marker: should stop "
        "growing once a workload's shapes are warm)")
    add("stage_compiles_total", "counter", scs["compiles"],
        "Stage programs compiled (first dispatches)")
    add("stage_async_compiles_total", "counter", scs["async_compiles"],
        "Stage programs compiled on the background pool")
    add("stage_compile_seconds_total", "counter",
        round(scs["compile_s"], 6),
        "Seconds spent tracing+compiling stage programs")
    from spark_rapids_tpu.aux import transitions as _tr
    trt = _tr.totals()
    add("h2d_transitions_total", "counter", trt["h2d_count"],
        "Packed host->device batch uploads through the transition gateway")
    add("h2d_bytes_total", "counter", trt["h2d_bytes"],
        "Bytes uploaded host->device")
    add("h2d_seconds_total", "counter", trt["h2d_seconds"],
        "Seconds in device_put dispatch for H2D uploads")
    add("d2h_transitions_total", "counter", trt["d2h_count"],
        "Packed device->host batch downloads through the transition "
        "gateway")
    add("d2h_bytes_total", "counter", trt["d2h_bytes"],
        "Bytes downloaded device->host")
    add("d2h_seconds_total", "counter", trt["d2h_seconds"],
        "Seconds blocked fetching D2H downloads")
    add("device_syncs_total", "counter", trt["sync_count"],
        "Non-transfer blocking device syncs (count forces, overflow "
        "checks) through the transition gateway")
    add("device_sync_seconds_total", "counter", trt["sync_seconds"],
        "Seconds blocked in non-transfer device syncs")
    from spark_rapids_tpu.serving import server as _srv
    hists = _srv.latency_histograms()
    if hists:
        full = "spark_rapids_tpu_serving_latency_seconds"
        lines.append(f"# HELP {full} Serving submission latency by stage "
                     "(queue wait, admission, cache lookup, plan, "
                     "compile, execute, collect, e2e)")
        lines.append(f"# TYPE {full} histogram")
        for stage in sorted(hists):
            h = hists[stage]
            lbl = escape_label_value(stage)
            for le, n in h["buckets"]:
                le_s = "+Inf" if le == float("inf") else repr(le)
                lines.append(f'{full}_bucket{{stage="{lbl}",le="{le_s}"}} '
                             f'{n}')
            lines.append(f'{full}_sum{{stage="{lbl}"}} '
                         f'{round(h["sum"], 6)}')
            lines.append(f'{full}_count{{stage="{lbl}"}} {h["count"]}')
    from spark_rapids_tpu.aux import profiler as _prof
    for op, s in sorted(_prof.range_stats().items()):
        full = "spark_rapids_tpu_op_range_seconds_total"
        if f"# TYPE {full} counter" not in lines:
            lines.append(f"# HELP {full} Wall seconds inside operator "
                         "ranges")
            lines.append(f"# TYPE {full} counter")
        lines.append(f'{full}{{op="{escape_label_value(op)}"}} '
                     f'{s["total_s"]}')
    return "\n".join(lines) + "\n"
