"""Process-wide fault-injection framework + recovery bookkeeping.

Generalizes the ``RmmSpark.forceRetryOOM`` pattern (memory/retry.py's
thread-local injection counters) into ONE mechanism every layer shares:
a registry of *named fault points* with deterministic arm/fire semantics.

Reference: the RmmSpark JNI state machine injects OOMs at allocation
points (tests/.../RmmSparkRetrySuiteBase.scala:27-53); the plugin's
shuffle suites script peer loss through mocked transports.  Here the
same discipline covers every data-movement layer:

- ``memory.alloc``      tracked allocation points (memory/retry.py)
- ``shuffle.fetch``     client fetch attempts (shuffle/client_server.py)
- ``shuffle.send``      server block sends (shuffle/client_server.py)
- ``shuffle.connect``   transport connection setup (socket_transport.py)
- ``task.run``          task start in the parallel runner (plan/base.py)
- ``parallel.collective``  mesh collective shuffle (parallel/collective.py)
- ``pipeline.prefetch`` prefetch-spool start (exec/pipeline.py producer)
- ``memory.block``      allocation admission (memory/catalog.py reserve):
                        an injected never-releasing hold the watchdog
                        must detect, dump and cancel
- ``watchdog.sweep``    inside the watchdog sweep (memory/arbiter.py):
                        the daemon must survive a faulted pass

Semantics (mirroring ``force_retry_oom(num_ooms, skip)``): arming a point
with ``n`` and ``skip`` makes the next ``skip`` triggers pass and the
``n`` after that raise.  Deterministic — no randomness, no wall clock —
so chaos tests assert bit-identical results and exact event counts.

Conf-driven arming rides ``spark.rapids.chaos.*`` keys (value ``"n"`` or
``"n:skip"``); ``TpuOverrides.apply``/``TpuSession.set_conf`` re-arm on
every query so each action sees a fresh fault budget.

The module also keeps process-wide *recovery counters* (fetch retries,
failovers, task retries, breaker trips, map re-runs, worker expiries):
every recovery emit site notes its transition here so ``bench.py`` can
report what recovery cost across a run without scraping event logs.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple


class InjectedFault(Exception):
    """Default exception an armed fault point raises (task/exec layers).
    Classified retryable: the task runner re-attempts work that dies of
    one, exactly like a real transient executor failure."""


@dataclasses.dataclass
class _ArmedFault:
    remaining: int          # faults still to fire
    skip: int               # triggers to let pass first
    exc_factory: Callable[[str], BaseException]
    fired: int = 0          # total faults this arming has raised


_LOCK = threading.Lock()
_ARMED: Dict[str, _ArmedFault] = {}
#: lifetime fire counts per point (survive disarm; bench/test introspection)
_FIRED_TOTAL: Dict[str, int] = {}

#: recovery-transition counters (emit sites call note_recovery)
_RECOVERY: Dict[str, int] = {}


def _default_exc(point: str) -> BaseException:
    return InjectedFault(f"injected fault at {point!r}")


def arm_fault(point: str, n: int = 1, skip: int = 0,
              exc: Optional[Callable[[str], BaseException]] = None) -> None:
    """Arms ``point`` to raise on its next ``n`` triggers after letting
    ``skip`` pass (reference: RmmSpark.forceRetryOOM(num_ooms, skip)).
    ``exc`` is a callable ``point -> exception``; defaults per layer are
    applied by the trigger site via ``maybe_fire``'s armed state."""
    if n <= 0:
        disarm(point)
        return
    with _LOCK:
        _ARMED[point] = _ArmedFault(int(n), max(0, int(skip)),
                                    exc or _default_exc)


def disarm(point: str) -> None:
    with _LOCK:
        _ARMED.pop(point, None)


def disarm_all() -> None:
    with _LOCK:
        _ARMED.clear()


def maybe_fire(point: str) -> None:
    """Called at a fault point: no-op unless armed.  Zero-cost when the
    chaos layer is idle (one dict lookup under no lock)."""
    if not _ARMED:        # benign race: arming is test/chaos-conf driven
        return
    with _LOCK:
        st = _ARMED.get(point)
        if st is None:
            return
        if st.skip > 0:
            st.skip -= 1
            return
        st.remaining -= 1
        st.fired += 1
        _FIRED_TOTAL[point] = _FIRED_TOTAL.get(point, 0) + 1
        if st.remaining <= 0:
            del _ARMED[point]
        exc = st.exc_factory(point)
    from spark_rapids_tpu.aux.events import emit
    emit("faultInjected", point=point, exc=type(exc).__name__)
    raise exc


def is_armed(point: str) -> bool:
    with _LOCK:
        return point in _ARMED


def fault_stats() -> Dict[str, int]:
    """Lifetime fault fire counts per point."""
    with _LOCK:
        return dict(_FIRED_TOTAL)


def reset_fault_stats() -> None:
    with _LOCK:
        _FIRED_TOTAL.clear()


# ---------------------------------------------------------------------------
# recovery counters (the "what did resilience cost" ledger)
# ---------------------------------------------------------------------------

#: THE recovery vocabulary: event kind -> ledger/summary key.  Emit sites
#: pair each event with note_recovery(key); tracing's per-query summary
#: and bench.py's chaos payload both derive from this map, so adding a
#: recovery kind here propagates to every surface.
RECOVERY_KINDS: Dict[str, str] = {
    "fetchRetry": "fetch_retries",
    "fetchFailover": "fetch_failovers",
    "taskRetry": "task_retries",
    "taskDegraded": "tasks_degraded",
    "breakerTrip": "breaker_trips",
    "mapRerun": "map_reruns",
    "workerExpired": "workers_expired",
    "collectiveFallback": "collective_fallbacks",
    "faultInjected": "faults_injected",
    "deadlockBreak": "deadlock_breaks",
    "taskCancelled": "tasks_cancelled",
    "watchdogDump": "watchdog_dumps",
}


def note_recovery(kind: str, n: int = 1) -> None:
    """Recovery emit sites (fetchRetry, taskRetry, ...) tally here so a
    whole bench run's recovery overhead is one snapshot away."""
    with _LOCK:
        _RECOVERY[kind] = _RECOVERY.get(kind, 0) + n


def recovery_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_RECOVERY)


def reset_recovery_stats() -> None:
    with _LOCK:
        _RECOVERY.clear()


# ---------------------------------------------------------------------------
# conf-driven arming (spark.rapids.chaos.*)
# ---------------------------------------------------------------------------

def parse_chaos_spec(spec: str) -> Optional[Tuple[int, int]]:
    """``"n"`` or ``"n:skip"`` -> (n, skip); empty/0 -> None (disarmed).
    Raises ValueError on malformed specs (set_conf-time validation)."""
    s = str(spec).strip()
    if not s or s.lower() in ("0", "false", "off", "none"):
        return None
    parts = s.split(":")
    if len(parts) > 2:
        raise ValueError(f"chaos spec {spec!r}: want 'n' or 'n:skip'")
    n = int(parts[0])
    skip = int(parts[1]) if len(parts) == 2 else 0
    if n < 0 or skip < 0:
        raise ValueError(f"chaos spec {spec!r}: negative counts")
    return (n, skip) if n else None


def chaos_spec_ok(spec: str) -> bool:
    """Conf checker form of ``parse_chaos_spec``."""
    try:
        parse_chaos_spec(spec)
        return True
    except (ValueError, TypeError):
        return False


def _retry_oom(point: str) -> BaseException:
    from spark_rapids_tpu.memory.retry import RetryOOM
    return RetryOOM(f"injected RetryOOM at {point!r}")


def _conn_error(point: str) -> BaseException:
    return ConnectionError(f"injected connection fault at {point!r}")


def _block_hold(point: str) -> BaseException:
    from spark_rapids_tpu.memory.arbiter import InjectedBlockHold
    return InjectedBlockHold(f"injected allocation hold at {point!r}")


#: chaos conf key suffix -> (fault point, exception factory)
CHAOS_POINTS: Dict[str, Tuple[str, Callable[[str], BaseException]]] = {
    "shuffle.fetch": ("shuffle.fetch", _conn_error),
    "shuffle.send": ("shuffle.send", _conn_error),
    "shuffle.connect": ("shuffle.connect", _conn_error),
    "task.run": ("task.run", _default_exc),
    "parallel.collective": ("parallel.collective", _default_exc),
    "memory.alloc": ("memory.alloc", _retry_oom),
    "pipeline.prefetch": ("pipeline.prefetch", _default_exc),
    "memory.block": ("memory.block", _block_hold),
    "watchdog.sweep": ("watchdog.sweep", _default_exc),
}

_CHAOS_PREFIX = "spark.rapids.chaos."


def arm_from_conf(conf) -> List[str]:
    """Syncs the armed set with the conf's ``spark.rapids.chaos.*`` keys:
    a set spec arms its point, an empty spec disarms it (a pooled thread
    must not inherit a previous session's chaos).  Returns armed points."""
    armed: List[str] = []
    for suffix, (point, exc) in CHAOS_POINTS.items():
        spec = conf.get(_CHAOS_PREFIX + suffix, "")
        parsed = parse_chaos_spec(spec) if spec else None
        if parsed is None:
            disarm(point)
        else:
            n, skip = parsed
            arm_fault(point, n, skip, exc)
            armed.append(point)
    return armed


# ---------------------------------------------------------------------------
# circuit breaker (stage-scoped degradation)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Counts faults toward a threshold; once tripped, callers degrade to
    their safe path instead of burning retries (the task runner drops to
    single-threaded inline execution for the rest of the stage).

    ``threshold <= 0`` disables (never trips)."""

    def __init__(self, threshold: int, name: str = "stage"):
        self.threshold = int(threshold)
        self.name = name
        self._failures = 0
        self._tripped = False
        self._lock = threading.Lock()

    @property
    def failures(self) -> int:
        with self._lock:
            return self._failures

    @property
    def tripped(self) -> bool:
        with self._lock:
            return self._tripped

    def record_failure(self) -> bool:
        """Returns True exactly once: on the failure that trips it."""
        if self.threshold <= 0:
            return False
        with self._lock:
            self._failures += 1
            if not self._tripped and self._failures >= self.threshold:
                self._tripped = True
                just_tripped = True
            else:
                just_tripped = False
        if just_tripped:
            note_recovery("breaker_trips")
            from spark_rapids_tpu.aux.events import emit
            emit("breakerTrip", name=self.name, failures=self._failures,
                 threshold=self.threshold)
        return just_tripped
