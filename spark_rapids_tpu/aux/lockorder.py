"""Runtime lock-order validator for the engine's concurrency core.

The engine's four process-wide synchronization primitives form a strict
acquisition hierarchy (``CANONICAL_LOCK_ORDER``): a thread holding a lock
may only acquire locks *later* in the order.  The discipline is what
keeps the arbiter/semaphore/catalog/spool interplay deadlock-free (the
arbiter is the innermost rendezvous everything signals into; it never
calls back out — memory/arbiter.py "the arbiter never calls back into
the caller", memory/catalog.py "lock order catalog -> arbiter,
one-directional").

Two enforcement layers share the declaration in this module:

- **static**: ``tools/lint``'s ``lock-order`` rule builds the
  lock-acquisition graph over the package source (every ``with`` block
  on a lock created by the factories below, and every call reachable
  under it) and rejects edges that go backward in the canonical order;
- **runtime**: conf ``spark.rapids.debug.lockOrder`` arms the
  instrumented wrappers below.  Each tracked acquire records the
  (held -> acquiring) edge for the calling thread; an edge that goes
  backward counts as a violation and emits a ``lockOrderViolation``
  event (surfaced in ``render_prometheus()`` and the tools profiler).

The factories return plain ``threading`` primitives semantically — when
the validator is disarmed the per-acquire overhead is one global flag
read — so the four call sites construct through here unconditionally.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set, Tuple

__all__ = [
    "CANONICAL_LOCK_ORDER", "tracked_condition", "tracked_rlock",
    "set_enabled", "force_enabled", "is_enabled", "observed_edges",
    "violations_total", "violation_pairs", "reset_observations",
    "sync_from_conf",
]

#: THE declared acquisition order, outermost first: a thread holding a
#: lock may only acquire locks strictly later in this tuple.  The static
#: lint rule parses this literal; the runtime wrappers index into it —
#: one source of truth for both directions of the cross-check.
CANONICAL_LOCK_ORDER: Tuple[str, ...] = (
    "spool",        # exec/pipeline.py   PrefetchSpool._cond
    "catalog",      # memory/catalog.py  BufferCatalog._lock
    "semaphore",    # memory/semaphore.py TpuSemaphore._cond
    "arbiter",      # memory/arbiter.py  ResourceArbiter._cond
)

_RANK: Dict[str, int] = {n: i for i, n in enumerate(CANONICAL_LOCK_ORDER)}

#: effective hot-path flag: conf-synced base, overridable by tests
_ENABLED = False
_CONF_ENABLED = False
_FORCED = None


def _refresh() -> None:
    global _ENABLED
    _ENABLED = _CONF_ENABLED if _FORCED is None else _FORCED

_STATE_LOCK = threading.Lock()
#: every (held, acquired) pair seen since the last reset — the runtime
#: half of the static/runtime cross-check (tests assert each observed
#: edge is forward in CANONICAL_LOCK_ORDER)
_EDGES: Set[Tuple[str, str]] = set()
#: back-edges, kept separately so a violation survives edge inspection
_VIOLATIONS: Set[Tuple[str, str]] = set()
_VIOLATIONS_TOTAL = 0


class _HeldStack(threading.local):
    def __init__(self):
        self.stack: List[str] = []


_HELD = _HeldStack()


def set_enabled(on: bool) -> None:
    """Conf-synced arming (session init / set_conf).  A session built
    with default conf DISARMS — the sampler/watchdog singleton
    lifecycle; tests that must stay armed across incidental session
    construction use ``force_enabled``."""
    global _CONF_ENABLED
    _CONF_ENABLED = bool(on)
    _refresh()


def force_enabled(on) -> None:
    """Test override that wins over conf syncs: ``True``/``False`` pin
    the validator regardless of session construction; ``None`` returns
    control to the conf."""
    global _FORCED
    _FORCED = on if on is None else bool(on)
    _refresh()


def is_enabled() -> bool:
    return _ENABLED


def sync_from_conf(conf) -> None:
    """Arms/disarms the validator from ``spark.rapids.debug.lockOrder``
    (session init / set_conf, the sampler/watchdog sync pattern)."""
    from spark_rapids_tpu import config as C
    set_enabled(conf.get(C.DEBUG_LOCK_ORDER.key, False))


def observed_edges() -> Set[Tuple[str, str]]:
    with _STATE_LOCK:
        return set(_EDGES)


def violation_pairs() -> Set[Tuple[str, str]]:
    with _STATE_LOCK:
        return set(_VIOLATIONS)


def violations_total() -> int:
    with _STATE_LOCK:
        return _VIOLATIONS_TOTAL


def reset_observations() -> None:
    global _VIOLATIONS_TOTAL
    with _STATE_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()
        _VIOLATIONS_TOTAL = 0


def _note_acquire(name: str) -> None:
    """Runs BEFORE the actual acquire (the violation must be recorded
    even if the bad acquisition then deadlocks)."""
    stack = _HELD.stack
    if stack and name not in stack:
        global _VIOLATIONS_TOTAL
        rank = _RANK.get(name)
        fresh_violations = []
        with _STATE_LOCK:
            for held in stack:
                if held == name:
                    continue
                edge = (held, name)
                _EDGES.add(edge)
                held_rank = _RANK.get(held)
                backward = (rank is None or held_rank is None
                            or rank <= held_rank)
                if backward:
                    _VIOLATIONS_TOTAL += 1
                    if edge not in _VIOLATIONS:
                        _VIOLATIONS.add(edge)
                        fresh_violations.append(edge)
        for held, acq in fresh_violations:
            # emitted outside _STATE_LOCK, before the offending acquire
            # (the event sinks use their own leaf locks)
            from spark_rapids_tpu.aux.events import emit
            emit("lockOrderViolation", held=held, acquiring=acq,
                 order="<".join(CANONICAL_LOCK_ORDER),
                 thread=threading.current_thread().name)
    stack.append(name)


def _note_release(name: str) -> None:
    stack = _HELD.stack
    # pop the most recent matching entry (reentrant holds pop one level)
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


class TrackedCondition(threading.Condition):
    """``threading.Condition`` that reports lock acquisition order.

    ``wait()`` internally releases/re-acquires through the inner RLock's
    ``_release_save``/``_acquire_restore`` (bound by Condition at
    construction, bypassing the overrides) — correct for tracking: the
    thread logically holds the lock across a wait, and no new ordering
    edge is created by the re-acquire."""

    def __init__(self, name: str):
        super().__init__()
        self._lo_name = name
        inner_acquire = self.acquire
        inner_release = self.release

        # Condition binds acquire/release as INSTANCE attributes from its
        # lock, so class-level overrides would be shadowed: rebind here
        def acquire(*a, **k):
            if _ENABLED:
                _note_acquire(name)
            got = inner_acquire(*a, **k)
            if not got:
                _note_release(name)
            return got

        def release():
            inner_release()
            # release-side tracking is UNCONDITIONAL: disarming while a
            # thread holds the lock must still pop its stack entry, or a
            # later re-arm sees phantom held locks (a no-op on the empty
            # stack when never armed)
            _note_release(name)

        self.acquire = acquire
        self.release = release

    def __enter__(self):
        if _ENABLED:
            _note_acquire(self._lo_name)
        return super().__enter__()

    def __exit__(self, *exc):
        out = super().__exit__(*exc)
        _note_release(self._lo_name)
        return out


class TrackedRLock:
    """Re-entrant lock that reports acquisition order.  Exposes the
    ``_release_save``/``_acquire_restore``/``_is_owned`` protocol so it
    can also back a ``threading.Condition`` if ever needed."""

    def __init__(self, name: str):
        self._lo_name = name
        self._inner = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _ENABLED:
            _note_acquire(self._lo_name)
        got = self._inner.acquire(blocking, timeout)
        if not got:
            _note_release(self._lo_name)
        return got

    def release(self) -> None:
        self._inner.release()
        # unconditional: see TrackedCondition.release
        _note_release(self._lo_name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition compatibility passthroughs
    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, state):
        return self._inner._acquire_restore(state)

    def _is_owned(self):
        return self._inner._is_owned()

    def __repr__(self):
        return f"TrackedRLock({self._lo_name!r})"


def tracked_condition(name: str) -> TrackedCondition:
    """Factory the four concurrency-core sites construct through; the
    static lint rule keys lock identity off these literal names."""
    return TrackedCondition(name)


def tracked_rlock(name: str) -> TrackedRLock:
    return TrackedRLock(name)
