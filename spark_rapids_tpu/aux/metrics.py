"""Operator metrics.

Reference: GpuExec.scala:36-120 — ``GpuMetric`` wrappers over SQLMetric
with levels ESSENTIAL/MODERATE/DEBUG selected by
``spark.rapids.sql.metrics.level``; standard names (opTime,
numOutputRows, numOutputBatches, ...).

Instrumentation wraps each exec's ``execute_partition`` with counters,
a wall-clock timer, the profiler's operator range (NVTX analog, gated on
the ranges-enabled flag so the disabled path stays zero-cost) and — when
a ``QueryExecution`` is active — a per-partition child span so layer
events attribute to the operator that triggered them.
``collect_metrics`` renders the tree's totals."""

from __future__ import annotations

import enum
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.aux import events as EV
from spark_rapids_tpu.aux import profiler as _prof
from spark_rapids_tpu.plan.base import Exec


class MetricLevel(enum.IntEnum):
    ESSENTIAL = 0
    MODERATE = 1
    DEBUG = 2

    @staticmethod
    def parse(s: str) -> "MetricLevel":
        try:
            return MetricLevel[str(s).strip().upper()]
        except KeyError:
            raise ValueError(
                f"invalid metrics level {s!r}; expected one of "
                f"{', '.join(MetricLevel.__members__)}") from None


# standard metric names (reference GpuExec.scala:49-120) with their levels
STANDARD_METRICS = {
    "numOutputRows": MetricLevel.ESSENTIAL,
    "numOutputBatches": MetricLevel.MODERATE,
    "opTime": MetricLevel.MODERATE,
    "streamTime": MetricLevel.DEBUG,
}


class OpMetric:
    __slots__ = ("name", "level", "value", "pending")

    def __init__(self, name: str, level: MetricLevel):
        self.name = name
        self.level = level
        self.value = 0
        #: DeferredCounts observed before they were forced; resolved
        #: (without a sync) once the query's download forces them
        self.pending = None

    def add(self, v) -> None:
        self.value += v

    def defer(self, count) -> None:
        if self.pending is None:
            self.pending = []
        self.pending.append(count)

    def resolve(self) -> None:
        """Folds deferred counts the query has since forced into the
        value; never syncs (unforced counts stay pending)."""
        if not self.pending:
            return
        still = []
        for c in self.pending:
            if c.is_forced:
                self.value += int(c)
            else:
                still.append(c)
        self.pending = still or None

    def __repr__(self):
        return f"{self.name}={self.value}"


def _ensure_metrics(node: Exec, level: MetricLevel) -> Dict[str, OpMetric]:
    ms = {}
    for name, lv in STANDARD_METRICS.items():
        if lv <= level:
            ms[name] = OpMetric(name, lv)
    node.metrics = ms
    return ms


_END = object()


def instrument_plan(plan: Exec, level: MetricLevel) -> Exec:
    """Wraps every node's execute_partition with metric recording (the
    GpuMetric counters around internalDoExecuteColumnar).

    Metrics are reset first: plan rewrites shallow-copy nodes but SHARE
    the metrics dicts, so without the reset repeated actions on the same
    DataFrame accumulate across queries (the re-run staleness bug) and
    ``collect_metrics`` / ``explain(analyze=True)`` stop being per-query.
    """
    reset_metrics(plan)
    for node in plan.collect_nodes():
        if getattr(node, "_instrumented", False):
            continue
        ms = _ensure_metrics(node, level)
        if not ms:
            continue
        inner = node.execute_partition

        def wrapped(pidx, _inner=inner, _ms=ms, _name=node.name):
            rows = _ms.get("numOutputRows")
            batches = _ms.get("numOutputBatches")
            optime = _ms.get("opTime")
            q = EV.active_query()
            pspan = q.start_partition(id(_ms), pidx) if q is not None \
                else None
            it = _inner(pidx)
            try:
                while True:
                    t0 = time.perf_counter()
                    if pspan is not None:
                        EV.push_span(pspan.span_id)
                    try:
                        # NVTX-range analog around the pull that does this
                        # operator's work; ranges_enabled() keeps the
                        # disabled path to one module-global read
                        if _prof.ranges_enabled():
                            with _prof.op_range(_name):
                                b = next(it, _END)
                        else:
                            b = next(it, _END)
                    finally:
                        if pspan is not None:
                            EV.pop_span()
                    if b is _END:
                        break
                    dt = time.perf_counter() - t0
                    if rows is not None:
                        # deferred device counts must not sync here; track
                        # them and fold in lazily once the query's own
                        # download forces them (resolve())
                        rc = b.row_count
                        from spark_rapids_tpu.columnar.column import \
                            DeferredCount
                        if not isinstance(rc, DeferredCount) or rc.is_forced:
                            n = int(rc)
                            rows.add(n)
                            if pspan is not None:
                                pspan.rows += n
                        else:
                            rows.defer(rc)
                    if batches is not None:
                        batches.add(1)
                    if optime is not None:
                        optime.add(dt)
                    if pspan is not None:
                        pspan.batches += 1
                    yield b
            finally:
                if q is not None and pspan is not None:
                    q.end_partition(pspan)

        node.execute_partition = wrapped
        node._instrumented = True
    return plan


def reset_metrics(plan: Exec) -> None:
    """Zeroes every node's OpMetric counters so the next action reports
    per-query values (called at query start by ``instrument_plan``)."""
    for node in plan.collect_nodes():
        for m in (getattr(node, "metrics", None) or {}).values():
            m.value = 0
            m.pending = None


def collect_metrics(plan: Exec) -> List[Dict]:
    """Per-node metric snapshot (driver-side report; the reference surfaces
    these in the Spark UI via SQLMetrics)."""
    out = []
    for node in plan.collect_nodes():
        ms = getattr(node, "metrics", None) or {}
        if ms:
            for m in ms.values():
                m.resolve()
            out.append({"node": node.node_desc(),
                        **{m.name: round(m.value, 6) if
                           isinstance(m.value, float) else m.value
                           for m in ms.values()}})
    return out
