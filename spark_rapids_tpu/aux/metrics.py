"""Operator metrics.

Reference: GpuExec.scala:36-120 — ``GpuMetric`` wrappers over SQLMetric
with levels ESSENTIAL/MODERATE/DEBUG selected by
``spark.rapids.sql.metrics.level``; standard names (opTime,
numOutputRows, numOutputBatches, ...).

Instrumentation wraps each exec's ``execute_partition`` with counters and
a wall-clock timer; ``collect_metrics`` renders the tree's totals."""

from __future__ import annotations

import enum
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.plan.base import Exec


class MetricLevel(enum.IntEnum):
    ESSENTIAL = 0
    MODERATE = 1
    DEBUG = 2

    @staticmethod
    def parse(s: str) -> "MetricLevel":
        try:
            return MetricLevel[str(s).upper()]
        except KeyError:
            return MetricLevel.MODERATE


# standard metric names (reference GpuExec.scala:49-120) with their levels
STANDARD_METRICS = {
    "numOutputRows": MetricLevel.ESSENTIAL,
    "numOutputBatches": MetricLevel.MODERATE,
    "opTime": MetricLevel.MODERATE,
    "streamTime": MetricLevel.DEBUG,
}


class OpMetric:
    __slots__ = ("name", "level", "value")

    def __init__(self, name: str, level: MetricLevel):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v) -> None:
        self.value += v

    def __repr__(self):
        return f"{self.name}={self.value}"


def _ensure_metrics(node: Exec, level: MetricLevel) -> Dict[str, OpMetric]:
    ms = {}
    for name, lv in STANDARD_METRICS.items():
        if lv <= level:
            ms[name] = OpMetric(name, lv)
    node.metrics = ms
    return ms


def instrument_plan(plan: Exec, level: MetricLevel) -> Exec:
    """Wraps every node's execute_partition with metric recording (the
    GpuMetric counters around internalDoExecuteColumnar)."""

    for node in plan.collect_nodes():
        if getattr(node, "_instrumented", False):
            continue
        ms = _ensure_metrics(node, level)
        if not ms:
            continue
        inner = node.execute_partition

        def wrapped(pidx, _inner=inner, _ms=ms):
            t0 = time.perf_counter()
            rows = _ms.get("numOutputRows")
            batches = _ms.get("numOutputBatches")
            optime = _ms.get("opTime")
            for b in _inner(pidx):
                if rows is not None:
                    # deferred device counts must not sync here; count rows
                    # lazily only when already forced, else count batches
                    rc = b.row_count
                    from spark_rapids_tpu.columnar.column import DeferredCount
                    if not isinstance(rc, DeferredCount) or rc.is_forced:
                        rows.add(int(rc))
                if batches is not None:
                    batches.add(1)
                if optime is not None:
                    optime.add(time.perf_counter() - t0)
                yield b
                t0 = time.perf_counter()

        node.execute_partition = wrapped
        node._instrumented = True
    return plan


def collect_metrics(plan: Exec) -> List[Dict]:
    """Per-node metric snapshot (driver-side report; the reference surfaces
    these in the Spark UI via SQLMetrics)."""
    out = []
    for node in plan.collect_nodes():
        ms = getattr(node, "metrics", None) or {}
        if ms:
            out.append({"node": node.node_desc(),
                        **{m.name: round(m.value, 6) if
                           isinstance(m.value, float) else m.value
                           for m in ms.values()}})
    return out
