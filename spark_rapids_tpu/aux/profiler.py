"""Profiler + trace ranges.

Reference: (a) NVTX ranges around operators/metrics (NvtxWithMetrics.scala,
conf ``spark.rapids.sql.nvtx.enabled``) — here jax profiler
TraceAnnotations, visible in xprof/tensorboard traces; (b) the built-in
CUPTI profiler (profiler.scala:37,315 ProfilerOnExecutor/Driver) writing
trace files to a path, scoped by job/time ranges — here
``jax.profiler.start_trace`` (xprof) driven by the same conf shape."""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional


_ENABLED = False


def set_ranges_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def ranges_enabled() -> bool:
    """Hot-path gate for callers that wrap work in op_range (the exec
    instrumentation): one module-global read when disabled."""
    return _ENABLED


@contextlib.contextmanager
def op_range(name: str):
    """NVTX-range analog: annotates the jax trace when profiling and always
    records wall time into the thread's range stats."""
    if not _ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        import jax.profiler
        with jax.profiler.TraceAnnotation(name):
            yield
    except ImportError:            # pragma: no cover
        yield
    finally:
        _range_stats_add(name, time.perf_counter() - t0)


_STATS_LOCK = threading.Lock()
_RANGE_STATS: Dict[str, list] = {}


def _range_stats_add(name: str, secs: float) -> None:
    with _STATS_LOCK:
        s = _RANGE_STATS.setdefault(name, [0, 0.0])
        s[0] += 1
        s[1] += secs


def range_stats() -> Dict[str, dict]:
    with _STATS_LOCK:
        return {k: {"count": v[0], "total_s": round(v[1], 6)}
                for k, v in _RANGE_STATS.items()}


def reset_range_stats() -> None:
    with _STATS_LOCK:
        _RANGE_STATS.clear()


class Profiler:
    """Executor-side profiler driver (reference: ProfilerOnExecutor) —
    starts/stops an xprof trace into ``path``; ``profile(df_action)`` is
    the scoped form the reference drives via job/stage ranges."""

    def __init__(self, path: str):
        self.path = path
        self._active = False

    def start(self) -> None:
        if self._active:
            return
        os.makedirs(self.path, exist_ok=True)
        import jax.profiler
        jax.profiler.start_trace(self.path)
        set_ranges_enabled(True)
        self._active = True

    def stop(self) -> None:
        if not self._active:
            return
        import jax.profiler
        jax.profiler.stop_trace()
        set_ranges_enabled(False)
        self._active = False

    @contextlib.contextmanager
    def scoped(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()
