"""Live resource sampler: a continuous signal between query events.

Reference: the spark-rapids ``ProfilerOnExecutor``/``ProfilerOnDriver``
pair runs an always-on, low-overhead collector beside the query engine so
offline tools see resource state BETWEEN the discrete events the layers
emit.  Here a single daemon thread wakes every
``spark.rapids.sample.intervalMs`` and emits one ``resourceSample`` event
through the process event bus (``aux.events.emit``) carrying read-only
snapshots of:

- the buffer catalog (pool used / limit / high-watermark, spillable
  bytes, host/disk spill tiers, live buffer count),
- the device admission semaphore (permits, holders, queued waiters),
- the prefetch spools (live spool count, queued batches/bytes),
- the task registry (active = started − finished tasks).

Samples are emitted OUTSIDE any query context, so they route to global
sinks; when the session has ``spark.rapids.sql.eventLog.path`` set the
sampler registers its own ``JsonlEventLogSink`` on the same path (appends
are line-atomic, so query events and samples interleave cleanly) and the
offline reader (``spark_rapids_tpu.tools``) aligns samples to queries by
timestamp.  Sampling never touches query data or results — every hook is
a counter read under an existing lock.

Lifecycle: ``TpuSession`` calls ``sync_from_conf`` at construction and on
``set_conf`` of any ``spark.rapids.sample.*`` / eventLog key; the sampler
is a process-wide singleton (one thread regardless of session count) and
stops at ``session.stop()``.
"""

from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu.aux import events as EV


def collect_sample() -> dict:
    """One read-only resource snapshot (the resourceSample payload).
    Cheap by construction: a handful of counter reads; no syncs, no
    device traffic, no allocation beyond the payload dict."""
    payload = {}
    from spark_rapids_tpu.memory.device_manager import get_runtime
    rt = get_runtime()
    if rt is not None:
        st = rt.catalog.stats()
        payload.update(
            pool_used_bytes=st["device_bytes"],
            pool_limit_bytes=st["device_limit"],
            pool_peak_bytes=st["device_peak_bytes"],
            spillable_bytes=st["spillable_bytes"],
            host_spill_bytes=st["host_bytes"],
            disk_spill_bytes=st["disk_bytes"],
            buffers=st["buffers"],
        )
        sem = rt.semaphore.stats()
        payload.update(
            semaphore_permits=sem["max_concurrent"],
            semaphore_holders=sem["holders"],
            semaphore_waiting=sem["waiting"],
        )
        payload["active_tasks"] = rt.metrics.active_count()
    from spark_rapids_tpu.exec.pipeline import live_spool_stats
    ls = live_spool_stats()
    payload.update(
        prefetch_spools=ls["spools"],
        prefetch_queued_batches=ls["queued_batches"],
        prefetch_queued_bytes=ls["queued_bytes"],
    )
    return payload


class ResourceSampler:
    """The background sampling thread + its (optional) event-log sink."""

    def __init__(self, interval_ms: int, log_path: Optional[str] = None,
                 max_bytes: int = 0, compress: bool = False):
        self.interval_ms = int(interval_ms)
        self.log_path = log_path or None
        self.max_bytes = int(max_bytes or 0)
        self.compress = bool(compress)
        self._sink: Optional[EV.JsonlEventLogSink] = None
        if self.log_path:
            # small batches: a sampler ticking every 100ms must not sit on
            # 6s of samples before they reach disk
            self._sink = EV.JsonlEventLogSink(
                self.log_path, max_bytes=max_bytes, compress=compress,
                flush_every=8)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        if self._sink is not None:
            EV.add_global_sink(self._sink)
        t = threading.Thread(target=self._run, name="tpu-resource-sampler",
                             daemon=True)
        self._thread = t
        t.start()

    def sample_once(self) -> dict:
        """Takes and emits one sample immediately (tests / manual use)."""
        payload = collect_sample()
        payload["interval_ms"] = self.interval_ms
        EV.emit("resourceSample", **payload)
        self.samples += 1
        return payload

    def _run(self) -> None:
        interval_s = max(0.001, self.interval_ms / 1000.0)
        while not self._stop.wait(interval_s):
            try:
                self.sample_once()
            except Exception:   # noqa: BLE001 - a failed sample is skipped,
                pass            # never fatal to the engine

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
        if self._sink is not None:
            EV.remove_global_sink(self._sink)
            self._sink.close()


_LOCK = threading.Lock()
_SAMPLER: Optional[ResourceSampler] = None


def active_sampler() -> Optional[ResourceSampler]:
    with _LOCK:
        return _SAMPLER


def stop_sampler() -> None:
    global _SAMPLER
    with _LOCK:
        cur, _SAMPLER = _SAMPLER, None
    if cur is not None:
        cur.stop()


def sync_from_conf(conf) -> Optional[ResourceSampler]:
    """Reconciles the singleton with ``spark.rapids.sample.*``: enabling
    starts it, disabling stops it, a changed interval / log path restarts
    it.  Idempotent — safe to call on every session init / set_conf."""
    global _SAMPLER
    from spark_rapids_tpu import config as C
    enabled = conf.get(C.SAMPLE_ENABLED.key, False)
    interval = conf.get(C.SAMPLE_INTERVAL_MS.key, 100)
    path = conf.get(C.EVENT_LOG_PATH.key, "") or None
    max_bytes = int(conf.get(C.EVENT_LOG_MAX_BYTES.key, 0) or 0)
    compress = bool(conf.get(C.EVENT_LOG_COMPRESS.key, False))
    stale = None
    with _LOCK:
        cur = _SAMPLER
        if not enabled:
            _SAMPLER, stale = None, cur
        elif cur is not None and cur.running and \
                cur.interval_ms == interval and cur.log_path == path and \
                cur.max_bytes == max_bytes and cur.compress == compress:
            # every knob the sink was built from matches — keep it; a
            # changed compress/maxBytes must rebuild the sink or it would
            # keep writing the OLD format to the shared path
            return cur
        else:
            stale = cur
            _SAMPLER = ResourceSampler(interval, path,
                                       max_bytes=max_bytes,
                                       compress=compress)
            _SAMPLER.start()
        out = _SAMPLER
    if stale is not None:
        stale.stop()
    return out
