"""Query-scoped observability: the span tree + metric/event funnel.

Reference: the plugin's per-exec ``GpuMetric`` map + ``GpuTaskMetrics``
accumulators + the Spark SQL UI's per-query execution graph.  A
``QueryExecution`` plays the SQLExecution role: it assigns a query id,
mirrors the physical plan as a span tree (one span per exec node, child
spans for partitions = tasks), and funnels every existing signal into
one place —

- ``OpMetric`` counters from ``instrument_plan`` (rows/batches/opTime),
- ``TaskMetrics`` deltas from the runtime's ``MetricsRegistry``
  (spill bytes, retry/split-retry/OOM counts, semaphore wait),
- events emitted by the memory / shuffle layers (``aux.events.emit``),
  attributed to the operator span whose pull triggered them.

``DataFrame.explain(analyze=True)`` and bench attribution render from
here; the JSONL event log (``spark.rapids.sql.eventLog.path``) receives
queryStart / spanMetrics / queryEnd plus every layer event.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.aux import events as EV

_query_ids = itertools.count(1)
_span_ids = itertools.count(1)

_LAST_LOCK = threading.Lock()
_LAST_SUMMARY: Optional[dict] = None

#: process-wide registry of in-flight QueryExecutions (registered on
#: __enter__, removed at finish) + a bounded tail of finished summaries.
#: The console's /queries endpoint reads both; the registry is a plain
#: dict under its own leaf lock so a scrape never touches engine locks.
_LIVE_LOCK = threading.Lock()
_LIVE: Dict[int, "QueryExecution"] = {}
_RECENT: collections.deque = collections.deque(maxlen=32)


def live_queries() -> List["QueryExecution"]:
    """The QueryExecutions currently in flight in this process."""
    with _LIVE_LOCK:
        return list(_LIVE.values())


def recent_summaries() -> List[dict]:
    """Bounded tail (newest last) of finished-query summary dicts."""
    with _LIVE_LOCK:
        return list(_RECENT)


def last_query_summary() -> Optional[dict]:
    """Summary dict of the most recently finished query in this process
    (bench.py embeds this so BENCH_*.json is attributable)."""
    with _LAST_LOCK:
        return _LAST_SUMMARY


def _nondefault_conf(conf) -> dict:
    """Registered conf values that differ from their defaults, JSON-safe.
    Rides the queryStart event so the offline AutoTuner recommends FROM
    the session's actual settings (an absent key = registry default)."""
    from spark_rapids_tpu import config as C
    out = {}
    for key, entry in C.registry().items():
        try:
            v = conf.get(key)
        except Exception:   # noqa: BLE001 - snapshot must never fail a query
            continue
        if v != entry.default:
            out[key] = v if isinstance(v, (bool, int, float)) else str(v)
    return out


class Span:
    """One node of the query's span tree.  ``kind`` is ``query`` (root),
    ``exec`` (one physical plan node) or ``partition`` (one task of an
    exec node)."""

    __slots__ = ("span_id", "parent_id", "name", "desc", "kind", "device",
                 "children", "start", "end", "metrics", "rows", "batches",
                 "pidx")

    def __init__(self, name: str, parent_id: Optional[int] = None,
                 desc: str = "", kind: str = "exec", device: bool = False,
                 pidx: Optional[int] = None):
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.name = name
        self.desc = desc or name
        self.kind = kind
        self.device = device
        self.children: List[Span] = []
        self.start = time.monotonic()
        self.end: Optional[float] = None
        self.metrics: Dict = {}
        self.rows = 0
        self.batches = 0
        self.pidx = pidx

    @property
    def duration_s(self) -> float:
        return (self.end if self.end is not None else time.monotonic()) \
            - self.start


#: event kinds folded into per-node attribution at finish
_ATTR_ZERO = {"spill_count": 0, "spill_bytes": 0, "retry_count": 0,
              "split_retry_count": 0, "oom_count": 0,
              "blocked_count": 0, "blocked_wait_s": 0.0,
              "deadlock_breaks": 0}


class QueryExecution:
    """Context manager scoping one query (one DataFrame action).

    Entering activates this query for the context (and, through the
    task pool's contextvar copies, for every task thread of the query);
    ``attach_plan`` builds the exec-span tree from the physical plan the
    overrides produced; exiting harvests metrics, emits
    spanMetrics/queryEnd, and publishes the summary."""

    def __init__(self, description: str = "",
                 sinks: Optional[List[EV.EventSink]] = None,
                 ring_size: int = 2048):
        self.query_id = next(_query_ids)
        self.description = description
        self.root = Span("query", kind="query", desc=description or "query")
        self.ring = EV.RingBufferSink(ring_size)
        self._sinks = list(sinks or [])
        self._lock = threading.Lock()
        #: id(node.metrics) -> exec span.  The metrics dict is the stable
        #: identity: plan rewrites shallow-copy nodes but SHARE the
        #: metrics dict, so the instrumentation wrapper (bound to the
        #: dict) and the attached plan's copies resolve to the same span.
        self._node_spans: Dict[int, Span] = {}
        self._span_index: Dict[int, Span] = {self.root.span_id: self.root}
        self._plan = None
        self._token = None
        self._start_snapshot = None
        self._transitions_snapshot = None
        self.summary_dict: Optional[dict] = None
        self.finished = False
        #: cached predict_plan_costs rows for the attached plan (fixed
        #: weights keep the live progress fraction monotone) + a
        #: high-water mark so reported progress never regresses across
        #: console scrapes even when a new partition wave opens
        self._live_cost: Optional[List[Dict]] = None
        self._live_cost_key: Optional[int] = None
        self._progress_hwm = 0.0
        #: non-default conf values captured at from_conf (v2 event-log
        #: schema: rides the queryStart payload so the offline AutoTuner
        #: knows what it is tuning FROM)
        self.conf_snapshot: Dict = {}

    @staticmethod
    def from_conf(conf=None, description: str = "") -> "QueryExecution":
        from spark_rapids_tpu import config as C
        sinks: List[EV.EventSink] = []
        ring = 2048
        if conf is not None:
            path = conf.get(C.EVENT_LOG_PATH.key, "")
            if path:
                sinks.append(EV.JsonlEventLogSink(
                    path,
                    max_bytes=conf.get(C.EVENT_LOG_MAX_BYTES.key, 0),
                    compress=conf.get(C.EVENT_LOG_COMPRESS.key, False)))
            ring = conf.get(C.EVENT_LOG_RING_SIZE.key, 2048)
        qe = QueryExecution(description, sinks, ring)
        if conf is not None:
            qe.conf_snapshot = _nondefault_conf(conf)
        return qe

    # -- lifecycle -----------------------------------------------------------
    def __enter__(self) -> "QueryExecution":
        self._token = EV._activate(self)
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        self._start_snapshot = rt.metrics.snapshot() if rt is not None \
            else None
        from spark_rapids_tpu.aux import transitions as TR
        self._transitions_snapshot = TR.snapshot()
        start_payload = {"description": self.description}
        if self.conf_snapshot:
            start_payload["conf"] = dict(self.conf_snapshot)
        self.record_event("queryStart", start_payload)
        with _LIVE_LOCK:
            _LIVE[self.query_id] = self
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            self.finish(error=exc)
        finally:
            EV._deactivate(self._token)
        return False

    # -- span tree -----------------------------------------------------------
    def attach_plan(self, plan) -> None:
        """Mirrors the executed physical plan as exec spans.  Re-attaching
        (a speculation replay re-applies the overrides) rebuilds the tree
        for the plan that actually runs; already-recorded events keep
        their span ids and fall back to the root for attribution."""
        with self._lock:
            self._plan = plan
            self._node_spans.clear()
            self.root.children = []
            self._span_index = {self.root.span_id: self.root}

            def build(node, parent: Span) -> None:
                sp = Span(node.name, parent.span_id, desc=node.node_desc(),
                          device=getattr(node, "is_device", False))
                parent.children.append(sp)
                self._span_index[sp.span_id] = sp
                self._node_spans[id(getattr(node, "metrics", None))] = sp
                for c in node.children:
                    build(c, sp)

            build(plan, self.root)

    def start_partition(self, node_key: int, pidx: int) -> Span:
        """Child span for one partition (task) of an exec node; called by
        the instrumentation wrapper at generator start."""
        with self._lock:
            parent = self._node_spans.get(node_key, self.root)
            sp = Span(f"partition-{pidx}", parent.span_id,
                      kind="partition", pidx=pidx)
            parent.children.append(sp)
            self._span_index[sp.span_id] = sp
            return sp

    def end_partition(self, span: Span) -> None:
        span.end = time.monotonic()

    def events(self) -> List[EV.Event]:
        return self.ring.events()

    # -- live console view ---------------------------------------------------
    def span_names(self) -> Dict[int, str]:
        """span_id -> operator name for every span of this query (the
        console joins BufferCatalog attribution tags through this)."""
        with self._lock:
            return {sid: sp.name for sid, sp in self._span_index.items()}

    def _cost_predictions_locked(self) -> Optional[List[Dict]]:
        """Pre-order per-node prediction rows for the attached plan,
        cached per plan identity (attach_plan builds exec spans in the
        same pre-order, so row i describes exec span i).  With a
        configured machine profile the rows carry ``predicted_s`` from
        the calibrated fit (the cost model's first live consumer);
        without one they still carry ``estimate_rows`` so per-node
        progress fractions work profile-free.  Caller holds _lock."""
        plan = self._plan
        if plan is None:
            return None
        if self._live_cost_key == id(plan):
            return self._live_cost
        rows: Optional[List[Dict]] = None
        try:
            from spark_rapids_tpu import config as C
            from spark_rapids_tpu.plan import cost as PC
            path = self.conf_snapshot.get(
                C.HISTORY_MACHINE_PROFILE_PATH.key)
            enabled = self.conf_snapshot.get(
                C.HISTORY_COST_MODEL_ENABLED.key,
                C.HISTORY_COST_MODEL_ENABLED.default)
            profile = (PC.load_machine_profile(str(path))
                       if path and enabled else None)
            if profile is not None:
                rows = PC.predict_plan_costs(plan, profile, live=True)
            else:
                rows = []

                def walk(node) -> None:
                    rows.append({"node": type(node).__name__,
                                 "rows": PC.estimate_rows(node),
                                 "predicted_s": None})
                    for c in node.children:
                        walk(c)

                walk(plan)
        except Exception:   # noqa: BLE001 - console view, never fails a query
            rows = None
        self._live_cost = rows
        self._live_cost_key = id(plan)
        return rows

    def live_snapshot(self) -> dict:
        """Point-in-time JSON view of this query for the console
        /queries endpoint: the exec-span tree with rows/batches so far
        (summed from the live partition child spans — OpMetric values
        only harvest into exec spans at finish), plus a progress
        fraction and an ETA joined against the machine-profile cost
        predictions.  Reads only this query's own lock."""
        now = time.monotonic()
        with self._lock:
            execs = self._exec_spans()
            preds = self._cost_predictions_locked()
            if preds is not None and len(preds) != len(execs):
                preds = None    # replay attached a different-shape plan
            finished = self.finished
            summary = self.summary_dict
            nodes = []
            weighted_total = 0.0
            weighted_done = 0.0
            profiled = False
            for i, sp in enumerate(execs):
                parts = [c for c in sp.children if c.kind == "partition"]
                live_rows = sum(c.rows for c in parts)
                live_batches = sum(c.batches for c in parts)
                if finished and sp.metrics:
                    live_rows = int(sp.metrics.get("numOutputRows",
                                                   live_rows) or 0)
                    live_batches = int(sp.metrics.get("numOutputBatches",
                                                      live_batches) or 0)
                pred = preds[i] if preds is not None else None
                pred_rows = int(pred["rows"]) if pred else None
                pred_s = pred.get("predicted_s") if pred else None
                if pred_s is not None:
                    profiled = True
                done = len(parts) > 0 and all(c.end is not None
                                              for c in parts)
                if finished or done:
                    frac = 1.0
                elif pred_rows:
                    frac = min(1.0, live_rows / max(1, pred_rows))
                else:
                    frac = 0.0
                weight = max(float(pred_s), 1e-9) \
                    if pred_s is not None else 1.0
                weighted_total += weight
                weighted_done += weight * frac
                nodes.append({
                    "span_id": sp.span_id, "parent_id": sp.parent_id,
                    "node": sp.name, "desc": sp.desc[:120],
                    "device": sp.device,
                    "rows": live_rows, "batches": live_batches,
                    "partitions": len(parts),
                    "partitions_done": sum(1 for c in parts
                                           if c.end is not None),
                    "predicted_rows": pred_rows,
                    "predicted_s": pred_s,
                    "frac": round(frac, 6),
                })
            if finished:
                progress = 1.0
            elif weighted_total > 0:
                progress = weighted_done / weighted_total
            else:
                progress = 0.0
            # high-water mark: a fresh partition wave lowers a node's
            # raw fraction; the reported number must stay monotone
            progress = max(progress, self._progress_hwm)
            self._progress_hwm = progress
            elapsed = ((self.root.end if self.root.end is not None
                        else now) - self.root.start)
            eta_s: Optional[float] = None
            eta_source: Optional[str] = None
            if finished:
                eta_s, eta_source = 0.0, "finished"
            elif profiled and weighted_done > 0:
                # calibrate the profile's absolute scale to this run:
                # remaining predicted seconds x (elapsed / completed
                # predicted seconds)
                eta_s = ((weighted_total - weighted_done)
                         * (elapsed / weighted_done))
                eta_source = "machine_profile"
            elif progress > 0:
                eta_s = elapsed * (1.0 - progress) / progress
                eta_source = "elapsed_extrapolation"
            snap = {
                "query_id": self.query_id,
                "description": self.description,
                "status": "finished" if finished else "running",
                "elapsed_s": round(elapsed, 6),
                "progress": round(progress, 6),
                "eta_s": (None if eta_s is None else round(eta_s, 6)),
                "eta_source": eta_source,
                "nodes": nodes,
            }
            if finished and summary is not None:
                snap["status"] = summary.get("status", "finished")
                snap["duration_s"] = summary.get("duration_s")
            return snap

    # -- event funnel --------------------------------------------------------
    def record_event(self, kind: str, payload: dict,
                     span_id: Optional[int] = None) -> None:
        with self._lock:
            sid = span_id if span_id is not None else EV.current_span_id()
            if sid is None or sid not in self._span_index:
                sid = self.root.span_id
            # ts assigned AND delivered under the lock: sink (file) order
            # is timestamp order, which the event-log schema test pins
            ev = EV.Event(kind, self.query_id, sid, time.monotonic(),
                          dict(payload))
            self.ring.emit(ev)
            for s in self._sinks:
                s.emit(ev)
            tap = EV.console_tap()
            if tap is not None:
                tap.emit(ev)

    def _attribute_events(self) -> Dict[int, dict]:
        """Folds layer events onto their exec span (partition spans roll
        up to their parent node) for per-node spill/retry columns."""
        per: Dict[int, dict] = {}
        for ev in self.ring.events():
            # span ids orphaned by a replay's attach_plan rebuild fall
            # back to the root so pressure events still count
            sp = self._span_index.get(ev.span_id) or self.root
            if sp.kind == "partition":
                sp = self._span_index.get(sp.parent_id, self.root)
            if sp.kind == "query" and ev.kind not in ("spill", "retryOOM",
                                                      "splitRetry", "oom",
                                                      "threadBlocked",
                                                      "deadlockBreak"):
                continue
            d = per.setdefault(sp.span_id, dict(_ATTR_ZERO))
            if ev.kind == "spill":
                d["spill_count"] += 1
                d["spill_bytes"] += int(ev.payload.get("bytes", 0))
            elif ev.kind == "retryOOM":
                d["retry_count"] += 1
            elif ev.kind == "splitRetry":
                d["split_retry_count"] += 1
            elif ev.kind == "oom":
                d["oom_count"] += 1
            elif ev.kind == "threadBlocked":
                d["blocked_count"] += 1
                d["blocked_wait_s"] = round(
                    d["blocked_wait_s"]
                    + float(ev.payload.get("wait_s", 0.0) or 0.0), 6)
            elif ev.kind == "deadlockBreak":
                d["deadlock_breaks"] += 1
        return per

    # -- finish / summary ----------------------------------------------------
    def finish(self, error=None) -> dict:
        if self.finished:
            return self.summary_dict
        self.finished = True
        now = time.monotonic()
        # harvest final OpMetric values into the exec spans
        plan = self._plan
        if plan is not None:
            with self._lock:
                node_spans = dict(self._node_spans)
            for node in plan.collect_nodes():
                ms = getattr(node, "metrics", None) or {}
                sp = node_spans.get(id(ms))
                if sp is None:
                    continue
                sp.end = now
                for m in ms.values():
                    m.resolve()
                sp.metrics = {m.name: (round(m.value, 6)
                                       if isinstance(m.value, float)
                                       else m.value)
                              for m in ms.values()}
        attr = self._attribute_events()
        # per-query TaskMetrics delta from the process registry
        delta = {}
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        if rt is not None and self._start_snapshot is not None:
            total, finished = rt.metrics.snapshot()
            t0, f0 = self._start_snapshot
            delta = {
                "tasks": finished - f0,
                "retry_count": total.retry_count - t0.retry_count,
                "split_retry_count":
                    total.split_retry_count - t0.split_retry_count,
                "oom_count": total.oom_count - t0.oom_count,
                "spill_count": total.spill_count - t0.spill_count,
                "spill_bytes": total.spill_bytes - t0.spill_bytes,
                "semaphore_wait_s": round(
                    total.semaphore_wait_seconds
                    - t0.semaphore_wait_seconds, 6),
                # cooperative-arbitration parks (memory/arbiter.py)
                "alloc_wait_s": round(
                    total.alloc_wait_seconds - t0.alloc_wait_seconds, 6),
                # max cannot be snapshot-subtracted like the counters;
                # take THIS query's peak from its tasks' taskEnd events
                "max_device_bytes": max(
                    (int(ev.payload.get("max_device_bytes", 0))
                     for ev in self.ring.events()
                     if ev.kind == "taskEnd"), default=0),
            }
        # recovery ledger: what resilience cost THIS query (chaos/fault
        # recovery transitions emitted by the shuffle/task layers; the
        # kind->key vocabulary lives in aux/faults.py)
        from spark_rapids_tpu.aux.faults import RECOVERY_KINDS
        recovery: Dict[str, int] = {}
        for ev in self.ring.events():
            key = RECOVERY_KINDS.get(ev.kind)
            if key is not None:
                recovery[key] = recovery.get(key, 0) + 1
        self.root.end = now
        # span depth map: the offline reader (tools/reader.py) rebuilds
        # the tree from parent_id/depth — the in-memory children links
        # don't survive the JSONL round trip
        depths: Dict[int, int] = {}

        def _depth_walk(sp: Span, d: int) -> None:
            depths[sp.span_id] = d
            for c in sp.children:
                _depth_walk(c, d + 1)

        _depth_walk(self.root, 0)
        nodes = []
        for sp in self._exec_spans():
            row = {"span_id": sp.span_id, "parent_id": sp.parent_id,
                   "depth": depths.get(sp.span_id, 1), "node": sp.name,
                   "desc": sp.desc[:120],
                   "start_s": round(sp.start, 6),
                   "end_s": round(sp.end if sp.end is not None else now, 6),
                   **sp.metrics}
            parts = [{"pidx": c.pidx, "start_s": round(c.start, 6),
                      "end_s": round(c.end if c.end is not None else now, 6),
                      "rows": c.rows, "batches": c.batches}
                     for c in sp.children if c.kind == "partition"]
            if parts:
                row["partitions"] = parts
            extra = attr.get(sp.span_id)
            if extra:
                row.update({k: v for k, v in extra.items() if v})
            nodes.append(row)
            self.record_event("spanMetrics", row, span_id=sp.span_id)
        summary = {
            "query_id": self.query_id,
            "description": self.description,
            "status": "error" if error is not None else "ok",
            "duration_s": round(self.root.duration_s, 6),
            "events": len(self.ring) + self.ring.dropped,
            "events_dropped": self.ring.dropped,
            **delta,
            "nodes": nodes,
        }
        if recovery:
            summary["recovery"] = recovery
        # host-transition ledger: snapshot-delta of the gateway counters
        # (aux/transitions.py) — robust to ring drops, like TaskMetrics
        if self._transitions_snapshot is not None:
            from spark_rapids_tpu.aux import transitions as TR
            ledger = TR.snapshot().delta(self._transitions_snapshot)
            if TR.enabled():
                summary["transitions"] = ledger
        # calibrated cost-model cross-check (report-only; docs/history.md):
        # predicted wall time from the tools/history machine profile vs
        # this query's measured duration, emitted before sinks close so
        # the residual lands in the event log for `tools audit`
        cost = self._cost_crosscheck(plan, summary["duration_s"])
        if cost is not None:
            summary["cost"] = cost
            self.record_event("costModel", cost)
        self.summary_dict = summary
        self.record_event("queryEnd",
                          {k: v for k, v in summary.items()
                           if k != "nodes"})
        for s in self._sinks:
            s.close()
        global _LAST_SUMMARY
        with _LAST_LOCK:
            _LAST_SUMMARY = summary
        with _LIVE_LOCK:
            _LIVE.pop(self.query_id, None)
            _RECENT.append(summary)
        return summary

    def _cost_crosscheck(self, plan, measured_s: float):
        """Predicted-vs-measured residual against the configured machine
        profile, or None when no profile is set/loadable.  Defaults are
        absent from ``conf_snapshot`` (non-default-only), so a missing
        path key simply means the cost model is off."""
        if plan is None:
            return None
        from spark_rapids_tpu import config as C
        path = self.conf_snapshot.get(C.HISTORY_MACHINE_PROFILE_PATH.key)
        enabled = self.conf_snapshot.get(
            C.HISTORY_COST_MODEL_ENABLED.key,
            C.HISTORY_COST_MODEL_ENABLED.default)
        if not path or not enabled:
            return None
        try:
            from spark_rapids_tpu.plan.cost import (load_machine_profile,
                                                    predict_plan_costs)
            profile = load_machine_profile(str(path))
            if profile is None:
                return None
            rows = predict_plan_costs(plan, profile)
            predicted = sum(r["predicted_s"] for r in rows
                            if r["predicted_s"] is not None)
            covered = sum(1 for r in rows
                          if r["predicted_s"] is not None)
            residual = ((measured_s - predicted) / measured_s
                        if measured_s > 0 else 0.0)
            return {"profile_version": profile.version,
                    "residual_bound": profile.residual_bound,
                    "predicted_s": round(predicted, 6),
                    "measured_s": round(measured_s, 6),
                    "residual": round(residual, 6),
                    "nodes": len(rows), "covered": covered}
        except Exception:   # noqa: BLE001 - report-only, never fails a query
            return None

    def _exec_spans(self) -> List[Span]:
        out: List[Span] = []

        def walk(sp: Span) -> None:
            if sp.kind == "exec":
                out.append(sp)
            for c in sp.children:
                walk(c)

        walk(self.root)
        return out

    # -- rendering -----------------------------------------------------------
    def render_tree(self, show_partitions: bool = False) -> str:
        """The EXPLAIN ANALYZE body: the plan tree annotated with
        rows/batches/opTime (and spill/retry where attributed), plus the
        query-level summary footer."""
        attr = self._attribute_events()
        lines = [f"== Analyzed Plan: query {self.query_id} "
                 f"{self.description!r} ({self.root.duration_s:.3f}s) =="]

        _SHORT = {"numOutputRows": "rows", "numOutputBatches": "batches",
                  "opTime": "opTime", "streamTime": "streamTime",
                  # pipelining boundaries (exec/pipeline.py): measured
                  # overlap per boundary — how long each side of the spool
                  # waited on the other, and the deepest the queue ran
                  "producerStallTime": "pStall",
                  "consumerStallTime": "cStall",
                  "peakQueueDepth": "qDepth"}

        def fmt(sp: Span) -> str:
            bits = []
            for key, short in _SHORT.items():
                if key in sp.metrics:
                    v = sp.metrics[key]
                    bits.append(f"{short}={v}{'s' if 'Time' in key else ''}")
            extra = attr.get(sp.span_id) or {}
            for k, v in extra.items():
                if v:
                    bits.append(f"{k}={v}")
            return f" [{' '.join(bits)}]" if bits else ""

        def walk(sp: Span, indent: int) -> None:
            if sp.kind == "partition":
                if not show_partitions:
                    return
                lines.append("  " * indent
                             + f"{sp.name} rows={sp.rows} "
                             f"batches={sp.batches} "
                             f"time={sp.duration_s:.4f}s")
                return
            mark = "*" if sp.device else " "
            lines.append("  " * indent + mark + sp.desc + fmt(sp))
            for c in sp.children:
                walk(c, indent + 1)

        for c in self.root.children:
            walk(c, 0)
        summary = self.summary_dict or {}
        lines.append("== Query Summary ==")
        lines.append(" ".join(
            f"{k}={summary[k]}" for k in
            ("tasks", "retry_count", "split_retry_count", "oom_count",
             "spill_count", "spill_bytes", "semaphore_wait_s",
             "alloc_wait_s", "max_device_bytes") if k in summary))
        rec = summary.get("recovery")
        if rec:
            lines.append("== Recovery ==")
            lines.append(" ".join(f"{k}={v}" for k, v in sorted(
                rec.items())))
        tr = summary.get("transitions")
        if tr:
            lines.append("== Transitions ==")
            lines.append(
                f"h2d={tr.get('h2d_count', 0)} "
                f"({tr.get('h2d_bytes', 0)}B {tr.get('h2d_s', 0.0)}s) "
                f"d2h={tr.get('d2h_count', 0)} "
                f"({tr.get('d2h_bytes', 0)}B {tr.get('d2h_s', 0.0)}s) "
                f"syncs={tr.get('sync_count', 0)} "
                f"({tr.get('sync_s', 0.0)}s)")
        return "\n".join(lines)


@contextlib.contextmanager
def query_scope(conf=None, description: str = ""):
    """Action-level wrapper: opens a QueryExecution unless one is already
    active (nested actions — cache materialization, explain(analyze) —
    join the outer query) or tracing is disabled by conf."""
    active = EV.active_query()
    if active is not None:
        yield active
        return
    if conf is not None:
        from spark_rapids_tpu import config as C
        if not conf.get(C.TRACING_ENABLED.key, True):
            yield None
            return
    qe = QueryExecution.from_conf(conf, description)
    with qe:
        yield qe
