"""Host-transition & device-sync ledger: the instrumented gateway.

ROADMAP item 2 (millisecond serving floor) claims the engine's latency
gap is per-batch host round trips, blocking device syncs and unnecessary
D2H at operator boundaries.  This module is the instrument that makes
that claim falsifiable: every H2D upload, D2H download and blocking
device sync in the package routes through here (the ``sync-site`` lint
rule pins the discipline for ``block_until_ready``/``jax.device_get``),
emitting schema-v4 ``hostTransition`` / ``deviceSync`` events and
aggregating into a process-lifetime ledger that ``QueryExecution``
snapshots per query.

Reference analog: the plugin wraps every transition operator
(GpuRowToColumnarExec / GpuColumnarToRowExec) in dedicated GPU metrics
and NVTX ranges; Theseus (PAPERS.md) makes data movement the first-class
optimization object.  Semantics:

- **hostTransition** (direction ``h2d``/``d2h``): one per packed batch
  transfer, carrying bytes, the column encoding kinds crossing the
  boundary, plane count and measured duration.  H2D duration is the
  ``device_put`` dispatch wall (the transfer itself may complete
  asynchronously); D2H duration is the true blocking fetch.
- **deviceSync**: one per blocking sync that is NOT a batch transfer —
  deferred-count forces, speculation overflow checks, AQE/exchange count
  fetches — carrying the site label and measured duration.  A D2H batch
  download is a sync too, but it is counted ONCE, as a transition;
  ``sync_count``/``sync_seconds`` cover only the non-transfer syncs.

Conf (``spark.rapids.sql.transitions.*``) syncs through
``sync_from_conf`` at session construction / ``set_conf`` — the same
process-singleton lifecycle as the sampler and lock-order validator.
Disabled, every wrapper degrades to the raw operation (the trimodal
bit-identity test pins that results never change either way).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

from spark_rapids_tpu.aux import events as EV

#: instrumentation master switch + per-boundary event emission switch
#: (module-internal; mutated ONLY by sync_from_conf)
_ENABLED = True
_EVENTS = True

_LOCK = threading.Lock()


@dataclasses.dataclass
class TransitionStats:
    """Process-lifetime ledger counters.  ``QueryExecution`` snapshots at
    query start and subtracts at finish — robust to ring-buffer drops,
    the same discipline as the TaskMetrics registry."""
    h2d_count: int = 0
    h2d_bytes: int = 0
    h2d_seconds: float = 0.0
    d2h_count: int = 0
    d2h_bytes: int = 0
    d2h_seconds: float = 0.0
    sync_count: int = 0
    sync_seconds: float = 0.0

    def delta(self, start: "TransitionStats") -> dict:
        """JSON-safe per-query ledger from a start-of-query snapshot."""
        return {
            "h2d_count": self.h2d_count - start.h2d_count,
            "h2d_bytes": self.h2d_bytes - start.h2d_bytes,
            "h2d_s": round(self.h2d_seconds - start.h2d_seconds, 6),
            "d2h_count": self.d2h_count - start.d2h_count,
            "d2h_bytes": self.d2h_bytes - start.d2h_bytes,
            "d2h_s": round(self.d2h_seconds - start.d2h_seconds, 6),
            "sync_count": self.sync_count - start.sync_count,
            "sync_s": round(self.sync_seconds - start.sync_seconds, 6),
        }


_TOTAL = TransitionStats()


def enabled() -> bool:
    return _ENABLED


def snapshot() -> TransitionStats:
    """Copy of the process-lifetime counters (for per-query deltas)."""
    with _LOCK:
        return dataclasses.replace(_TOTAL)


def totals() -> dict:
    """Process-lifetime ledger for render_prometheus()."""
    with _LOCK:
        return {
            "h2d_count": _TOTAL.h2d_count,
            "h2d_bytes": _TOTAL.h2d_bytes,
            "h2d_seconds": round(_TOTAL.h2d_seconds, 6),
            "d2h_count": _TOTAL.d2h_count,
            "d2h_bytes": _TOTAL.d2h_bytes,
            "d2h_seconds": round(_TOTAL.d2h_seconds, 6),
            "sync_count": _TOTAL.sync_count,
            "sync_seconds": round(_TOTAL.sync_seconds, 6),
        }


def sync_from_conf(conf) -> None:
    """Arms/disarms the ledger from ``spark.rapids.sql.transitions.*``
    (called at session construction and from set_conf, like the sampler
    and lock-order singletons).  Counters are never reset — they are
    process-lifetime; only the recording toggles change."""
    global _ENABLED, _EVENTS
    from spark_rapids_tpu import config as C
    _ENABLED = bool(conf.get(C.TRANSITIONS_ENABLED.key, True))
    _EVENTS = bool(conf.get(C.TRANSITIONS_EVENTS.key, True))


# ---------------------------------------------------------------------------
# transition recording (the packed transfer paths call these directly —
# they own the timed operation; columnar/transfer.py)
# ---------------------------------------------------------------------------

def record_h2d(nbytes: int, duration_s: float, kinds: str = "",
               planes: int = 0) -> None:
    """One packed host->device upload.  ``kinds`` is the comma-joined
    column encoding-kind set crossing the boundary
    (scalar/string/dec128/array/dict/rle)."""
    if not _ENABLED:
        return
    with _LOCK:
        _TOTAL.h2d_count += 1
        _TOTAL.h2d_bytes += int(nbytes)
        _TOTAL.h2d_seconds += duration_s
    if _EVENTS:
        EV.emit("hostTransition", direction="h2d", bytes=int(nbytes),
                duration_s=round(duration_s, 6), kinds=kinds,
                planes=int(planes))


def record_d2h(nbytes: int, duration_s: float, site: str = "download",
               planes: int = 0) -> None:
    """One packed device->host download (the blocking fetch itself —
    counted as a transition, NOT double-counted as a sync)."""
    if not _ENABLED:
        return
    with _LOCK:
        _TOTAL.d2h_count += 1
        _TOTAL.d2h_bytes += int(nbytes)
        _TOTAL.d2h_seconds += duration_s
    if _EVENTS:
        EV.emit("hostTransition", direction="d2h", bytes=int(nbytes),
                duration_s=round(duration_s, 6), site=site,
                planes=int(planes))


def _record_sync(site: str, duration_s: float,
                 nbytes: Optional[int] = None) -> None:
    with _LOCK:
        _TOTAL.sync_count += 1
        _TOTAL.sync_seconds += duration_s
    if _EVENTS:
        payload = {"site": site, "duration_s": round(duration_s, 6)}
        if nbytes is not None:
            payload["bytes"] = int(nbytes)
        EV.emit("deviceSync", **payload)


# ---------------------------------------------------------------------------
# blocking-sync wrappers (THE sanctioned sync call sites; the sync-site
# lint rule bans raw block_until_ready/jax.device_get elsewhere)
# ---------------------------------------------------------------------------

def fetch(arr, site: str) -> np.ndarray:
    """Blocking device->host fetch of one array (``np.asarray`` on a
    device array): timed and counted as a deviceSync.  Host inputs pass
    through at numpy cost — safe on either side of the boundary."""
    if not _ENABLED:
        return np.asarray(arr)
    t0 = time.perf_counter()
    out = np.asarray(arr)
    _record_sync(site, time.perf_counter() - t0, nbytes=out.nbytes)
    return out


def sync_int(x, site: str) -> int:
    """Blocking scalar sync (``int()`` of a 0-d device array — the
    deferred-count force shape)."""
    if not _ENABLED:
        return int(x)
    t0 = time.perf_counter()
    out = int(x)
    _record_sync(site, time.perf_counter() - t0)
    return out


def block_until_ready(x, site: str = "dispatch"):
    """Timed ``block_until_ready`` — the dispatch-boundary sync."""
    if not _ENABLED:
        return x.block_until_ready()
    t0 = time.perf_counter()
    out = x.block_until_ready()
    _record_sync(site, time.perf_counter() - t0)
    return out


def device_get(x, site: str = "device_get"):
    """Timed ``jax.device_get`` — the multi-array blocking fetch."""
    import jax
    if not _ENABLED:
        return jax.device_get(x)
    t0 = time.perf_counter()
    out = jax.device_get(x)
    _record_sync(site, time.perf_counter() - t0)
    return out
