"""Columnar data plane: host (Arrow-layout) and device (JAX array) columns.

Counterpart of the reference's GpuColumnVector.java / RapidsHostColumnVector /
ColumnarBatch interop layer (sql-plugin/src/main/java/com/nvidia/spark/rapids/
GpuColumnVector.java), rebuilt around TPU/XLA constraints:

- Device batches are padded to power-of-two row buckets so every XLA program
  is compiled once per (schema, bucket) rather than once per row count.
- Strings on device are rectangular uint8 [rows, max_len] + lengths, because
  TPU vector units want fixed-stride layouts (cuDF uses offsets+chars which
  suits GPU byte kernels; that layout remains the host/wire form here).
- Validity is a bool vector; padding rows are always invalid.
"""

from spark_rapids_tpu.columnar.column import (  # noqa: F401
    DeviceColumn, HostColumn, bucket_rows)
from spark_rapids_tpu.columnar.batch import (  # noqa: F401
    ColumnarBatch, HostColumnarBatch, batch_from_arrow, batch_to_arrow,
    batch_from_pydict)

__all__ = [
    "DeviceColumn", "HostColumn", "ColumnarBatch", "HostColumnarBatch",
    "batch_from_arrow", "batch_to_arrow", "batch_from_pydict", "bucket_rows",
]
