"""Columnar batches (device Table / host RecordBatch equivalents).

Reference counterparts: Spark's ``ColumnarBatch`` + cuDF ``Table`` interop in
GpuColumnVector.java (from(Table), from(ColumnarBatch)), and host-side
``RapidsHostColumnVector`` batches.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (DeviceColumn, HostColumn,
                                              bucket_rows)


@dataclasses.dataclass
class ColumnarBatch:
    """A device-resident batch: list of DeviceColumns + logical row count.

    All columns share the same bucket (padded leading dim), so a whole batch
    feeds a single jit'ed XLA program with static shapes.
    """

    columns: List[DeviceColumn]
    row_count: int
    names: Optional[List[str]] = None

    def __post_init__(self):
        from spark_rapids_tpu.columnar.column import DeferredCount
        deferred = isinstance(self.row_count, DeferredCount)
        for c in self.columns:
            if deferred or isinstance(c.row_count, DeferredCount):
                # identity check only — never force a device sync here
                if c.row_count is not self.row_count:
                    raise ValueError(
                        "deferred-count batch requires every column to "
                        "share the batch's count object")
            elif c.row_count != self.row_count:
                raise ValueError(
                    f"column rows {c.row_count} != batch rows {self.row_count}")
        if self.columns:
            b0 = self.columns[0].bucket
            for c in self.columns[1:]:
                if c.bucket != b0:
                    raise ValueError(
                        f"mixed buckets in batch: {c.bucket} != {b0} "
                        "(all columns must share one padded shape)")

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def schema(self) -> T.StructType:
        names = self.names or [f"c{i}" for i in range(len(self.columns))]
        return T.StructType([T.StructField(n, c.data_type)
                             for n, c in zip(names, self.columns)])

    @property
    def bucket(self) -> int:
        if not self.columns:
            return bucket_rows(self.row_count)
        return self.columns[0].bucket

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def sized_nbytes(self) -> int:
        """Unpadded logical size estimate (planner/coalesce sizing).

        A deferred row count is NOT forced here (spill registration sits on
        the hot path and a host sync per batch dominates tunnel latency);
        the padded size is returned instead — conservative, and truthful
        about what HBM actually holds."""
        if self.bucket == 0:
            return 0
        from spark_rapids_tpu.columnar.column import DeferredCount
        rc = self.row_count
        if isinstance(rc, DeferredCount) and not rc.is_forced:
            return self.nbytes()
        return int(self.nbytes() * (int(rc) / max(self.bucket, 1)))

    def to_host(self, spec_rows=None) -> "HostColumnarBatch":
        from spark_rapids_tpu.columnar.transfer import download_host_batch
        return download_host_batch(self, spec_rows=spec_rows)

    def select(self, indices: Sequence[int]) -> "ColumnarBatch":
        names = None if self.names is None else [self.names[i] for i in indices]
        return ColumnarBatch([self.columns[i] for i in indices],
                             self.row_count, names)

    def __repr__(self):
        return (f"ColumnarBatch(rows={self.row_count}, "
                f"cols=[{', '.join(str(c.data_type) for c in self.columns)}])")


@dataclasses.dataclass
class HostColumnarBatch:
    """Host-resident batch over Arrow arrays (wire/spill/CPU-exec form)."""

    columns: List[HostColumn]
    row_count: int
    names: Optional[List[str]] = None

    def __post_init__(self):
        for c in self.columns:
            if len(c) != self.row_count:
                raise ValueError(
                    f"ragged batch: column has {len(c)} rows, batch has "
                    f"{self.row_count}")

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def schema(self) -> T.StructType:
        names = self.names or [f"c{i}" for i in range(len(self.columns))]
        return T.StructType([T.StructField(n, c.data_type)
                             for n, c in zip(names, self.columns)])

    def to_device(self, bucket: Optional[int] = None) -> ColumnarBatch:
        from spark_rapids_tpu.columnar.transfer import upload_host_batch
        return upload_host_batch(self, bucket)

    def to_arrow(self):
        import pyarrow as pa
        names = self.names or [f"c{i}" for i in range(len(self.columns))]
        return pa.record_batch([c.arrow for c in self.columns], names=names)

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def slice(self, offset: int, length: int) -> "HostColumnarBatch":
        return HostColumnarBatch([c.slice(offset, length) for c in self.columns],
                                 length, self.names)

    def to_pydict(self):
        names = self.names or [f"c{i}" for i in range(len(self.columns))]
        return {n: c.to_pylist() for n, c in zip(names, self.columns)}

    def __repr__(self):
        return (f"HostColumnarBatch(rows={self.row_count}, "
                f"cols=[{', '.join(str(c.data_type) for c in self.columns)}])")


def batch_from_arrow(rb) -> HostColumnarBatch:
    """From a pyarrow RecordBatch or Table."""
    import pyarrow as pa
    if isinstance(rb, pa.Table):
        rb = rb.combine_chunks()
        cols = [HostColumn(rb.column(i)) for i in range(rb.num_columns)]
        return HostColumnarBatch(cols, rb.num_rows, list(rb.column_names))
    cols = [HostColumn(rb.column(i)) for i in range(rb.num_columns)]
    return HostColumnarBatch(cols, rb.num_rows, list(rb.schema.names))


def batch_to_arrow(batch) -> "object":
    if isinstance(batch, ColumnarBatch):
        batch = batch.to_host()
    return batch.to_arrow()


def batch_from_pydict(d, schema: Optional[T.StructType] = None) -> HostColumnarBatch:
    cols = []
    names = []
    n = None
    for name, values in d.items():
        dt = None
        if schema is not None:
            dt = schema.types[schema.field_index(name)]  # match by name
        if isinstance(values, np.ndarray):
            col = HostColumn.from_numpy(values, data_type=dt)
        else:
            col = HostColumn.from_pylist(list(values), dt)
        if n is None:
            n = len(col)
        cols.append(col)
        names.append(name)
    return HostColumnarBatch(cols, n or 0, names)


def concat_host_batches(batches: Iterable[HostColumnarBatch]) -> HostColumnarBatch:
    import pyarrow as pa
    batches = list(batches)
    assert batches, "cannot concat zero batches"
    # a column may arrive dictionary-encoded from one source and plain
    # from another (encoded scan vs adapted/evolved file): arrow refuses
    # mixed concat, so decode the minority form per column (all-encoded
    # columns concat encoded — arrow unifies the dictionaries)
    if len(batches) > 1 and any(c.is_dict_encoded
                                for b in batches for c in b.columns):
        mixed = [ci for ci in range(min(b.num_columns for b in batches))
                 if len({b.columns[ci].is_dict_encoded
                         for b in batches}) > 1]
        if mixed:
            from spark_rapids_tpu.columnar.encoding import host_decoded
            fixed = []
            for b in batches:
                cols = list(b.columns)
                for ci in mixed:
                    c = cols[ci]
                    if c.is_dict_encoded:
                        cols[ci] = HostColumn(host_decoded(c.arrow),
                                              c.data_type)
                fixed.append(HostColumnarBatch(cols, b.row_count,
                                               b.names))
            batches = fixed
    tables = [pa.Table.from_batches([b.to_arrow()]) for b in batches]
    return batch_from_arrow(pa.concat_tables(tables).combine_chunks())
