"""Host and device column vectors.

Reference counterparts:
- ``GpuColumnVector.java`` (device column over cuDF ColumnVector, type
  mapping, batch<->Table) — here ``DeviceColumn`` over jax Arrays.
- ``RapidsHostColumnVector.java`` / ``RapidsHostColumnBuilder.java`` — here
  ``HostColumn`` over pyarrow Arrays (Arrow layout is the host/wire format,
  as JCudfSerialization's host layout is for the reference).

Design (TPU-first):
- A device column is (data, validity, row_count) where ``data``/``validity``
  are jax arrays whose leading dim is a *bucket* (next power of two >= rows,
  min 1024).  All kernels mask by validity and by ``iota < row_count``.
- Fixed-width types map 1:1 to a jax dtype.  float64 is kept f64 (XLA on TPU
  emulates; ops that are f64-hot are planner-tagged).  decimal64 is int64 data
  + scale in the DataType.  decimal128 is int64[bucket, 2] hi/lo limbs.
- Strings/binary: uint8[bucket, max_len] + int32 lengths.  max_len is padded
  to a power of two to bound compile cache size.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T

MIN_ROW_BUCKET = 1024
MIN_STR_BUCKET = 8


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def bucket_rows(n: int, minimum: int = MIN_ROW_BUCKET) -> int:
    """Padded leading-dim for ``n`` logical rows (static-shape discipline)."""
    return max(minimum, _next_pow2(n))


def bucket_strlen(n: int) -> int:
    return max(MIN_STR_BUCKET, _next_pow2(n))


class DeferredCount:
    """A row count living on device until the host actually needs it.

    Host round-trips dominate accelerator latency (a scalar fetch over the
    device tunnel costs ~10-100ms — far more than dispatching a 1M-row
    kernel), so filters/aggregations keep their output row counts as 0-d
    device arrays.  Chained device kernels read ``traceable()`` (no sync);
    any host-side use (int conversion, comparisons, arithmetic) forces ONE
    cached sync.  The reference has no analog: cuDF kernels return counts
    synchronously because CUDA launch+sync latency is microseconds.
    """

    __slots__ = ("_dev", "_val")

    def __init__(self, dev, val=None):
        self._dev = dev
        self._val = val

    def traceable(self):
        """What device kernels should consume (0-d array; no sync)."""
        return self._dev if self._val is None else self._val

    @property
    def is_forced(self) -> bool:
        return self._val is not None

    def _force(self) -> int:
        if self._val is None:
            from spark_rapids_tpu.aux import transitions as TR
            self._val = TR.sync_int(self._dev, site="count-force")
        return self._val

    # device-side interop (jnp ops accept this without a sync)
    def __jax_array__(self):
        return _jnp().asarray(self.traceable())

    # host-side interop (forces the sync, once)
    def __int__(self):
        return self._force()

    def __index__(self):
        return self._force()

    def __bool__(self):
        return self._force() != 0

    def __hash__(self):
        return hash(self._force())

    def __repr__(self):
        return str(self._val) if self._val is not None else "<deferred>"

    @staticmethod
    def _v(o):
        return o._force() if isinstance(o, DeferredCount) else o

    def __eq__(self, o):
        if self is o:
            return True             # same deferred count: no sync needed
        return self._force() == DeferredCount._v(o)

    def __ne__(self, o):
        return not self.__eq__(o)

    def __lt__(self, o):
        return self._force() < DeferredCount._v(o)

    def __le__(self, o):
        return self._force() <= DeferredCount._v(o)

    def __gt__(self, o):
        return self._force() > DeferredCount._v(o)

    def __ge__(self, o):
        return self._force() >= DeferredCount._v(o)

    def __add__(self, o):
        return self._force() + DeferredCount._v(o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._force() - DeferredCount._v(o)

    def __rsub__(self, o):
        return DeferredCount._v(o) - self._force()

    def __mul__(self, o):
        return self._force() * DeferredCount._v(o)

    __rmul__ = __mul__

    def __floordiv__(self, o):
        return self._force() // DeferredCount._v(o)

    def __truediv__(self, o):
        return self._force() / DeferredCount._v(o)

    def __rtruediv__(self, o):
        return DeferredCount._v(o) / self._force()

    def __mod__(self, o):
        return self._force() % DeferredCount._v(o)


def rc_traceable(rc):
    """Row count as a jit argument: device scalar if deferred (no sync)."""
    return rc.traceable() if isinstance(rc, DeferredCount) else rc


def known_empty(rc) -> bool:
    """True only when a row count is empty WITHOUT forcing a deferred
    count (forcing costs a host round trip per batch on a tunnel-attached
    chip; callers treat "maybe non-empty" batches as live)."""
    if isinstance(rc, DeferredCount):
        return rc.is_forced and int(rc) == 0
    return int(rc) == 0


def force_counts(rcs) -> None:
    """Forces many deferred counts with ONE device sync (stacked fetch).
    Callers that need several batches' exact row counts (AQE partition
    sizing) must not pay a tunnel round trip per batch."""
    jnp = _jnp()
    pending = [rc for rc in rcs
               if isinstance(rc, DeferredCount) and not rc.is_forced]
    if not pending:
        return
    from spark_rapids_tpu.aux import transitions as TR
    stacked = TR.fetch(jnp.stack([jnp.asarray(rc.traceable())
                                  for rc in pending]),
                       site="count-force-batch")
    for rc, v in zip(pending, stacked):
        rc._val = int(v)


def sum_counts(rcs) -> int:
    """Totals row counts with at most ONE device sync (batches already
    forced contribute host-side; the rest are summed on device first)."""
    jnp = _jnp()
    static = 0
    deferred = []
    for rc in rcs:
        if isinstance(rc, DeferredCount) and not rc.is_forced:
            deferred.append(rc.traceable())
        else:
            static += int(rc)
    if deferred:
        total = deferred[0]
        for d in deferred[1:]:
            total = total + d
        from spark_rapids_tpu.aux import transitions as TR
        static += TR.sync_int(total, site="count-sum")
    return static


_X64_READY = False


def _jnp():
    """jax.numpy with 64-bit types enforced.

    A SQL engine cannot live without int64/float64 (LongType, TimestampType,
    decimal limbs), so x64 mode is a hard requirement of the runtime — the
    reference equivalently requires 64-bit cuDF types throughout.
    """
    global _X64_READY
    import jax
    if not _X64_READY:
        jax.config.update("jax_enable_x64", True)
        _X64_READY = True
    return jax.numpy


def _ragged_indices(lens64: np.ndarray):
    """For per-row lengths, returns (row_idx, within) flat coordinates of
    every payload byte — shared by the rectangularize (scatter) and
    flatten (gather) directions."""
    total = int(lens64.sum())
    row_idx = np.repeat(np.arange(len(lens64), dtype=np.int64), lens64)
    head = np.repeat(np.cumsum(lens64) - lens64, lens64)
    within = np.arange(total, dtype=np.int64) - head
    return row_idx, within


def _validity_buffer(valid: np.ndarray):
    """(packed-bits arrow validity buffer or None, null_count)."""
    import pyarrow as pa
    valid = np.asarray(valid, dtype=bool)
    if valid.all():
        return None, 0
    return (pa.py_buffer(np.packbits(valid, bitorder="little").tobytes()),
            int((~valid).sum()))


def _decimal128_from_limbs(hi: np.ndarray, lo: np.ndarray, valid, dt):
    """Builds an arrow decimal128 array from int64 hi/lo limbs (vectorized)."""
    import pyarrow as pa
    n = len(lo)
    buf = np.empty((n, 2), dtype=np.int64)
    buf[:, 0] = lo  # little-endian: low limb first
    buf[:, 1] = hi
    vbuf, nulls = (None, 0) if valid is None else _validity_buffer(valid)
    return pa.Array.from_buffers(
        pa.decimal128(dt.precision, dt.scale), n,
        [vbuf, pa.py_buffer(buf.tobytes())], null_count=nulls)


def is_device_array_type(dt: T.DataType) -> bool:
    """Arrays of fixed-width scalars ride the device as a padded rectangular
    plane (data [bucket, max_elems] + lengths + element validity) — the same
    layout trick as strings.  Nested/string elements stay on the host tier."""
    if not isinstance(dt, T.ArrayType):
        return False
    e = dt.element_type
    return isinstance(e, (T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                          T.FloatType, T.DoubleType, T.BooleanType,
                          T.DateType, T.TimestampType))


def _elem_np_dtype(elem: T.DataType):
    if isinstance(elem, T.DateType):
        return np.dtype(np.int32)
    if isinstance(elem, T.TimestampType):
        return np.dtype(np.int64)
    return elem.np_dtype


def _list_from_rectangular(vals: np.ndarray, lens: np.ndarray,
                           elem_valid: np.ndarray, valid: np.ndarray,
                           dt: T.ArrayType):
    """Builds an arrow ListArray from [n, w] values + lengths (vectorized)."""
    import pyarrow as pa
    n = len(lens)
    lens64 = np.where(valid, lens, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lens64, out=offsets[1:])
    if lens64.sum():
        row_idx, within = _ragged_indices(lens64)
        flat = np.ascontiguousarray(vals[row_idx, within])
        flat_valid = np.ascontiguousarray(elem_valid[row_idx, within])
    else:
        flat = np.zeros(0, dtype=vals.dtype)
        flat_valid = np.zeros(0, dtype=bool)
    elem_col = HostColumn.from_numpy(flat, flat_valid, dt.element_type)
    vbuf, nulls = _validity_buffer(valid)
    return pa.Array.from_buffers(
        pa.list_(T.to_arrow(dt.element_type)), n,
        [vbuf, pa.py_buffer(offsets.tobytes())],
        null_count=nulls, children=[elem_col.arrow])


def _binary_from_rectangular(chars: np.ndarray, lens: np.ndarray,
                             valid: np.ndarray):
    """Builds an arrow binary array from uint8[n, w] + lengths (vectorized)."""
    import pyarrow as pa
    n = len(lens)
    lens64 = np.where(valid, lens, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lens64, out=offsets[1:])
    if lens64.sum():
        row_idx, within = _ragged_indices(lens64)
        flat = np.ascontiguousarray(chars[row_idx, within])
    else:
        flat = np.zeros(0, dtype=np.uint8)
    vbuf, nulls = _validity_buffer(valid)
    return pa.Array.from_buffers(
        pa.binary(), n,
        [vbuf, pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes())],
        null_count=nulls)


# ---------------------------------------------------------------------------
# Host column
# ---------------------------------------------------------------------------

class HostColumn:
    """A host column: pyarrow Array + our logical DataType.

    The Arrow buffers are the host representation for IO, shuffle wire format
    and CPU-fallback compute (the reference's analog is JCudfSerialization's
    host columnar layout + RapidsHostColumnVector).
    """

    __slots__ = ("arrow", "data_type", "_plain_cache")

    def __init__(self, arrow_array, data_type: Optional[T.DataType] = None):
        import pyarrow as pa
        if isinstance(arrow_array, pa.ChunkedArray):
            arrow_array = arrow_array.combine_chunks()
        if pa.types.is_date64(arrow_array.type):
            # canonical date repr is date32 (days); date64 (ms) is ingested
            arrow_array = arrow_array.cast(pa.date32())
        self.arrow = arrow_array
        self.data_type = data_type or T.from_arrow(arrow_array.type)
        #: memoized decoded form of a dictionary-encoded array (columns
        #: are immutable; every value-plane accessor below would
        #: otherwise re-decode the full column)
        self._plain_cache = None

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_numpy(data: np.ndarray, validity: Optional[np.ndarray] = None,
                   data_type: Optional[T.DataType] = None) -> "HostColumn":
        import pyarrow as pa
        dt = data_type or T.from_numpy_dtype(data.dtype)
        if data.dtype.kind == "M":
            # normalize datetime64 of any unit to our canonical physical repr
            if isinstance(dt, T.DateType):
                data = data.astype("datetime64[D]").astype(np.int32)
            else:
                data = data.astype("datetime64[us]").astype(np.int64)
        mask = None if validity is None else ~np.asarray(validity, dtype=bool)
        if isinstance(dt, T.NullType):
            arr = pa.nulls(len(data))
        elif isinstance(dt, T.DecimalType):
            # unscaled repr: int64 for decimal64, [n,2] (hi,lo) limbs for 128
            if dt.is_decimal128 and data.ndim == 2:
                hi, lo = data[:, 0].astype(np.int64), data[:, 1].astype(np.int64)
            else:
                lo = data.astype(np.int64)
                hi = np.where(lo < 0, np.int64(-1), np.int64(0))
            arr = _decimal128_from_limbs(hi, lo,
                                         None if mask is None else ~mask, dt)
        elif isinstance(dt, T.TimestampType):
            arr = pa.array(data.astype(np.int64), type=pa.int64(),
                           mask=mask).cast(pa.timestamp("us", tz="UTC"))
        elif isinstance(dt, T.DateType):
            arr = pa.array(data.astype(np.int32), type=pa.int32(),
                           mask=mask).cast(pa.date32())
        else:
            arr = pa.array(data, type=T.to_arrow(dt), mask=mask)
        return HostColumn(arr, dt)

    @staticmethod
    def from_pylist(values, data_type: Optional[T.DataType] = None) -> "HostColumn":
        import pyarrow as pa
        if data_type is not None:
            return HostColumn(pa.array(values, type=T.to_arrow(data_type)),
                              data_type)
        arr = pa.array(values)
        return HostColumn(arr)

    # -- accessors ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self.arrow)

    @property
    def is_dict_encoded(self) -> bool:
        import pyarrow as pa
        return pa.types.is_dictionary(self.arrow.type)

    def _plain(self):
        """The non-dictionary arrow form (value-plane accessors below
        need real buffers; dictionary indices would masquerade as data).
        Decode routes through the one sanctioned host decode helper and
        is memoized (immutable column, many accessors)."""
        if not self.is_dict_encoded:
            return self.arrow
        if self._plain_cache is None:
            from spark_rapids_tpu.columnar.encoding import host_decoded
            self._plain_cache = host_decoded(self.arrow)
        return self._plain_cache

    @property
    def null_count(self) -> int:
        if self.is_dict_encoded:
            # a valid index pointing at a null dictionary VALUE is a
            # null row; only the decoded form counts those
            return self._plain().null_count
        return self.arrow.null_count

    def validity_np(self) -> np.ndarray:
        """Returns bool[rows], True where valid."""
        import pyarrow.compute as pc
        arr = self._plain()
        if arr.null_count == 0:
            return np.ones(len(arr), dtype=bool)
        return pc.is_valid(arr).to_numpy(zero_copy_only=False)

    def data_np(self) -> np.ndarray:
        """Dense data as numpy, nulls filled with zeros (use validity_np)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        dt = self.data_type
        if isinstance(dt, (T.StringType, T.BinaryType)):
            raise TypeError("use string_np() for string columns")
        if isinstance(dt, T.ArrayType):
            raise TypeError("use list_np() for array columns")
        if isinstance(dt, T.DecimalType):
            # vectorized unscaled-limb extraction straight from the arrow
            # 16-byte little-endian buffer (reference: cuDF DECIMAL64/128
            # columns expose unscaled values the same way)
            arr = self._plain()
            if not pa.types.is_decimal128(arr.type):
                arr = arr.cast(pa.decimal128(dt.precision, dt.scale))
            n = len(arr)
            raw = np.frombuffer(arr.buffers()[1], dtype=np.int64,
                                offset=arr.offset * 16, count=2 * n).reshape(n, 2)
            lo = raw[:, 0].copy()
            hi = raw[:, 1].copy()
            valid = self.validity_np()
            lo[~valid] = 0
            hi[~valid] = 0
            if dt.is_decimal128:
                return np.stack([hi, lo], axis=1)  # device layout is [hi, lo]
            return lo
        arr = self._plain()
        if isinstance(dt, T.TimestampType):
            arr = arr.cast("int64")
        elif isinstance(dt, T.DateType):
            arr = arr.cast("int32")
        elif isinstance(dt, T.NullType):
            return np.zeros(len(arr), dtype=np.int8)
        if arr.null_count:
            import pyarrow as pa
            zero = pa.scalar(0, type=arr.type) if dt.np_dtype.kind != "b" \
                else pa.scalar(False, type=arr.type)
            arr = pc.fill_null(arr, zero)
        return arr.to_numpy(zero_copy_only=False).astype(dt.np_dtype, copy=False)

    def string_np(self, max_len: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Rectangularizes to (uint8[rows, max_len], int32 lengths)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self._plain()
        if pa.types.is_string(arr.type):
            arr = arr.cast(pa.binary())
        elif pa.types.is_large_string(arr.type) or pa.types.is_large_binary(arr.type):
            arr = arr.cast(pa.binary())
        filled = pc.fill_null(arr, b"")
        lens = pc.binary_length(filled).to_numpy(zero_copy_only=False).astype(np.int32)
        ml = int(lens.max()) if len(lens) else 0
        width = bucket_strlen(max(ml, 1) if max_len is None else max_len)
        out = np.zeros((len(arr), width), dtype=np.uint8)
        combined = filled.combine_chunks() if isinstance(filled, pa.ChunkedArray) else filled
        buf = combined.buffers()
        # arrow binary: buffers = [validity, offsets(int32), data]
        offsets = np.frombuffer(buf[1], dtype=np.int32,
                                count=len(arr) + 1, offset=combined.offset * 4)
        databuf = np.frombuffer(buf[2], dtype=np.uint8) if buf[2] is not None \
            else np.zeros(0, dtype=np.uint8)
        np.minimum(lens, width, out=lens)
        # vectorized ragged->rectangular scatter
        if lens.sum():
            lens64 = lens.astype(np.int64)
            row_idx, within = _ragged_indices(lens64)
            starts = np.repeat(offsets[:-1].astype(np.int64), lens64)
            out[row_idx, within] = databuf[starts + within]
        return out, lens

    def list_np(self, max_len: Optional[int] = None):
        """Rectangularizes a list column to (values[rows, w], int32 lengths,
        elem_valid[rows, w]) — the device array-plane layout."""
        import pyarrow as pa
        import pyarrow.compute as pc
        dt = self.data_type
        if not isinstance(dt, T.ArrayType):
            raise TypeError("list_np on a non-array column")
        arr = self._plain()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_large_list(arr.type):
            arr = arr.cast(pa.list_(arr.type.value_type))
        lens = pc.list_value_length(arr)
        lens = pc.fill_null(lens, 0).to_numpy(zero_copy_only=False)\
            .astype(np.int32)
        ml = int(lens.max()) if len(lens) else 0
        width = bucket_strlen(max(ml, 1) if max_len is None else max_len)
        edt = _elem_np_dtype(dt.element_type)
        out = np.zeros((len(arr), width), dtype=edt)
        ev = np.zeros((len(arr), width), dtype=bool)
        np.minimum(lens, width, out=lens)
        if lens.sum():
            # flatten() drops null-row slots, so align via raw offsets
            offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                                    count=len(arr) + 1, offset=arr.offset * 4)
            values = HostColumn(arr.values, dt.element_type)
            vdata = values.data_np()
            vvalid = values.validity_np()
            lens64 = lens.astype(np.int64)
            row_idx, within = _ragged_indices(lens64)
            starts = np.repeat(offsets[:-1].astype(np.int64), lens64)
            out[row_idx, within] = vdata[starts + within]
            ev[row_idx, within] = vvalid[starts + within]
        return out, lens, ev

    def to_pylist(self):
        return self.arrow.to_pylist()

    def slice(self, offset: int, length: int) -> "HostColumn":
        return HostColumn(self.arrow.slice(offset, length), self.data_type)

    def nbytes(self) -> int:
        n = sum(b.size for b in self.arrow.buffers() if b is not None)
        if self.is_dict_encoded:
            # .buffers() on a DictionaryArray covers only the indices;
            # the dictionary's value buffers are real host bytes too
            n += sum(b.size
                     for b in self.arrow.dictionary.buffers()
                     if b is not None)
        return n

    def __repr__(self):
        return f"HostColumn({self.data_type}, rows={len(self)}, nulls={self.null_count})"


# ---------------------------------------------------------------------------
# Device column
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DeviceColumn:
    """A device column vector (reference: GpuColumnVector over cudf).

    Invariants:
      - ``data.shape[0] == validity.shape[0] == bucket >= row_count``
      - rows in ``[row_count, bucket)`` have ``validity == False``
      - scalar types: data is 1-D jax array of the mapped dtype
      - string/binary: data is uint8[bucket, strwidth]; ``lengths`` int32[bucket]
      - decimal128: data is int64[bucket, 2] (hi limb, lo limb-as-int64-bits)
      - array<fixed-width>: data is elem[bucket, max_elems]; ``lengths``
        int32[bucket]; ``elem_valid`` bool[bucket, max_elems]
    """

    data: Any                      # jax Array
    validity: Any                  # jax bool Array [bucket]
    row_count: int
    data_type: T.DataType
    lengths: Any = None            # jax int32 Array [bucket] (strings/arrays)
    elem_valid: Any = None         # jax bool Array [bucket, w] (arrays only)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_host(col: HostColumn, bucket: Optional[int] = None) -> "DeviceColumn":
        jnp = _jnp()
        n = len(col)
        b = bucket_rows(n) if bucket is None else bucket
        if b < n:
            raise ValueError(f"bucket {b} smaller than row count {n}")
        if b & (b - 1):
            raise ValueError(f"bucket {b} must be a power of two "
                             "(static-shape compile-cache discipline)")
        valid = np.zeros(b, dtype=bool)
        valid[:n] = col.validity_np()
        dt = col.data_type
        if is_device_array_type(dt):
            vals, lens, ev = col.list_np()
            w = vals.shape[1]
            data = np.zeros((b, w), dtype=vals.dtype)
            data[:n] = vals
            lengths = np.zeros(b, dtype=np.int32)
            lengths[:n] = lens
            elem_valid = np.zeros((b, w), dtype=bool)
            elem_valid[:n] = ev
            return DeviceColumn(jnp.asarray(data), jnp.asarray(valid), n, dt,
                                lengths=jnp.asarray(lengths),
                                elem_valid=jnp.asarray(elem_valid))
        if isinstance(dt, (T.StringType, T.BinaryType)):
            chars, lens = col.string_np()
            data = np.zeros((b, chars.shape[1]), dtype=np.uint8)
            data[:n] = chars
            lengths = np.zeros(b, dtype=np.int32)
            lengths[:n] = lens
            return DeviceColumn(jnp.asarray(data), jnp.asarray(valid), n, dt,
                                lengths=jnp.asarray(lengths))
        raw = col.data_np()
        if isinstance(dt, T.DecimalType) and dt.is_decimal128:
            data = np.zeros((b, 2), dtype=np.int64)
            data[:n] = raw
        else:
            data = np.zeros((b,) + raw.shape[1:], dtype=raw.dtype)
            data[:n] = raw
        return DeviceColumn(jnp.asarray(data), jnp.asarray(valid), n, dt)

    @staticmethod
    def from_parts(data, validity, row_count: int, data_type: T.DataType,
                   lengths=None, elem_valid=None) -> "DeviceColumn":
        return DeviceColumn(data, validity, row_count, data_type, lengths,
                            elem_valid)

    # -- accessors ----------------------------------------------------------
    @property
    def bucket(self) -> int:
        return int(self.data.shape[0])

    def __len__(self) -> int:
        return self.row_count

    @property
    def is_string(self) -> bool:
        return isinstance(self.data_type, (T.StringType, T.BinaryType))

    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.elem_valid is not None:
            n += self.elem_valid.size
        return int(n)

    def to_host(self) -> HostColumn:
        n = self.row_count
        return assemble_host_column(
            self.data_type, n,
            None if isinstance(self.data_type, T.NullType)
            else np.asarray(self.data)[:n],
            np.asarray(self.validity)[:n],
            None if self.lengths is None else np.asarray(self.lengths)[:n],
            None if self.elem_valid is None
            else np.asarray(self.elem_valid)[:n])

    def with_row_count(self, n: int) -> "DeviceColumn":
        return DeviceColumn(self.data, self.validity, n, self.data_type,
                            self.lengths, self.elem_valid)

    def __repr__(self):
        return (f"DeviceColumn({self.data_type}, rows={self.row_count}, "
                f"bucket={self.bucket})")


def assemble_host_column(dt: T.DataType, n: int, raw, valid,
                         lens=None, ev=None) -> HostColumn:
    """Rebuilds a HostColumn from already-fetched numpy planes (shared by
    DeviceColumn.to_host and the packed batch download in transfer.py)."""
    import pyarrow as pa
    if isinstance(dt, T.NullType):
        return HostColumn(pa.nulls(n), dt)
    if isinstance(dt, T.ArrayType):
        return HostColumn(_list_from_rectangular(raw, lens, ev, valid, dt),
                          dt)
    if isinstance(dt, (T.StringType, T.BinaryType)):
        binary = _binary_from_rectangular(raw, lens, valid)
        if isinstance(dt, T.StringType):
            try:
                return HostColumn(binary.cast(pa.string()), dt)
            except pa.ArrowInvalid:
                # kernel produced non-UTF8 bytes; decode with replacement
                py = [None if v is None else v.decode("utf-8", "replace")
                      for v in binary.to_pylist()]
                return HostColumn(pa.array(py, type=pa.string()), dt)
        return HostColumn(binary, dt)
    if isinstance(dt, T.DecimalType):
        if dt.is_decimal128:
            hi, lo = raw[:, 0], raw[:, 1]
        else:
            lo = raw.astype(np.int64)
            hi = np.where(lo < 0, np.int64(-1), np.int64(0))
        return HostColumn(_decimal128_from_limbs(hi, lo, valid, dt), dt)
    return HostColumn.from_numpy(raw, valid, dt)
