"""Encoded columnar execution: dictionary / RLE columns kept alive past
the scan.

"GPU Acceleration of SQL Analytics on Compressed Data" (PAPERS.md) shows
filters, joins and aggregations can run directly over dictionary- and
run-length-encoded columns without materializing; the reference plugin
keeps cuDF's encoded columns alive and runs nvcomp codecs on the byte
paths.  The TPU port:

- ``DictionaryColumn``: device codes (int32) + a process-cached
  ``Dictionary`` (host values + lazily-uploaded device value planes).
  The dictionary uploads ONCE per distinct content fingerprint; batches
  ship only their narrow code planes over the tunnel (H2D is the scarce
  resource on a tunnel-attached chip).
- ``RleColumn``: run values + run ends, padded to a pow2 *runs* bucket —
  sorted/constant fixed-width columns ship runs instead of rows.
- **Code-space predicates**: a filter conjunct whose only column input is
  one dictionary column evaluates ONCE over the (tiny) dictionary values
  on the CPU oracle backend, producing a bool lookup table the compiled
  program indexes by code — ``col = lit`` / ``IN`` / range / LIKE all
  reduce to one gather.  Tables are pow2-padded RUNTIME ARGUMENTS, so
  encoded filter chains compile to one executable across dictionaries
  and literal values alike (the encoded analog of literal promotion).
- **Late materialization**: filters compact code planes; only surviving
  rows ever gather through the dictionary, and only where an operator
  genuinely needs values.

Every decode funnels through ``decode_dictionary``/``decode_rle`` in
THIS module (lint rule ``encoded-materialize``): callers use the
``materialize*`` helpers, which count decoded bytes and emit the
``encodingFallback`` events the AutoTuner and ``tools profile`` read.
Every piece degrades per column to eager decode (oversized / null-valued
/ non-unique dictionaries, mismatched join/merge dictionaries, unsorted
sort keys), so ``spark.rapids.sql.encoding.enabled=false`` — or any
unsupported shape — reproduces the plain path bit-identically.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import dataclasses

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (DeviceColumn, HostColumn, _jnp,
                                              bucket_rows)

#: synced from spark.rapids.sql.encoding.* by TpuOverrides.apply
ENCODING_ENABLED = True
LATE_MATERIALIZATION = True
MAX_DICTIONARY_SIZE = 1 << 16
RLE_ENABLED = False

#: minimum runs-per-row advantage before an upload RLE-encodes a column
_RLE_MIN_RATIO = 8

_STATS_LOCK = threading.Lock()
_STATS = {
    "encoded_columns": 0,        # device columns that arrived encoded
    "rle_columns": 0,
    "encoded_bytes_in": 0,       # H2D bytes shipped for encoded planes
    "encoded_bytes_out": 0,      # D2H bytes shipped as codes
    "decode_avoided_bytes": 0,   # plain-plane bytes the encoding skipped
    "decoded_bytes": 0,          # bytes actually materialized later
    "dict_fallbacks": 0,         # per-column decodes forced by operators
}


def encoding_stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def _bump(**kv) -> None:
    with _STATS_LOCK:
        for k, v in kv.items():
            _STATS[k] += v


# ---------------------------------------------------------------------------
# Dictionary: process-cached values, uploaded once per content fingerprint
# ---------------------------------------------------------------------------

_DICT_CACHE: "OrderedDict[tuple, Dictionary]" = OrderedDict()
_DICT_CACHE_MAX = 256
#: byte bound on cached dictionary VALUE payloads (host values + the
#: lazily-uploaded device planes track them ~1:1): the planes live
#: outside the BufferCatalog's accounting, so the cache — not the spill
#: framework — must bound their residency
_DICT_CACHE_MAX_BYTES = 64 << 20
_DICT_LOCK = threading.Lock()


class Dictionary:
    """The value side of a dictionary-encoded column.

    Host values stay resident (translation / D2H reassembly); the device
    value planes upload lazily, once per fingerprint, through the normal
    packed-transfer path.  Content-addressed: two parquet row groups (or
    two files) writing the same dictionary share one instance, so join
    sides and merged aggregation partials compare codes directly.
    """

    __slots__ = ("values", "fingerprint", "size", "value_type",
                 "_dev", "_sorted", "_tables", "_lock")

    def __init__(self, values, fingerprint: tuple):
        self.values = values            # pyarrow Array, no nulls
        self.fingerprint = fingerprint
        self.size = len(values)
        self.value_type = T.from_arrow(values.type)
        self._dev: Optional[DeviceColumn] = None
        self._sorted: Optional[bool] = None
        self._tables: Dict[tuple, Any] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _fingerprint_of(values) -> tuple:
        h = hashlib.sha1()
        for buf in values.buffers():
            if buf is not None:
                h.update(memoryview(buf))
        return (h.hexdigest(), len(values), str(values.type))

    @classmethod
    def of(cls, values) -> "Dictionary":
        """The cached Dictionary for an arrow values array (LRU-bounded;
        holding the entry keeps both host values and device planes
        alive)."""
        fp = cls._fingerprint_of(values)
        with _DICT_LOCK:
            hit = _DICT_CACHE.get(fp)
            if hit is not None:
                _DICT_CACHE.move_to_end(fp)
                return hit
        dic = cls(values, fp)
        with _DICT_LOCK:
            _DICT_CACHE[fp] = dic
            total = sum(d.value_nbytes for d in _DICT_CACHE.values())
            while len(_DICT_CACHE) > 1 and \
                    (len(_DICT_CACHE) > _DICT_CACHE_MAX or
                     total > _DICT_CACHE_MAX_BYTES):
                _k, evicted = _DICT_CACHE.popitem(last=False)
                total -= evicted.value_nbytes
        return dic

    @property
    def value_nbytes(self) -> int:
        return sum(b.size for b in self.values.buffers()
                   if b is not None)

    @property
    def is_sorted(self) -> bool:
        """Values ascending (bytewise for strings — the device sort
        order): code order is then value order and sorts ride the codes."""
        if self._sorted is None:
            import pyarrow.compute as pc
            if self.size <= 1:
                self._sorted = True
            else:
                a = self.values.slice(0, self.size - 1)
                b = self.values.slice(1)
                self._sorted = bool(pc.all(pc.less_equal(a, b)).as_py())
        return self._sorted

    def device_column(self) -> DeviceColumn:
        """Device value planes (data/validity/lengths), uploaded once.
        Empty dictionaries get one invalid dummy row so gathers stay
        in-bounds (every code is null anyway)."""
        if self._dev is not None:
            return self._dev
        with self._lock:
            if self._dev is None:
                import pyarrow as pa
                vals = self.values
                if self.size == 0:
                    vals = pa.nulls(1, type=self.values.type)
                hc = HostColumn(vals, self.value_type)
                b = bucket_rows(max(len(vals), 1), minimum=8)
                dev = DeviceColumn.from_host(hc, bucket=b)
                _bump(encoded_bytes_in=dev.nbytes())
                self._dev = dev
        return self._dev

    def host_column(self) -> HostColumn:
        return HostColumn(self.values, self.value_type)

    def lookup_table(self, key: tuple, build) -> Any:
        """Device-resident pow2-padded bool table for one translated
        predicate, cached per (predicate identity) on this dictionary."""
        with self._lock:
            hit = self._tables.get(key)
            if hit is not None:
                return hit
        table = build()
        with self._lock:
            self._tables[key] = table
        return table

    @property
    def table_bucket(self) -> int:
        return bucket_rows(max(self.size, 1), minimum=8)

    def __repr__(self):
        return (f"Dictionary(size={self.size}, {self.value_type}, "
                f"fp={self.fingerprint[0][:8]})")


def reassemble_host_dictionary(codes_np: np.ndarray, valid_np: np.ndarray,
                               dic: "Dictionary", dt) -> HostColumn:
    """Host dictionary array from fetched code/validity planes (shared
    by ``DictionaryColumn.to_host`` and the packed download): null rows
    mask out, empty dictionaries get one dummy null value so the arrow
    array stays constructible."""
    import pyarrow as pa
    codes = codes_np.astype(np.int32, copy=False)
    _bump(encoded_bytes_out=codes.nbytes + valid_np.nbytes)
    idx = pa.array(np.where(valid_np, codes, 0), type=pa.int32(),
                   mask=~valid_np)
    values = dic.values if dic.size else pa.nulls(1, type=dic.values.type)
    return HostColumn(pa.DictionaryArray.from_arrays(idx, values), dt)


@dataclasses.dataclass
class DictionaryColumn(DeviceColumn):
    """Device column whose ``data`` plane holds int32 dictionary CODES;
    ``data_type`` stays the LOGICAL type.  Only encoding-aware paths may
    consume the codes; everything else must pass through
    ``materialize*`` (enforced by the encoded-materialize lint rule)."""

    dictionary: Any = None

    def to_host(self) -> HostColumn:
        n = int(self.row_count)
        return reassemble_host_dictionary(
            np.asarray(self.data)[:n], np.asarray(self.validity)[:n],
            self.dictionary, self.data_type)

    def with_row_count(self, n) -> "DictionaryColumn":
        return DictionaryColumn(self.data, self.validity, n, self.data_type,
                                None, None, dictionary=self.dictionary)

    def __repr__(self):
        return (f"DictionaryColumn({self.data_type}, rows={self.row_count}, "
                f"dict={self.dictionary.size})")


@dataclasses.dataclass
class RleColumn(DeviceColumn):
    """Run-length-encoded fixed-width device column: ``data`` holds the
    run VALUES, ``validity`` the run validity — both padded to a pow2
    RUNS bucket (smaller than the row bucket) — and ``run_ends`` the
    exclusive cumulative row end of each run (padding runs end at
    int32 max).  ``bucket`` reports the LOGICAL row bucket so the batch
    invariant holds; every row-shaped consumer must materialize first."""

    run_ends: Any = None           # int32 [runs_bucket]
    logical_bucket: int = 0

    @property
    def bucket(self) -> int:
        return self.logical_bucket

    @property
    def runs_bucket(self) -> int:
        return int(self.data.shape[0])

    def to_host(self) -> HostColumn:
        n = int(self.row_count)
        vals = np.asarray(self.data)
        rvalid = np.asarray(self.validity)
        ends = np.asarray(self.run_ends).astype(np.int64)
        _bump(encoded_bytes_out=vals.nbytes + rvalid.nbytes + ends.nbytes)
        idx = np.searchsorted(ends, np.arange(n, dtype=np.int64),
                              side="right")
        idx = np.clip(idx, 0, len(vals) - 1)
        from spark_rapids_tpu.columnar.column import assemble_host_column
        return assemble_host_column(self.data_type, n, vals[idx],
                                    rvalid[idx])

    def with_row_count(self, n) -> "RleColumn":
        return RleColumn(self.data, self.validity, n, self.data_type,
                         None, None, run_ends=self.run_ends,
                         logical_bucket=self.logical_bucket)

    def __repr__(self):
        return (f"RleColumn({self.data_type}, rows={self.row_count}, "
                f"runs_bucket={self.runs_bucket})")


def is_encoded(col: DeviceColumn) -> bool:
    return isinstance(col, (DictionaryColumn, RleColumn))


def batch_has_encoded(batch) -> bool:
    return any(is_encoded(c) for c in batch.columns)


def rewrap_like(proto: DeviceColumn, data, validity, rc, lengths=None,
                elem_valid=None) -> DeviceColumn:
    """Rebuilds a column from transformed planes, preserving dictionary
    encoding when the prototype carried one (row-space ops — gather,
    compact, concat, slice — transform code planes like any other int
    plane).  RLE prototypes must be materialized BEFORE row-space ops."""
    if isinstance(proto, DictionaryColumn):
        return DictionaryColumn(data, validity, rc, proto.data_type,
                                None, None, dictionary=proto.dictionary)
    return DeviceColumn(data, validity, rc, proto.data_type, lengths,
                        elem_valid)


# ---------------------------------------------------------------------------
# host-side decode (the ONE sanctioned arrow decode site)
# ---------------------------------------------------------------------------

def host_decoded(arrow_array):
    """Plain (non-dictionary) form of an arrow array; identity for
    already-plain arrays.  All host consumers that need value planes
    route here (columnar/column.py accessors)."""
    import pyarrow as pa
    if isinstance(arrow_array, pa.ChunkedArray):
        arrow_array = arrow_array.combine_chunks()
    if pa.types.is_dictionary(arrow_array.type):
        return arrow_array.dictionary_decode()
    return arrow_array


# ---------------------------------------------------------------------------
# device decode primitives (in-trace; everything funnels through these)
# ---------------------------------------------------------------------------

def decode_dictionary(codes, valid, vplanes, jnp):
    """Gathers value planes by code.  ``vplanes`` = (vdata, vvalid,
    vlens) from ``Dictionary.device_column()``.  Traced or eager.

    Null rows get ZEROED planes, not the gathered value-0 bytes: the
    engine-wide invariant (eager upload zero-fills null slots) that
    lets sort/partition word comparisons treat all null rows as equal
    without re-masking data everywhere."""
    vdata, vvalid, vlens = vplanes
    safe = jnp.clip(codes.astype(np.int32), 0, vdata.shape[0] - 1)
    v = valid & jnp.take(vvalid, safe)
    data = jnp.take(vdata, safe, axis=0)
    vmask = v.reshape(v.shape + (1,) * (data.ndim - 1))
    data = jnp.where(vmask, data, jnp.zeros_like(data))
    lens = None
    if vlens is not None:
        lens = jnp.where(v, jnp.take(vlens, safe),
                         jnp.zeros((), dtype=vlens.dtype))
    return data, v, lens


def decode_rle(run_vals, run_valid, run_ends, bucket, jnp):
    """Expands runs to rows: row i belongs to the first run whose end
    exceeds i (padding runs end at int32 max and are invalid).  Null
    rows decode to zeroed data (same invariant as decode_dictionary)."""
    rowpos = jnp.arange(bucket, dtype=np.int32)
    idx = jnp.searchsorted(run_ends, rowpos, side="right")
    idx = jnp.clip(idx, 0, run_vals.shape[0] - 1)
    v = jnp.take(run_valid, idx)
    data = jnp.take(run_vals, idx, axis=0)
    vmask = v.reshape(v.shape + (1,) * (data.ndim - 1))
    return jnp.where(vmask, data, jnp.zeros_like(data)), v


def _dict_planes(dic: Dictionary):
    dev = dic.device_column()
    return (dev.data, dev.validity, dev.lengths)


def _note_fallback(site: str, detail: str, nbytes: int) -> None:
    _bump(dict_fallbacks=1, decoded_bytes=nbytes)
    from spark_rapids_tpu.aux.events import emit
    emit("encodingFallback", site=site, detail=detail, bytes=nbytes)


def materialize(col: DeviceColumn, site: str = "operator",
                detail: str = "") -> DeviceColumn:
    """THE sanctioned eager decode: one compiled program per column
    shape.  Counts decoded bytes and (for operator-forced decodes)
    emits the ``encodingFallback`` evidence the AutoTuner reads."""
    jnp = _jnp()
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    if isinstance(col, DictionaryColumn):
        dic = col.dictionary
        planes = _dict_planes(dic)
        key = ("dict", str(col.data.dtype), tuple(col.data.shape),
               tuple((str(p.dtype), tuple(p.shape))
                     for p in planes if p is not None),
               planes[2] is not None)

        def build():
            def run(codes, valid, vplanes):
                return decode_dictionary(codes, valid, vplanes, jnp)
            return run

        fn = get_or_build("encoding.decode", key, build)
        data, v, lens = fn(col.data, col.validity, planes)
        out = DeviceColumn(data, v, col.row_count, col.data_type,
                           lengths=lens)
    elif isinstance(col, RleColumn):
        bucket = col.logical_bucket
        key = ("rle", str(col.data.dtype), tuple(col.data.shape), bucket)

        def build():
            def run(run_vals, run_valid, run_ends):
                return decode_rle(run_vals, run_valid, run_ends, bucket,
                                  jnp)
            return run

        fn = get_or_build("encoding.decode", key, build)
        data, v = fn(col.data, col.validity, col.run_ends)
        out = DeviceColumn(data, v, col.row_count, col.data_type)
    else:
        return col
    _note_fallback(site, detail or str(col.data_type), out.nbytes())
    return out


def materialize_batch(batch, ordinals: Optional[Sequence[int]] = None,
                      site: str = "operator"):
    """Batch with the selected (default: all) encoded columns decoded;
    returns the input unchanged when nothing decodes."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    want = set(range(len(batch.columns))) if ordinals is None \
        else set(ordinals)
    if not any(is_encoded(c) for i, c in enumerate(batch.columns)
               if i in want):
        return batch
    cols = [materialize(c, site=site, detail=(batch.names[i]
                                              if batch.names else str(i)))
            if i in want and is_encoded(c) else c
            for i, c in enumerate(batch.columns)]
    return ColumnarBatch(cols, batch.row_count, batch.names)


def materialize_rle_batch(batch, site: str = "operator"):
    """Row-space batch ops handle dictionary codes natively but cannot
    see through runs; this decodes only the RLE columns."""
    rle = [i for i, c in enumerate(batch.columns)
           if isinstance(c, RleColumn)]
    if not rle:
        return batch
    return materialize_batch(batch, ordinals=rle, site=site)


def align_batches(batches: List, site: str = "merge") -> List:
    """Makes a batch list safe to combine column-wise: RLE decodes, and a
    dictionary column position keeps its codes only when EVERY batch
    carries the SAME dictionary fingerprint there (else that position
    decodes in every batch)."""
    batches = [materialize_rle_batch(b, site=site) for b in batches]
    if not batches:
        return batches
    ncols = len(batches[0].columns)
    bad: List[int] = []
    for ci in range(ncols):
        cols = [b.columns[ci] for b in batches]
        encs = [c for c in cols if isinstance(c, DictionaryColumn)]
        if not encs:
            continue
        fps = {c.dictionary.fingerprint for c in encs}
        if len(encs) != len(cols) or len(fps) != 1:
            bad.append(ci)
    if not bad:
        return batches
    return [materialize_batch(b, ordinals=bad, site=site) for b in batches]


# ---------------------------------------------------------------------------
# upload / download classification (columnar/transfer.py hooks)
# ---------------------------------------------------------------------------

#: logical value types whose dictionary planes the device decode handles
#: (1-D data planes; decimal128's 2-limb plane is excluded)
_DICT_VALUE_OK = (T.StringType, T.BinaryType, T.ByteType, T.ShortType,
                  T.IntegerType, T.LongType, T.FloatType, T.DoubleType,
                  T.BooleanType, T.DateType, T.TimestampType)


def classify_host_column(col: HostColumn):
    """Upload-side decision for one host column:

    - ``("dict", Dictionary, codes_np, valid_np)``: keep encoded.
    - ``("rle", vals_np, valid_np, ends_np)``: runs beat rows.
    - ``None``: upload plain (decoding dictionary-typed arrows first is
      the caller's job via ``host_decoded``).
    """
    import pyarrow as pa
    import pyarrow.compute as pc
    arr = col.arrow
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type):
        if not ENCODING_ENABLED:
            return None
        values = arr.dictionary
        ok = isinstance(T.from_arrow(values.type), _DICT_VALUE_OK) and \
            not isinstance(col.data_type, T.DecimalType)
        reason = None
        if not ok:
            reason = "valueType"
        elif len(values) > MAX_DICTIONARY_SIZE:
            reason = "maxDictionarySize"
        elif values.null_count:
            reason = "nullsInDictionary"
        elif len(values) and pc.count_distinct(values).as_py() != \
                len(values):
            # duplicated values would break code-space equality
            reason = "duplicateValues"
        if reason is not None:
            _bump(dict_fallbacks=1)
            from spark_rapids_tpu.aux.events import emit
            emit("encodingFallback", site="upload", detail=reason,
                 bytes=0, dict_size=len(values))
            return None
        dic = Dictionary.of(values)
        idx = arr.indices
        valid = pc.is_valid(arr).to_numpy(zero_copy_only=False)
        codes = pc.fill_null(idx, 0).to_numpy(zero_copy_only=False)
        codes = codes.astype(_narrow_code_dtype(dic.size), copy=False)
        return ("dict", dic, codes, valid)
    if RLE_ENABLED and ENCODING_ENABLED:
        dt = col.data_type
        npdt = getattr(dt, "np_dtype", None)
        if npdt is not None and not dt.is_nested and \
                not isinstance(dt, (T.StringType, T.BinaryType,
                                    T.DecimalType)) and len(col) >= 64:
            vals = col.data_np()
            if vals.ndim == 1:
                valid = col.validity_np()
                change = np.empty(len(vals), dtype=bool)
                change[0] = True
                np.not_equal(vals[1:], vals[:-1], out=change[1:])
                change[1:] |= valid[1:] != valid[:-1]
                starts = np.flatnonzero(change)
                if len(starts) * _RLE_MIN_RATIO <= len(vals):
                    ends = np.empty(len(starts), dtype=np.int32)
                    ends[:-1] = starts[1:]
                    ends[-1] = len(vals)
                    return ("rle", vals[starts], valid[starts], ends)
    return None


def _narrow_code_dtype(size: int):
    """Narrowest transfer dtype for codes (device codes are int32; the
    unpack program widens for free inside the jit)."""
    if size <= (1 << 7):
        return np.dtype(np.int8)
    if size <= (1 << 15):
        return np.dtype(np.int16)
    return np.dtype(np.int32)


def note_encoded_upload(n_dict: int, n_rle: int, encoded_bytes: int,
                        avoided_bytes: int) -> None:
    _bump(encoded_columns=n_dict, rle_columns=n_rle,
          encoded_bytes_in=encoded_bytes,
          decode_avoided_bytes=max(0, avoided_bytes))
    from spark_rapids_tpu.aux.events import emit
    emit("encodedBatch", dict_columns=n_dict, rle_columns=n_rle,
         encoded_bytes=encoded_bytes,
         decode_avoided_bytes=max(0, avoided_bytes))


# ---------------------------------------------------------------------------
# code-space predicates inside fused stages
# ---------------------------------------------------------------------------

class DictContains:
    """Internal translated predicate: ``table[code]`` where ``table`` is
    the conjunct evaluated once over the dictionary values.  Lives only
    inside a fused-stage trace (built per batch by ``plan_fused_stage``;
    never part of a logical plan).  Mimics the Expression eval protocol
    the chain tracer calls.

    Null rows take the conjunct's NULL-INPUT verdict (``null_keep``, a
    runtime arg next to the table): ``s IS NULL`` or ``coalesce(s, d) =
    d`` keep null rows in row space and must keep them here too."""

    __slots__ = ("ordinal", "slot")
    children: tuple = ()

    def __init__(self, ordinal: int, slot: int):
        self.ordinal = ordinal
        self.slot = slot

    @property
    def data_type(self):
        return T.BOOLEAN

    def sql(self) -> str:
        return f"dict_contains(input[{self.ordinal}], $tab{self.slot})"

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expressions.base import TCol
        jnp = _jnp()
        tc = ctx.cols[self.ordinal]
        table, null_keep = ctx.enc_tables[self.slot]
        safe = jnp.clip(tc.data.astype(np.int32), 0, table.shape[0] - 1)
        keep = jnp.where(tc.valid, jnp.take(table, safe), null_keep)
        return TCol(keep, True, T.BOOLEAN)

    def eval(self, ctx):
        return self.eval_tpu(ctx)


def _refs(expr) -> List[int]:
    from spark_rapids_tpu.expressions.base import BoundReference
    return [e.ordinal for e in
            expr.collect(lambda n: isinstance(n, BoundReference))]


def _all_deterministic(expr) -> bool:
    return not expr.collect(lambda n: not getattr(n, "deterministic", True))


def _strip_alias(expr):
    from spark_rapids_tpu.expressions.base import Alias
    while isinstance(expr, Alias):
        expr = expr.children[0]
    return expr


def _eval_conjunct_over(values_hc: HostColumn, n: int, expr, ordinal: int,
                        ncols: int) -> np.ndarray:
    """keep-mask of ``expr`` over ``n`` rows of host values at position
    ``ordinal`` on the CPU oracle backend: True only where definitively
    true (null and false both drop, exactly like the row-space filter)."""
    from spark_rapids_tpu.expressions.base import EvalContext
    from spark_rapids_tpu.expressions.evaluator import host_batch_tcols
    from spark_rapids_tpu.columnar.batch import HostColumnarBatch
    hb = HostColumnarBatch([values_hc], n, ["v"])
    cols: List = [None] * ncols
    cols[ordinal] = host_batch_tcols(hb)[0]
    ctx = EvalContext(cols, "cpu", n)
    tc = expr.eval_cpu(ctx)
    if tc.is_scalar:
        return np.full(n, bool(tc.valid) and bool(tc.data))
    data = np.asarray(tc.data, dtype=bool)
    valid = np.asarray(tc.valid)
    if valid.ndim == 0:
        valid = np.full(n, bool(valid))
    return data[:n] & valid[:n]


def _build_lookup_table(dic: Dictionary, expr, ordinal: int, ncols: int):
    """One translated conjunct's runtime binding: (device bool table
    padded to the dictionary's pow2 bucket, null-input verdict).  The
    null verdict comes from evaluating the SAME conjunct over one null
    value — ``IS NULL``-shaped predicates keep their null rows."""
    import pyarrow as pa
    jnp = _jnp()
    padded = dic.table_bucket
    table = np.zeros(padded, dtype=bool)
    if dic.size:
        table[:dic.size] = _eval_conjunct_over(
            dic.host_column(), dic.size, expr, ordinal, ncols)
    null_hc = HostColumn(pa.nulls(1, type=dic.values.type),
                         dic.value_type)
    null_keep = bool(_eval_conjunct_over(null_hc, 1, expr, ordinal,
                                         ncols)[0])
    return (jnp.asarray(table), jnp.asarray(null_keep))


def _table_cache_key(expr) -> tuple:
    """Identity of a translated conjunct for the per-dictionary table
    cache.  ``sql()`` renders promoted literals as value-independent
    slots, so their concrete VALUES must ride along — two parameterized
    queries sharing a program must not share a lookup table."""
    from spark_rapids_tpu.plan.stages import PromotedLiteral
    lits = expr.collect(lambda n: isinstance(n, PromotedLiteral))
    return (expr.sql(), tuple(repr(p.value) for p in lits))


class FusedEncodingPlan:
    """Per-(stage, batch-encoding) translation of a fused op chain.

    - ``ops``: the chain with translatable conjuncts swapped for
      ``DictContains`` lookups.
    - ``decode_ordinals``: input ordinals decoded IN-TRACE (columns some
      expression needs as values); their dictionary planes ride as
      runtime args — still one program, no extra dispatch.
    - ``tables``: device bool tables, runtime args (value-independent
      program).
    - ``final_dicts``: per post-chain output position, the Dictionary a
      kept (passthrough) column still carries — late materialization.
    """

    __slots__ = ("ops", "tables", "decode_ordinals", "decode_dicts",
                 "rle_ordinals", "rle_buckets", "final_dicts", "sig")

    def __init__(self, ops, tables, decode_ordinals, decode_dicts,
                 rle_ordinals, rle_buckets, final_dicts, sig):
        self.ops = ops
        self.tables = tables
        self.decode_ordinals = decode_ordinals
        self.decode_dicts = decode_dicts
        self.rle_ordinals = rle_ordinals
        self.rle_buckets = rle_buckets
        self.final_dicts = final_dicts
        self.sig = sig

    def runtime_args(self, batch):
        """Per-call arg binding (plans are cached and shared across
        concurrent partition tasks — no per-batch state lives on the
        plan): tables and dictionary planes are batch-independent, RLE
        run planes come from THIS batch's columns."""
        dplanes = tuple(_dict_planes(d) for d in self.decode_dicts)
        rplanes = tuple((batch.columns[i].data, batch.columns[i].validity,
                         batch.columns[i].run_ends)
                        for i in self.rle_ordinals)
        return (tuple(self.tables), dplanes, rplanes)

    def prepare_cols(self, cols, enc_args, jnp):
        """In-trace column prep: decode-mode dictionaries gather through
        their value-plane args; RLE expands.  Kept columns stay as code
        TCols only ``DictContains`` / bare passthrough may touch."""
        _tables, dplanes, rplanes = enc_args
        cols = list(cols)
        for k, o in enumerate(self.decode_ordinals):
            from spark_rapids_tpu.expressions.base import TCol
            tc = cols[o]
            data, v, lens = decode_dictionary(tc.data, tc.valid,
                                              dplanes[k], jnp)
            cols[o] = TCol(data, v, tc.dtype, lengths=lens)
        for k, o in enumerate(self.rle_ordinals):
            from spark_rapids_tpu.expressions.base import TCol
            tc = cols[o]
            bucket = self.rle_buckets[k]
            rv, rvalid, rends = rplanes[k]
            data, v = decode_rle(rv, rvalid, rends, bucket, jnp)
            cols[o] = TCol(data, v, tc.dtype)
        return cols


def _batch_enc_fingerprint(batch) -> tuple:
    out = []
    for i, c in enumerate(batch.columns):
        if isinstance(c, DictionaryColumn):
            out.append((i, "d", c.dictionary.fingerprint))
        elif isinstance(c, RleColumn):
            out.append((i, "r", tuple(c.data.shape), c.logical_bucket))
    return tuple(out)


def plan_fused_stage(ops, batch, key_exprs=(), other_exprs=(),
                     cache: Optional[dict] = None
                     ) -> Optional[FusedEncodingPlan]:
    """Translates a fused [filter|project]* chain for one batch's column
    encodings.  ``key_exprs`` (hash-agg grouping) may consume kept codes
    as bare references; ``other_exprs`` (agg value inputs) force a
    decode of any encoded column they touch.  Returns None when the
    batch carries no encoded columns."""
    dict_in = {i: c for i, c in enumerate(batch.columns)
               if isinstance(c, DictionaryColumn)}
    rle_in = {i: c for i, c in enumerate(batch.columns)
              if isinstance(c, RleColumn)}
    if not dict_in and not rle_in:
        return None
    cache_key = None
    if cache is not None:
        cache_key = _batch_enc_fingerprint(batch)
        hit = cache.get(cache_key)
        if hit is not None:
            return hit
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import BoundReference
    ncols = len(batch.columns)
    decode: set = set()

    def analyze(extra_decode: set):
        """One pass over the chain; returns (translated ops, final
        provenance map: post-chain position -> kept input ordinal,
        table slots as (src ordinal, chain position, conjunct)).
        Conjuncts a later use invalidates land in ``extra_decode`` and
        the caller re-runs to a fixed point."""
        prov: List[Optional[int]] = list(range(ncols))
        table_slots: List[tuple] = []   # (src ordinal, position, expr)

        def kept(pos: int) -> Optional[int]:
            src = prov[pos] if pos < len(prov) else None
            if src is None or src not in dict_in or src in decode or \
                    src in extra_decode:
                return None
            return src

        def visit_pred(e):
            if isinstance(e, P.And):
                kids = [visit_pred(c) for c in e.children]
                return e.with_children(kids)
            rs = _refs(e)
            enc = sorted({r for r in rs if kept(r) is not None})
            if not enc:
                return e
            if len(set(rs)) == 1 and len(enc) == 1 and \
                    _all_deterministic(e) and \
                    isinstance(getattr(e, "data_type", None),
                               T.BooleanType):
                slot = len(table_slots)
                # the conjunct's BoundReference carries the CURRENT chain
                # position; the table is keyed by the INPUT dictionary
                table_slots.append((kept(enc[0]), enc[0], e))
                return DictContains(enc[0], slot)
            for r in enc:
                extra_decode.add(kept(r))
            return e

        new_ops = []
        for kind, payload in ops:
            if kind == "filter":
                new_ops.append(("filter", visit_pred(payload)))
            else:
                new_prov: List[Optional[int]] = []
                for e in payload:
                    base = _strip_alias(e)
                    if isinstance(base, BoundReference) and \
                            kept(base.ordinal) is not None:
                        new_prov.append(prov[base.ordinal])
                    else:
                        for r in _refs(e):
                            if r < len(prov) and kept(r) is not None:
                                extra_decode.add(kept(r))
                        new_prov.append(None)
                new_ops.append(("project", payload))
                prov = new_prov
        # post-chain consumers (hash-agg inputs)
        for e in key_exprs:
            base = _strip_alias(e)
            if isinstance(base, BoundReference) and \
                    kept(base.ordinal) is not None:
                continue
            for r in _refs(e):
                if r < len(prov) and kept(r) is not None:
                    extra_decode.add(kept(r))
        for e in other_exprs:
            for r in _refs(e):
                if r < len(prov) and kept(r) is not None:
                    extra_decode.add(kept(r))
        return new_ops, prov, table_slots

    # iterate to a fixed point: translating under a decode set that a
    # later use (or a failed table build) invalidates re-runs the
    # analysis with the wider decode set
    tables: List = []
    for _ in range(2 * ncols + 2):
        extra: set = set()
        new_ops, prov, table_slots = analyze(extra)
        if extra:
            decode |= extra
            continue
        # build the lookup tables (cached per dictionary + conjunct); a
        # conjunct whose oracle evaluation fails is not translatable —
        # decode its column and re-plan instead of failing the query
        tables = []
        failed: set = set()
        for src, pos, expr in table_slots:
            dic = dict_in[src].dictionary
            key = _table_cache_key(expr)
            try:
                tables.append(dic.lookup_table(
                    key, lambda d=dic, e=expr, o=pos:
                    _build_lookup_table(d, e, o, max(ncols, o + 1))))
            except Exception:  # noqa: BLE001 — translation is an
                failed.add(src)  # optimization, never a query error
        if not failed:
            break
        decode |= failed
    decode_ordinals = sorted(decode)
    rle_ordinals = sorted(rle_in)
    final_dicts: List[Optional[Dictionary]] = []
    for pos in range(len(prov)):
        src = prov[pos]
        final_dicts.append(dict_in[src].dictionary
                           if src is not None and src in dict_in and
                           src not in decode else None)
    sig = (tuple(decode_ordinals),
           tuple((i, tuple(rle_in[i].data.shape),
                  rle_in[i].logical_bucket) for i in rle_ordinals),
           tuple(int(t[0].shape[0]) for t in tables),
           tuple(i for i, d in enumerate(final_dicts) if d is not None))
    plan = FusedEncodingPlan(
        new_ops, tables, decode_ordinals,
        [dict_in[o].dictionary for o in decode_ordinals],
        rle_ordinals, [rle_in[o].logical_bucket for o in rle_ordinals],
        final_dicts, sig)
    if cache is not None:
        if len(cache) > 64:
            cache.clear()
        cache[cache_key] = plan
    return plan


def eval_exprs_keep_encoded(exprs, batch, names=None):
    """``eval_exprs_tpu`` that passes bare-reference outputs of
    dictionary columns through ENCODED (the aggregate's final projection
    of grouped keys, e.g.) — codes then ride all the way to the
    download boundary, which reassembles them against the host
    dictionary without ever gathering values."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.expressions import evaluator as EV
    from spark_rapids_tpu.expressions.base import BoundReference
    keep: Dict[int, int] = {}
    for i, e in enumerate(exprs):
        base = _strip_alias(e)
        if isinstance(base, BoundReference) and \
                base.ordinal < len(batch.columns) and \
                isinstance(batch.columns[base.ordinal], DictionaryColumn):
            keep[i] = base.ordinal
    if not keep:
        return EV.eval_exprs_tpu(exprs, batch, names)
    others = [e for i, e in enumerate(exprs) if i not in keep]
    ob = EV.eval_exprs_tpu(others, batch) if others else None
    oc = iter(ob.columns) if ob is not None else iter(())
    cols = []
    for i, e in enumerate(exprs):
        if i in keep:
            c = batch.columns[keep[i]]
            if c.row_count is not batch.row_count:
                c = c.with_row_count(batch.row_count)
            cols.append(c)
        else:
            cols.append(next(oc))
    return ColumnarBatch(cols, batch.row_count,
                         names or EV._out_names(exprs))


# ---------------------------------------------------------------------------
# join / sort helpers
# ---------------------------------------------------------------------------

def join_key_dicts(batch, keys) -> List[Optional[Dictionary]]:
    """Per join key: the Dictionary when the key is a bare reference to
    a dictionary column of this batch (code-space join candidate)."""
    from spark_rapids_tpu.expressions.base import BoundReference
    out: List[Optional[Dictionary]] = []
    for k in keys:
        base = _strip_alias(k)
        dic = None
        if ENCODING_ENABLED and isinstance(base, BoundReference) and \
                base.ordinal < len(batch.columns):
            c = batch.columns[base.ordinal]
            if isinstance(c, DictionaryColumn):
                dic = c.dictionary
        out.append(dic)
    return out


def codes_key_column(batch, key_expr) -> DeviceColumn:
    """The int32 code plane of a bare-ref dictionary key, shaped as a
    plain INT column for the hash-join/sort word machinery."""
    from spark_rapids_tpu.expressions.base import BoundReference
    base = _strip_alias(key_expr)
    assert isinstance(base, BoundReference)
    col = batch.columns[base.ordinal]
    return DeviceColumn(col.data, col.validity, batch.row_count, T.INT)


def shadow_sort_batch(batch, specs) -> Tuple[Any, Any]:
    """Sort prep: RLE decodes; a dictionary SORT KEY keeps its codes
    only when the dictionary is value-sorted (codes are then
    order-isomorphic), else it materializes; payload dictionary columns
    ride the gather as int planes.  Returns (shadow batch, rewrap fn)
    mapping sorted outputs back to their encodings."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.expressions.base import BoundReference
    batch = materialize_rle_batch(batch, site="sort")
    if not batch_has_encoded(batch):
        return batch, lambda out: out
    key_ords = set()
    expr_ref_ords = set()
    for s in specs:
        base = _strip_alias(s.expr)
        if isinstance(base, BoundReference):
            key_ords.add(base.ordinal)
        else:
            expr_ref_ords.update(_refs(s.expr))
    shadow = []
    wrap: Dict[int, Dictionary] = {}
    for i, c in enumerate(batch.columns):
        if not isinstance(c, DictionaryColumn):
            shadow.append(c)
            continue
        unsorted_key = i in key_ords and not c.dictionary.is_sorted
        if unsorted_key or i in expr_ref_ords:
            shadow.append(materialize(c, site="sort",
                                      detail=(batch.names[i]
                                              if batch.names else str(i))))
            continue
        shadow.append(DeviceColumn(c.data, c.validity, c.row_count,
                                   T.INT))
        wrap[i] = c.dictionary
    shadow_b = ColumnarBatch(shadow, batch.row_count, batch.names)
    if not wrap:
        return shadow_b, lambda out: out
    logical = [c.data_type for c in batch.columns]

    def rewrap(out):
        cols = list(out.columns)
        for i, dic in wrap.items():
            c = cols[i]
            cols[i] = DictionaryColumn(c.data, c.validity, c.row_count,
                                       logical[i], None, None,
                                       dictionary=dic)
        return ColumnarBatch(cols, out.row_count, out.names)

    return shadow_b, rewrap
