"""Packed host<->device batch transfer.

The device tunnel has a large fixed cost per transfer (~80ms observed over
the axon tunnel; PCIe/DMA setup elsewhere) that dwarfs per-byte cost for
typical batch columns, so a batch is shipped as ONE buffer per element
width instead of two transfers (data + validity) per column:

- all 8-byte planes (int64/float64/decimal limbs)  -> one int64 buffer
- all 4-byte planes (int32/float32/date32/lengths) -> one int32 buffer
- all 2-byte planes (int16)                        -> one int16 buffer
- all 1-byte planes (uint8 string bytes, bool)     -> one uint8 buffer

Width-grouping matters because same-width ``bitcast_convert_type`` is free
(metadata-only) while cross-width bitcasts reshape the physical layout and
are slow on TPU.  All-valid validity planes are never transferred at all; a
per-bucket cached ones-mask is shared on device.

Reference analog: JCudfSerialization packs a whole table into one host
buffer for the same reason (per-transfer overhead), see
sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java and
RapidsShuffleInternalManagerBase.scala's serialized-table path.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.aux import transitions as TR
from spark_rapids_tpu.columnar.column import (DeviceColumn, HostColumn,
                                              _jnp, assemble_host_column,
                                              bucket_rows,
                                              is_device_array_type)

# canonical transport dtype per element width
_CANON = {8: np.dtype(np.int64), 4: np.dtype(np.int32),
          2: np.dtype(np.int16), 1: np.dtype(np.uint8)}


class _Plane:
    """One host numpy plane destined for the device, with its target dtype."""

    __slots__ = ("array", "target_dtype", "to_bool")

    def __init__(self, array: np.ndarray, target_dtype=None, to_bool=False):
        self.array = array
        self.target_dtype = target_dtype or array.dtype
        self.to_bool = to_bool


def _host_planes(col: HostColumn, bucket: int):
    """Decomposes one host column into (planes, descriptor, extra).

    descriptor: (kind, has_validity) where kind identifies how to
    reassemble: 'scalar' | 'dec128' | 'string' | 'array' | 'dict' |
    'rle'.  ``extra`` carries the Dictionary (dict) or the runs bucket
    (rle); None otherwise.
    """
    from spark_rapids_tpu.columnar import encoding as ENC
    n = len(col)
    dt = col.data_type
    enc = ENC.classify_host_column(col)
    if enc is not None and enc[0] == "dict":
        # encoded upload: ship ONLY the narrow code plane (+ validity);
        # the dictionary's value planes upload once per fingerprint
        _k, dic, codes, valid_np = enc
        planes = []
        all_valid = bool(valid_np.all())
        if not all_valid:
            v = np.zeros(bucket, dtype=np.uint8)
            v[:n] = valid_np
            planes.append(_Plane(v, to_bool=True))
        cbuf = np.zeros(bucket, dtype=codes.dtype)
        cbuf[:n] = codes
        planes.append(_Plane(cbuf, target_dtype=np.dtype(np.int32)))
        return planes, ("dict", not all_valid), dic
    if enc is not None and enc[0] == "rle":
        _k, rvals, rvalid, rends = enc
        n_runs = len(rvals)
        rbucket = ENC.bucket_rows(max(n_runs, 1), minimum=8)
        planes = []
        rv = np.zeros(rbucket, dtype=np.uint8)
        rv[:n_runs] = rvalid
        planes.append(_Plane(rv, to_bool=True))
        data = np.zeros(rbucket, dtype=rvals.dtype)
        data[:n_runs] = rvals
        planes.append(_Plane(data))
        ends = np.full(rbucket, np.iinfo(np.int32).max, dtype=np.int32)
        ends[:n_runs] = rends
        planes.append(_Plane(ends))
        return planes, ("rle", True), bucket
    if col.is_dict_encoded:
        # rejected dictionary (oversized / null values / unsupported
        # value type) or encoding disabled: decode ONCE here so the
        # plane accessors below don't each re-decode
        col = HostColumn(ENC.host_decoded(col.arrow), dt)
    valid_np = col.validity_np()
    all_valid = bool(valid_np.all())
    planes: List[Optional[_Plane]] = []

    def pad1(a, dtype=None):
        dtype = dtype or a.dtype
        out = np.zeros(bucket, dtype=dtype)
        out[:n] = a
        return out

    if not all_valid:
        v = np.zeros(bucket, dtype=np.uint8)
        v[:n] = valid_np
        planes.append(_Plane(v, to_bool=True))

    if is_device_array_type(dt):
        vals, lens, ev = col.list_np()
        w = vals.shape[1]
        data = np.zeros((bucket, w), dtype=vals.dtype)
        data[:n] = vals
        lengths = pad1(lens, np.int32)
        elem_valid = np.zeros((bucket, w), dtype=np.uint8)
        elem_valid[:n] = ev
        planes += [_Plane(data), _Plane(lengths),
                   _Plane(elem_valid, to_bool=True)]
        return planes, ("array", not all_valid), None
    if isinstance(dt, (T.StringType, T.BinaryType)):
        chars, lens = col.string_np()
        data = np.zeros((bucket, chars.shape[1]), dtype=np.uint8)
        data[:n] = chars
        planes += [_Plane(data), _Plane(pad1(lens, np.int32))]
        return planes, ("string", not all_valid), None
    raw = col.data_np()
    if isinstance(dt, T.DecimalType) and dt.is_decimal128:
        data = np.zeros((bucket, 2), dtype=np.int64)
        data[:n] = raw
        planes.append(_Plane(data))
        return planes, ("dec128", not all_valid), None
    data = np.zeros((bucket,) + raw.shape[1:], dtype=raw.dtype)
    data[:n] = raw
    planes.append(_Plane(data))
    return planes, ("scalar", not all_valid), None


def upload_host_batch(hb, bucket: Optional[int] = None):
    """HostColumnarBatch -> ColumnarBatch in <=4 device transfers total."""
    import jax
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    jnp = _jnp()
    n = hb.row_count
    b = bucket or bucket_rows(n)
    if not hb.columns:
        return ColumnarBatch([], n, hb.names)

    all_planes: List[_Plane] = []
    descs = []
    extras = []
    for col in hb.columns:
        planes, desc, extra = _host_planes(col, b)
        descs.append((desc, len(planes)))
        extras.append(extra)
        all_planes += planes

    # group plane payloads by element width
    groups: Dict[int, List[_Plane]] = {}
    for p in all_planes:
        groups.setdefault(p.array.dtype.itemsize, []).append(p)

    host_bufs = {}
    layout = []  # per-plane: (width, elem_offset, shape, str(target), to_bool)
    offsets = {w: 0 for w in groups}
    for p in all_planes:
        w = p.array.dtype.itemsize
        layout.append((w, offsets[w], p.array.shape,
                       str(p.target_dtype), p.to_bool))
        offsets[w] += p.array.size
    for w, ps in groups.items():
        canon = _CANON[w]
        buf = np.empty(sum(p.array.size for p in ps), dtype=canon)
        o = 0
        for p in ps:
            flat = np.ascontiguousarray(p.array).view(canon).ravel()
            buf[o:o + flat.size] = flat
            o += flat.size
        host_bufs[w] = buf

    n_allvalid = sum(1 for (d, _np) in descs if not d[1])
    widths = tuple(sorted(host_bufs))
    # row count is a TRACED argument: one compiled program serves every
    # batch sharing this (layout, bucket) — remainder batches with odd row
    # counts must not trigger recompiles
    key = (tuple(layout), widths,
           tuple(host_bufs[w].size for w in widths), b, n_allvalid > 0)
    def build():
        def unpack(bufs, rows):
            byw = dict(zip(widths, bufs))
            outs = []
            for (w, off, shape, tgt, to_bool) in layout:
                size = int(np.prod(shape))
                seg = byw[w][off:off + size].reshape(shape)
                tdt = np.dtype(tgt)
                if to_bool or tdt == np.bool_:
                    seg = seg.astype(jnp.bool_)
                elif tdt != seg.dtype:
                    if tdt.itemsize == seg.dtype.itemsize:
                        seg = jax.lax.bitcast_convert_type(seg, tdt)
                    else:
                        # width change (narrow dictionary codes -> the
                        # device's int32): a real convert, fused in-jit
                        seg = seg.astype(tdt)
                outs.append(seg)
            # shared all-valid row mask, created on device (no transfer);
            # one per batch so buffer lifetimes stay independent (spill may
            # delete any batch's arrays)
            ones = (jnp.arange(b) < rows) if n_allvalid else None
            return outs, ones

        return unpack
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("transfer.unpack", key, build)

    # the ONE H2D boundary of the upload path: packed width-grouped
    # buffers cross in a single device_put dispatch (ledger: duration is
    # dispatch wall — the copy may complete asynchronously)
    t0_h2d = time.perf_counter()
    dev_bufs = jax.device_put([host_bufs[w] for w in widths])
    TR.record_h2d(sum(buf.nbytes for buf in host_bufs.values()),
                  time.perf_counter() - t0_h2d,
                  kinds=",".join(sorted({d[0] for d, _ in descs})),
                  planes=len(all_planes))
    planes_dev, ones = fn(dev_bufs, n)

    cols = []
    i = 0
    n_dict = n_rle = enc_bytes = avoided = 0
    for col, ((kind, has_valid), np_count), extra in zip(hb.columns, descs,
                                                         extras):
        dt = col.data_type
        take = planes_dev[i:i + np_count]
        i += np_count
        validity = take[0] if has_valid else ones
        rest = take[1:] if has_valid else take
        if kind == "array":
            data, lengths, elem_valid = rest
            cols.append(DeviceColumn(data, validity, n, dt,
                                     lengths=lengths, elem_valid=elem_valid))
        elif kind == "string":
            data, lengths = rest
            cols.append(DeviceColumn(data, validity, n, dt, lengths=lengths))
        elif kind == "dict":
            from spark_rapids_tpu.columnar.encoding import DictionaryColumn
            dic = extra
            cols.append(DictionaryColumn(rest[0], validity, n, dt,
                                         None, None, dictionary=dic))
            n_dict += 1
            codes_bytes = 4 * b
            enc_bytes += codes_bytes
            vals_bytes = sum(buf.size for buf in dic.values.buffers()
                             if buf is not None)
            per_row = vals_bytes / max(dic.size, 1)
            avoided += int(max(0, n * per_row + 4 * b - codes_bytes))
        elif kind == "rle":
            from spark_rapids_tpu.columnar.encoding import RleColumn
            data, ends = rest
            cols.append(RleColumn(data, validity, n, dt, None, None,
                                  run_ends=ends, logical_bucket=b))
            n_rle += 1
            run_bytes = int(data.size * data.dtype.itemsize +
                            ends.size * 4 + validity.size)
            enc_bytes += run_bytes
            avoided += max(0, b * int(np.dtype(dt.np_dtype).itemsize)
                           - run_bytes)
        else:
            cols.append(DeviceColumn(rest[0], validity, n, dt))
    if n_dict or n_rle:
        from spark_rapids_tpu.columnar import encoding as ENC
        ENC.note_encoded_upload(n_dict, n_rle, enc_bytes, avoided)
    return ColumnarBatch(cols, n, hb.names)


# ---------------------------------------------------------------------------
# device -> host (packed download)
# ---------------------------------------------------------------------------

#: speculative row cap for single-round-trip downloads when the row count
#: is still deferred: planes are sliced to this many rows and the count is
#: packed INTO the buffer, so the fetch itself resolves whether it was
#: enough (results above the cap pay one extra round trip — rare: results
#: a user collects are small).  Default only — the D2H boundary exec
#: carries its conf value per instance (per-query conf travels with the
#: plan, not this module)
_DL_SPEC_ROWS = 8192


def _plane_words(seg, jnp):
    """Flat uint32 words carrying ``seg``'s device bits.

    TPU-safe: the X64 rewriter (f64 emulated as an f32 double-double pair,
    i64 as u32 pairs) implements NO 64-bit ``bitcast_convert_type``, so
    64-bit planes decompose arithmetically — f64 ships as its dd (hi, lo)
    f32 pair, which IS the exact device value (ops/f64bits.py docstring);
    i64/u64 split into (lo32, hi32) by shift/mask.  Sub-word types pack
    little-endian into u32 lanes."""
    import jax
    from spark_rapids_tpu.ops.f64bits import f64_bitcast_ok
    if seg.dtype == jnp.bool_:
        seg = seg.astype(np.uint8)
    flat = seg.ravel()
    dt = np.dtype(str(flat.dtype))
    if dt == np.float64:
        if f64_bitcast_ok():
            # real binary64 backend (CPU tests): exact bits, then split
            flat = jax.lax.bitcast_convert_type(flat, np.uint64)
            dt = np.dtype(np.uint64)
        else:
            hi = flat.astype(np.float32)
            lo = (flat - hi.astype(np.float64)).astype(np.float32)
            uh = jax.lax.bitcast_convert_type(hi, np.uint32)
            ul = jax.lax.bitcast_convert_type(lo, np.uint32)
            return jnp.stack([uh, ul], axis=-1).ravel()
    if dt.itemsize == 8:
        u = flat if dt == np.uint64 else flat.astype(np.uint64)
        lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (u >> np.uint64(32)).astype(np.uint32)
        return jnp.stack([lo, hi], axis=-1).ravel()
    ut = {1: np.uint8, 2: np.uint16, 4: np.uint32}[dt.itemsize]
    if dt != ut:
        flat = jax.lax.bitcast_convert_type(flat, ut)
    if dt.itemsize == 4:
        return flat
    per = 4 // dt.itemsize
    pad = (-int(flat.shape[0])) % per
    if pad:
        flat = jnp.pad(flat, (0, pad))
    w = flat.astype(np.uint32).reshape(-1, per)
    shifts = jnp.arange(per, dtype=np.uint32) * np.uint32(8 * dt.itemsize)
    # lanes occupy disjoint bits, so a sum is a bitwise-or
    return jnp.sum(w << shifts[None, :], axis=1, dtype=np.uint32)


def _plane_nwords(shape, dtype) -> int:
    n = int(np.prod(shape))
    isz = 1 if str(dtype) == "bool" else np.dtype(str(dtype)).itemsize
    if isz == 8:
        return 2 * n
    if isz == 4:
        return n
    per = 4 // isz
    return -(-n // per)


def _pack_planes(planes, shrink: int, rc_traced):
    """One jitted program: slice every plane to ``shrink`` rows, encode to
    uint32 words, append the row count — ONE buffer, hence ONE tunnel
    round trip.  ``jax.device_get`` on a list costs one blocking fetch PER
    array on a tunnel-attached chip (~58ms each), which dominated
    small-result collects; a single packed buffer makes the whole download
    one sync."""
    jnp = _jnp()
    sig = tuple((str(p.dtype), tuple(p.shape)) for p in planes)
    key = (sig, shrink)
    def build():
        def run(ps, rc):
            chunks = [_plane_words(p[:shrink], jnp) for p in ps]
            u = jnp.asarray(rc, dtype=np.int64).astype(np.uint64).reshape(1)
            chunks.append(jnp.concatenate([
                (u & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                (u >> np.uint64(32)).astype(np.uint32)]))
            return jnp.concatenate(chunks)

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("transfer.pack", key, build)
    return fn(planes, rc_traced)


def _unpack_buffer(buf: np.ndarray, planes, shrink: int):
    """Host-side mirror of _pack_planes: decodes the uint32 word stream
    back into per-plane numpy arrays (little-endian lanes)."""
    out = []
    o = 0
    for p in planes:
        shape = (min(shrink, int(p.shape[0])),) + tuple(p.shape[1:])
        sdt = str(p.dtype)
        nw = _plane_nwords(shape, sdt)
        w = buf[o:o + nw]
        o += nw
        n = int(np.prod(shape))
        if sdt == "float64":
            from spark_rapids_tpu.ops.f64bits import f64_bitcast_ok
            pair = w.reshape(-1, 2)
            if f64_bitcast_ok():
                v = pair[:, 0].astype(np.uint64) | \
                    (pair[:, 1].astype(np.uint64) << np.uint64(32))
                arr = v.view(np.float64)
            else:
                hi = np.ascontiguousarray(pair[:, 0]).view(np.float32)
                lo = np.ascontiguousarray(pair[:, 1]).view(np.float32)
                arr = hi.astype(np.float64) + lo.astype(np.float64)
        elif sdt in ("int64", "uint64"):
            pair = w.reshape(-1, 2).astype(np.uint64)
            v = pair[:, 0] | (pair[:, 1] << np.uint64(32))
            arr = v.view(np.int64) if sdt == "int64" else v
        elif sdt == "bool":
            arr = w.view(np.uint8)[:n].astype(bool)
        else:
            dt = np.dtype(sdt)
            arr = w.view(dt)[:n] if dt.itemsize < 4 else \
                w.view(dt)
        out.append(arr[:n].reshape(shape))
    rc = int(buf[o] | (np.uint64(buf[o + 1]) << np.uint64(32)))
    return out, rc


def download_host_batch(cb, spec_rows=None) -> "object":
    """ColumnarBatch -> HostColumnarBatch in ONE device round trip.

    All planes are packed into a single uint8 buffer on device (cheap — a
    fused slice+bitcast+concat program) together with the row count, then
    fetched with one blocking call.  When the row count is deferred and the
    bucket is large, planes are speculatively sliced to ``spec_rows``
    (default ``_DL_SPEC_ROWS``; the D2H boundary exec passes its
    convert-time conf value) rows; the packed count reveals whether that
    was enough, and only an oversized result pays a second (exactly-sized)
    round trip.
    """
    from spark_rapids_tpu.columnar import encoding as ENC
    from spark_rapids_tpu.columnar.batch import HostColumnarBatch
    from spark_rapids_tpu.columnar.column import DeferredCount, rc_traceable
    if not cb.columns:
        return HostColumnarBatch([], int(cb.row_count), cb.names)
    # RLE planes are runs-shaped (per-column buckets would break the
    # shared slice-to-shrink program); dictionary columns download their
    # CODE planes — a D2H reduction — and reassemble against the
    # host-resident dictionary values below
    cb = ENC.materialize_rle_batch(cb, site="download")

    planes = []   # device arrays, in fixed role order per column
    descs = []    # (data_type, [role names present], Dictionary|None)
    for c in cb.columns:
        dt = c.data_type
        dic = c.dictionary if isinstance(c, ENC.DictionaryColumn) else None
        col_planes = []
        if not isinstance(dt, T.NullType):
            col_planes.append(("data", c.data))
        col_planes.append(("valid", c.validity))
        if c.lengths is not None:
            col_planes.append(("lens", c.lengths))
        if c.elem_valid is not None:
            col_planes.append(("ev", c.elem_valid))
        descs.append((dt, [r for r, _ in col_planes], dic))
        planes.extend(p for _, p in col_planes)

    rc = cb.row_count
    bucket = int(cb.columns[0].data.shape[0])
    deferred = isinstance(rc, DeferredCount) and not rc.is_forced
    if deferred:
        if spec_rows is None:   # explicit sentinel: small conf values
            spec_rows = _DL_SPEC_ROWS             # must stick
        shrink = min(bucket, bucket_rows(spec_rows, minimum=8))
    else:
        # known count: slice exactly (never ship padding rows; d2h
        # bandwidth is the scarcest resource on a tunnel-attached device)
        shrink = min(bucket, bucket_rows(max(int(rc), 1), minimum=8))
    # the ONE D2H boundary of the download path: all planes cross as a
    # single packed buffer per round trip (ledger: duration is the true
    # blocking fetch — counted as a transition, not a sync)
    t0_d2h = time.perf_counter()
    buf = np.asarray(_pack_planes(planes, shrink, rc_traceable(rc)))
    TR.record_d2h(buf.nbytes, time.perf_counter() - t0_d2h,
                  site="download", planes=len(planes))
    fetched, n = _unpack_buffer(buf, planes, shrink)
    if deferred:
        rc._val = n   # the fetch resolved the count: cache it
    if n > shrink:
        # speculation miss: fetch again at the exact size (one more trip)
        shrink = min(bucket, bucket_rows(max(n, 1), minimum=8))
        t0_d2h = time.perf_counter()
        buf = np.asarray(_pack_planes(planes, shrink, n))
        TR.record_d2h(buf.nbytes, time.perf_counter() - t0_d2h,
                      site="download-miss", planes=len(planes))
        fetched, _ = _unpack_buffer(buf, planes, shrink)

    cols = []
    i = 0
    for (dt, roles, dic) in descs:
        byrole = {}
        for r in roles:
            byrole[r] = fetched[i]
            i += 1
        raw = byrole.get("data")
        if dic is not None:
            cols.append(ENC.reassemble_host_dictionary(
                raw[:n], byrole["valid"][:n], dic, dt))
            continue
        cols.append(assemble_host_column(
            dt, n,
            None if raw is None else raw[:n],
            byrole["valid"][:n],
            None if "lens" not in byrole else byrole["lens"][:n],
            None if "ev" not in byrole else byrole["ev"][:n]))
    return HostColumnarBatch(cols, n, cb.names)
