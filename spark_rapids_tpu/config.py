"""Typed, self-registering configuration system.

Mirrors the reference's ``RapidsConf.scala`` (sql-plugin/src/main/scala/com/
nvidia/spark/rapids/RapidsConf.scala:121 ConfEntry, :260 ConfBuilder, :319
registry): every config is a typed ``ConfEntry`` registered at import time in a
global registry, with startup/commonly-used/internal levels, and user docs
generated from the registry (reference generates docs/configs.md the same way).

Keys use the ``spark.rapids.*`` namespace for drop-in familiarity for users of
the reference plugin; TPU-specific keys live under ``spark.rapids.tpu.*``.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

__all__ = ["ConfEntry", "TpuConf", "registry", "generate_docs", "ConfLevel"]


class ConfLevel(enum.Enum):
    STARTUP = "startup"          # read once at plugin init
    COMMONLY_USED = "common"     # per-query tunables users touch
    INTERNAL = "internal"        # test/debug knobs


_REGISTRY: Dict[str, "ConfEntry"] = {}
_REGISTRY_LOCK = threading.Lock()


def registry() -> Dict[str, "ConfEntry"]:
    return dict(_REGISTRY)


@dataclasses.dataclass
class ConfEntry(Generic[T]):
    key: str
    doc: str
    default: T
    converter: Callable[[str], T]
    level: ConfLevel = ConfLevel.COMMONLY_USED
    checker: Optional[Callable[[T], bool]] = None

    def get(self, conf: "TpuConf") -> T:
        return conf.get(self.key)

    def __post_init__(self):
        with _REGISTRY_LOCK:
            if self.key in _REGISTRY:
                raise ValueError(f"duplicate conf key {self.key}")
            _REGISTRY[self.key] = self


def _to_bool(s: str) -> bool:
    if isinstance(s, bool):
        return s
    v = s.strip().lower()
    if v in ("true", "1", "yes", "on"):
        return True
    if v in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"not a boolean: {s!r}")


def _bytes_conv(s: str) -> int:
    """Parses byte sizes like '512m', '8g' (Spark-style suffixes)."""
    if isinstance(s, int):
        return s
    v = s.strip().lower()
    mult = 1
    for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30), ("t", 1 << 40)):
        if v.endswith(suffix + "b"):
            v, mult = v[:-2], m
            break
        if v.endswith(suffix):
            v, mult = v[:-1], m
            break
    if v.endswith("b"):
        v = v[:-1]
    return int(float(v) * mult)


def conf_bool(key, doc, default, level=ConfLevel.COMMONLY_USED) -> ConfEntry[bool]:
    return ConfEntry(key, doc, default, _to_bool, level)


def conf_int(key, doc, default, level=ConfLevel.COMMONLY_USED,
             checker=None) -> ConfEntry[int]:
    return ConfEntry(key, doc, default, int, level, checker)


def conf_float(key, doc, default, level=ConfLevel.COMMONLY_USED) -> ConfEntry[float]:
    return ConfEntry(key, doc, default, float, level)


def parse_bytes(s) -> int:
    """Public byte-size parser ("512m", "1g", plain ints)."""
    return _bytes_conv(str(s))


def conf_str(key, doc, default, level=ConfLevel.COMMONLY_USED,
             checker=None) -> ConfEntry[str]:
    return ConfEntry(key, doc, default, str, level, checker)


def conf_bytes(key, doc, default, level=ConfLevel.COMMONLY_USED,
               checker=None) -> ConfEntry[int]:
    return ConfEntry(key, doc, default, _bytes_conv, level, checker)


# ---------------------------------------------------------------------------
# Registered entries.  Counterparts cited to reference RapidsConf.scala keys.
# ---------------------------------------------------------------------------

SQL_ENABLED = conf_bool(
    "spark.rapids.sql.enabled",
    "Enable or disable TPU acceleration of SQL plans entirely.",
    True)

SQL_MODE = conf_str(
    "spark.rapids.sql.mode",
    "Operating mode: 'executeOnGPU' runs supported plans on the TPU; "
    "'explainOnly' plans and logs what would run on TPU but executes on CPU "
    "(reference RapidsConf 'spark.rapids.sql.mode').",
    "executeOnGPU")

EXPLAIN = conf_str(
    "spark.rapids.sql.explain",
    "What to log about plan placement: NONE, NOT_ON_GPU, ALL.",
    "NOT_ON_GPU")

TEST_ENABLED = conf_bool(
    "spark.rapids.sql.test.enabled",
    "Test mode: fail if any operator in the plan did not translate to the TPU "
    "(reference 'spark.rapids.sql.test.enabled').",
    False, ConfLevel.INTERNAL)

TEST_ALLOWED_NONGPU = conf_str(
    "spark.rapids.sql.test.allowedNonGpu",
    "Comma-separated exec class names allowed to stay on CPU in test mode.",
    "", ConfLevel.INTERNAL)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
INCOMPATIBLE_OPS = conf_bool(
    "spark.rapids.sql.incompatibleOps.enabled",
    "Enable operators whose TPU results can differ from CPU in documented "
    "ways (float ordering, regex dialect...). Reference "
    "'spark.rapids.sql.incompatibleOps.enabled'.",
    True)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
HAS_NANS = conf_bool(
    "spark.rapids.sql.hasNans",
    "Assume floating point data may contain NaN (affects agg/join tagging).",
    True)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
VARIABLE_FLOAT_AGG = conf_bool(
    "spark.rapids.sql.variableFloatAgg.enabled",
    "Allow float aggregations whose result can vary with evaluation order.",
    True)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
IMPROVED_FLOAT_OPS = conf_bool(
    "spark.rapids.sql.improvedFloatOps.enabled",
    "Use float paths faster than, but not bit-identical to, CPU.",
    True)

BATCH_SIZE_BYTES = conf_bytes(
    "spark.rapids.sql.batchSizeBytes",
    "Target output batch size in bytes (CoalesceGoal TargetSize; reference "
    "'spark.rapids.sql.batchSizeBytes' default 1g; TPU default smaller since "
    "HBM per chip is smaller).",
    512 << 20)

MAX_READER_BATCH_SIZE_ROWS = conf_int(
    "spark.rapids.sql.reader.batchSizeRows",
    "Max rows a file reader produces per batch.",
    1 << 20)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
MAX_READER_BATCH_SIZE_BYTES = conf_bytes(
    "spark.rapids.sql.reader.batchSizeBytes",
    "Soft max bytes a file reader produces per batch.",
    512 << 20)

CONCURRENT_TPU_TASKS = conf_int(
    "spark.rapids.sql.concurrentGpuTasks",
    "Number of tasks that may hold the device concurrently (TpuSemaphore; "
    "reference 'spark.rapids.sql.concurrentGpuTasks', RapidsConf.scala:544).",
    2)

TASK_PARALLELISM = conf_int(
    "spark.rapids.tpu.taskParallelism",
    "Task threads driving plan partitions concurrently (the executor-cores "
    "analog: host I/O and shuffle ser/deser overlap device dispatch, with "
    "device admission still bounded by concurrentGpuTasks). 0 = auto "
    "(min(4, cpu_count)); 1 = serial.",
    0)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
ROW_BUCKET_MIN = conf_int(
    "spark.rapids.tpu.batch.rowBucketMin",
    "Minimum padded row-count bucket for device batches. Device batches are "
    "padded to power-of-two row buckets so XLA compiles once per bucket "
    "rather than once per batch size (TPU-first static-shape discipline).",
    1 << 10, ConfLevel.STARTUP)

DEVICE_POOL_FRACTION = conf_float(
    "spark.rapids.memory.gpu.allocFraction",
    "Fraction of HBM to dedicate to the buffer pool at init "
    "(reference 'spark.rapids.memory.gpu.allocFraction').",
    0.8)

DEVICE_POOL_SIZE = conf_bytes(
    "spark.rapids.tpu.memory.pool.size",
    "Absolute device pool size override for tests; 0 = use allocFraction of "
    "detected HBM.",
    0, ConfLevel.INTERNAL)

HOST_SPILL_STORAGE_SIZE = conf_bytes(
    "spark.rapids.memory.host.spillStorageSize",
    "Bytes of host memory used to spill device buffers before disk "
    "(reference 'spark.rapids.memory.host.spillStorageSize').",
    1 << 30)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
PAGEABLE_POOL_SIZE = conf_bytes(
    "spark.rapids.memory.host.pageablePool.size",
    "Host allocation pool size.",
    1 << 30, ConfLevel.STARTUP)

MEMORY_ARBITRATION_ENABLED = conf_bool(
    "spark.rapids.memory.arbitration.enabled",
    "Cooperative memory arbitration (memory/arbiter.py): a registered "
    "task thread that cannot allocate BLOCKS until concurrent tasks "
    "release memory, and only a detected deadlock (every device-holding "
    "task blocked) wakes one victim with a forced Retry/SplitAndRetry "
    "OOM (reference: the RmmSpark/SparkResourceAdaptor thread-state "
    "machine).  Disabled, reserve() raises RetryOOM on first shortfall "
    "as before.",
    True)

MEMORY_ARBITRATION_MAX_BLOCK_MS = conf_int(
    "spark.rapids.memory.arbitration.maxBlockMs",
    "Liveness backstop: the longest ONE allocation park may wait before "
    "falling back to a plain RetryOOM toward the task's retry frame.  "
    "Validated > 0 at set_conf.",
    10_000,
    checker=lambda v: int(v) > 0)

WATCHDOG_ENABLED = conf_bool(
    "spark.rapids.watchdog.enabled",
    "Hung-query watchdog (memory/arbiter.py): a daemon observing "
    "per-task last-progress timestamps (task-runner heartbeats, spool "
    "progress, alloc/semaphore wait entries).  A task with no progress "
    "for timeoutMs gets a full thread-state + holder-stack dump "
    "(watchdogDump event), then a forced arbitration round, then "
    "cancellation — surfacing as a retryable TaskCancelled the "
    "task-retry/circuit-breaker machinery re-executes or degrades.",
    False)

WATCHDOG_TIMEOUT_MS = conf_int(
    "spark.rapids.watchdog.timeoutMs",
    "Per-task no-progress budget before the watchdog dumps and "
    "escalates.  Validated > 0 at set_conf.",
    60_000,
    checker=lambda v: int(v) > 0)

WATCHDOG_POLL_MS = conf_int(
    "spark.rapids.watchdog.pollMs",
    "Watchdog sweep interval.  Validated > 0 at set_conf.",
    100,
    checker=lambda v: int(v) > 0)

OOM_INJECTION_MODE = conf_str(
    "spark.rapids.sql.test.injectRetryOOM",
    "Deterministic OOM fault injection for tests: 'false', 'true' (first "
    "alloc of each task), or '<n>' to fault the n-th tracked allocation "
    "(reference RapidsConf.scala:1541 TEST_RETRY_OOM_INJECTION_MODE).",
    "false", ConfLevel.INTERNAL)

FORCE_MERGE_REPARTITION_DEPTH = conf_int(
    "spark.rapids.sql.test.agg.forceMergeRepartitionDepth",
    "Test hook: force the aggregate merge's hash re-partition fallback "
    "while recursion depth < N (0 = only under real pressure; reference "
    "pattern: the spark.rapids.sql.test.* fault knobs).",
    0, ConfLevel.INTERNAL)

FORCE_OOC_SORT = conf_bool(
    "spark.rapids.sql.test.sort.forceOutOfCore",
    "Test hook: force the external (sorted-runs + merge) sort path "
    "regardless of memory pressure.",
    False, ConfLevel.INTERNAL)

FORCE_RUNNING_WINDOW = conf_bool(
    "spark.rapids.sql.test.window.forceRunning",
    "Test hook: force the batched running-window path for eligible specs "
    "regardless of memory pressure.",
    False, ConfLevel.INTERNAL)

FORCE_BOUNDED_WINDOW = conf_bool(
    "spark.rapids.sql.test.window.forceBoundedBatched",
    "Test hook: force the chunked bounded-frame window path (tail-carry "
    "between batches) regardless of memory pressure.",
    False, ConfLevel.INTERNAL)

BOUNDED_WINDOW_MAX_SPAN = conf_int(
    "spark.rapids.sql.window.batched.bounded.rowLimit",
    "Largest preceding+following ROWS span the chunked bounded-window "
    "path carries between batches; wider frames concatenate the whole "
    "partition (reference: spark.rapids.sql.window.batched.bounded."
    "row.max).",
    4096, ConfLevel.COMMONLY_USED)

JOIN_BUILD_SWAP_ENABLED = conf_bool(
    "spark.rapids.sql.join.buildSideSwap.enabled",
    "Runtime build-side choice for inner equi-joins: build on the "
    "smaller side regardless of SQL order (reference: "
    "GpuShuffledHashJoinExec build-side selection).",
    True)

JOIN_BUILD_SWAP_MAX_BYTES = conf_bytes(
    "spark.rapids.sql.join.buildSideSwap.maxBuildBytes",
    "Largest build side for which the swap comparison materializes the "
    "probe partition; above it the probe streams unswapped.",
    "256m")

SPECULATIVE_SIZING_ENABLED = conf_bool(
    "spark.rapids.sql.join.speculativeSizing.enabled",
    "Size join pair tables optimistically by the probe bucket and check "
    "overflow flags at the collect sync (replay exact on overflow) "
    "instead of paying a device round trip per join.",
    True)

SHUFFLE_DEVICE_SHRINK_THRESHOLD = conf_bytes(
    "spark.rapids.shuffle.deviceStore.shrinkThresholdBytes",
    "Map batches whose reduce-fanout-multiplied padded footprint exceeds "
    "this are padding-shrunk (costs one count sync) before the "
    "per-partition compacts of the device-resident shuffle store.",
    "64m")

DOWNLOAD_SPECULATIVE_ROWS = conf_int(
    "spark.rapids.sql.collect.speculativeRows",
    "Row cap for single-round-trip result downloads while the row count "
    "is still deferred; larger results pay one extra round trip.  "
    "Applies to the result-download path (the device->host plan "
    "boundary and host-staged shuffle downloads); internal spill/"
    "sampling downloads keep the built-in default.  Validated >= 1 at "
    "set_conf.",
    8192,
    checker=lambda v: int(v) >= 1)

CTE_REUSE_ENABLED = conf_bool(
    "spark.rapids.sql.cteReuse.enabled",
    "Materialize a CTE referenced more than once exactly once and share "
    "the batches (Spark WithCTE/ReusedExchange analog).",
    True)

RANGE_BOUNDS_SAMPLE_ROWS = conf_int(
    "spark.rapids.sql.rangePartitioning.sampleRowsPerBatch",
    "Rows sampled per input batch (device-gathered, one download total) "
    "when computing range-partition bounds.",
    1024)

COLLECT_AGG_ENABLED = conf_bool(
    "spark.rapids.sql.agg.collectOnDevice.enabled",
    "Device collect_list/collect_set/count-distinct sets via padded "
    "[group, max_len] array planes (COMPLETE mode, fixed-width values); "
    "disabled falls back to the host collect tier.",
    True)

LIMIT_DEFERRED_FORCE_INTERVAL = conf_int(
    "spark.rapids.sql.limit.deferredForceInterval",
    "Deferred-count limit budget is forced to host every N batches so a "
    "satisfied limit stops pulling its child (amortized early exit).  "
    "Validated >= 1 at set_conf.",
    8,
    checker=lambda v: int(v) >= 1)

COLLECTIVE_EXCHANGE_ENABLED = conf_bool(
    "spark.rapids.shuffle.collective.enabled",
    "Mesh shuffles lower to ONE fused ICI all-to-all when the reduce "
    "count matches the device count (multi-chip path).",
    True)

DISTRIBUTION_ENABLED = conf_bool(
    "spark.rapids.sql.distribution.enabled",
    "Partition-aware planning: propagate delivered distributions "
    "(hash/range/single, with a mesh-axis binding) through the plan and "
    "ELIDE every shuffle exchange whose child is already partitioned as "
    "required — co-partitioned joins and aggregates above joins skip "
    "their re-shuffle entirely (plan/distribution.py; the "
    "EnsureRequirements dual).  Disabled reproduces the eager-exchange "
    "plans exactly.",
    True)


def _mesh_shape_ok(v: str) -> bool:
    # THE parser (parallel/mesh.py) is the one validity definition; the
    # checker just runs it so set_conf and session init cannot diverge
    from spark_rapids_tpu.parallel.mesh import parse_mesh_shape
    try:
        parse_mesh_shape(v)
        return True
    except ValueError:
        return False


def _mesh_axes_ok(v: str) -> bool:
    from spark_rapids_tpu.parallel.mesh import parse_mesh_axes
    try:
        parse_mesh_axes(v)
        return True
    except ValueError:
        return False


MESH_ENABLED = conf_bool(
    "spark.rapids.mesh.enabled",
    "Build and activate the device mesh from spark.rapids.mesh.* at "
    "session init (parallel/mesh.py); shuffle exchanges then lower to "
    "the in-mesh ICI path where eligible.  Off leaves mesh activation "
    "to explicit set_active_mesh() calls.",
    False)

MESH_SHAPE = conf_str(
    "spark.rapids.mesh.shape",
    "Mesh shape as comma-separated positive extents (e.g. '8' or '2,4'); "
    "empty uses all visible devices in one data-parallel dimension.  The "
    "product must divide the visible device count — validated at "
    "set_conf/session init, not at the first collective.",
    "", checker=_mesh_shape_ok)

MESH_AXES = conf_str(
    "spark.rapids.mesh.axes",
    "Comma-separated mesh axis names, one per shape dimension, "
    "non-empty and unique; the FIRST axis is the data axis partition "
    "parallelism shards over (the NamedSharding binding the planner's "
    "distribution pass records).",
    "data", checker=_mesh_axes_ok)

SCAN_CACHE_ENABLED = conf_bool(
    "spark.rapids.sql.scanCache.enabled",
    "Keep decoded (host) and uploaded (device) scan batches resident for "
    "repeated queries over static files (the file-cache + device-resident "
    "catalog analog, filecache.scala).  Unbounded residency: intended for "
    "benchmark/repeat-query sessions.  Process-sticky once enabled "
    "(interleaved default-conf sessions do not clear it); release with "
    "io.multifile.enable_scan_cache(False).",
    False)

SPILL_TO_DISK_DIR = conf_str(
    "spark.rapids.tpu.spill.dir",
    "Directory for the disk tier of the buffer catalog.",
    "", ConfLevel.STARTUP)

SHUFFLE_MANAGER_MODE = conf_str(
    "spark.rapids.shuffle.mode",
    "Shuffle mode: DEFAULT (in-memory host store) | MULTITHREADED "
    "(pooled writer/reader over spill files) | CACHED (alias CACHE_ONLY: "
    "buffer catalog + client/server transport) "
    "(reference RapidsShuffleManagerMode UCX|CACHE_ONLY|MULTITHREADED).",
    "DEFAULT")

SHUFFLE_WRITER_THREADS = conf_int(
    "spark.rapids.shuffle.multiThreaded.writer.threads",
    "Thread pool size for multithreaded shuffle writes.",
    8)

SHUFFLE_READER_THREADS = conf_int(
    "spark.rapids.shuffle.multiThreaded.reader.threads",
    "Thread pool size for multithreaded shuffle reads.",
    8)

def _chaos_spec_ok(v) -> bool:
    from spark_rapids_tpu.aux.faults import chaos_spec_ok
    return chaos_spec_ok(v)


SHUFFLE_TRANSPORT_TIMEOUT_MS = conf_int(
    "spark.rapids.shuffle.transport.timeoutMs",
    "Default bound for otherwise-unbounded transport waits: "
    "Transaction.wait(None) and bounce-buffer acquire(None) resolve to "
    "this, so a dead peer surfaces as a retryable TimeoutError through "
    "the fetch-retry policy instead of pinning a sender thread forever.  "
    "Validated > 0 at set_conf.",
    120_000,
    checker=lambda v: int(v) > 0)

SHUFFLE_FETCH_TIMEOUT_MS = conf_int(
    "spark.rapids.shuffle.fetch.timeoutMs",
    "Per-attempt wait for in-flight shuffle data frames after a transfer "
    "ack (replaces the old hardcoded 30s client timeout; validated > 0 at "
    "set_conf).",
    30_000,
    checker=lambda v: int(v) > 0)

SHUFFLE_FETCH_MAX_RETRIES = conf_int(
    "spark.rapids.shuffle.fetch.maxRetries",
    "Fetch attempts per peer beyond the first before giving up on that "
    "peer (then failing over to an alternate replica if one is known; "
    "reference: lost UCX peers surface as fetch failures -> retry).",
    3,
    checker=lambda v: int(v) >= 0)

SHUFFLE_FETCH_RETRY_WAIT_MS = conf_int(
    "spark.rapids.shuffle.fetch.retryWaitMs",
    "Base backoff between fetch retries; doubles per attempt with "
    "deterministic jitter, capped at retryMaxWaitMs.",
    50,
    checker=lambda v: int(v) >= 0)

SHUFFLE_FETCH_RETRY_MAX_WAIT_MS = conf_int(
    "spark.rapids.shuffle.fetch.retryMaxWaitMs",
    "Backoff ceiling for fetch retries.",
    2_000,
    checker=lambda v: int(v) >= 0)

TASK_MAX_FAILURES = conf_int(
    "spark.rapids.task.maxFailures",
    "Attempts per task before its failure propagates (the "
    "spark.task.maxFailures analog).  Only failures that strike BEFORE a "
    "task yields output are retried — a partially-consumed task cannot "
    "re-run without duplicating rows.",
    2,
    checker=lambda v: int(v) >= 1)

TASK_BREAKER_THRESHOLD = conf_int(
    "spark.rapids.task.breaker.threshold",
    "Task failures within one stage that trip the circuit breaker: the "
    "rest of the stage degrades to single-threaded inline execution "
    "instead of failing the query.  0 disables the breaker.",
    3,
    checker=lambda v: int(v) >= 0)

CHAOS_SHUFFLE_FETCH = conf_str(
    "spark.rapids.chaos.shuffle.fetch",
    "Deterministic fault injection at the shuffle-fetch point: 'n' or "
    "'n:skip' raises ConnectionError on the n triggers after skipping "
    "skip (generalizes spark.rapids.sql.test.injectRetryOOM to the "
    "shuffle layer; empty disables).",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_SHUFFLE_SEND = conf_str(
    "spark.rapids.chaos.shuffle.send",
    "Fault injection at the server block-send point ('n' or 'n:skip').",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_SHUFFLE_CONNECT = conf_str(
    "spark.rapids.chaos.shuffle.connect",
    "Fault injection at transport connection setup ('n' or 'n:skip').",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_TASK_RUN = conf_str(
    "spark.rapids.chaos.task.run",
    "Fault injection at task start in the parallel runner ('n' or "
    "'n:skip'); exercises task-level retry + the stage circuit breaker.",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_PARALLEL_COLLECTIVE = conf_str(
    "spark.rapids.chaos.parallel.collective",
    "Fault injection at the mesh collective shuffle ('n' or 'n:skip'); "
    "exercises the fallback to the host-staged exchange path.",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

PIPELINE_ENABLED = conf_bool(
    "spark.rapids.pipeline.enabled",
    "Pipelined execution: the planner inserts bounded-depth, thread-backed "
    "prefetch boundaries (exec/pipeline.py) so host decode, host<->device "
    "transfer and TPU compute overlap instead of serializing per batch.",
    True)

PIPELINE_DEPTH = conf_int(
    "spark.rapids.pipeline.depth",
    "Batches buffered per pipeline boundary (the prefetch spool's queue "
    "depth).  Validated >= 1 at set_conf.",
    2,
    checker=lambda v: int(v) >= 1)

PIPELINE_MAX_IN_FLIGHT_BYTES = conf_bytes(
    "spark.rapids.pipeline.maxInFlightBytes",
    "Byte budget for in-flight prefetched batches per boundary; a "
    "producer blocks (releasing device admission) once queued bytes "
    "exceed it.  Queued device batches also register with the spill "
    "framework, so they count against — and can be evicted from — the "
    "device-store budget.",
    "256m",
    checker=lambda v: int(v) >= 1)

CHAOS_PIPELINE_PREFETCH = conf_str(
    "spark.rapids.chaos.pipeline.prefetch",
    "Fault injection at prefetch-spool start ('n' or 'n:skip'); exercises "
    "producer-thread failure re-raise at the consumer and the task-retry "
    "recovery path over pipelined plans.",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_MEMORY_ALLOC = conf_str(
    "spark.rapids.chaos.memory.alloc",
    "Fault injection at tracked allocation points: raises RetryOOM "
    "through the shared chaos mechanism ('n' or 'n:skip'); the thread-"
    "scoped spark.rapids.sql.test.injectRetryOOM remains for framed "
    "per-task injection.",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_MEMORY_BLOCK = conf_str(
    "spark.rapids.chaos.memory.block",
    "Fault injection at the allocation admission point ('n' or "
    "'n:skip'): an injected NEVER-RELEASING allocation hold — the task "
    "parks arbitration-immune until the hung-query watchdog dumps, "
    "escalates and cancels it.  Exercises the hang-detection path "
    "deterministically.",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

CHAOS_WATCHDOG_SWEEP = conf_str(
    "spark.rapids.chaos.watchdog.sweep",
    "Fault injection inside the watchdog's sweep loop ('n' or "
    "'n:skip'); exercises the daemon's survive-a-bad-sweep discipline.",
    "", ConfLevel.INTERNAL,
    checker=_chaos_spec_ok)

SHUFFLE_COMPRESSION_CODEC = conf_str(
    "spark.rapids.shuffle.compression.codec",
    "Codec for shuffle payloads: none | lz4 | zlib (reference nvcomp "
    "LZ4/ZSTD; here the libtpucol LZ4 block codec or zlib).",
    "lz4")

RANGES_ENABLED = conf_bool(
    "spark.rapids.sql.nvtx.enabled",
    "Annotate operator ranges into the active profiler trace "
    "(reference: NVTX ranges, NvtxWithMetrics.scala).",
    False)

JOIN_SUBPARTITION_THRESHOLD = conf_bytes(
    "spark.rapids.sql.join.subPartitionThresholdBytes",
    "Build sides larger than this re-partition into hash buckets joined "
    "independently (reference: GpuSubPartitionHashJoin.scala).",
    "1g")

JOIN_NUM_SUBPARTITIONS = conf_int(
    "spark.rapids.sql.join.numSubPartitions",
    "Bucket count for oversized-join sub-partitioning.",
    16)

EXCHANGE_REUSE_ENABLED = conf_bool(
    "spark.sql.exchange.reuse",
    "Collapse structurally identical exchange subtrees to one instance "
    "so repeated subquery pipelines shuffle once (Spark's ReuseExchange; "
    "the reference re-tags reused exchanges in updateForAdaptivePlan, "
    "GpuOverrides.scala:4589).",
    True)

ADAPTIVE_COALESCE_ENABLED = conf_bool(
    "spark.sql.adaptive.coalescePartitions.enabled",
    "Post-shuffle adaptive partition coalescing from materialized sizes "
    "(reference: GpuCustomShuffleReaderExec consuming AQE specs).",
    True)

ADVISORY_PARTITION_BYTES = conf_bytes(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes",
    "Target size for adaptive partition coalescing.",
    "64m")

ADAPTIVE_MESH_ALIGN = conf_bool(
    "spark.rapids.sql.adaptive.meshAlign",
    "With an active device mesh, adaptive coalescing picks partition "
    "counts that are MULTIPLES of the mesh size (balanced contiguous "
    "merge), so post-AQE stages keep an even device mapping and later "
    "exchanges stay eligible for the in-mesh ICI path.",
    True)

FILECACHE_ENABLED = conf_bool(
    "spark.rapids.filecache.enabled",
    "Cache remote file ranges on local disk (reference: the closed-source "
    "FileCache reimplemented open, SURVEY.md §2.7).",
    False)

FILECACHE_MAX_BYTES = conf_bytes(
    "spark.rapids.filecache.maxBytes",
    "Local disk budget for the file cache.",
    "1g", ConfLevel.STARTUP)

METRICS_LEVEL = conf_str(
    "spark.rapids.sql.metrics.level",
    "Metric verbosity: ESSENTIAL | MODERATE | DEBUG (reference GpuExec.scala:36).",
    "MODERATE",
    checker=lambda v: str(v).strip().upper() in ("ESSENTIAL", "MODERATE",
                                                 "DEBUG"))

TRACING_ENABLED = conf_bool(
    "spark.rapids.tpu.tracing.enabled",
    "Wrap every DataFrame action in a QueryExecution trace: a span tree "
    "mirroring the physical plan that funnels operator metrics, task "
    "metrics and spill/retry/semaphore/shuffle events into one query "
    "summary (explain(analyze=True), event log, bench attribution).",
    True)

TRANSITIONS_ENABLED = conf_bool(
    "spark.rapids.sql.transitions.enabled",
    "Host-transition & device-sync ledger (aux/transitions.py): time and "
    "count every H2D upload, D2H download and blocking device sync "
    "through the instrumented gateway, aggregated per query into the "
    "summary/explain(analyze=True) ledger and the transitions/sync "
    "buckets of tools profile.  Off = wrappers degrade to the raw "
    "operations (results are bit-identical either way).",
    True)

TRANSITIONS_EVENTS = conf_bool(
    "spark.rapids.sql.transitions.events",
    "Emit one hostTransition/deviceSync event per boundary crossing "
    "(schema v4) into the event bus for timeline tools (tools trace).  "
    "Requires spark.rapids.sql.transitions.enabled; off keeps the "
    "aggregate ledger but skips per-crossing events on hot paths.",
    True)

EVENT_LOG_PATH = conf_str(
    "spark.rapids.sql.eventLog.path",
    "When set, every traced query appends its events to this JSONL file "
    "(Spark event-log analog): one JSON object per line carrying the "
    "event kind, query_id, span_id and a monotonic timestamp.",
    "")

EVENT_LOG_MAX_BYTES = conf_bytes(
    "spark.rapids.sql.eventLog.maxBytes",
    "Size-based event-log rotation: once the JSONL file crosses this many "
    "bytes it renames to <path>.N (N increasing, oldest smallest) and a "
    "fresh file (with a schema-version header) takes its place; the "
    "offline profiler reads the rotated set in order.  0 = never rotate.",
    0,
    checker=lambda v: int(v) >= 0)

EVENT_LOG_COMPRESS = conf_bool(
    "spark.rapids.sql.eventLog.compress",
    "Gzip-compress the event log: each write batch lands as one complete "
    "gzip member, preserving line atomicity; readers sniff the gzip magic "
    "(no extension requirement).  Do not mix compressed and plain sinks "
    "on one path.",
    False)

SAMPLE_ENABLED = conf_bool(
    "spark.rapids.sample.enabled",
    "Background resource sampler (aux/sampler.py): a low-overhead daemon "
    "thread periodically emits resourceSample events (memory pool "
    "used/watermark, spillable bytes, semaphore holders/waiters, prefetch "
    "spool depth, active tasks) into the event bus so offline timelines "
    "have a continuous signal between query events (reference: the "
    "always-on ProfilerOnExecutor).",
    False)

SAMPLE_INTERVAL_MS = conf_int(
    "spark.rapids.sample.intervalMs",
    "Milliseconds between resource samples.  Validated > 0 at set_conf.",
    100,
    checker=lambda v: int(v) > 0)

EVENT_LOG_RING_SIZE = conf_int(
    "spark.rapids.sql.eventLog.ringBufferSize",
    "Events retained per query in the in-memory ring buffer (the "
    "test/introspection sink); older events beyond it drop and the drop "
    "count is reported in the query summary.",
    2048)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
STABLE_SORT = conf_bool(
    "spark.rapids.sql.stableSort.enabled",
    "Force stable full sorts (disables some out-of-core optimizations).",
    False)

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
ENABLE_FLOAT_CAST_STRING = conf_bool(
    "spark.rapids.sql.castFloatToString.enabled",
    "Enable float->string casts (formatting can differ from CPU in last ulp).",
    True)

ENABLE_REGEX = conf_bool(
    "spark.rapids.sql.regexp.enabled",
    "Enable regular expression acceleration via the transpiler "
    "(reference 'spark.rapids.sql.regexp.enabled').",
    True)

MULTITHREADED_READ_NUM_THREADS = conf_int(
    "spark.rapids.sql.multiThreadedRead.numThreads",
    "Thread pool size for MULTITHREADED file readers.",
    8)

READER_TYPE = conf_str(
    "spark.rapids.sql.format.parquet.reader.type",
    "Parquet reader strategy: AUTO | PERFILE | COALESCING | MULTITHREADED "
    "(reference RapidsConf.scala:314 RapidsReaderType).",
    "AUTO")

CSV_READER_TYPE = conf_str(
    "spark.rapids.sql.format.csv.reader.type",
    "CSV reader strategy (same values as the parquet key).",
    "AUTO")

JSON_READER_TYPE = conf_str(
    "spark.rapids.sql.format.json.reader.type",
    "JSON reader strategy (same values as the parquet key).",
    "AUTO")

ORC_READER_TYPE = conf_str(
    "spark.rapids.sql.format.orc.reader.type",
    "ORC reader strategy (same values as the parquet key).",
    "AUTO")

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
AVRO_READER_TYPE = conf_str(
    "spark.rapids.sql.format.avro.reader.type",
    "Avro reader strategy (same values as the parquet key).",
    "AUTO")

# lint: ok=conf-registry -- reference-compat key, reserved (not yet wired)
DEVICE_STRING_MAX_LEN = conf_int(
    "spark.rapids.tpu.string.maxDeviceLen",
    "Strings longer than this stay on the host tier (device strings are "
    "padded [rows, max_len] uint8; padding cost grows with max length).",
    256)

DEBUG_LOCK_ORDER = conf_bool(
    "spark.rapids.debug.lockOrder",
    "Arm the runtime lock-order validator (aux/lockorder.py): the "
    "catalog/arbiter/semaphore/spool locks record every (held -> "
    "acquiring) edge per thread and check it against the canonical "
    "acquisition order the static lint rule enforces "
    "(spool < catalog < semaphore < arbiter); a backward edge counts in "
    "lock_order_violations_total and emits a lockOrderViolation event.  "
    "Debug/test knob: adds one flag read per lock acquire when off.",
    False, ConfLevel.INTERNAL)

DEBUG_PLAN_CHECK = conf_bool(
    "spark.rapids.debug.planCheck",
    "Arm the runtime plan-invariant verifier (plan/verify.py): every "
    "post-optimization physical plan is walked against the structural "
    "contracts the planner passes establish — encoding materialize "
    "boundaries, prefetch-node placement, spillable registration of "
    "queued batches, exchange-reuse key consistency.  A violation "
    "counts in plan_invariant_violations_total and emits a "
    "planInvariantViolation event (mirroring spark.rapids.debug."
    "lockOrder).  Debug/test knob: adds one plan walk per action when "
    "on.",
    False, ConfLevel.INTERNAL)

AUDIT_LEDGER = conf_bool(
    "spark.rapids.audit.ledger",
    "Record a per-program audit ledger row (stageProgram event) every "
    "time the stage compiler builds an executable: the closed jaxpr's "
    "structural signatures, primitive set, const shapes/fingerprints "
    "(never buffers), arg signature, cost-analysis flops/bytes and "
    "cache-key provenance — the input of the offline compiled-program "
    "auditor (python -m spark_rapids_tpu.tools audit, docs/audit.md).  "
    "Rows are recorded only while a sink that will store them is live "
    "(an eventLog.path file sink or a global sink): the analysis costs "
    "a few ms per BUILD, and a row that would die in the per-query "
    "ring buffer is not worth it.  Steady-state dispatch is untouched.",
    True)

RMM_DEBUG = conf_bool(
    "spark.rapids.memory.gpu.debug",
    "Log every pool allocation/free (reference RapidsConf.scala:375).",
    False, ConfLevel.INTERNAL)

COMPILE_CACHE_DIR = conf_str(
    "spark.rapids.sql.compile.cacheDir",
    "Directory for the persistent (on-disk) XLA compilation cache: "
    "compiled stage executables survive across queries AND sessions, so "
    "a restarted process re-traces (cheap) but never re-compiles a "
    "known program (expensive — tens of seconds per program on a "
    "tunnel-attached TPU).  Empty (the default) never enables the disk "
    "tier; the in-process executable cache is always on.  The setting is "
    "enable-only per process: an already-enabled tier stays on even if a "
    "later session leaves this empty (interleaved default-conf sessions "
    "must not drop it) — disable explicitly via "
    "exec.stage_compiler.set_persistent_cache_dir('').",
    "")

COMPILE_ASYNC = conf_bool(
    "spark.rapids.sql.compile.async",
    "Background stage compilation: a cache-missing stage program lowers "
    "and compiles on a daemon pool thread while the consumer overlaps "
    "the previous batch's compute (the fused stage exec runs a "
    "one-batch look-ahead), so first-batch compile latency stops "
    "stalling the pipeline.",
    False)

COMPILE_MAX_PROGRAMS = conf_int(
    "spark.rapids.sql.compile.maxPrograms",
    "Bound on the process-wide executable cache (exec/stage_compiler): "
    "least-recently-used programs beyond it are dropped (and recompile "
    "on next use — or reload from compile.cacheDir when set).  "
    "Validated >= 1 at set_conf.",
    4096,
    checker=lambda v: int(v) >= 1)

COMPILE_LITERAL_PROMOTION = conf_bool(
    "spark.rapids.sql.compile.literalPromotion",
    "Promote scalar literals in fused-stage filters/projections to "
    "runtime arguments of the compiled program, so plans differing only "
    "in literal values (dates, thresholds, year filters) share ONE "
    "executable instead of compiling per value — bounds compile-cache "
    "key cardinality for templated/parameterized query workloads.",
    True)

STAGE_FUSION_ENABLED = conf_bool(
    "spark.rapids.sql.compile.stageFusion.enabled",
    "Whole-stage fusion planner pass (plan/stages.py): collapse maximal "
    "device operator pipelines (filter/project chains, hash-agg update "
    "and merge/final passes) into single compiled XLA programs.  "
    "Disabling falls back to per-operator dispatch (differential-test "
    "hook; large end-to-end slowdown).",
    True)

CBO_ENABLED = conf_bool(
    "spark.rapids.sql.optimizer.enabled",
    "Enable the transition cost-based optimizer (reference CostBasedOptimizer.scala).",
    False)

ENCODING_ENABLED = conf_bool(
    "spark.rapids.sql.encoding.enabled",
    "Encoded columnar execution (columnar/encoding.py): parquet scans "
    "keep dictionary pages encoded, batches ship int codes + a "
    "once-per-fingerprint dictionary to the device, fused filters "
    "evaluate code-space lookup tables, hash-agg group keys and join "
    "keys hash the codes when dictionaries match, and sorts ride the "
    "codes of value-sorted dictionaries.  Every unsupported shape "
    "falls back per column to eager decode; disabling reproduces the "
    "plain (decode-at-scan) path exactly.",
    True)

ENCODING_LATE_MAT = conf_bool(
    "spark.rapids.sql.encoding.lateMaterialization",
    "Defer dictionary decode past filters: encoded columns survive the "
    "fused filter/project chain as compacted code planes and only "
    "SURVIVING rows gather through the dictionary where an operator "
    "needs values.  Disabling inserts an explicit materialize node "
    "above encoded scans (plan/encoding.py), keeping the H2D savings "
    "but decoding before any operator runs.",
    True)

ENCODING_MAX_DICT_SIZE = conf_int(
    "spark.rapids.sql.encoding.maxDictionarySize",
    "Dictionaries larger than this many values fall back to eager "
    "decode at upload (high-cardinality columns gain little from "
    "code-space execution and their lookup tables stop fitting the "
    "compile-friendly pow2 buckets).  Validated >= 1 at set_conf.",
    1 << 16,
    checker=lambda v: int(v) >= 1)

ENCODING_RLE_ENABLED = conf_bool(
    "spark.rapids.sql.encoding.rle.enabled",
    "Opportunistic run-length encoding at upload: fixed-width host "
    "columns whose run count is at most rows/8 ship run values + run "
    "ends instead of row planes and expand in-trace inside fused "
    "stages.  Off by default (run detection costs a host pass per "
    "uploaded column).",
    False)

SPILL_CODEC = conf_str(
    "spark.rapids.memory.spill.codec",
    "Codec for host->disk spill files: none | lz4 | zlib (the shuffle "
    "serializer's frame format; reference nvcomp-compressed spill).  "
    "Compressed spill multiplies effective spill capacity under the "
    "same disk budget; spill events and pool stats report the actual "
    "on-disk (compressed) bytes plus the logical bytes.",
    "lz4",
    checker=lambda v: str(v).strip().lower() in ("none", "", "lz4",
                                                 "zlib"))

COLUMN_PRUNING_ENABLED = conf_bool(
    "spark.rapids.sql.columnPruning.enabled",
    "Prune unused columns at scans before plan rewrite (Spark performs this "
    "in its logical optimizer; this engine plans physical trees directly). "
    "On TPU every pruned column is a host->device transfer avoided.",
    True)

# ---------------------------------------------------------------------------
# concurrent query serving (spark_rapids_tpu/serving)
# ---------------------------------------------------------------------------

SERVING_MAX_CONCURRENT = conf_int(
    "spark.rapids.serving.maxConcurrentQueries",
    "Queries the QueryServer executes concurrently; submissions past "
    "this wait in the admission queue.  The per-query device working "
    "sets still arbitrate through the shared pool + TpuSemaphore "
    "budgets — this bounds QUERY-level concurrency, the semaphore "
    "bounds TASK-level device concurrency.  Validated >= 1 at set_conf.",
    4,
    checker=lambda v: int(v) >= 1)

SERVING_MEMORY_RESERVATION = conf_bytes(
    "spark.rapids.serving.queryMemoryReservation",
    "Device-pool bytes the admission controller reserves per admitted "
    "query (Sparkle-style static memory partitioning of the shared "
    "pool): a query is only admitted while the sum of reservations "
    "fits the pool limit.  0 = pool limit / maxConcurrentQueries.  "
    "Reservations are admission-time accounting, not allocations — the "
    "arbiter still resolves real contention inside the pool.",
    "0")

SERVING_QUEUE_TIMEOUT_MS = conf_int(
    "spark.rapids.serving.queueTimeoutMs",
    "How long a submission may wait in the admission queue before "
    "failing with AdmissionTimeout (a bounded queue sheds load instead "
    "of stacking it).  Validated >= 1 at set_conf.",
    60_000,
    checker=lambda v: int(v) >= 1)

SERVING_QUEUE_BACKOFF_MS = conf_int(
    "spark.rapids.serving.queueBackoffMs",
    "Initial re-check backoff for a queued submission; doubles up to "
    "32x between admission re-checks (release notifications short-cut "
    "the wait).  Validated >= 1 at set_conf.",
    20,
    checker=lambda v: int(v) >= 1)

SERVING_PLAN_CACHE_MAX = conf_int(
    "spark.rapids.serving.planCache.maxPlans",
    "Physical plans the cross-query plan cache keeps (LRU).  Keyed by "
    "the normalized plan structure — literal-promoted queries share an "
    "entry and its compiled-executable set — plus the literal values; "
    "an exact repeat skips planning AND compilation.  0 disables.",
    64,
    checker=lambda v: int(v) >= 0)

SERVING_PLAN_CACHE_MAX_BYTES = conf_bytes(
    "spark.rapids.serving.planCache.maxBytes",
    "Byte budget for the physical plans the cross-query plan cache "
    "retains (estimated per-variant from the plan tree; compiled "
    "executables are process-wide jit caches and are not counted).  "
    "Acts alongside the planCache.maxPlans count bound — whichever "
    "trips first evicts LRU non-leased variants, counted in the "
    "cache's evictions stat and visible on the console /server "
    "endpoint.  0 = unbounded (count bound only).",
    "0")

SERVING_RESULT_CACHE_MAX_BYTES = conf_bytes(
    "spark.rapids.serving.resultCache.maxBytes",
    "In-memory budget for the deterministic query/CTE result cache "
    "(keyed by exact plan signature + input-file fingerprints; any "
    "file change invalidates).  Under pressure entries spill to an "
    "on-disk arrow tier (resultCache.spill) bounded at 4x this.  "
    "0 disables.",
    "256m")

SERVING_RESULT_CACHE_SPILL = conf_bool(
    "spark.rapids.serving.resultCache.spill",
    "Spill result-cache entries to an on-disk arrow tier instead of "
    "dropping them when the in-memory budget is exceeded.",
    True)

SERVING_AUTOTUNE_ENABLED = conf_bool(
    "spark.rapids.serving.autotune.enabled",
    "Close the AutoTuner into an online loop: after each query the "
    "server evaluates the rule set (tools/autotune.py) over the live "
    "event stream + resourceSample feed and applies accepted conf "
    "deltas (pipeline depth, concurrentGpuTasks, batch size) to the "
    "NEXT admitted query, emitting an autotuneApplied event per delta.",
    False)


# ---------------------------------------------------------------------------
# cross-run metrics warehouse + calibrated cost model (tools/history)
# ---------------------------------------------------------------------------

HISTORY_PATH = conf_str(
    "spark.rapids.history.path",
    "Path of the persistent cross-run history warehouse (SQLite). When "
    "set, bench.py auto-ingests each run's payload and event log after "
    "the benchmark completes, so `tools history regress|calibrate` "
    "accumulate a baseline without manual ingestion. Empty disables. "
    "Reference: the spark-rapids-tools Qualification/Profiling store "
    "over Spark event logs.",
    "")

HISTORY_MACHINE_PROFILE_PATH = conf_str(
    "spark.rapids.history.machineProfilePath",
    "Path of a machine-profile JSON artifact written by `tools history "
    "calibrate`. When set (and costModel.enabled), df.explain() renders "
    "a report-only `== Cost ==` section with per-operator predicted "
    "cost from the calibrated profile, and each query's end-of-run "
    "summary cross-checks prediction vs measured per-stage time "
    "(queryEnd `cost` block + a costModel event for `tools audit`). "
    "Never changes plans or results. Empty disables.",
    "")

HISTORY_COST_MODEL_ENABLED = conf_bool(
    "spark.rapids.history.costModel.enabled",
    "Master switch for the report-only predicted-cost annotation layer "
    "(the `== Cost ==` explain section and the post-run predicted-vs-"
    "measured cross-check). Only meaningful when machineProfilePath is "
    "set; leaves query execution and results bit-identical either way.",
    True)

HISTORY_REGRESS_MIN_RUNS = conf_int(
    "spark.rapids.history.regress.minRuns",
    "Baseline runs `tools history regress` requires per query/metric "
    "before trusting a verdict; with fewer samples the metric is "
    "skipped (reported, never failed). Guards cold warehouses from "
    "judging against noise.",
    3,
    checker=lambda v: v >= 1)

HISTORY_REGRESS_MAD_BANDS = conf_float(
    "spark.rapids.history.regress.madBands",
    "Noise-band multiplier for `tools history regress`: the band "
    "around the baseline median is max(5% of |median|, madBands x "
    "1.4826 x MAD), so genuinely noisy metrics widen their own band "
    "instead of flagging every run (1.4826 scales the median absolute "
    "deviation to a Gaussian sigma).",
    3.0)


# ---------------------------------------------------------------------------
# live engine console (spark_rapids_tpu/aux/console.py)
# ---------------------------------------------------------------------------

CONSOLE_ENABLED = conf_bool(
    "spark.rapids.console.enabled",
    "Serve the embedded live-engine console over HTTP (stdlib "
    "ThreadingHTTPServer, no dependencies): /metrics (Prometheus "
    "exposition), /queries (live span trees with progress/ETA), "
    "/memory (pool gauges + per-query byte attribution), /server "
    "(QueryServer admission/cache/latency stats), /debug/dump "
    "(on-demand watchdog ladder) and /events (ring tail).  All "
    "handlers read lock-protected snapshots only.  Off by default "
    "with zero overhead when disabled.  Reference: the Spark UI / "
    "PrometheusServlet sink.",
    False)

CONSOLE_PORT = conf_int(
    "spark.rapids.console.port",
    "TCP port the console binds.  0 picks an ephemeral port (the "
    "bound port is logged in the consoleLifecycle event and exposed "
    "via active_console().port for tests/bench).  Validated >= 0 at "
    "set_conf.",
    0,
    checker=lambda v: 0 <= int(v) <= 65535)

CONSOLE_BIND_ADDRESS = conf_str(
    "spark.rapids.console.bindAddress",
    "Interface the console listens on.  Defaults to loopback; set "
    "0.0.0.0 deliberately to scrape from another host — the console "
    "is unauthenticated diagnostics, not a public API.",
    "127.0.0.1")


class TpuConf:
    """Immutable snapshot of config values (reference: ``new RapidsConf(conf)``
    re-read per query, GpuOverrides.scala:4564)."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        # (environment overrides are applied by default_conf(), which scans
        # SPARK_RAPIDS_CONF_* env vars; a bare TpuConf() reads only `settings`)
        self._values: Dict[str, Any] = {}
        settings = dict(settings or {})
        for k, entry in _REGISTRY.items():
            if k in settings:
                raw = settings.pop(k)
                val = entry.converter(raw)  # converters accept non-strings too
                if entry.checker is not None and not entry.checker(val):
                    raise ValueError(f"invalid value for {k}: {raw!r}")
                self._values[k] = val
            else:
                self._values[k] = entry.default
        self._extra = settings  # unregistered keys kept verbatim

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._values:
            return self._values[key]
        if key in self._extra:
            # the key's rule may have registered AFTER this conf snapshot
            # was built (operator modules import lazily): convert through
            # the now-known entry instead of returning the raw string —
            # a literal "false" is truthy and would silently defeat
            # boolean gates (ADVICE-class bug, r5 review)
            raw = self._extra[key]
            entry = _REGISTRY.get(key)
            if entry is not None and isinstance(raw, str):
                val = entry.converter(raw)
                self._values[key] = val
                return val
            return raw
        entry = _REGISTRY.get(key)
        if entry is not None:
            return entry.default
        return default

    def with_overrides(self, **kv) -> "TpuConf":
        merged = {**self._values, **self._extra}
        merged.update({k.replace("__", "."): v for k, v in kv.items()})
        return TpuConf(merged)

    def set(self, key: str, value: Any) -> "TpuConf":
        merged = {**self._values, **self._extra, key: value}
        return TpuConf(merged)

    # convenience accessors used on hot paths
    @property
    def is_sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED.key)

    @property
    def is_explain_only(self) -> bool:
        return self.get(SQL_MODE.key).lower() == "explainonly"

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES.key)

    @property
    def is_test_enabled(self) -> bool:
        return self.get(TEST_ENABLED.key)

    def __repr__(self):
        non_default = {k: v for k, v in self._values.items()
                       if v != _REGISTRY[k].default}
        return f"TpuConf({non_default})"


def generate_docs() -> str:
    """Generates the configuration reference (reference: docs/configs.md is
    generated from RapidsConf; RapidsConf.scala 'object RapidsConf' doc gen)."""
    lines = ["# spark-rapids-tpu Configuration", "",
             "| Key | Default | Level | Description |",
             "|---|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        doc = " ".join(str(e.doc).split())
        lines.append(f"| {e.key} | {e.default!r} | {e.level.value} | {doc} |")
    return "\n".join(lines) + "\n"


def default_conf() -> TpuConf:
    overrides = {}
    prefix = "SPARK_RAPIDS_CONF_"
    for k, v in os.environ.items():
        if k.startswith(prefix):
            overrides[k[len(prefix):].replace("_", ".")] = v
    return TpuConf(overrides)
