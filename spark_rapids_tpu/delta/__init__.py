"""Delta-style ACID table layer (SURVEY.md §2.7 delta-lake module —
GpuMergeIntoCommand, GpuOptimisticTransaction, GpuDeltaTaskStatisticsTracker,
OPTIMIZE/ZORDER — re-designed for one table-format version, as §7
de-scopes the 9 per-version shims)."""

from spark_rapids_tpu.delta.table import DeltaTable  # noqa: F401
