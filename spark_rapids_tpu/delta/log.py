"""Delta transaction log.

Reference: the delta-lake module's transaction plumbing
(GpuOptimisticTransaction over Delta's OptimisticTransaction; per-file
statistics via GpuDeltaTaskStatisticsTracker / GpuStatisticsCollection).

Format (delta-protocol-shaped, one JSON action per line):
``_delta_log/00000000000000000000.json`` etc., actions: metaData, add
(path + numRecords + per-column min/max/nullCount stats), remove,
commitInfo.  A snapshot is the log replay; commits are optimistic —
the writer re-checks the version it read before renaming its commit file
(single-filesystem CAS via O_EXCL create)."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu import types as T


class ConcurrentModificationException(Exception):
    """Another writer committed the version this transaction targeted."""


def _log_dir(path: str) -> str:
    return os.path.join(path, "_delta_log")


def _version_file(path: str, version: int) -> str:
    return os.path.join(_log_dir(path), f"{version:020d}.json")


class Snapshot:
    """Replayed state at a version: schema + live files (+stats)."""

    def __init__(self, version: int, schema_json: Optional[str],
                 files: Dict[str, dict]):
        self.version = version
        self.schema_json = schema_json
        self.files = files               # path -> add action

    @property
    def schema(self) -> Optional[T.StructType]:
        if not self.schema_json:
            return None
        return _schema_from_json(self.schema_json)

    def file_paths(self) -> List[str]:
        return sorted(self.files)


def _schema_to_json(schema: T.StructType) -> str:
    def field(f):
        return {"name": f.name, "type": f.data_type.simple_name,
                "nullable": f.nullable}
    return json.dumps({"type": "struct",
                       "fields": [field(f) for f in schema.fields]})


_NAME_TO_TYPE = {
    "boolean": T.BOOLEAN, "tinyint": T.BYTE, "byte": T.BYTE,
    "smallint": T.SHORT, "short": T.SHORT, "int": T.INT,
    "integer": T.INT, "bigint": T.LONG, "long": T.LONG,
    "float": T.FLOAT, "double": T.DOUBLE, "string": T.STRING,
    "binary": T.BINARY, "date": T.DATE, "timestamp": T.TIMESTAMP,
}


def _type_from_name(n: str) -> T.DataType:
    if n in _NAME_TO_TYPE:
        return _NAME_TO_TYPE[n]
    if n.startswith("decimal("):
        p, s = n[8:-1].split(",")
        return T.DecimalType(int(p), int(s))
    if n.startswith("array<") and n.endswith(">"):
        return T.ArrayType(_type_from_name(n[6:-1]))
    raise ValueError(f"cannot parse delta type {n!r}")


def _schema_from_json(s: str) -> T.StructType:
    d = json.loads(s)
    return T.StructType([
        T.StructField(f["name"], _type_from_name(f["type"]),
                      f.get("nullable", True))
        for f in d["fields"]])


class DeltaLog:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def latest_version(self) -> int:
        d = _log_dir(self.path)
        if not os.path.isdir(d):
            return -1
        versions = [int(f[:-5]) for f in os.listdir(d)
                    if f.endswith(".json") and f[:-5].isdigit()]
        return max(versions) if versions else -1

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.latest_version()
        if version is None:
            version = latest
        if version < 0:
            return Snapshot(-1, None, {})
        if version > latest:
            raise ValueError(f"version {version} > latest {latest}")
        schema_json = None
        files: Dict[str, dict] = {}
        for v in range(version + 1):
            p = _version_file(self.path, v)
            with open(p) as f:
                for line in f:
                    if not line.strip():
                        continue
                    action = json.loads(line)
                    if "metaData" in action:
                        schema_json = action["metaData"].get("schemaString")
                    elif "add" in action:
                        files[action["add"]["path"]] = action["add"]
                    elif "remove" in action:
                        files.pop(action["remove"]["path"], None)
        return Snapshot(version, schema_json, files)

    def commit(self, read_version: int, actions: List[dict],
               operation: str) -> int:
        """Optimistic commit: targets read_version + 1; O_EXCL create is
        the CAS (reference: OptimisticTransaction.commit's conflict
        detection collapsed to the filesystem primitive)."""
        version = read_version + 1
        actions = list(actions) + [{
            "commitInfo": {"operation": operation,
                           "timestamp": int(time.time() * 1000)}}]
        os.makedirs(_log_dir(self.path), exist_ok=True)
        target = _version_file(self.path, version)
        try:
            fd = os.open(target, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise ConcurrentModificationException(
                f"version {version} was committed by another writer "
                f"(read version {read_version} is stale)")
        with os.fdopen(fd, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")
        return version

    def history(self) -> List[dict]:
        out = []
        for v in range(self.latest_version() + 1):
            with open(_version_file(self.path, v)) as f:
                for line in f:
                    a = json.loads(line)
                    if "commitInfo" in a:
                        out.append({"version": v, **a["commitInfo"]})
        return out


def compute_file_stats(hb, schema: T.StructType) -> dict:
    """Per-file column stats (reference: GpuStatisticsCollection —
    min/max/nullCount per column feed data skipping)."""
    import pyarrow.compute as pc
    stats = {"numRecords": int(hb.row_count), "minValues": {},
             "maxValues": {}, "nullCount": {}}
    for f in schema.fields:
        try:
            col = hb.column_by_name(f.name)
        except (KeyError, AttributeError):
            cols = {n: c for n, c in zip(hb.schema.names, hb.columns)}
            col = cols.get(f.name)
        if col is None:
            continue
        # encoded scans hand back dictionary arrays; arrow's min_max has
        # no dictionary kernel, and null VALUES in a dictionary only
        # count as row nulls on the decoded form
        from spark_rapids_tpu.columnar.encoding import host_decoded
        arr = host_decoded(col.arrow)
        stats["nullCount"][f.name] = arr.null_count
        if f.data_type.is_numeric or isinstance(
                f.data_type, (T.DateType, T.TimestampType, T.StringType)):
            if len(arr) > arr.null_count:
                mn = pc.min(arr).as_py()
                mx = pc.max(arr).as_py()
                stats["minValues"][f.name] = _stat_value(mn)
                stats["maxValues"][f.name] = _stat_value(mx)
    return stats


def _stat_value(v):
    import datetime
    import decimal
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    if hasattr(v, "item"):
        return v.item()
    return v
