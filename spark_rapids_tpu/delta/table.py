"""Delta-style table operations.

Reference: delta-lake/ GPU commands — GpuMergeIntoCommand,
GpuUpdateCommand/GpuDeleteCommand (copy-on-write file rewrite),
GpuOptimizeExecutor (compaction + ZORDER BY via the zorder kernels), all
through GpuOptimisticTransaction.  The engine's own columnar pipeline does
the row work; this layer owns files + log actions."""

from __future__ import annotations

import os
import uuid
from typing import Dict, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.delta.log import (ConcurrentModificationException,
                                        DeltaLog, Snapshot,
                                        _schema_to_json, compute_file_stats)
from spark_rapids_tpu.expressions.base import Expression, bind_references


class DeltaTable:
    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        self.log = DeltaLog(path)

    # -- creation / write ----------------------------------------------------
    @classmethod
    def create(cls, session, path: str, df) -> "DeltaTable":
        t = cls(session, path)
        t._write_df(df, mode="overwrite", operation="CREATE TABLE AS SELECT")
        return t

    @classmethod
    def for_path(cls, session, path: str) -> "DeltaTable":
        t = cls(session, path)
        if t.log.latest_version() < 0:
            raise FileNotFoundError(f"no delta table at {path}")
        return t

    def write(self, df, mode: str = "append") -> None:
        op = "WRITE" if mode == "append" else "OVERWRITE"
        self._write_df(df, mode=mode, operation=op)

    def _write_df(self, df, mode: str, operation: str) -> None:
        snap = self.log.snapshot()
        schema = df.schema
        adds = self._write_files(df)
        actions: List[dict] = []
        if snap.version < 0 or mode == "overwrite":
            actions.append({"metaData": {
                "id": str(uuid.uuid4()),
                "schemaString": _schema_to_json(schema),
                "format": {"provider": "parquet"}}})
        if mode == "overwrite":
            for p in snap.file_paths():
                actions.append({"remove": {"path": p,
                                           "dataChange": True}})
        actions.extend({"add": a} for a in adds)
        self.log.commit(snap.version, actions, operation)

    def _write_files(self, df, batches=None) -> List[dict]:
        """Writes data files + computes per-file stats; returns add
        actions."""
        from spark_rapids_tpu.columnar.batch import (ColumnarBatch,
                                                     concat_host_batches)
        os.makedirs(self.path, exist_ok=True)
        schema = df.schema if df is not None else None
        if batches is None:
            plan = df._executed_plan()
            batches = list(plan.execute_all())
        host = []
        for b in batches:
            host.append(b.to_host() if isinstance(b, ColumnarBatch) else b)
        if not host:
            return []
        hb = concat_host_batches(host) if len(host) > 1 else host[0]
        if hb.row_count == 0:
            return []
        name = f"part-{uuid.uuid4().hex[:12]}.parquet"
        fpath = os.path.join(self.path, name)
        import pyarrow as pa
        import pyarrow.parquet as pq
        pq.write_table(pa.Table.from_batches([hb.to_arrow()]), fpath)
        stats = compute_file_stats(hb, hb.schema if schema is None
                                   else schema)
        return [{"path": name, "size": os.path.getsize(fpath),
                 "dataChange": True, "stats": stats}]

    # -- read ----------------------------------------------------------------
    def to_df(self, predicate: Optional[Expression] = None):
        """Scan of the live files; per-file min/max stats skip files that
        cannot match a simple comparison predicate (data skipping)."""
        snap = self.log.snapshot()
        schema = snap.schema
        paths = [os.path.join(self.path, p) for p in
                 self._skip_files(snap, predicate)]
        if not paths:
            from spark_rapids_tpu.columnar.batch import batch_from_pydict
            empty = batch_from_pydict({f.name: [] for f in schema.fields},
                                      schema)
            return self.session.create_dataframe(empty)
        df = self.session.read.parquet(*paths)
        if predicate is not None:
            df = df.filter(predicate)
        return df

    def _skip_files(self, snap: Snapshot, predicate) -> List[str]:
        files = snap.file_paths()
        bound = _simple_bound(predicate)
        if bound is None:
            return files
        name, op, value = bound
        keep = []
        for p in files:
            st = snap.files[p].get("stats") or {}
            mn = st.get("minValues", {}).get(name)
            mx = st.get("maxValues", {}).get(name)
            if mn is None or mx is None:
                keep.append(p)
                continue
            if op == ">" and not (mx > value):
                continue
            if op == ">=" and not (mx >= value):
                continue
            if op == "<" and not (mn < value):
                continue
            if op == "<=" and not (mn <= value):
                continue
            if op == "=" and not (mn <= value <= mx):
                continue
            keep.append(p)
        return keep

    # -- DML -----------------------------------------------------------------
    def delete(self, condition: Expression) -> int:
        """Copy-on-write DELETE (reference GpuDeleteCommand): rewrite the
        files that contain matching rows without them."""
        from spark_rapids_tpu.expressions.predicates import Not
        snap = self.log.snapshot()
        schema = snap.schema
        cond = bind_references(condition, schema)
        removed, adds, deleted = self._rewrite_files(
            snap, keep_predicate=Not(cond))
        actions = [{"remove": {"path": p, "dataChange": True}}
                   for p in removed]
        actions += [{"add": a} for a in adds]
        if actions:
            self.log.commit(snap.version, actions, "DELETE")
        return deleted

    def update(self, set_exprs: Dict[str, Expression],
               condition: Optional[Expression] = None) -> int:
        """Copy-on-write UPDATE (reference GpuUpdateCommand)."""
        from spark_rapids_tpu.expressions.base import Alias, col
        from spark_rapids_tpu.expressions.conditional import If
        snap = self.log.snapshot()
        schema = snap.schema
        cond = bind_references(condition, schema) if condition is not None \
            else None
        removed: List[str] = []
        adds: List[dict] = []
        touched = 0
        for p in snap.file_paths():
            df = self.session.read.parquet(os.path.join(self.path, p))
            n_match = df.filter(cond).count() if cond is not None \
                else df.count()
            if n_match == 0:
                continue
            touched += n_match
            proj = []
            for f in schema.fields:
                if f.name in set_exprs:
                    new = bind_references(set_exprs[f.name], schema)
                    e = If(cond, new, col(f.name)) if cond is not None \
                        else new
                    proj.append(Alias(bind_references(e, schema), f.name))
                else:
                    proj.append(col(f.name))
            out = df.select(*proj)
            removed.append(p)
            adds.extend(self._write_files(out))
        actions = [{"remove": {"path": p, "dataChange": True}}
                   for p in removed]
        actions += [{"add": a} for a in adds]
        if actions:
            self.log.commit(snap.version, actions, "UPDATE")
        return touched

    def merge(self, source_df, on: str,
              when_matched_update: Optional[Dict[str, Expression]] = None,
              when_not_matched_insert: bool = True) -> dict:
        """MERGE (reference GpuMergeIntoCommand, low-shuffle variant
        de-scoped): matched rows update, unmatched source rows insert."""
        from spark_rapids_tpu.expressions.base import Alias, col, lit
        snap = self.log.snapshot()
        schema = snap.schema
        target = self.to_df()
        # matched keys (semi-join on the key column)
        src_keys = set(r[on] for r in
                       source_df.select(col(on)).collect())
        stats = {"updated": 0, "inserted": 0}
        # prefix source columns so they never collide with target names;
        # a constant __src__match marker makes "the join found a source
        # row" unambiguous even for all-null source values
        src_cols = [Alias(col(on), on),
                    Alias(lit(1), "__src__match")]
        src_cols += [Alias(col(c), f"__src_{c}")
                     for c in source_df.columns if c != on]
        src2 = source_df.select(*src_cols)
        joined = target.join(src2, on=on, how="left", null_safe=False)
        from spark_rapids_tpu.expressions.conditional import If
        from spark_rapids_tpu.expressions.predicates import IsNotNull
        matched = IsNotNull(col("__src__match"))
        proj = []
        for f in schema.fields:
            if when_matched_update and f.name in when_matched_update:
                # update expressions may reference source values as
                # __src_<name>
                upd = when_matched_update[f.name]
                proj.append(Alias(If(matched, upd, col(f.name)), f.name))
            else:
                proj.append(Alias(col(f.name), f.name))
        updated_target = joined.select(*proj)
        # inserts: source rows whose key is absent from the target
        tgt_keys = set(r[on] for r in target.select(col(on)).collect())
        insert_rows = [r for r in source_df.collect()
                       if r[on] not in tgt_keys]
        stats["inserted"] = len(insert_rows)
        stats["updated"] = sum(1 for k in src_keys if k in tgt_keys)
        removed = snap.file_paths()
        adds = self._write_files(updated_target)
        if insert_rows:
            cols = {c: [r[c] for r in insert_rows]
                    for c in source_df.columns}
            ins_df = self.session.create_dataframe(cols, schema=schema)
            adds += self._write_files(ins_df)
        actions = [{"remove": {"path": p, "dataChange": True}}
                   for p in removed]
        actions += [{"add": a} for a in adds]
        self.log.commit(snap.version, actions, "MERGE")
        return stats

    # -- OPTIMIZE ------------------------------------------------------------
    def optimize(self, zorder_by: Optional[Sequence[str]] = None) -> dict:
        """Compacts all live files into one, optionally Z-ORDERed
        (reference: GpuOptimizeExecutor + zorder kernels)."""
        snap = self.log.snapshot()
        schema = snap.schema
        df = self.to_df()
        batches = [b.to_host() if hasattr(b, "to_host") and
                   not hasattr(b, "arrow_schema") else b
                   for b in df._executed_plan().execute_all()]
        from spark_rapids_tpu.columnar.batch import (concat_host_batches)
        if not batches:
            return {"filesRemoved": 0, "filesAdded": 0}
        hb = concat_host_batches([
            b.to_host() if hasattr(b, "bucket") else b for b in batches])
        if zorder_by:
            import numpy as np
            import pyarrow as pa
            from spark_rapids_tpu.ops.zorder_ops import zorder_permutation
            cols = {n: c for n, c in zip(hb.schema.names, hb.columns)}
            keys = [cols[n].data_np() for n in zorder_by]
            perm = zorder_permutation(keys, np)
            tab = pa.Table.from_batches([hb.to_arrow()]) \
                .take(pa.array(perm))
            from spark_rapids_tpu.columnar.batch import batch_from_arrow
            hb = batch_from_arrow(tab)
        adds = self._write_files_direct([hb], schema)
        removed = snap.file_paths()
        actions = [{"remove": {"path": p, "dataChange": False}}
                   for p in removed]
        actions += [{"add": a} for a in adds]
        self.log.commit(snap.version, actions,
                        "OPTIMIZE" + (" ZORDER" if zorder_by else ""))
        return {"filesRemoved": len(removed), "filesAdded": len(adds)}

    def _write_files_direct(self, batches, schema) -> List[dict]:
        from spark_rapids_tpu.columnar.batch import concat_host_batches
        hb = concat_host_batches(batches) if len(batches) > 1 else batches[0]
        if hb.row_count == 0:
            return []
        name = f"part-{uuid.uuid4().hex[:12]}.parquet"
        fpath = os.path.join(self.path, name)
        import pyarrow as pa
        import pyarrow.parquet as pq
        pq.write_table(pa.Table.from_batches([hb.to_arrow()]), fpath)
        stats = compute_file_stats(hb, schema)
        return [{"path": name, "size": os.path.getsize(fpath),
                 "dataChange": True, "stats": stats}]

    def _rewrite_files(self, snap: Snapshot, keep_predicate: Expression):
        """Rewrites each file keeping rows matching the predicate; returns
        (removed paths, add actions, dropped row count)."""
        schema = snap.schema
        removed: List[str] = []
        adds: List[dict] = []
        dropped = 0
        for p in snap.file_paths():
            df = self.session.read.parquet(os.path.join(self.path, p))
            total = df.count()
            kept_df = df.filter(keep_predicate)
            kept = kept_df.count()
            if kept == total:
                continue
            dropped += total - kept
            removed.append(p)
            if kept:
                adds.extend(self._write_files(kept_df))
        return removed, adds, dropped

    def history(self) -> List[dict]:
        return self.log.history()

    def version(self) -> int:
        return self.log.latest_version()


def _simple_bound(predicate):
    """(col, op, value) for a single comparison against a literal, else
    None (data skipping handles the simple shapes, like the reference)."""
    if predicate is None:
        return None
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import (AttributeReference,
                                                   BoundReference, Literal)
    ops = {P.GreaterThan: ">", P.GreaterThanOrEqual: ">=",
           P.LessThan: "<", P.LessThanOrEqual: "<=", P.EqualTo: "="}
    cls = type(predicate)
    if cls not in ops:
        return None
    left, right = predicate.children
    if isinstance(left, (AttributeReference,)) and isinstance(right, Literal):
        return (left.ref_name, ops[cls], right.value)
    if isinstance(left, BoundReference) and isinstance(right, Literal):
        return (left.ref_name, ops[cls], right.value)
    return None
