"""Physical operators: host (CPU fallback engine) and device (Tpu*) pairs.

Reference: SURVEY.md §2.6 operator families.  Naming mirrors the reference's
Gpu*Exec classes as Tpu*Exec; the Cpu*Exec side plays the role of Spark's CPU
operators (the fallback tier and the differential-test oracle).
"""

from spark_rapids_tpu.exec.basic import (  # noqa: F401
    CpuFilterExec, CpuInMemoryScanExec, CpuLimitExec, CpuProjectExec,
    CpuRangeExec, CpuSampleExec, CpuUnionExec, DeviceToHostExec,
    HostToDeviceExec, TpuCoalesceBatchesExec, TpuFilterExec,
    TpuInMemoryScanExec, TpuLimitExec, TpuProjectExec, TpuRangeExec,
    TpuSampleExec, TpuUnionExec)
from spark_rapids_tpu.exec.expand import (  # noqa: F401
    CpuExpandExec, CpuTakeOrderedAndProjectExec, TpuExpandExec,
    TpuTakeOrderedAndProjectExec)
from spark_rapids_tpu.exec.generate import (  # noqa: F401
    CpuGenerateExec, TpuGenerateExec)
