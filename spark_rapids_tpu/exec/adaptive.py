"""Adaptive (AQE-style) shuffle reader.

Reference: GpuCustomShuffleReaderExec (SURVEY.md §2.9) — consumes the
partition specs Spark's AQE derives from materialized shuffle statistics:
CoalescedPartitionSpec (merge small adjacent reduce partitions) and
PartialReducerPartitionSpec (split skewed ones).  Here the engine IS the
planner, so the reader derives the specs itself from the exchange's
materialized per-partition sizes.

The planner pass applies COALESCING universally (whole-partition merges
preserve hash-grouping and range order).  Skew-split specs are computed by
the same machinery but only applied where duplication is coordinated (the
shuffled-join path), mirroring Spark's own restriction."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Union

from spark_rapids_tpu.plan.base import Exec, UnaryExec


@dataclasses.dataclass(frozen=True)
class CoalescedPartitionSpec:
    """Read reduce partitions [start, end) as one output partition."""
    start: int
    end: int


@dataclasses.dataclass(frozen=True)
class PartialPartitionSpec:
    """Read a slice of one reduce partition's batches (skew split)."""
    partition: int
    batch_start: int
    batch_end: int


PartitionSpec = Union[CoalescedPartitionSpec, PartialPartitionSpec]


def _partition_sizes(exchange, target_bytes: Optional[int] = None
                     ) -> List[int]:
    """Materializes the exchange and sizes each reduce partition (the AQE
    'query stage statistics' step).

    Sync discipline: padded (bucket) sizes are computable WITHOUT a device
    round trip; logical sizes need the deferred counts forced (~150ms
    tunnel sync per exchange).  When the padded total already fits
    ``target_bytes``, the coalesce decision ("merge everything") is
    identical either way — the padded sizes are returned and the sync is
    skipped entirely (the common case for every exchange of a small-SF
    query)."""
    import numpy as np
    exchange._materialize()
    if getattr(exchange, "_collective", None) is not None:
        # mesh path: partitions are device shards; size = rows * row width
        _ctx, cols, counts, schema = exchange._collective
        from spark_rapids_tpu.aux import transitions as TR
        counts_h = TR.fetch(counts, site="aqe-shard-counts")
        row_bytes = sum(
            getattr(f.data_type, "np_dtype", None).itemsize
            if getattr(f.data_type, "np_dtype", None) is not None else 16
            for f in schema.fields) + len(schema.fields)
        return [int(c) * row_bytes for c in counts_h]
    def sizes_now():
        out = []
        for p in range(exchange.num_partitions):
            total = 0
            for b in exchange._store[p]:
                if hasattr(b, "sized_nbytes"):
                    total += b.sized_nbytes()
                elif hasattr(b, "nbytes"):
                    total += b.nbytes()
            out.append(total)
        return out

    padded = sizes_now()   # no sync: unforced counts report bucket bytes
    if target_bytes is not None and sum(padded) <= target_bytes:
        return padded
    # above target: the decision needs logical sizes — force the deferred
    # counts in ONE sync so sized_nbytes reports rows-x-width (padded
    # sizes would make every partition look uniformly huge and disable
    # coalesce/skew decisions entirely)
    from spark_rapids_tpu.columnar.column import force_counts
    force_counts([b.row_count
                  for p in range(exchange.num_partitions)
                  for b in exchange._store[p]
                  if hasattr(b, "row_count")])
    # counts forced: sized_nbytes now reports logical rows x width
    return sizes_now()


def _balanced_contiguous(sizes: Sequence[int],
                         k: int) -> List[CoalescedPartitionSpec]:
    """Exactly ``k`` contiguous, size-balanced groups covering every
    input partition (each group non-empty)."""
    n = len(sizes)
    k = max(1, min(k, n))
    cum: List[int] = []
    total = 0
    for s in sizes:
        total += s
        cum.append(total)
    specs: List[CoalescedPartitionSpec] = []
    start = 0
    for g in range(k):
        if g == k - 1:
            end = n
        else:
            target = total * (g + 1) / k
            end = start + 1
            # advance while under quota, leaving >= 1 input per
            # remaining group
            while end < n - (k - g - 1) and cum[end - 1] < target:
                end += 1
        specs.append(CoalescedPartitionSpec(start, end))
        start = end
    return specs


def coalesce_specs(sizes: Sequence[int], target_bytes: int,
                   align: int = 1) -> List[CoalescedPartitionSpec]:
    """Greedy adjacent merge up to the advisory size (Spark's
    coalescePartitions algorithm).  With ``align`` > 1 (the mesh size,
    mesh-aware AQE) the output count snaps to the nearest achievable
    MULTIPLE of ``align`` via a balanced contiguous re-split, so
    post-AQE stages keep an even device mapping."""
    specs: List[CoalescedPartitionSpec] = []
    start = 0
    acc = 0
    for i, sz in enumerate(sizes):
        if i > start and acc + sz > target_bytes:
            specs.append(CoalescedPartitionSpec(start, i))
            start, acc = i, 0
        acc += sz
    if start < len(sizes) or not specs:
        specs.append(CoalescedPartitionSpec(start, max(len(sizes), 1)))
    if align > 1 and len(sizes) >= align and len(specs) % align:
        # nearest multiple of align, clamped to what the input count can
        # actually supply: rounding UP past len(sizes) must floor to the
        # largest achievable multiple, never give up (12 inputs on an
        # 8-mesh round to 16 but snap to 8, not stay at 12)
        k = max(align, int(round(len(specs) / align)) * align)
        k = min(k, (len(sizes) // align) * align)
        specs = _balanced_contiguous(sizes, k)
    return specs


def _emit_coalesce_event(before: int, after: int, align: int,
                         ici_active: bool) -> None:
    """One ``aqeCoalesce`` record per AQE decision: the mesh-alignment
    evidence AutoTuner rule 10 cites (``aligned`` is judged against the
    ACTIVE mesh size, not the requested align, so a misaligned count
    with meshAlign disabled still shows up as misaligned)."""
    from spark_rapids_tpu.aux.events import emit
    from spark_rapids_tpu.parallel.mesh import active_mesh
    ctx = active_mesh()
    mesh = ctx.num_devices if ctx is not None else 0
    emit("aqeCoalesce", before=before, after=after, align=align,
         mesh=mesh, ici_active=bool(ici_active),
         aligned=(mesh <= 1 or after % mesh == 0))


def skew_split_specs(exchange, pidx: int,
                     target_bytes: int) -> List[PartialPartitionSpec]:
    """Splits one partition's batch list into roughly target-sized runs
    (PartialReducerPartitionSpec analog)."""
    exchange._materialize()
    batches = exchange._store[pidx]
    specs = []
    start = 0
    acc = 0
    for i, b in enumerate(batches):
        sz = b.sized_nbytes() if hasattr(b, "sized_nbytes") else \
            (b.nbytes() if hasattr(b, "nbytes") else 0)
        if i > start and acc + sz > target_bytes:
            specs.append(PartialPartitionSpec(pidx, start, i))
            start, acc = i, 0
        acc += sz
    specs.append(PartialPartitionSpec(pidx, start, len(batches)))
    return specs


def detect_skew(sizes: Sequence[int], factor: float = 5.0,
                min_bytes: int = 64 << 20) -> List[int]:
    """Skewed partition indexes: > factor * median AND > min size
    (Spark skewJoin detection)."""
    if not sizes:
        return []
    srt = sorted(sizes)
    median = srt[len(srt) // 2]
    return [i for i, s in enumerate(sizes)
            if s > max(median * factor, min_bytes)]


class SharedCoalesceSpecs:
    """ONE coalesce plan for the two sides of a shuffled join: partition i
    of both exchanges must merge identically or the key pairing breaks
    (Spark coordinates AQE shuffle reads across join children the same
    way).  Sizes are summed across sides so the target bound applies to
    the pair."""

    def __init__(self, left_ex, right_ex, target_bytes: int,
                 align: int = 1):
        import threading
        self._exs = (left_ex, right_ex)
        self._target = target_bytes
        self._align = align
        self._specs: Optional[List[PartitionSpec]] = None
        self._lock = threading.Lock()

    def get(self) -> List[PartitionSpec]:
        if self._specs is None:
            from spark_rapids_tpu.plan.base import release_semaphore_for_wait
            release_semaphore_for_wait()
            with self._lock:
                if self._specs is None:
                    # halve the target per side: the padded-fits-target
                    # shortcut must hold for the SUM of both sides
                    lsz = _partition_sizes(self._exs[0], self._target // 2)
                    rsz = _partition_sizes(self._exs[1], self._target // 2)
                    sizes = [a + b for a, b in zip(lsz, rsz)]
                    # whole-partition coalescing only — a partial split
                    # on one side without the other would break pairing
                    self._specs = coalesce_specs(sizes, self._target,
                                                 self._align)
                    _emit_coalesce_event(
                        len(sizes), len(self._specs), self._align,
                        any(getattr(ex, "_collective", None) is not None
                            for ex in self._exs))
        return self._specs


class AdaptiveShuffleReaderExec(UnaryExec):
    """Reads an exchange through derived partition specs."""

    def __init__(self, exchange, target_bytes: int = 64 << 20,
                 specs: Optional[List[PartitionSpec]] = None,
                 shared: Optional[SharedCoalesceSpecs] = None,
                 align: int = 1):
        super().__init__(exchange)
        self.target_bytes = target_bytes
        self._specs = specs
        #: coordinated specs shared with the sibling join side
        self._shared = shared
        #: snap coalesced counts to multiples of this (the mesh size)
        self._align = align

    @property
    def is_device(self):  # type: ignore[override]
        return self.children[0].is_device

    @property
    def specs(self) -> List[PartitionSpec]:
        if self._specs is None:
            if self._shared is not None:
                self._specs = self._shared.get()
                return self._specs
            # materializes the child exchange: drop device admission and
            # serialize against concurrent tasks (plan/base.py semantics)
            from spark_rapids_tpu.plan.base import release_semaphore_for_wait
            release_semaphore_for_wait()
            with self._exec_lock:
                if self._specs is None:
                    sizes = _partition_sizes(self.children[0],
                                             self.target_bytes)
                    self._specs = coalesce_specs(sizes, self.target_bytes,
                                                 self._align)
                    _emit_coalesce_event(
                        len(sizes), len(self._specs), self._align,
                        getattr(self.children[0], "_collective", None)
                        is not None)
        return self._specs

    @property
    def num_partitions(self):
        return len(self.specs)

    def execute_partition(self, pidx):
        spec = self.specs[pidx]
        ex = self.children[0]
        if isinstance(spec, CoalescedPartitionSpec):
            for p in range(spec.start, min(spec.end, ex.num_partitions)):
                yield from ex.execute_partition(p)
        else:
            ex._materialize()
            batches = ex._store[spec.partition]
            for b in batches[spec.batch_start:spec.batch_end]:
                if ex.is_device and not hasattr(b, "bucket"):
                    from spark_rapids_tpu.exec.basic import upload_batches
                    yield from upload_batches([b])
                else:
                    yield b

    def node_desc(self):
        if self._specs is None:
            return "AdaptiveShuffleReader[pending]"
        nc = sum(1 for s in self._specs
                 if isinstance(s, CoalescedPartitionSpec))
        np_ = len(self._specs) - nc
        return (f"AdaptiveShuffleReader[{len(self._specs)}p "
                f"({nc} coalesced, {np_} partial)]")


def _potential_collective(ex) -> bool:
    """True when ``ex`` would take the in-mesh ICI path on materialize
    (hash partitioning at the mesh size, mesh-shardable schema): these
    exchanges map reduce partitions 1:1 onto device shards, and the
    reader must preserve that mapping — coalescing across shards would
    concatenate batches living on different devices into one downstream
    kernel, destroying the locality the collective bought."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    return isinstance(ex, TpuShuffleExchangeExec) and \
        ex._collective_eligible(ex.partitioning) is not None


def insert_adaptive_readers(plan: Exec, target_bytes: int,
                            align: int = 1) -> Exec:
    """Planner pass (TOP-down): wrap every shuffle exchange whose parent
    will iterate its reduce partitions (coalescing whole partitions is
    safe: hash groups and range order are preserved).

    Join inputs pair partition i with partition i, so the two sides of a
    shuffled join read through ONE coordinated spec (Spark coordinates
    AQE shuffle reads across join children identically); a join side
    that CANNOT be coordinated gets no reader at all — an independently
    coalesced side would silently mis-pair the join keys.

    Mesh-aware: exchanges riding the in-mesh ICI path keep their 1:1
    shard mapping (no reader); host-staged exchanges under an active
    mesh coalesce to counts that are MULTIPLES of the mesh size
    (``align``, conf spark.rapids.sql.adaptive.meshAlign) so later
    stages stay evenly device-mapped and ICI-eligible."""
    from spark_rapids_tpu.exec.basic import TpuCoalesceBatchesExec
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.plan.base import BinaryExec

    def unwrap(c):
        """(exchange, rewrap) looking through the post-shuffle batch
        coalescer the transition pass inserts."""
        if isinstance(c, CpuShuffleExchangeExec):
            return c, (lambda inner: inner)
        if isinstance(c, TpuCoalesceBatchesExec) and \
                isinstance(c.children[0], CpuShuffleExchangeExec):
            return c.children[0], \
                (lambda inner, outer=c: outer.with_children([inner]))
        return None, None

    #: identity memo: a node shared by several parents (ReuseExchange)
    #: must map to ONE rewritten node, or the sharing silently splits
    #: into per-parent copies that each re-materialize the shuffle
    memo: dict = {}

    def visit(node: Exec, no_wrap: bool = False) -> Exec:
        # an exchange's own rebuild is flag-independent (no_wrap only
        # tells the PARENT not to wrap it) — normalize the key so a
        # shared exchange visited from join and non-join parents stays
        # one instance
        flag = (False if isinstance(node, CpuShuffleExchangeExec)
                else no_wrap)
        key = (id(node), flag)
        if key in memo:
            return memo[key]
        out = _visit(node, no_wrap)
        memo[key] = out
        return out

    def _visit(node: Exec, no_wrap: bool = False) -> Exec:
        if isinstance(node, BinaryExec):
            if no_wrap:
                # a downstream shuffled join relies on THIS subtree's
                # delivered partition count (its own exchange was elided
                # by the distribution pass): nothing below may coalesce,
                # including nested joins' exchange pairs — a 2->1 merge
                # here would leave the downstream join reading partition
                # i against an unrelated (or never-read) partition i
                return node.with_children([visit(c, no_wrap=True)
                                           for c in node.children])
            l, r = node.children
            lex, lwrap = unwrap(l)
            rex, rwrap = unwrap(r)
            if (lex is not None and rex is not None and
                    lex.num_partitions == rex.num_partitions and
                    lex.num_partitions > 1 and
                    not _potential_collective(lex) and
                    not _potential_collective(rex)):
                # rebuild through the memoized visit so an exchange shared
                # with other consumers (ReuseExchange) stays ONE instance
                lex = visit(lex, no_wrap=True)
                rex = visit(rex, no_wrap=True)
                shared = SharedCoalesceSpecs(lex, rex, target_bytes,
                                             align)
                return node.with_children([
                    lwrap(AdaptiveShuffleReaderExec(lex, target_bytes,
                                                    shared=shared,
                                                    align=align)),
                    rwrap(AdaptiveShuffleReaderExec(rex, target_bytes,
                                                    shared=shared,
                                                    align=align))])
            # un-coordinatable (or an ICI pair whose 1:1 shard pairing
            # must survive untouched): children recurse with their
            # top-level exchange left unwrapped
            return node.with_children([visit(c, no_wrap=True)
                                       for c in node.children])
        new_children = []
        for c in node.children:
            # partition-preserving unary nodes (coalescer, project, filter,
            # fused stages...) are transparent to partition pairing: the
            # no-wrap flag must flow through ALL of them down to the next
            # exchange, or a join input reached through e.g. a project
            # would get an independently coalesced reader and silently
            # mis-pair join partitions (ADVICE r4).  Exchanges reset
            # partitioning, so propagation stops there.
            child_no_wrap = (
                no_wrap and isinstance(node, UnaryExec) and
                not isinstance(node, CpuShuffleExchangeExec) and
                node.num_partitions == node.children[0].num_partitions)
            c2 = visit(c, no_wrap=child_no_wrap)
            if isinstance(c2, CpuShuffleExchangeExec) and \
                    not isinstance(node, AdaptiveShuffleReaderExec) and \
                    not child_no_wrap:
                if _potential_collective(c2):
                    # ICI shuffles map reduce partitions 1:1 onto device
                    # shards; coalescing would concatenate batches living
                    # on different devices into one downstream kernel
                    new_children.append(c2)
                    continue
                c2 = AdaptiveShuffleReaderExec(c2, target_bytes,
                                               align=align)
            new_children.append(c2)
        return node.with_children(new_children)

    return visit(plan)
