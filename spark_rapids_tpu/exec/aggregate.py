"""Hash-aggregate operator (partial / final / complete modes).

Reference: GpuAggregateExec.scala — first-pass iterator (:549) does
per-batch partial aggregation, GpuMergeAggregateIterator (:711) concats and
re-aggregates (with spill + re-partition fallback), final-pass (:578)
applies result projections.  GpuHashAggregateExec :1711.

TPU path: each batch runs through ops/agg_ops.segmented_aggregate (sort +
segmented reductions, one fused XLA program); cross-batch merge re-runs the
same kernel with merge kinds.  CPU oracle: pyarrow TableGroupBy with the
same declarative buffer algebra.

Two-stage planning (partial -> hash exchange -> final) is assembled by the
DataFrame layer, mirroring Spark's physical aggregation pattern.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (ColumnarBatch, HostColumnarBatch,
                                             concat_host_batches)
from spark_rapids_tpu.expressions.aggregates import (AggregateExpression,
                                                     BufferSpec)
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression)
from spark_rapids_tpu.plan.base import Exec, UnaryExec, closing_source

PARTIAL, FINAL, COMPLETE = "partial", "final", "complete"


class _AggLayout:
    """Buffer layout shared by both engines and both stages."""

    def __init__(self, grouping: Sequence[Expression],
                 aggs: Sequence[AggregateExpression]):
        self.grouping = list(grouping)
        self.aggs = list(aggs)
        self.num_keys = len(self.grouping)
        # flattened buffers with (agg_idx, spec)
        self.flat: List[Tuple[int, BufferSpec]] = []
        for ai, a in enumerate(self.aggs):
            for spec in a.func.buffers():
                self.flat.append((ai, spec))

    def key_name(self, i: int) -> str:
        e = self.grouping[i]
        return getattr(e, "alias_name", None) or e.sql()

    def buffer_name(self, j: int) -> str:
        ai, spec = self.flat[j]
        return f"{self.aggs[ai].out_name}#{spec.name}"

    @property
    def buffer_schema(self) -> T.StructType:
        fields = [T.StructField(self.key_name(i),
                                self.grouping[i].data_type,
                                self.grouping[i].nullable)
                  for i in range(self.num_keys)]
        fields += [T.StructField(self.buffer_name(j), spec.dtype, True)
                   for j, (ai, spec) in enumerate(self.flat)]
        return T.StructType(fields)

    @property
    def result_schema(self) -> T.StructType:
        fields = [T.StructField(self.key_name(i),
                                self.grouping[i].data_type,
                                self.grouping[i].nullable)
                  for i in range(self.num_keys)]
        fields += [T.StructField(a.out_name, a.func.data_type,
                                 a.func.nullable) for a in self.aggs]
        return T.StructType(fields)

    def update_input_exprs(self) -> List[Expression]:
        """Pre-step projection: keys then one column per buffer (inputs
        cast so reduction dtype == buffer dtype — reference: cudfUpdate
        input projections)."""
        from spark_rapids_tpu.expressions.cast import Cast
        out = list(self.grouping)
        for ai, spec in self.flat:
            e = self.aggs[ai].func.inputs()[spec.input_ordinal]
            if spec.update_kind == "sum" and e.data_type != spec.dtype:
                e = Cast(e, spec.dtype)
            out.append(e)
        return out

    def update_specs(self):
        return [(self.num_keys + j, spec.update_kind, spec.count_valid_only,
                 spec.dtype) for j, (_ai, spec) in enumerate(self.flat)]

    def merge_specs(self):
        return [(self.num_keys + j, spec.merge_kind, spec.count_valid_only,
                 spec.dtype) for j, (_ai, spec) in enumerate(self.flat)]

    def final_exprs(self) -> List[Expression]:
        """Projection from buffer layout to results."""
        exprs: List[Expression] = []
        for i in range(self.num_keys):
            exprs.append(Alias(
                BoundReference(i, self.grouping[i].data_type,
                               self.grouping[i].nullable),
                self.key_name(i)))
        j = 0
        for a in self.aggs:
            refs = []
            for spec in a.func.buffers():
                refs.append(BoundReference(self.num_keys + j, spec.dtype,
                                           True))
                j += 1
            exprs.append(Alias(a.func.evaluate(refs), a.out_name))
        return exprs


#: re-partition fan-out per fallback level; 4 bits of the 32-bit key hash
#: are consumed per level, so 7 levels exhaust the hash
MERGE_BUCKETS = 16
_MAX_REPARTITION_DEPTH = 7
#: test hook: force the re-partition fallback while depth < this value
#: (deterministic analog of arming forceSplitAndRetryOOM at exactly the
#: merge site — the allocation-hook injection can fire at an earlier
#: catalog add, which is outside the merge's catch scope by design)
FORCE_REPARTITION_BELOW_DEPTH = 0
#: observability: bumped once per re-partition pass (tests assert on it)
REPARTITION_EVENTS = 0


def _key_hash_u32(hb: HostColumnarBatch, lay: "_AggLayout") -> np.ndarray:
    """murmur3 over the buffer batch's key columns (host tier)."""
    from spark_rapids_tpu.expressions.base import BoundReference, EvalContext
    from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                        tcol_to_host_column)
    from spark_rapids_tpu.expressions.hashing import Murmur3Hash
    refs = [BoundReference(i, lay.grouping[i].data_type, True)
            for i in range(lay.num_keys)]
    ctx = EvalContext(host_batch_tcols(hb), "cpu", hb.row_count)
    h = Murmur3Hash(*refs).eval_cpu(ctx)
    hv = np.asarray(tcol_to_host_column(h, hb.row_count).arrow)
    return hv.astype(np.int64).astype(np.uint32)  # two's-complement bits


def _repartition_spillables(spill_batches, lay: "_AggLayout", depth: int):
    """Splits spillable buffer batches into MERGE_BUCKETS disjoint-key
    groups of spillable host batches, consuming 4 fresh hash bits per
    recursion depth so a level-N bucket re-splits instead of collapsing
    back into one bucket."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
    global REPARTITION_EVENTS
    REPARTITION_EVENTS += 1
    buckets = [[] for _ in range(MERGE_BUCKETS)]
    for sb in spill_batches:
        hb = sb.get_host_batch()
        sb.close()
        h = _key_hash_u32(hb, lay)
        pid = ((h >> np.uint32(4 * depth)) % MERGE_BUCKETS).astype(np.int64)
        order = np.argsort(pid, kind="stable")
        counts = np.bincount(pid, minlength=MERGE_BUCKETS)
        tab = pa.Table.from_batches([hb.to_arrow()]).take(pa.array(order))
        off = 0
        for k in range(MERGE_BUCKETS):
            if counts[k]:
                piece = batch_from_arrow(tab.slice(off, int(counts[k])))
                piece.names = hb.names
                buckets[k].append(SpillableColumnarBatch.from_host(piece))
            off += int(counts[k])
    return buckets


def merge_partials_out_of_core(lay: "_AggLayout", spill_partials,
                               depth: int = 0):
    """Merges spillable buffer-layout partials, yielding DEVICE batches
    whose key sets are pairwise disjoint.

    Fast path: one concat + segmented merge under the retry frame.  When
    that cannot fit — a SplitAndRetryOOM surfaces (injected or real), or
    the estimated concat size exceeds half the free device pool — the
    partials are hash-RE-partitioned on the host into MERGE_BUCKETS
    spillable groups and each bucket merges independently, recursing on
    a bucket that still doesn't fit.  Reference:
    GpuMergeAggregateIterator (GpuAggregateExec.scala:711) — concat-and-
    merge first, repartition-and-recurse on OOM.
    """
    from spark_rapids_tpu.memory.device_manager import free_device_headroom
    from spark_rapids_tpu.memory.retry import (SplitAndRetryOOM,
                                               maybe_inject_oom,
                                               with_retry_no_split)
    from spark_rapids_tpu.ops.agg_ops import segmented_aggregate
    from spark_rapids_tpu.ops.batch_ops import concat_batches
    nk = lay.num_keys

    def attempt():
        maybe_inject_oom()
        from spark_rapids_tpu.columnar.encoding import materialize_batch
        batches = [materialize_batch(sb.get_batch(), site="agg-merge")
                   for sb in spill_partials]
        big = concat_batches(batches) if len(batches) > 1 else batches[0]
        return segmented_aggregate(big, nk, lay.merge_specs())

    too_big = False
    if nk > 0 and depth < _MAX_REPARTITION_DEPTH:
        too_big = depth < FORCE_REPARTITION_BELOW_DEPTH
        if not too_big:
            budget = free_device_headroom(2)
            if budget is not None:
                est = sum(sb.sized_nbytes for sb in spill_partials)
                too_big = est > budget
    if not too_big:
        try:
            merged = with_retry_no_split(None, attempt)
            for sb in spill_partials:
                sb.close()
            yield merged
            return
        except SplitAndRetryOOM:
            # merge state can't shrink by re-running; fall through to the
            # re-partition fallback (a global agg has nothing to split on)
            if nk == 0 or depth >= _MAX_REPARTITION_DEPTH:
                raise
    for bucket in _repartition_spillables(spill_partials, lay, depth):
        if not bucket:
            continue
        yield from merge_partials_out_of_core(lay, bucket, depth + 1)


class CpuHashAggregateExec(UnaryExec):
    """Arrow-groupby based oracle/fallback with the same buffer algebra."""

    def __init__(self, grouping, aggs, mode, child: Exec):
        super().__init__(child)
        self.layout = _AggLayout(grouping, aggs)
        self.mode = mode

    @property
    def schema(self):
        return self.layout.buffer_schema if self.mode == PARTIAL else \
            self.layout.result_schema

    # ------------------------------------------------------------------
    def _project_update_input(self, hb: HostColumnarBatch):
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_cpu
        exprs = []
        for i, e in enumerate(self.layout.update_input_exprs()):
            nm = self.layout.key_name(i) if i < self.layout.num_keys else \
                f"v{i - self.layout.num_keys}"
            exprs.append(Alias(e, nm))
        return eval_exprs_cpu(exprs, hb)

    def _arrow_groupby(self, table, key_names, specs):
        """specs: list of (src_col_name, kind, count_valid_only).  Returns
        arrow table with key cols then one col per spec, in order."""
        import pyarrow as pa
        import pyarrow.compute as pc
        aggs = []
        post = []  # names in output, spec order
        # Spark NaN semantics for float min/max: NaN is larger than any
        # value, so max -> NaN when any NaN present, min skips NaN unless
        # the group is all-NaN.  Arrow's kernels skip NaN entirely, so NaN
        # is masked to null and tracked via a companion any(is_nan) agg.
        nan_patch = {}       # spec index -> nanflag result name
        extra_aggs = []
        added_cols = set()
        for idx, (col_name, kind, cvo) in enumerate(specs):
            if kind in ("min", "max") and \
                    pa.types.is_floating(table.column(col_name).type):
                nn, nanflag = f"{col_name}__nn", f"{col_name}__nan"
                if nn not in added_cols:
                    src = table.column(col_name)
                    isnan = pc.is_nan(src)
                    table = table.append_column(
                        nn, pc.if_else(pc.fill_null(isnan, False),
                                       pa.scalar(None, src.type), src))
                    table = table.append_column(
                        nanflag, pc.fill_null(isnan, False))
                    added_cols.add(nn)
                    extra_aggs.append(
                        (nanflag, "any",
                         pc.ScalarAggregateOptions(skip_nulls=True,
                                                   min_count=0)))
                specs[idx] = (nn, kind, cvo)
                nan_patch[idx] = f"{nanflag}_any"
        for col_name, kind, cvo in specs:
            if kind == "count":
                opt = pc.CountOptions(mode="only_valid" if cvo else "all")
                aggs.append((col_name, "count", opt))
            elif kind in ("sum", "min", "max", "mean"):
                opt = pc.ScalarAggregateOptions(skip_nulls=True, min_count=0)
                aggs.append((col_name, kind, opt))
            elif kind in ("first", "last"):
                opt = pc.ScalarAggregateOptions(skip_nulls=False, min_count=0)
                aggs.append((col_name, kind, opt))
            elif kind in ("first_valid", "last_valid"):
                opt = pc.ScalarAggregateOptions(skip_nulls=True, min_count=0)
                aggs.append((col_name, kind.split("_")[0], opt))
            elif kind in ("list", "distinct"):
                # variable-length state (CollectList/CollectSet/Percentile);
                # COMPLETE-mode only, so no merge of list buffers is needed
                aggs.append((col_name, kind, None))
            else:
                raise ValueError(kind)
        all_aggs = aggs + extra_aggs
        if key_names:
            gb = table.group_by(key_names, use_threads=False)
            res = gb.aggregate(all_aggs)
        elif any(a[1] in ("list", "distinct") for a in all_aggs):
            # scalar aggregation has no hash_list kernel: group by a
            # constant key instead, then ignore it
            const = pa.array([0] * table.num_rows, type=pa.int8())
            res = table.append_column("__g", const)                 .group_by(["__g"], use_threads=False).aggregate(all_aggs)
        else:
            # reduction: aggregate to one row
            res = table.group_by([], use_threads=False).aggregate(all_aggs)
        # output order: aggregate cols are named f"{col}_{fn}"; build in
        # spec order (duplicate (col, fn) pairs collapse to one output col)
        out_cols, out_names = [], []
        for idx, ((col_name, kind, cvo), (src, fn, _o)) in \
                enumerate(zip(specs, aggs)):
            res_name = f"{src}_{fn}"
            c = res.column(res_name)
            if idx in nan_patch:
                anyn = pc.fill_null(res.column(nan_patch[idx]), False)
                nanval = pa.scalar(float("nan"), type=T.to_arrow(
                    T.DOUBLE) if pa.types.is_float64(
                        table.column(src).type) else pa.float32())
                if kind == "max":
                    c = pc.if_else(anyn, nanval, c)
                else:
                    c = pc.if_else(pc.and_(anyn, pc.is_null(c)), nanval, c)
            out_cols.append(c)
            out_names.append(res_name)
        keys = [res.column(k) for k in key_names]
        return keys, out_cols, res.num_rows

    def _update(self, hb: HostColumnarBatch) -> HostColumnarBatch:
        """Raw input -> buffer layout (CPU m2 via sum-of-squares algebra)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        lay = self.layout
        proj = self._project_update_input(hb)
        table = pa.Table.from_batches([proj.to_arrow()])
        key_names = [lay.key_name(i) for i in range(lay.num_keys)]
        specs = []
        extra_sq = {}
        for j, (_ai, spec) in enumerate(lay.flat):
            k = spec.update_kind
            if k == "m2":
                # m2 = sum(x^2) - sum(x)^2 / n  (post-computed)
                sq_name = f"v{j}__sq"
                if sq_name not in extra_sq:
                    x = table.column(f"v{j}")
                    table = table.append_column(sq_name, pc.multiply(x, x))
                    extra_sq[sq_name] = True
                specs.append((sq_name, "sum", True))
            else:
                specs.append((f"v{j}", k, spec.count_valid_only))
        keys, cols, nrows = self._arrow_groupby(table, key_names, specs)
        # post: m2 needs n & mean of the same input — find sibling buffers
        out = []
        for j, (_ai, spec) in enumerate(lay.flat):
            c = cols[j]
            if spec.update_kind == "m2":
                n, mean = cols[j - 2], cols[j - 1]
                sumx = pc.multiply(n, mean)
                corr = pc.if_else(pc.greater(n, 0.0),
                                  pc.divide(pc.multiply(sumx, sumx),
                                            pc.if_else(pc.greater(n, 0.0),
                                                       n, 1.0)),
                                  0.0)
                c = pc.fill_null(pc.subtract(pc.fill_null(c, 0.0), corr), 0.0)
                c = pc.max_element_wise(c, 0.0)  # clamp fp negatives
            at = T.to_arrow(spec.dtype)
            c = pc.cast(c, at, safe=False) if c.type != at else c
            out.append(c)
        arrs = keys + out
        names = key_names + [lay.buffer_name(j) for j in range(len(out))]
        combined = [a.combine_chunks() if isinstance(a, pa.ChunkedArray)
                    else a for a in arrs]
        return batch_from_arrow(pa.table(dict(zip(names, combined))))

    def _merge(self, hb: HostColumnarBatch) -> HostColumnarBatch:
        """Buffer layout -> merged buffer layout."""
        import pyarrow as pa
        import pyarrow.compute as pc
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        lay = self.layout
        table = pa.Table.from_batches([hb.to_arrow()])
        key_names = [lay.key_name(i) for i in range(lay.num_keys)]
        specs = []
        j = 0
        renames = {}
        while j < len(lay.flat):
            _ai, spec = lay.flat[j]
            k = spec.merge_kind
            bn = lay.buffer_name(j)
            if k == "m2_cnt":
                # decompose to sums: n, wsum = n*mu, sq = m2 + wsum^2/n
                n = table.column(bn)
                mu = table.column(lay.buffer_name(j + 1))
                m2 = table.column(lay.buffer_name(j + 2))
                wsum = pc.multiply(n, mu)
                sq = pc.add(m2, pc.if_else(
                    pc.greater(n, 0.0),
                    pc.divide(pc.multiply(wsum, wsum),
                              pc.if_else(pc.greater(n, 0.0), n, 1.0)), 0.0))
                table = table.append_column(f"__w{j}", wsum)
                table = table.append_column(f"__q{j}", sq)
                specs.append((bn, "sum", True))
                specs.append((f"__w{j}", "sum", True))
                specs.append((f"__q{j}", "sum", True))
                renames[j + 1] = "recompute_mean"
                renames[j + 2] = "recompute_m2"
                j += 3
                continue
            specs.append((bn, k, spec.count_valid_only))
            j += 1
        keys, cols, nrows = self._arrow_groupby(table, key_names, specs)
        out = []
        for j, (_ai, spec) in enumerate(lay.flat):
            c = cols[j]
            if renames.get(j) == "recompute_mean":
                n, w = cols[j - 1], cols[j]
                c = pc.if_else(pc.greater(n, 0.0),
                               pc.divide(w, pc.if_else(pc.greater(n, 0.0),
                                                       n, 1.0)), 0.0)
            elif renames.get(j) == "recompute_m2":
                n, w, q = cols[j - 2], cols[j - 1], cols[j]
                wsum2 = pc.if_else(pc.greater(n, 0.0),
                                   pc.divide(pc.multiply(w, w),
                                             pc.if_else(pc.greater(n, 0.0),
                                                        n, 1.0)), 0.0)
                c = pc.max_element_wise(pc.subtract(q, wsum2), 0.0)
            at = T.to_arrow(spec.dtype)
            c = pc.cast(c, at, safe=False) if c.type != at else c
            out.append(c)
        arrs = keys + out
        names = key_names + [lay.buffer_name(k2) for k2 in range(len(out))]
        combined = [a.combine_chunks() if isinstance(a, pa.ChunkedArray)
                    else a for a in arrs]
        return batch_from_arrow(pa.table(dict(zip(names, combined))))

    def _finalize(self, hb: HostColumnarBatch) -> HostColumnarBatch:
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_cpu
        return eval_exprs_cpu(self.layout.final_exprs(), hb)

    def execute_partition(self, pidx):
        batches = list(self.child.execute_partition(pidx))
        lay = self.layout
        if not batches:
            if lay.num_keys == 0 and self.mode in (COMPLETE, FINAL) and \
                    self.child.num_partitions == 1:
                yield self._empty_reduction()
            return
        hb = concat_host_batches(batches)
        if self.mode in (PARTIAL, COMPLETE):
            buf = self._update(hb)
        else:
            buf = self._merge(hb)
        if self.mode == PARTIAL:
            yield buf
        elif lay.num_keys == 0 and buf.row_count == 0 and \
                self.child.num_partitions == 1:
            # empty INPUT BATCHES (a drained filter/join still yields
            # 0-row batches): global aggregation must emit its one row
            yield self._empty_reduction()
        else:
            yield self._finalize(buf)

    def _empty_reduction(self) -> HostColumnarBatch:
        """Global aggregation over zero rows still yields one row
        (count=0, sum=null ...)."""
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        lay = self.layout
        cols = {}
        for j, (_ai, spec) in enumerate(lay.flat):
            # the SEMANTIC kind decides the empty value: a count slot is 0
            # on empty input even in FINAL mode, where merge_kind is "sum"
            # (merging counts) and would wrongly produce null; collect
            # buffers are EMPTY ARRAYS, never null (Spark CollectList/
            # CollectSet semantics)
            k = spec.update_kind
            if k in ("list", "distinct"):
                cols[lay.buffer_name(j)] = pa.array(
                    [[]], type=T.to_arrow(spec.dtype))
                continue
            zero = 0 if k == "count" or k.startswith("m2") else None
            if spec.dtype == T.DOUBLE and zero == 0:
                zero = 0.0
            cols[lay.buffer_name(j)] = pa.array([zero],
                                                type=T.to_arrow(spec.dtype))
        buf = batch_from_arrow(pa.table(cols))
        return self._finalize(buf)

    def node_desc(self):
        ks = ", ".join(e.sql() for e in self.layout.grouping)
        asym = ", ".join(a.func.sql() for a in self.layout.aggs)
        return f"HashAggregate[{self.mode}]({ks})[{asym}]"


class TpuHashAggregateExec(CpuHashAggregateExec):
    is_device = True

    def _has_collect(self) -> bool:
        return any(spec.update_kind in ("list", "distinct")
                   for _ai, spec in self.layout.flat)

    def _complete_collect(self, pidx):
        """COMPLETE-mode device path for variable-length buffers
        (collect_list/collect_set/count-distinct sets): one concat of the
        partition, scalar slots through segmented_aggregate, collect
        slots through segmented_collect — both sort by the same key
        words, so group order is identical and the buffer columns zip
        (reference: the cuDF collect-backed ObjectHashAggregate path,
        aggregateFunctions.scala)."""
        from spark_rapids_tpu.columnar.column import known_empty
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        from spark_rapids_tpu.ops.agg_ops import (segmented_aggregate,
                                                  segmented_collect_many)
        from spark_rapids_tpu.ops.batch_ops import concat_batches
        lay = self.layout
        batches = [b for b in self.child.execute_partition(pidx)
                   if not known_empty(b.row_count)]
        if not batches:
            if lay.num_keys == 0 and self.child.num_partitions == 1:
                yield self._empty_reduction().to_device()
            return
        big = concat_batches(batches)
        exprs = []
        for i, e in enumerate(lay.update_input_exprs()):
            nm = lay.key_name(i) if i < lay.num_keys else \
                f"v{i - lay.num_keys}"
            exprs.append(Alias(e, nm))
        proj = eval_exprs_tpu(exprs, big)
        nk = lay.num_keys
        scalar = [(j, spec) for j, (_ai, spec) in enumerate(lay.flat)
                  if spec.update_kind not in ("list", "distinct")]
        collect = [(j, spec) for j, (_ai, spec) in enumerate(lay.flat)
                   if spec.update_kind in ("list", "distinct")]
        buf_cols = {}
        keys_cols = None
        n = None
        if scalar:
            sspecs = [(nk + j, spec.update_kind, spec.count_valid_only,
                       spec.dtype) for j, spec in scalar]
            sres = segmented_aggregate(proj, nk, sspecs)
            keys_cols = list(sres.columns[:nk])
            n = sres.row_count
            for (j, _), c in zip(scalar, sres.columns[nk:]):
                buf_cols[j] = c
        if collect:
            # ONE stacked max-width sync for every collect slot
            many = segmented_collect_many(
                proj, nk, [(nk + j, spec.update_kind == "distinct")
                           for j, spec in collect])
            for (j, _spec), cres in zip(collect, many):
                if keys_cols is None:
                    keys_cols = list(cres.columns[:nk])
                    n = cres.row_count
                buf_cols[j] = cres.columns[nk]
        # the scalar and collect passes each produced their own deferred
        # group count (same value: same sort, same keys); a batch requires
        # ONE shared count object, so rewrap every column with it.  For a
        # GLOBAL aggregation the scalar pass reduces to a tiny bucket
        # while collect keeps the input bucket — slice collect planes down
        # (the single group always fits)
        from spark_rapids_tpu.columnar.column import DeviceColumn
        raw = keys_cols + [buf_cols[j] for j in range(len(lay.flat))]
        target = min(int(c.data.shape[0]) for c in raw)
        cols = []
        for c in raw:
            d, v = c.data, c.validity
            ln, ev = c.lengths, c.elem_valid
            if int(d.shape[0]) != target:
                d, v = d[:target], v[:target]
                ln = None if ln is None else ln[:target]
                ev = None if ev is None else ev[:target]
            cols.append(DeviceColumn(d, v, n, c.data_type, ln, ev))
        merged = ColumnarBatch(cols, n)
        merged.names = [lay.key_name(i) for i in range(nk)] + \
            [lay.buffer_name(j) for j in range(len(lay.flat))]
        if nk == 0 and int(merged.row_count) == 0:
            yield self._empty_reduction().to_device()
        else:
            yield eval_exprs_tpu(lay.final_exprs(), merged)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
        from spark_rapids_tpu.ops.agg_ops import segmented_aggregate
        lay = self.layout
        if self.mode == COMPLETE and self._has_collect():
            yield from self._complete_collect(pidx)
            return
        # partials register spillable as they accumulate — under pressure
        # the catalog can push earlier partials down a tier while later
        # child batches are still aggregating (GpuMergeAggregateIterator's
        # aggregated-batch queue semantics)
        partials: List[SpillableColumnarBatch] = []
        n_partials = 0
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                if self.mode in (PARTIAL, COMPLETE):
                    exprs = []
                    for i, e in enumerate(lay.update_input_exprs()):
                        nm = lay.key_name(i) if i < lay.num_keys else \
                            f"v{i - lay.num_keys}"
                        exprs.append(Alias(e, nm))
                    proj = eval_exprs_tpu(exprs, b)
                    p = with_retry_no_split(
                        None, lambda: segmented_aggregate(
                            proj, lay.num_keys, lay.update_specs()))
                else:
                    p = b  # already in buffer layout (post-shuffle)
                partials.append(SpillableColumnarBatch.from_device(p))
                n_partials += 1
        if not partials:
            if lay.num_keys == 0 and self.mode in (COMPLETE, FINAL) and \
                    self.child.num_partitions == 1:
                yield self._empty_reduction().to_device()
            return
        if n_partials == 1 and self.mode != FINAL:
            # unwrap, don't close: get_batch()+close() deleted the very
            # arrays being yielded (latent while the fusion pass replaced
            # every non-FINAL aggregate; exposed by stageFusion.enabled=
            # false)
            merged_batches = [partials[0].release()]
        else:
            merged_batches = merge_partials_out_of_core(lay, partials)
        names = [lay.key_name(i) for i in range(lay.num_keys)] + \
            [lay.buffer_name(j) for j in range(len(lay.flat))]
        for merged in merged_batches:
            if self.mode == PARTIAL:
                merged.names = list(names)
                yield merged
            elif lay.num_keys == 0 and merged.row_count == 0:
                # global aggregation over zero rows still yields one row
                yield self._empty_reduction().to_device()
            else:
                yield eval_exprs_tpu(lay.final_exprs(), merged)

    def node_desc(self):
        return "Tpu" + super().node_desc()


def _tag_aggregate(meta) -> None:
    """Rejects device-unsupported agg shapes (planner fallback instead of
    wrong results — reference: GpuHashAggregateMeta.tagPlanForGpu)."""
    lay = meta.plan.layout
    for g in lay.grouping:
        if g.data_type.is_nested:
            meta.will_not_work(
                f"grouping key of type {g.data_type.simple_name} "
                "(nested keys have no device sort words)")
    for j, (ai, spec) in enumerate(lay.flat):
        dt = spec.dtype
        if isinstance(dt, (T.StringType, T.BinaryType)) and \
                spec.update_kind in ("min", "max"):
            meta.will_not_work(f"min/max over strings not on device yet "
                               f"(buffer {lay.buffer_name(j)})")
        if isinstance(dt, T.DecimalType) and dt.is_decimal128:
            # SUM buffers ride the 4x32-bit limb segment-sum kernel; the
            # scale-preserving widening cast covers the input projection.
            # Buffers at the 38-digit clamp (input precision >= 28) stay
            # host-tier: they can genuinely overflow, and the device
            # kernel wraps mod 2^128 instead of nulling (Spark non-ANSI).
            # Below the clamp Spark's +10-digit headroom means overflow
            # would need > 10^10 rows.  Other kinds (min/max/first/last,
            # avg's final divide) still lack decimal128 device kernels.
            from spark_rapids_tpu.expressions.aggregates import Sum
            func = lay.aggs[ai].func
            sum_ok = (isinstance(func, Sum) and
                      spec.update_kind == "sum" and
                      spec.merge_kind == "sum" and dt.precision < 38)
            if not sum_ok:
                meta.will_not_work(
                    f"decimal128 aggregation buffer "
                    f"{lay.buffer_name(j)} not on device "
                    "(sum below the 38-digit clamp is)")
        if spec.update_kind in ("list", "distinct"):
            from spark_rapids_tpu.expressions.aggregates import (
                CollectList, CollectSet, CountDistinct)
            func = lay.aggs[ai].func
            ins = func.inputs()
            vdt = ins[spec.input_ordinal].data_type if ins else None
            from spark_rapids_tpu import config as _C
            device_ok = (
                meta.conf.get(_C.COLLECT_AGG_ENABLED.key) and
                meta.plan.mode == COMPLETE and
                isinstance(func, (CollectList, CollectSet,
                                  CountDistinct)) and
                vdt is not None and not vdt.is_nested and
                not isinstance(vdt, (T.StringType, T.BinaryType)) and
                not (isinstance(vdt, T.DecimalType) and vdt.is_decimal128))
            if not device_ok:
                meta.will_not_work(
                    f"variable-length aggregation buffer "
                    f"{lay.buffer_name(j)} is host tier (device collect "
                    "covers COMPLETE-mode fixed-width values)")


from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

from spark_rapids_tpu.plan import typechecks as _AGG_TS  # noqa: E402

register_exec(
    CpuHashAggregateExec,
    convert=lambda p, m: TpuHashAggregateExec(p.layout.grouping,
                                              p.layout.aggs, p.mode,
                                              p.children[0]),
    sig=_AGG_TS.BASIC_WITH_ARRAYS,
    exprs_of=lambda p: list(p.layout.grouping) +
    [a.func for a in p.layout.aggs],
    extra_tag=_tag_aggregate,
    desc="hash aggregate (sort + segmented reduction; device collect "
         "via padded array planes)")
