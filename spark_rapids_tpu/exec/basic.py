"""Basic physical operators.

Reference: basicPhysicalOperators.scala (GpuProjectExec :350 tiered
projection, GpuFilterExec :795, GpuRangeExec), limit.scala, GpuUnionExec,
GpuSampleExec in GpuOverrides registrations; transitions
GpuRowToColumnarExec.scala / GpuColumnarToRowExec.scala / HostColumnarToGpu.scala.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (ColumnarBatch, HostColumnarBatch,
                                             batch_from_arrow)
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, TCol)
from spark_rapids_tpu.expressions.evaluator import (eval_exprs_cpu,
                                                    eval_exprs_tpu, _out_names)
from spark_rapids_tpu.plan.base import (Exec, LeafExec, UnaryExec,
                                        closing_source)


def _project_schema(exprs: Sequence[Expression]) -> T.StructType:
    names = _out_names(exprs)
    return T.StructType([T.StructField(n, e.data_type, e.nullable)
                         for n, e in zip(names, exprs)])


# ---------------------------------------------------------------------------
# Scans
# ---------------------------------------------------------------------------

class CpuInMemoryScanExec(LeafExec):
    """Scan over in-memory arrow batches, pre-split into partitions.

    Carries a device-column cache shared by every plan derived from the same
    source DataFrame: the first device action uploads each referenced column
    once, later actions (and later queries over the same DataFrame) reuse the
    device-resident columns — the TPU analog of a device-cached table
    (reference: shuffle/cache keep batches device-resident in
    RapidsBufferCatalog; here the scan itself is the resident tier).
    """

    def __init__(self, partitions: List[List[HostColumnarBatch]],
                 schema: T.StructType, col_indices=None, dev_cache=None):
        super().__init__()
        self.partitions = partitions
        self._schema = schema
        #: column subset (pruning); None = all
        self.col_indices = col_indices
        #: (pidx, batch_idx, col_ordinal_in_full_schema) -> DeviceColumn;
        #: shared across shallow copies / pruned clones of this scan
        self._dev_cache = {} if dev_cache is None else dev_cache

    @property
    def schema(self):
        if self.col_indices is None:
            return self._schema
        return T.StructType([self._schema.fields[i]
                             for i in self.col_indices])

    @property
    def num_partitions(self):
        return max(1, len(self.partitions))

    def with_pruned_columns(self, indices):
        base = self.col_indices or list(range(len(self._schema.fields)))
        if not indices and base:
            # a batch with zero columns loses its row count in arrow form;
            # keep the narrowest column so row semantics survive
            def width(i):
                dt = self.schema.fields[i].data_type
                npdt = getattr(dt, "np_dtype", None)
                if dt.is_nested or npdt is None:  # strings/nested: wide
                    return 64
                return npdt.itemsize
            indices = [min(range(len(base)), key=width)]
        return CpuInMemoryScanExec(self.partitions, self._schema,
                                   [base[i] for i in indices],
                                   self._dev_cache)

    def _host_batches(self, pidx):
        if pidx >= len(self.partitions):
            return
        for hb in self.partitions[pidx]:
            if self.col_indices is None:
                yield hb
            else:
                yield HostColumnarBatch(
                    [hb.columns[i] for i in self.col_indices],
                    hb.row_count,
                    None if hb.names is None else
                    [hb.names[i] for i in self.col_indices])

    def execute_partition(self, pidx):
        yield from self._host_batches(pidx)

    def node_desc(self):
        cols = "" if self.col_indices is None else \
            f", cols={list(self.col_indices)}"
        return f"InMemoryScan[{self.num_partitions}p{cols}]"


def upload_batches(batches):
    """Host->device upload with device admission (the semaphore is acquired
    before the first device use; released by run_task at task completion)."""
    from spark_rapids_tpu.memory.device_manager import get_runtime
    from spark_rapids_tpu.plan.base import closing_source
    rt = get_runtime()
    with closing_source(iter(batches)) as it:
        for hb in it:
            if rt is not None:
                rt.semaphore.acquire_if_necessary()
            yield hb.to_device()


class TpuInMemoryScanExec(CpuInMemoryScanExec):
    is_device = True

    def __init__(self, cpu: CpuInMemoryScanExec):
        super().__init__(cpu.partitions, cpu._schema, cpu.col_indices,
                         cpu._dev_cache)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.memory.device_manager import get_runtime
        if pidx >= len(self.partitions):
            return
        rt = get_runtime()
        indices = self.col_indices or \
            list(range(len(self._schema.fields)))
        for bi, hb in enumerate(self.partitions[pidx]):
            if rt is not None:
                rt.semaphore.acquire_if_necessary()
            def alive(i):
                dc = self._dev_cache.get((pidx, bi, i))
                if dc is None:
                    return False
                deleted = getattr(dc.data, "is_deleted", None)
                return not (deleted and deleted())

            missing = [i for i in indices if not alive(i)]
            if missing:
                sub = HostColumnarBatch(
                    [hb.columns[i] for i in missing], hb.row_count,
                    [str(i) for i in missing])
                dev = sub.to_device()
                for i, dc in zip(missing, dev.columns):
                    self._dev_cache[(pidx, bi, i)] = dc
            names = None if hb.names is None else \
                [hb.names[i] for i in indices]
            yield ColumnarBatch(
                [self._dev_cache[(pidx, bi, i)] for i in indices],
                hb.row_count, names)

    def node_desc(self):
        cols = "" if self.col_indices is None else \
            f", cols={list(self.col_indices)}"
        return f"TpuInMemoryScan[{self.num_partitions}p{cols}]"


# ---------------------------------------------------------------------------
# Project / Filter
# ---------------------------------------------------------------------------

class CpuProjectExec(UnaryExec):
    def __init__(self, exprs: Sequence[Expression], child: Exec):
        super().__init__(child)
        self.exprs = list(exprs)

    @property
    def schema(self):
        return _project_schema(self.exprs)

    def execute_partition(self, pidx):
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                yield eval_exprs_cpu(self.exprs, b)

    def node_desc(self):
        return f"Project[{', '.join(e.sql() for e in self.exprs)}]"


class TpuProjectExec(UnaryExec):
    """Whole-stage-fused device projection (reference: GpuProjectExec with
    tiered project; here the whole expr list is one XLA program)."""

    is_device = True

    def __init__(self, exprs: Sequence[Expression], child: Exec):
        super().__init__(child)
        self.exprs = list(exprs)

    @property
    def schema(self):
        return _project_schema(self.exprs)

    def execute_partition(self, pidx):
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                yield eval_exprs_tpu(self.exprs, b)

    def node_desc(self):
        return f"TpuProject[{', '.join(e.sql() for e in self.exprs)}]"


class CpuFilterExec(UnaryExec):
    def __init__(self, condition: Expression, child: Exec):
        super().__init__(child)
        self.condition = condition

    def execute_partition(self, pidx):
        import pyarrow as pa
        import pyarrow.compute as pc
        from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                            tcol_to_host_column)
        from spark_rapids_tpu.expressions.base import EvalContext
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                cols = host_batch_tcols(b)
                ctx = EvalContext(cols, "cpu", b.row_count)
                pred = self.condition.eval_cpu(ctx)
                keep_col = tcol_to_host_column(pred, b.row_count)
                mask = pc.fill_null(keep_col.arrow.cast(pa.bool_()), False)
                rb = b.to_arrow().filter(mask)
                yield batch_from_arrow(pa.Table.from_batches([rb]))

    def node_desc(self):
        return f"Filter[{self.condition.sql()}]"


class TpuFilterExec(UnaryExec):
    """Filter = fused predicate eval + stable compaction gather; bucket is
    preserved so no recompilation across batches (see ops.batch_ops)."""

    is_device = True

    def __init__(self, condition: Expression, child: Exec):
        super().__init__(child)
        self.condition = condition

    def execute_partition(self, pidx):
        from spark_rapids_tpu.expressions.base import EvalContext, valid_array
        from spark_rapids_tpu.expressions.evaluator import device_batch_tcols
        from spark_rapids_tpu.ops import compact_batch
        from spark_rapids_tpu.columnar.column import _jnp
        jnp = _jnp()
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                cols = device_batch_tcols(b)
                ctx = EvalContext(cols, "tpu", b.bucket)
                pred = self.condition.eval_tpu(ctx)
                keep = valid_array(pred, ctx)
                if not pred.is_scalar:
                    keep = keep & pred.data
                else:
                    keep = keep & bool(pred.data)
                # padding rows must never be kept
                rowpos = jnp.arange(b.bucket)
                keep = keep & (rowpos < b.row_count)
                yield compact_batch(b, keep)

    def node_desc(self):
        return f"TpuFilter[{self.condition.sql()}]"


# ---------------------------------------------------------------------------
# Range
# ---------------------------------------------------------------------------

class CpuRangeExec(LeafExec):
    """SELECT id FROM range(start, end, step) (reference GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1, batch_rows: int = 1 << 20):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self._parts = max(1, num_partitions)
        self.batch_rows = batch_rows

    @property
    def schema(self):
        return T.StructType([T.StructField("id", T.LONG, False)])

    @property
    def num_partitions(self):
        return self._parts

    def _partition_range(self, pidx):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self._parts)
        lo = min(pidx * per, total)
        hi = min(lo + per, total)
        return lo, hi

    def execute_partition(self, pidx):
        from spark_rapids_tpu.columnar.batch import batch_from_pydict
        lo, hi = self._partition_range(pidx)
        pos = lo
        while pos < hi:
            n = min(self.batch_rows, hi - pos)
            vals = self.start + (pos + np.arange(n, dtype=np.int64)) * self.step
            yield batch_from_pydict({"id": vals}, self.schema)
            pos += n

    def node_desc(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class TpuRangeExec(CpuRangeExec):
    is_device = True

    def __init__(self, cpu: CpuRangeExec):
        super().__init__(cpu.start, cpu.end, cpu.step, cpu._parts,
                         cpu.batch_rows)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.columnar.column import (DeviceColumn, _jnp,
                                                      bucket_rows)
        jnp = _jnp()
        lo, hi = self._partition_range(pidx)
        pos = lo
        while pos < hi:
            n = min(self.batch_rows, hi - pos)
            b = bucket_rows(n)
            vals = self.start + (pos + jnp.arange(b, dtype=np.int64)) * self.step
            valid = jnp.arange(b) < n
            col = DeviceColumn(vals, valid, n, T.LONG)
            yield ColumnarBatch([col], n, ["id"])
            pos += n

    def node_desc(self):
        return f"TpuRange({self.start}, {self.end}, {self.step})"


# ---------------------------------------------------------------------------
# Limit / Union / Sample
# ---------------------------------------------------------------------------

class CpuLimitExec(UnaryExec):
    """Local limit per partition; with single-partition input it is global
    (reference: Local/Global/CollectLimitExec trio)."""

    def __init__(self, n: int, child: Exec):
        super().__init__(child)
        self.n = n

    def execute_partition(self, pidx):
        from spark_rapids_tpu.plan.base import closing_source
        left = self.n
        # budget check BEFORE pulling: a satisfied limit must not make
        # the source decode one more batch just to discard it, and the
        # deterministic close propagates the early exit upstream (stops
        # prefetch producers, releases queued spillables)
        with closing_source(self.child.execute_partition(pidx)) as it:
            while left > 0:
                try:
                    b = next(it)
                except StopIteration:
                    return
                if b.row_count <= left:
                    left -= b.row_count
                    yield b
                else:
                    yield b.slice(0, left)
                    left = 0

    def node_desc(self):
        return f"Limit[{self.n}]"


#: default for spark.rapids.sql.limit.deferredForceInterval — the limit
#: execs carry their convert-time conf value per instance
LIMIT_DEFERRED_FORCE_INTERVAL = 8


def _deferred_limited(batches, n: int, force_interval=None):
    """Limit over a batch stream with the remaining budget kept ON DEVICE
    while counts are deferred (forcing each batch's count would cost a
    tunnel sync per batch).  Amortized early exit: every
    ``force_interval``-th (default LIMIT_DEFERRED_FORCE_INTERVAL)
    deferred batch forces the budget once so a satisfied limit stops
    pulling the source."""
    import numpy as _np

    if force_interval is None:      # explicit sentinel: a conf value of
        force_interval = LIMIT_DEFERRED_FORCE_INTERVAL   # 1 must stick

    from spark_rapids_tpu.columnar.column import (DeferredCount, _jnp,
                                                  rc_traceable)
    from spark_rapids_tpu.ops import take_front
    from spark_rapids_tpu.plan.base import closing_source
    jnp = _jnp()
    left = n   # int until a deferred count is consumed
    deferred_batches = 0
    # the satisfied-limit return (and a downstream close) must stop the
    # source deterministically, not at GC time
    with closing_source(iter(batches)) as it:
        while True:
            # budget check BEFORE pulling: a satisfied limit must not
            # start the next partition's pipeline just to discard its
            # first batch
            if isinstance(left, int) and left <= 0:
                return
            try:
                b = next(it)
            except StopIteration:
                return
            rc = b.row_count
            if isinstance(left, int) and \
                    not (isinstance(rc, DeferredCount) and not rc.is_forced):
                if int(rc) <= left:
                    left -= int(rc)
                    yield b
                else:
                    yield take_front(b, left)
                    left = 0
                continue
            out = take_front(b, left if isinstance(left, int)
                             else DeferredCount(left))
            left = jnp.maximum(
                jnp.asarray(rc_traceable(left)) -
                jnp.asarray(rc_traceable(out.row_count)), 0)
            yield out
            deferred_batches += 1
            if deferred_batches % force_interval == 0:
                from spark_rapids_tpu.aux import transitions as TR
                left = int(TR.fetch(left, site="limit-force"))


class TpuLimitExec(UnaryExec):
    is_device = True

    #: conf-at-convert-time (spark.rapids.sql.limit.deferredForceInterval)
    deferred_force_interval = None

    def __init__(self, n: int, child: Exec):
        super().__init__(child)
        self.n = n

    def execute_partition(self, pidx):
        yield from _deferred_limited(self.child.execute_partition(pidx),
                                     self.n,
                                     self.deferred_force_interval)

    def node_desc(self):
        return f"TpuLimit[{self.n}]"


class CpuCteCacheExec(UnaryExec):
    """Materializes a multiply-referenced CTE subtree ONCE and replays the
    batches to every reference (Spark analog: WithCTE + ReusedExchangeExec
    collapse repeated CTE branches; the reference relies on Spark for this
    and only sees the deduped plan).  The analyzer wraps a CTE plan in
    this node when the statement references it more than once; conversion
    copies are re-merged by the exchange-reuse pass keyed on ``origin``
    (plan/overrides.py reuse_exchanges)."""

    #: execution epoch the next execution must rebuild for (stamped by
    #: ``refresh_cte_epochs`` per prepared action); class-level 0 keeps
    #: directly-driven test execs caching across calls
    _expected_epoch = 0

    def __init__(self, child: Exec):
        super().__init__(child)
        self._cache = None
        #: epoch the cached batches were materialized under — a cache
        #: from a previous action / speculation replay / changed input
        #: file set must never replay (it is only valid within the ONE
        #: action whose epoch stamped it)
        self._cache_epoch = None
        #: identity of the logical (analyzer-built) node — survives the
        #: shallow copies the rewrite passes make, letting reuse collapse
        #: converted copies back into one caching instance
        self.origin = id(self)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.plan.base import release_semaphore_for_wait
        if self._cache is None or self._cache_epoch != self._expected_epoch:
            release_semaphore_for_wait()
            with self._exec_lock:
                if self._cache is None or \
                        self._cache_epoch != self._expected_epoch:
                    self._cache = [list(self.child.execute_partition(p))
                                   for p in range(self.child.num_partitions)]
                    self._cache_epoch = self._expected_epoch
        yield from self._cache[pidx]

    def node_desc(self):
        return "CteCache"


class TpuCteCacheExec(CpuCteCacheExec):
    is_device = True

    def __init__(self, child: Exec, origin: int):
        super().__init__(child)
        self.origin = origin

    def node_desc(self):
        return "TpuCteCache"


def refresh_cte_epochs(plan: Exec) -> None:
    """Arms every CTE cache in ``plan`` for ONE upcoming execution: a
    fresh process-wide epoch is stamped on each node, so every reference
    within the action shares the single materialization while batches
    cached by a PREVIOUS action (a speculation replay in exact mode, a
    re-executed plan-cache entry, inputs whose files changed) always
    rebuild instead of replaying stale."""
    from spark_rapids_tpu.plan.base import next_execution_epoch
    nodes = [n for n in plan.collect_nodes()
             if isinstance(n, CpuCteCacheExec)]
    if not nodes:
        return
    epoch = next_execution_epoch()
    for n in nodes:
        n._expected_epoch = epoch


class CpuGlobalLimitExec(UnaryExec):
    """Single-output-partition global limit: streams child partitions in
    order until n rows are emitted (reference: CollectLimit/GlobalLimit
    trio, limit.scala; in-process, the 'shuffle to one partition' collapses
    to sequentially draining child partitions)."""

    def __init__(self, n: int, child: Exec):
        super().__init__(child)
        self.n = n

    @property
    def num_partitions(self):
        return 1

    def _limited(self, slicer):
        from spark_rapids_tpu.plan.base import closing_source
        left = self.n
        for cp in range(self.child.num_partitions):
            if left <= 0:
                return
            # check before every pull so a budget exhausted mid-partition
            # never decodes the discarded next batch
            with closing_source(self.child.execute_partition(cp)) as it:
                while left > 0:
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    if b.row_count <= left:
                        left -= b.row_count
                        yield b
                    else:
                        yield slicer(b, left)
                        left = 0

    def execute_partition(self, pidx):
        yield from self._limited(lambda b, k: b.slice(0, k))

    def node_desc(self):
        return f"GlobalLimit[{self.n}]"


class TpuGlobalLimitExec(CpuGlobalLimitExec):
    is_device = True

    #: conf-at-convert-time (spark.rapids.sql.limit.deferredForceInterval)
    deferred_force_interval = None

    def execute_partition(self, pidx):
        def stream():
            for cp in range(self.child.num_partitions):
                yield from self.child.execute_partition(cp)
        yield from _deferred_limited(stream(), self.n,
                                     self.deferred_force_interval)

    def node_desc(self):
        return f"TpuGlobalLimit[{self.n}]"


class CpuCoalescePartitionsExec(UnaryExec):
    """Shuffle-free partition-count reduction: merges adjacent child
    partitions (Spark coalesce() contract — never increases count, keeps
    per-partition order, no data movement)."""

    def __init__(self, n: int, child: Exec):
        super().__init__(child)
        self.n = n

    @property
    def num_partitions(self):
        return max(1, min(self.n, self.child.num_partitions))

    def execute_partition(self, pidx):
        total = self.child.num_partitions
        outs = self.num_partitions
        per = -(-total // outs)
        for cp in range(pidx * per, min((pidx + 1) * per, total)):
            yield from self.child.execute_partition(cp)

    def node_desc(self):
        return f"CoalescePartitions[{self.num_partitions}]"


class TpuCoalescePartitionsExec(CpuCoalescePartitionsExec):
    is_device = True

    def node_desc(self):
        return f"TpuCoalescePartitions[{self.num_partitions}]"


class CpuUnionExec(Exec):
    def __init__(self, children: Sequence[Exec]):
        super().__init__(children)

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def num_partitions(self):
        return sum(c.num_partitions for c in self.children)

    def _locate(self, pidx):
        for c in self.children:
            if pidx < c.num_partitions:
                return c, pidx
            pidx -= c.num_partitions
        raise IndexError(pidx)

    def execute_partition(self, pidx):
        child, sub = self._locate(pidx)
        yield from child.execute_partition(sub)

    def node_desc(self):
        return f"Union[{len(self.children)}]"


class TpuUnionExec(CpuUnionExec):
    is_device = True

    def node_desc(self):
        return f"TpuUnion[{len(self.children)}]"


class CpuSampleExec(UnaryExec):
    """Bernoulli sample (reference GpuSampleExec)."""

    def __init__(self, fraction: float, seed: int, child: Exec):
        super().__init__(child)
        self.fraction = fraction
        self.seed = seed

    def execute_partition(self, pidx):
        import pyarrow as pa
        rng = np.random.default_rng(self.seed + pidx)
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                mask = rng.random(b.row_count) < self.fraction
                rb = b.to_arrow().filter(pa.array(mask))
                yield batch_from_arrow(pa.Table.from_batches([rb]))

    def node_desc(self):
        return f"Sample[{self.fraction}]"


class TpuSampleExec(UnaryExec):
    is_device = True

    def __init__(self, fraction: float, seed: int, child: Exec):
        super().__init__(child)
        self.fraction = fraction
        self.seed = seed

    def execute_partition(self, pidx):
        import jax
        from spark_rapids_tpu.ops import compact_batch
        from spark_rapids_tpu.columnar.column import _jnp
        jnp = _jnp()
        key = jax.random.PRNGKey(self.seed + pidx)
        with closing_source(self.child.execute_partition(pidx)) as it:
            for i, b in enumerate(it):
                key, sub = jax.random.split(key)
                u = jax.random.uniform(sub, (b.bucket,))
                keep = (u < self.fraction) & \
                    (jnp.arange(b.bucket) < b.row_count)
                yield compact_batch(b, keep)

    def node_desc(self):
        return f"TpuSample[{self.fraction}]"


# ---------------------------------------------------------------------------
# Transitions (reference: GpuRowToColumnarExec / GpuColumnarToRowExec /
# HostColumnarToGpu; ours collapse to host<->device batch copies)
# ---------------------------------------------------------------------------

class TpuFilterProjectExec(UnaryExec):
    """Whole-stage fusion of Filter -> Project: predicate eval, projection,
    and stable compaction run as ONE jitted XLA program per batch — no
    intermediate columns materialize in HBM and dispatch overhead halves
    (the structural advantage over the reference's one-kernel-per-operator
    cuDF dispatch; planner pass fuse_device_stages builds these)."""

    is_device = True

    def __init__(self, condition: Expression, exprs: Sequence[Expression],
                 child: Exec):
        super().__init__(child)
        self.condition = condition
        self.exprs = list(exprs)

    @property
    def schema(self):
        return _project_schema(self.exprs)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.column import (DeferredCount,
                                                      DeviceColumn, _jnp)
        from spark_rapids_tpu.expressions.base import (EvalContext,
                                                       valid_array)
        from spark_rapids_tpu.expressions.evaluator import (
            _signature, device_batch_tcols, tcol_to_device_column)
        jnp = _jnp()
        from spark_rapids_tpu.columnar.encoding import materialize_batch
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                # this pre-fusion node reads raw column planes; the fused
                # stage exec (plan/stages.py) is the encoding-aware path
                b = materialize_batch(b, site="operator")
                key = (_signature([self.condition] + self.exprs, b), b.bucket)

                def build(dtypes=tuple(c.data_type for c in b.columns),
                          bucket=b.bucket):
                    # captures frozen at build time (NOT loop cells): a later
                    # jax retrace of this cached program must see the bucket/
                    # dtypes it was keyed under, not the loop's current batch
                    cond, exprs = self.condition, self.exprs

                    def run(arrs, row_count):
                        cols = [TCol(d, v, dt, lengths=ln, elem_valid=ev)
                                for (d, v, ln, ev), dt in zip(arrs, dtypes)]
                        ctx = EvalContext(cols, "tpu", bucket)
                        pred = cond.eval_tpu(ctx)
                        keep = valid_array(pred, ctx)
                        if not pred.is_scalar:
                            keep = keep & pred.data
                        else:
                            keep = keep & bool(pred.data)
                        keep = keep & (jnp.arange(bucket) < row_count)
                        dest = jnp.cumsum(keep) - 1
                        dest = jnp.where(keep, dest, bucket)
                        cnt = jnp.sum(keep)
                        live = jnp.arange(bucket) < cnt
                        outs = []
                        for e in exprs:
                            dc = tcol_to_device_column(e.eval_tpu(ctx), 0,
                                                       bucket, jnp)
                            nd = jnp.zeros_like(dc.data).at[dest].set(
                                dc.data, mode="drop")
                            nv = jnp.zeros_like(dc.validity).at[dest].set(
                                dc.validity & keep, mode="drop") & live
                            nl = None if dc.lengths is None else \
                                jnp.zeros_like(dc.lengths).at[dest].set(
                                    dc.lengths, mode="drop")
                            ne = None if dc.elem_valid is None else \
                                jnp.zeros_like(dc.elem_valid).at[dest].set(
                                    dc.elem_valid, mode="drop")
                            outs.append((nd, nv, nl, ne))
                        return outs, cnt

                    return run
                from spark_rapids_tpu.exec.stage_compiler import get_or_build
                fn = get_or_build("basic.filter_project", key, build)
                arrs = [(c.data, c.validity, c.lengths, c.elem_valid)
                        for c in b.columns]
                from spark_rapids_tpu.columnar.column import rc_traceable
                outs, cnt = fn(arrs, rc_traceable(b.row_count))
                rc = DeferredCount(cnt)
                cols = [DeviceColumn(d, v, rc, e.data_type, ln, ev)
                        for (d, v, ln, ev), e in zip(outs, self.exprs)]
                from spark_rapids_tpu.expressions.evaluator import _out_names
                yield ColumnarBatch(cols, rc, _out_names(self.exprs))

    def node_desc(self):
        return (f"TpuFilterProject[{self.condition.sql()}; "
                f"{', '.join(e.sql() for e in self.exprs)}]")


class TpuMaterializeEncodedExec(UnaryExec):
    """Explicit eager-decode boundary: every encoded column of every
    child batch materializes here.  The plan/encoding.py planner pass
    inserts this directly above encoded-capable device scans when
    ``spark.rapids.sql.encoding.lateMaterialization`` is off — the scan
    still ships codes over the tunnel (the H2D win), but operators only
    ever see plain columns."""

    is_device = True

    def execute_partition(self, pidx):
        from spark_rapids_tpu.columnar.encoding import materialize_batch
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                yield materialize_batch(b, site="eager")

    def node_desc(self):
        return "TpuMaterializeEncoded"


class HostToDeviceExec(UnaryExec):
    is_device = True

    def execute_partition(self, pidx):
        yield from upload_batches(self.child.execute_partition(pidx))

    def node_desc(self):
        return "HostToDevice"


class DeviceToHostExec(UnaryExec):
    """Device->host copy; the semaphore stays held until task completion
    (run_task), matching the reference's completion-listener release."""

    is_device = False

    #: conf-at-plan-time speculative download row cap
    #: (spark.rapids.sql.collect.speculativeRows); ``None`` falls back
    #: to the transfer-module default.  Set by ``insert_transitions``
    #: so per-query conf rides the plan instance
    dl_spec_rows = None

    def execute_partition(self, pidx):
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                yield b.to_host(spec_rows=self.dl_spec_rows)

    def node_desc(self):
        return "DeviceToHost"


class TpuCoalesceBatchesExec(UnaryExec):
    """Concatenates small device batches up to a target size (reference:
    GpuCoalesceBatches.scala CoalesceGoal/TargetSize)."""

    is_device = True

    def __init__(self, child: Exec, target_bytes: int = 512 << 20,
                 require_single_batch: bool = False):
        super().__init__(child)
        self.target_bytes = target_bytes
        self.require_single_batch = require_single_batch

    def execute_partition(self, pidx):
        from spark_rapids_tpu.ops import concat_batches
        pending: List[ColumnarBatch] = []
        pending_bytes = 0
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                pending.append(b)
                pending_bytes += b.sized_nbytes()
                if not self.require_single_batch and \
                        pending_bytes >= self.target_bytes:
                    yield concat_batches(pending)
                    pending, pending_bytes = [], 0
        if pending:
            yield concat_batches(pending)

    def node_desc(self):
        goal = "RequireSingleBatch" if self.require_single_batch else \
            f"TargetSize({self.target_bytes})"
        return f"TpuCoalesceBatches[{goal}]"
