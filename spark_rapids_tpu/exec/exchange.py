"""Shuffle exchange.

Reference: GpuShuffleExchangeExecBase.scala (device-side partition slicing,
GpuPartitioning.scala:37) + RapidsShuffleInternalManagerBase.scala (writer
materializes per-reduce-partition blocks; reader fetches + concatenates) +
ShuffleBufferCatalog (shuffle payloads tracked spillable).

In-process redesign: the "transport" collapses to a per-exec shuffle store
of spillable host batches (host-staged shuffle = the reference's default
mode, which serializes batches to host via JCudfSerialization).  The device
write path is one fused pass: evaluate pid per row, stable-sort by pid,
copy to host once, slice per target partition.  The multi-node design
(ICI all-to-all within a slice, host-staged DCN across) plugs in behind the
same exec via the parallel/ package.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch
from spark_rapids_tpu.plan.base import Exec, UnaryExec
from spark_rapids_tpu.plan.partitioning import (Partitioning,
                                                RangePartitioning,
                                                RoundRobinPartitioning)


def _sample_bounds(part: RangePartitioning, sample_rows, to_host_batch):
    """Computes n-1 range bounds from sampled key rows (reference:
    GpuRangePartitioner.createRangeBounds — sample, sort, pick evenly)."""
    from spark_rapids_tpu.exec.sort import CpuSortExec
    from spark_rapids_tpu.exec.basic import CpuInMemoryScanExec
    from spark_rapids_tpu.columnar.batch import concat_host_batches
    n = part.num_partitions
    if not sample_rows:
        return HostColumnarBatch([], 0, [])
    sample = concat_host_batches(sample_rows)
    # sort the sample by the specs over the *key* columns (already projected)
    from spark_rapids_tpu.exec.sort import SortSpec
    from spark_rapids_tpu.expressions.base import BoundReference
    key_specs = [SortSpec(BoundReference(i, sample.columns[i].data_type, True),
                          s.ascending, s.effective_nulls_first)
                 for i, s in enumerate(part.specs)]
    scan = CpuInMemoryScanExec([[sample]], sample.schema)
    sorted_sample = next(iter(CpuSortExec(key_specs, scan)
                              .execute_partition(0)))
    cnt = sorted_sample.row_count
    idx = [min(cnt - 1, (j + 1) * cnt // n) for j in range(n - 1)]
    # dedupe equal bounds is unnecessary: equal bounds just yield empty parts
    rows = [sorted_sample.slice(i, 1) for i in idx]
    from spark_rapids_tpu.columnar.batch import concat_host_batches as cc
    return cc(rows) if rows else HostColumnarBatch([], 0, [])


#: defaults for the round-5 shuffle knobs; the convert-time conf values
#: travel on each exchange INSTANCE (per-query conf must ride the plan,
#: not the process — concurrent sessions share this module)
SHRINK_THRESHOLD_BYTES = 64 << 20
RANGE_BOUNDS_SAMPLE_ROWS = 1024
COLLECTIVE_ENABLED = True


class _LazyPartitions:
    """Reduce-side view over mode-specific storage: partitions fetch on
    first access (the reduce task's fetch) and cache for re-execution.
    Distinct partitions fetch CONCURRENTLY (the lock guards only the
    bookkeeping, never the fetch itself — serializing fetches would undo
    the task pool's host-I/O overlap); a duplicate request for an
    in-flight partition waits for the first fetch instead of repeating
    it."""

    def __init__(self, n: int, fetch):
        import threading
        self._n = n
        self._fetch = fetch
        self._cache: Dict[int, List] = {}
        self._inflight: Dict[int, "threading.Event"] = {}
        self._lock = threading.Lock()
        self._bg = None

    #: optional callback fired once every partition has been fetched
    #: (storage can be released; results stay in the cache)
    on_all_fetched = None

    def __getitem__(self, pidx: int):
        import threading
        with self._lock:
            if pidx in self._cache:
                return self._cache[pidx]
            ev = self._inflight.get(pidx)
            if ev is None:
                ev = self._inflight[pidx] = threading.Event()
            else:
                ev = (ev, "waiter")
        if isinstance(ev, tuple):
            # blocking on another thread's in-flight fetch: drop device
            # admission first (the fetcher may be a bare warm thread whose
            # CACHED-mode map re-run needs a permit — holding ours while
            # waiting on it would deadlock the semaphore); re-acquired
            # lazily at the next device section / spool dequeue
            from spark_rapids_tpu.plan.base import \
                release_semaphore_for_wait
            release_semaphore_for_wait()
            ev[0].wait()
            return self[pidx]   # cached now; re-fetches if the owner failed
        try:
            res = self._fetch(pidx)
        except BaseException:
            with self._lock:       # let a later caller retry the fetch
                self._inflight.pop(pidx, None)
            ev.set()
            raise
        cb = None
        with self._lock:
            self._cache[pidx] = res
            self._inflight.pop(pidx, None)
            if len(self._cache) == self._n and \
                    self.on_all_fetched is not None:
                cb, self.on_all_fetched = self.on_all_fetched, None
        ev.set()
        if cb is not None:
            cb()
        return res

    def __len__(self):
        return self._n

    def prefetch(self, pidx: int) -> None:
        """Asynchronously warms ``pidx`` (pipelined shuffle read: the next
        reduce partition's frames fetch/deserialize while the current one
        is joined/aggregated).  At most ONE background fetch runs per
        store; errors are swallowed — the consumer's own access retries
        through the normal failure path, so a failed warm can neither
        poison the cache nor double-report a fault."""
        import contextvars
        import threading
        if pidx < 0 or pidx >= self._n:
            return
        with self._lock:
            if pidx in self._cache or pidx in self._inflight:
                return
            bg = self._bg
            if bg is not None and bg.is_alive():
                return

            def warm():
                try:
                    self[pidx]
                except BaseException:   # noqa: BLE001 - see docstring
                    pass
                finally:
                    # a CACHED-mode short fetch re-runs map tasks whose
                    # device sections acquire admission under THIS
                    # thread's identity; no task-completion listener
                    # covers a warm thread, so drop any hold ourselves
                    # (a leaked holder entry would pin a permit forever)
                    from spark_rapids_tpu.memory.device_manager import \
                        get_runtime
                    rt = get_runtime()
                    if rt is not None:
                        rt.semaphore.release_all()

            # carry the active query context so fetch events attribute
            ctx = contextvars.copy_context()
            t = threading.Thread(target=ctx.run, args=(warm,),
                                 name="tpu-prefetch-shuffle", daemon=True)
            self._bg = t
            # started INSIDE the lock: a not-yet-started thread reads as
            # not alive, and a concurrent prefetch would slip past the
            # single-flight guard (the warm itself blocks on this lock
            # only momentarily at its own bookkeeping)
            t.start()


class CpuShuffleExchangeExec(UnaryExec):
    """Host shuffle: materializes the map side once into a store of host
    batches grouped by reduce partition.  The storage/fetch path is chosen
    by ``spark.rapids.shuffle.mode`` (GpuShuffleEnv analog): DEFAULT
    in-memory store, MULTITHREADED spill-file writer/reader pools, CACHED
    catalog + client/server transport."""

    def __init__(self, partitioning: Partitioning, child: Exec,
                 shuffle_env=None):
        super().__init__(child)
        self.partitioning = partitioning
        #: the owning session's ShuffleEnv; None falls back to the
        #: process-wide env (standalone plan construction)
        self.shuffle_env = shuffle_env
        self._store: Optional[List[List]] = None

    @property
    def num_partitions(self):
        return self.partitioning.num_partitions

    # -- map side -----------------------------------------------------------
    def _split_pairs(self, hb: HostColumnarBatch, pids: np.ndarray, n: int):
        """Splits one batch into (reduce_partition, sub_batch) pairs."""
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        order = np.argsort(pids, kind="stable")
        counts = np.bincount(pids, minlength=n)
        tab = pa.Table.from_batches([hb.to_arrow()]).take(pa.array(order))
        off = 0
        out = []
        for p in range(n):
            if counts[p]:
                out.append((p, batch_from_arrow(tab.slice(off, counts[p]))))
            off += counts[p]
        return out

    def _map_pairs(self, mp: int, n: int):
        from spark_rapids_tpu.plan.base import closing_source
        part = self.partitioning
        if isinstance(part, RoundRobinPartitioning):
            part = RoundRobinPartitioning(n, start=mp)
        # early exit (a stopped map task) must close the child chain
        # deterministically — queued spillables/prefetch threads upstream
        # release now, not at GC
        with closing_source(self.child.execute_partition(mp)) as it:
            for hb in it:
                pids = part.partition_ids_cpu(hb)
                yield from self._split_pairs(hb, pids, n)

    def _materialize(self):
        if self._store is not None:
            return
        part = self.partitioning
        n = part.num_partitions
        if isinstance(part, RangePartitioning) and part.bounds is None:
            self._compute_bounds()
        from spark_rapids_tpu.shuffle.env import get_shuffle_env
        env = self.shuffle_env or get_shuffle_env()
        mode = env.mode if env is not None else "DEFAULT"
        if mode == "MULTITHREADED":
            self._store = self._materialize_multithreaded(env, n)
            return
        if mode == "CACHED":
            self._store = self._materialize_cached(env, n)
            return
        from spark_rapids_tpu.plan.base import (iter_partition_tasks,
                                                run_task_iter)
        store: List[List] = [[] for _ in range(n)]
        # map side: one task per map partition on the task pool (the
        # multithreaded shuffle writer analog); pairs come back in map
        # order so the store stays deterministic
        for p, sub in iter_partition_tasks(
                lambda mp: run_task_iter(
                    lambda m: self._map_pairs(m, n), mp),
                self.child.num_partitions):
            store[p].append(sub)
        self._store = store

    def _materialize_multithreaded(self, env, n: int):
        """MULTITHREADED mode (reference RapidsShuffleThreadedWriterBase):
        pool-parallel serialization into per-map spill files, read back
        per reduce partition on the reader pool."""
        from spark_rapids_tpu.shuffle.threaded import (ThreadedShuffleReader,
                                                       ThreadedShuffleWriter)
        sid = env.next_shuffle_id()
        outputs = []
        for mp in range(self.child.num_partitions):
            writer = ThreadedShuffleWriter(sid, mp, n, env.writer_pool,
                                           directory=env.shuffle_dir,
                                           codec=env.codec)
            outputs.append(writer.write(list(self._map_pairs(mp, n))))
        reader = ThreadedShuffleReader(env.reader_pool)
        lazy = _LazyPartitions(
            n, lambda pidx: list(reader.read(outputs, pidx)))

        def cleanup():
            import os
            for o in outputs:
                try:
                    os.unlink(o.path)
                except OSError:
                    pass
        lazy.on_all_fetched = cleanup
        return lazy

    def _materialize_cached(self, env, n: int):
        """CACHED mode (reference UCX shuffle): map output registered in
        the ShuffleBufferCatalog, reduce side fetches through the
        client/server state machines over the transport.

        Resilient reduce side: the exchange remembers which blocks each
        reduce partition expects (lineage metadata).  A fetch that comes
        back short — the producing executor died and heartbeat expiry
        invalidated its blocks — RE-RUNS the producing map tasks to
        regenerate exactly the missing blocks, then refetches (the
        FetchFailed -> stage-retry story, scoped to the lost maps)."""
        from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId
        from spark_rapids_tpu.shuffle.client_server import \
            ShuffleFetchFailed
        catalog, client, server = env.cached_machinery()
        sid = env.next_shuffle_id()
        written: Dict[int, set] = {p: set() for p in range(n)}

        def write_map(mp: int, only_pidx: Optional[int] = None) -> None:
            for p, sub in self._map_pairs(mp, n):
                if only_pidx is not None and p != only_pidx:
                    continue
                blk = ShuffleBlockId(sid, mp, p)
                catalog.add_batch(blk, sub, owner=server.executor_id)
                written[p].add(blk)

        for mp in range(self.child.num_partitions):
            write_map(mp)

        def fetch(pidx):
            from spark_rapids_tpu.aux.events import emit
            from spark_rapids_tpu.aux.faults import note_recovery
            expected = written[pidx]
            if not expected:
                return []
            # up to 3 passes: a transport-only failure earns one clean
            # refetch, and ONE lineage re-run is attempted for missing
            # blocks whenever the loss is detected (pass 0 or later)
            reran = False
            last_cause = "fetch kept failing with intact blocks"
            for attempt in range(3):
                try:
                    blocks = client.do_fetch(server, sid, pidx)
                    missing = expected - set(blocks)
                except ShuffleFetchFailed as e:
                    if attempt == 2:
                        raise
                    # a transport-level failure does NOT mean the blocks
                    # are gone: only regenerate what the catalog actually
                    # lost, else re-adding frames to intact blocks would
                    # DOUBLE their rows on the refetch
                    blocks = []
                    missing = expected - \
                        set(catalog.block_ids(sid, pidx))
                    last_cause = e.cause
                if not missing:
                    if not blocks:
                        # blocks intact, fetch failed anyway (transport):
                        # one more fetch pass, then surface the failure
                        continue
                    out = []
                    for b in blocks:
                        out.extend(client.received.read_batches(b))
                        client.received.drop(b)
                    # the fetched partition is cached by _LazyPartitions;
                    # release the map-side frames (reference:
                    # unregisterShuffle on consume)
                    catalog.drop_partition(sid, pidx)
                    return out
                if reran:
                    # give up — but not before releasing the frames this
                    # attempt DID fetch (the env-lifetime received
                    # catalog outlives the query; leaking here pins host
                    # memory until process exit)
                    for b in blocks:
                        client.received.drop(b)
                    raise ShuffleFetchFailed(
                        sid, pidx, server.executor_id,
                        f"{len(missing)} blocks missing after map re-run")
                # blocks invalidated (dead executor): re-run the
                # producing map tasks; write_map regenerates only this
                # partition's blocks (absent from the catalog, so the
                # re-add cannot duplicate frames)
                for b in expected:    # drop partial frames: refetch is
                    client.received.drop(b)   # all-or-nothing
                lost_maps = sorted({b.map_id for b in missing})
                note_recovery("map_reruns", len(lost_maps))
                emit("mapRerun", shuffle_id=sid, partition=pidx,
                     maps=len(lost_maps),
                     missing_blocks=len(missing))
                for mp in lost_maps:
                    write_map(mp, only_pidx=pidx)
                reran = True
            raise ShuffleFetchFailed(sid, pidx, server.executor_id,
                                     last_cause)
        return _LazyPartitions(n, fetch)

    def _compute_bounds(self):
        """Extra pass sampling key rows (the reference runs a sample job)."""
        part = self.partitioning
        samples = []
        rng = np.random.default_rng(0)
        for mp in range(self.child.num_partitions):
            for hb in self.child.execute_partition(mp):
                keys = part._key_batch_cpu(hb)
                k = min(hb.row_count, 1000)
                if k == 0:
                    continue
                take = np.sort(rng.choice(hb.row_count, size=k,
                                          replace=False))
                import pyarrow as pa
                tab = pa.Table.from_batches([keys.to_arrow()]) \
                    .take(pa.array(take))
                from spark_rapids_tpu.columnar.batch import batch_from_arrow
                samples.append(batch_from_arrow(tab))
        part.bounds = _sample_bounds(part, samples, None)

    # -- reduce side --------------------------------------------------------
    def execute_partition(self, pidx):
        from spark_rapids_tpu.plan.base import release_semaphore_for_wait
        if self._store is None:
            # drop device admission before blocking on the map side (the
            # map tasks need permits); re-acquired lazily downstream
            release_semaphore_for_wait()
            with self._exec_lock:
                self._materialize()
        self._prefetch_next(pidx)
        yield from self._store[pidx]

    def _prefetch_next(self, pidx: int) -> None:
        """Pipelined shuffle read: while this reduce partition streams to
        its consumer, the NEXT one's fetch/deserialize runs in the
        background (lazy stores only — an eager store is already local)."""
        import spark_rapids_tpu.exec.pipeline as _PL
        if _PL.PIPELINE_ENABLED and isinstance(self._store,
                                               _LazyPartitions):
            self._store.prefetch(pidx + 1)

    def node_desc(self):
        return f"Exchange[{self.partitioning.desc()}]"


class TpuShuffleExchangeExec(CpuShuffleExchangeExec):
    """Device shuffle.

    DEFAULT mode within one process keeps the store DEVICE-RESIDENT: map
    output batches never leave HBM (reference: the UCX caching writer keeps
    shuffle output on device in ShuffleBufferCatalog,
    RapidsShuffleInternalManagerBase.scala:1034).  Each map batch is first
    shrunk to its live row bucket (one sync at this materialization
    boundary), then each reduce partition is produced by a mask+compact
    kernel whose output count stays deferred.  The store is NOT yet
    catalog-spillable — an oversized shuffle should use MULTITHREADED mode
    (host-staged, spill-file backed) via spark.rapids.shuffle.mode.

    MULTITHREADED/CACHED modes keep the host-staged path from the base
    class (process-boundary semantics, spillable storage).
    """

    is_device = True

    #: set when the collective (mesh) path materialized this exchange:
    #: (MeshContext, sharded cols, per-device counts, schema)
    _collective = None

    #: conf-at-convert-time knobs (spark.rapids.shuffle.device.
    #: shrinkThresholdBytes / sql.rangeBounds.sampleRows /
    #: shuffle.collective.enabled / sql.collect.speculativeRows);
    #: ``None`` falls back to the module/transfer defaults so
    #: directly-driven test execs keep working
    shrink_threshold_bytes = None
    range_bounds_sample_rows = None
    collective_enabled = None
    dl_spec_rows = None

    def _collective_eligible(self, part):
        """The mesh path covers hash shuffles whose reduce count equals the
        mesh size and whose columns ride the sharded layout (no nested
        element-validity planes)."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.parallel.mesh import active_mesh
        from spark_rapids_tpu.plan.partitioning import HashPartitioning
        ce = self.collective_enabled
        if not (COLLECTIVE_ENABLED if ce is None else ce):
            return None
        ctx = active_mesh()
        if ctx is None or not isinstance(part, HashPartitioning):
            return None
        if part.num_partitions != ctx.num_devices:
            return None
        for f in self.child.schema.fields:
            if isinstance(f.data_type, (T.ArrayType, T.MapType,
                                        T.StructType)):
                return None
        return ctx

    def _materialize_collective(self, ctx):
        """Mesh execution: the whole shuffle is parallel/spmd.py's fused
        in-mesh exchange (shard -> compiled pid program -> one all_to_all
        collective; the UCX RDMA transport + catalogs + heartbeats of the
        reference collapse into the collective).  May raise
        ``SpmdHbmExceeded`` — handled by ``_materialize`` as a fallback
        to the host-staged spill-safe path."""
        from spark_rapids_tpu.parallel import spmd as _SPMD
        from spark_rapids_tpu.parallel.spmd import (check_hbm_budget,
                                                    spmd_hash_exchange)
        schema = self.child.schema
        # incremental HBM check while draining: an input that cannot
        # possibly fit stops pulling as soon as the running total proves
        # it, instead of materializing the rest first.  The host-staged
        # fallback then re-executes the child — the second pull rides
        # the scan cache / already-materialized upstream stores, but is
        # still a real cost, which is why this bails as EARLY as the
        # evidence allows.  The admission model itself lives in ONE
        # place: spmd.check_hbm_budget.
        budget = _SPMD._hbm_budget()
        total = 0
        batches = []
        for mp in range(self.child.num_partitions):
            for b in self.child.execute_partition(mp):
                batches.append(b)
                if budget is not None:
                    total += (b.nbytes() or 0) if hasattr(b, "nbytes") \
                        else 0
                    check_hbm_budget(total // max(1, ctx.num_devices),
                                     budget)
        out_cols, out_counts = spmd_hash_exchange(ctx, batches, schema,
                                                  self.partitioning)
        self._collective = (ctx, out_cols, out_counts, schema)

    def _materialize(self):
        if self._store is not None or self._collective is not None:
            return
        from spark_rapids_tpu.shuffle.env import get_shuffle_env
        env = self.shuffle_env or get_shuffle_env()
        mode = env.mode if env is not None else "DEFAULT"
        part = self.partitioning
        if mode == "DEFAULT":
            ctx = self._collective_eligible(part)
            if ctx is not None:
                from spark_rapids_tpu.parallel.spmd import SpmdHbmExceeded
                from spark_rapids_tpu.plan.base import _is_retryable
                try:
                    self._materialize_collective(ctx)
                    return
                except Exception as e:   # noqa: BLE001 - classified below
                    if not (_is_retryable(e) or
                            isinstance(e, SpmdHbmExceeded)):
                        raise
                    # per-stage ICI-vs-host choice: a working set that
                    # cannot fit per-device HBM (SpmdHbmExceeded) takes
                    # the host-staged spillable path; a lost chip fails
                    # the whole collective step and degrades the same
                    # way (Theseus-style: finish the plan when a
                    # participant dies mid-shuffle)
                    from spark_rapids_tpu.aux.events import emit
                    from spark_rapids_tpu.aux.faults import note_recovery
                    note_recovery("collective_fallbacks")
                    emit("collectiveFallback",
                         reason=("hbm" if isinstance(e, SpmdHbmExceeded)
                                 else "fault"),
                         error=f"{type(e).__name__}: {e}"[:160])
                    self._collective = None
        if mode != "DEFAULT":
            super()._materialize()
            return
        if isinstance(part, RangePartitioning) and part.bounds is None:
            self._compute_bounds()
        n = part.num_partitions
        from spark_rapids_tpu.plan.partitioning import SinglePartitioning
        store: List[List] = [[] for _ in range(n)]
        if isinstance(part, SinglePartitioning) or n == 1:
            # child partitions run as concurrent tasks via execute_all
            store[0].extend(self.child.execute_all())
            self._store = store
            return
        from spark_rapids_tpu.ops.batch_ops import (compact_batch,
                                                    shrink_batch)
        from spark_rapids_tpu.columnar.column import _jnp, rc_traceable
        from spark_rapids_tpu.plan.base import (iter_partition_tasks,
                                                run_task_iter)
        jnp = _jnp()
        # HBM guard: the device-resident store keeps one full-bucket
        # compacted copy of every map batch PER reduce partition (~n x
        # input bytes).  When that estimate crosses the free-HBM budget,
        # fall back to the host-staged path automatically instead of
        # OOMing the device (DEFAULT is the default mode; users shouldn't
        # need to know to flip spark.rapids.shuffle.mode=MULTITHREADED).
        budget = self._device_store_budget()
        state = {"stored_estimate": 0, "host_staging": False}
        state_lock = __import__("threading").Lock()

        #: only batches whose n-fold padded footprint is material get the
        #: padding-shrink (shrink needs the exact count -> a ~185ms tunnel
        #: sync); below the threshold the compacts just keep the input
        #: bucket and counts stay deferred (sync-free map side)
        shrink_threshold = self.shrink_threshold_bytes \
            if self.shrink_threshold_bytes is not None \
            else SHRINK_THRESHOLD_BYTES

        def map_gen(mp):
            from spark_rapids_tpu.plan.base import closing_source
            p_eff = part
            if isinstance(part, RoundRobinPartitioning):
                p_eff = RoundRobinPartitioning(n, start=mp)
            # STREAMED (materializing the whole partition to batch the
            # count syncs would defeat the host-staging fallback below):
            # only batches whose n-fold footprint is material pay the
            # shrink (and its one count sync); small batches flow through
            # sync-free with deferred counts.  closing_source: an
            # abandoned map task stops the chain now, not at GC
            with closing_source(self.child.execute_partition(mp)) as it:
                yield from _map_core(it, mp, p_eff)

        def _map_core(it, mp, p_eff):
            for b in it:
                # cap the n-fold storage cost: drop padding before the
                # per-partition compacts
                if b.nbytes() * n > shrink_threshold:
                    b = shrink_batch(b)
                with state_lock:
                    if not state["host_staging"]:
                        state["stored_estimate"] += b.nbytes() * n
                        if budget is not None and \
                                state["stored_estimate"] > budget:
                            # auto-fallback: the rest of the map output
                            # goes through the host-staged writer; batches
                            # already compacted stay on device (they fit
                            # the budget) and execute_partition handles
                            # the mixed store
                            import logging
                            logging.getLogger(__name__).info(
                                "device shuffle store would exceed HBM "
                                "budget (%d > %d bytes); host-staging the "
                                "remainder",
                                state["stored_estimate"], budget)
                            state["host_staging"] = True
                    staging = state["host_staging"]
                if staging:
                    yield from self._slice_host_pairs(b, p_eff, n)
                    continue
                pids = p_eff.partition_ids_tpu(b)
                rowpos = jnp.arange(b.bucket)
                inrow = rowpos < rc_traceable(b.row_count)
                for p in range(n):
                    yield p, compact_batch(b, (pids == p) & inrow)

        for p, sub in iter_partition_tasks(
                lambda mp: run_task_iter(map_gen, mp),
                self.child.num_partitions):
            store[p].append(sub)
        self._store = store

    def _device_store_budget(self):
        """Bytes the device-resident shuffle store may occupy: half the
        remaining device pool, or None when no runtime is initialized
        (tests that drive execs directly)."""
        from spark_rapids_tpu.memory.device_manager import \
            free_device_headroom
        return free_device_headroom(2)

    def _slice_host_pairs(self, b, part, n):
        """One device batch -> (pid, host slice) pairs via the device
        sort-by-pid writer (the _map_pairs core, batch-wise)."""
        from spark_rapids_tpu.columnar.column import DeviceColumn, _jnp
        from spark_rapids_tpu.ops.batch_ops import gather_batch
        from spark_rapids_tpu.ops.sort_ops import SortOrder, sort_permutation
        jnp = _jnp()
        pids = part.partition_ids_tpu(b)
        pid_col = DeviceColumn(pids.astype(np.int64),
                               jnp.ones(b.bucket, dtype=bool),
                               b.row_count, None)
        aug = ColumnarBatch([pid_col] + list(b.columns), b.row_count)
        perm = sort_permutation(aug, [SortOrder(0, True, True)])
        shuffled = gather_batch(b, perm, b.row_count)
        from spark_rapids_tpu.aux import transitions as TR
        counts = TR.fetch(jnp.bincount(
            jnp.clip(pids, 0, n), length=n + 1),
            site="shuffle-pid-counts")[:n]
        hb = shuffled.to_host(spec_rows=self.dl_spec_rows)
        hb.names = b.names
        off = 0
        for p in range(n):
            if counts[p]:
                yield p, hb.slice(off, int(counts[p]))
            off += int(counts[p])

    def execute_partition(self, pidx):
        from spark_rapids_tpu.plan.base import release_semaphore_for_wait
        if self._store is None and self._collective is None:
            release_semaphore_for_wait()
            with self._exec_lock:
                self._materialize()
        if self._store is not None:
            self._prefetch_next(pidx)
        if self._collective is not None:
            from spark_rapids_tpu.parallel import collective as C
            ctx, cols, counts, schema = self._collective
            yield C.shard_to_batch(ctx, cols, counts, schema, pidx)
            return
        from spark_rapids_tpu.columnar.batch import ColumnarBatch as _CB
        from spark_rapids_tpu.exec.basic import upload_batches
        host_pending = []
        for b in self._store[pidx]:
            if isinstance(b, _CB):
                yield b
            else:
                host_pending.append(b)
        if host_pending:
            yield from upload_batches(host_pending)

    def _map_pairs(self, mp: int, n: int):
        """Device shuffle write: pid eval + stable sort-by-pid on device,
        ONE host copy, then arrow slicing per reduce partition (shared
        per-batch core: ``_slice_host_pairs``)."""
        from spark_rapids_tpu.plan.base import closing_source
        part = self.partitioning
        if isinstance(part, RoundRobinPartitioning):
            part = RoundRobinPartitioning(n, start=mp)
        with closing_source(self.child.execute_partition(mp)) as it:
            for b in it:
                yield from self._slice_host_pairs(b, part, n)

    def _compute_bounds(self):
        self._compute_bounds_tpu()

    def _compute_bounds_tpu(self):
        """Samples on device, computes bounds on host (small).

        Fully fused: every-step-th row of each batch is gathered on device
        with a DEFERRED sample count, all samples concat on device, and
        ONE download ships them — the old per-batch host download + count
        force cost two tunnel round trips per input batch (~6s of a 7s
        query at 4 partitions)."""
        from spark_rapids_tpu.columnar.column import (DeferredCount, _jnp,
                                                      rc_traceable)
        from spark_rapids_tpu.ops.batch_ops import concat_batches, \
            gather_batch
        jnp = _jnp()
        part = self.partitioning
        samples = []
        for mp in range(self.child.num_partitions):
            for b in self.child.execute_partition(mp):
                keys = part._key_batch_tpu(b)
                if not keys.columns:
                    continue
                # evenly spaced over the LIVE rows (a stride over the
                # bucket would collapse to ~1 sample for a filtered batch
                # whose count is far below its padding)
                k = self.range_bounds_sample_rows \
                    if self.range_bounds_sample_rows is not None \
                    else RANGE_BOUNDS_SAMPLE_ROWS
                rc_t = jnp.asarray(rc_traceable(b.row_count),
                                   dtype=np.int64)
                j = jnp.arange(k, dtype=np.int64)
                idx = jnp.where(rc_t <= k,
                                jnp.minimum(j, jnp.maximum(rc_t - 1, 0)),
                                (j * rc_t) // k)
                cnt = DeferredCount(jnp.minimum(rc_t, k))
                samples.append(gather_batch(keys, idx, cnt))
        if not samples:
            part.bounds = _sample_bounds(part, [], None)
            return
        from spark_rapids_tpu.ops.batch_ops import _committed_device
        sample_devs = {id(d) for d in
                       (_committed_device(b) for b in samples)
                       if d is not None}
        if len(sample_devs) > 1:
            # mesh shards: sample batches committed to DIFFERENT devices
            # cannot concat in one program — gather per shard and merge
            # on host (bounded: <= RANGE_BOUNDS_SAMPLE_ROWS per shard)
            from spark_rapids_tpu.columnar.batch import concat_host_batches
            hbs = [b.to_host() for b in samples]
            live = [h for h in hbs if h.row_count]
            hb = concat_host_batches(live) if live else hbs[0]
        else:
            hb = concat_batches(samples).to_host()
        part.bounds = _sample_bounds(part, [hb] if hb.row_count else [],
                                     None)

    def node_desc(self):
        return f"TpuExchange[{self.partitioning.desc()}]"


# plan-rewrite registration (reference: ShuffleExchangeExec rule
# GpuOverrides.scala:4023 + GpuShuffleMeta)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

from spark_rapids_tpu.plan import typechecks as _TS  # noqa: E402

def _convert_exchange(p, m):
    from spark_rapids_tpu import config as C
    out = TpuShuffleExchangeExec(p.partitioning, p.children[0],
                                 shuffle_env=p.shuffle_env)
    # round-5 behavior knobs ride the INSTANCE (set from meta.conf at
    # convert time) — concurrent sessions must not race module globals
    out.shrink_threshold_bytes = C.parse_bytes(
        m.conf.get(C.SHUFFLE_DEVICE_SHRINK_THRESHOLD.key))
    out.range_bounds_sample_rows = int(
        m.conf.get(C.RANGE_BOUNDS_SAMPLE_ROWS.key))
    out.collective_enabled = bool(
        m.conf.get(C.COLLECTIVE_EXCHANGE_ENABLED.key))
    out.dl_spec_rows = int(m.conf.get(C.DOWNLOAD_SPECULATIVE_ROWS.key))
    return out


register_exec(CpuShuffleExchangeExec,
              convert=_convert_exchange,
              sig=_TS.BASIC_WITH_ARRAYS,
              exprs_of=lambda p: list(p.partitioning.exprs),
              extra_tag=lambda m: _TS.no_array_keys(
                  list(m.plan.partitioning.exprs), m,
                  "partitioning expression"),
              desc="shuffle exchange (device partition + host-staged store)")
