"""Expand and TakeOrderedAndProject operators.

Reference: GpuExpandExec.scala (Expand's projection-list fan-out that powers
ROLLUP/CUBE/GROUPING SETS) and the TakeOrderedAndProject registration in
GpuOverrides.scala commonExecs (:3999-4311) — per-partition top-K, gather to
one partition, final top-K, then project.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import concat_host_batches
from spark_rapids_tpu.exec.sort import (SortSpec, device_sort_batch,
                                        host_sort_batch)
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.evaluator import (eval_exprs_cpu,
                                                    eval_exprs_tpu)
from spark_rapids_tpu.plan.base import Exec, UnaryExec, closing_source


class CpuExpandExec(UnaryExec):
    """Emits one output row-set per projection list for every input batch
    (Spark ExpandExec; each projection is the same arity and output names).
    """

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str], child: Exec):
        super().__init__(child)
        if not projections:
            raise ValueError("Expand needs at least one projection")
        arity = len(projections[0])
        for p in projections:
            if len(p) != arity:
                raise ValueError("Expand projections must share arity")
        if len(names) != arity:
            raise ValueError("Expand names must match projection arity")
        self.projections = [list(p) for p in projections]
        self.names = list(names)

    @property
    def schema(self):
        fields = []
        for j, name in enumerate(self.names):
            dt = self.projections[0][j].data_type
            nullable = any(p[j].nullable for p in self.projections)
            for p in self.projections[1:]:
                dt = T.common_type(dt, p[j].data_type)
            fields.append(T.StructField(name, dt, nullable))
        return T.StructType(fields)

    def _coerced(self, proj):
        """Casts each projection output to the common column type so every
        emitted batch has the unified Expand schema."""
        from spark_rapids_tpu.expressions.cast import Cast
        from spark_rapids_tpu.expressions.base import Alias
        out_schema = self.schema
        coerced = []
        for j, e in enumerate(proj):
            want = out_schema.fields[j].data_type
            if e.data_type != want:
                e = Cast(e, want)
            coerced.append(Alias(e, self.names[j]))
        return coerced

    def execute_partition(self, pidx):
        coerced = [self._coerced(p) for p in self.projections]
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                for proj in coerced:
                    yield eval_exprs_cpu(proj, b, self.names)

    def node_desc(self):
        return f"Expand[{len(self.projections)} projections]"


class TpuExpandExec(CpuExpandExec):
    """Device Expand: each projection list is one fused XLA program over the
    same resident input batch — the fan-out costs no extra host transfers."""

    is_device = True

    def __init__(self, cpu: CpuExpandExec):
        super().__init__(cpu.projections, cpu.names, cpu.children[0])

    def execute_partition(self, pidx):
        coerced = [self._coerced(p) for p in self.projections]
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                for proj in coerced:
                    yield eval_exprs_tpu(proj, b, self.names)

    def node_desc(self):
        return f"TpuExpand[{len(self.projections)} projections]"


class CpuTakeOrderedAndProjectExec(UnaryExec):
    """ORDER BY + LIMIT [+ projection] collapsed into one operator.

    Local top-K per child partition, then a final merge + top-K + project in
    the single output partition (reference: GpuTopN in limit.scala driven by
    the TakeOrderedAndProjectExec rule)."""

    def __init__(self, n: int, specs: Sequence[SortSpec], child: Exec,
                 project: Optional[Sequence[Expression]] = None):
        super().__init__(child)
        self.n = n
        self.specs = list(specs)
        self.project = list(project) if project else None

    @property
    def schema(self):
        if self.project is None:
            return self.child.schema
        from spark_rapids_tpu.expressions.evaluator import _out_names
        return T.StructType([
            T.StructField(nm, e.data_type, e.nullable)
            for nm, e in zip(_out_names(self.project), self.project)])

    @property
    def num_partitions(self):
        return 1

    def _local_topk(self, cp: int):
        batches = list(self.child.execute_partition(cp))
        if not batches:
            return None
        b = host_sort_batch(concat_host_batches(batches), self.specs)
        return b.slice(0, min(self.n, b.row_count))

    def execute_partition(self, pidx):
        tops = [t for cp in range(self.child.num_partitions)
                for t in [self._local_topk(cp)] if t is not None]
        if not tops:
            return
        merged = host_sort_batch(concat_host_batches(tops), self.specs)
        merged = merged.slice(0, min(self.n, merged.row_count))
        if self.project is not None:
            merged = eval_exprs_cpu(self.project, merged)
        yield merged

    def node_desc(self):
        ks = ", ".join(f"{s.expr.sql()} {'ASC' if s.ascending else 'DESC'}"
                       for s in self.specs)
        return f"TakeOrderedAndProject[n={self.n}, {ks}]"


class TpuTakeOrderedAndProjectExec(CpuTakeOrderedAndProjectExec):
    is_device = True

    def __init__(self, cpu: CpuTakeOrderedAndProjectExec):
        super().__init__(cpu.n, cpu.specs, cpu.children[0], cpu.project)

    def _local_topk(self, cp: int):
        from spark_rapids_tpu.ops import concat_batches, take_front
        batches = list(self.child.execute_partition(cp))
        if not batches:
            return None
        b = device_sort_batch(concat_batches(batches), self.specs)
        return take_front(b, self.n)   # take_front clamps without a sync

    def execute_partition(self, pidx):
        from spark_rapids_tpu.ops import concat_batches, take_front
        tops = [t for cp in range(self.child.num_partitions)
                for t in [self._local_topk(cp)] if t is not None]
        if not tops:
            return
        merged = device_sort_batch(concat_batches(tops), self.specs)
        merged = take_front(merged, self.n)
        if self.project is not None:
            merged = eval_exprs_tpu(self.project, merged)
        yield merged

    def node_desc(self):
        return "Tpu" + super().node_desc()


# plan-rewrite registrations
from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuExpandExec,
              convert=lambda p, m: TpuExpandExec(p),
              sig=TS.BASIC_WITH_ARRAYS,
              exprs_of=lambda p: [e for proj in p.projections for e in proj],
              desc="projection fan-out (ROLLUP/CUBE/GROUPING SETS)")
register_exec(CpuTakeOrderedAndProjectExec,
              convert=lambda p, m: TpuTakeOrderedAndProjectExec(p),
              sig=TS.BASIC_WITH_ARRAYS,
              exprs_of=lambda p: ([s.expr for s in p.specs]
                                  + (p.project or [])),
              extra_tag=lambda m: TS.no_array_keys(
                  [s.expr for s in m.plan.specs], m, "sort key"),
              desc="order-by + limit + project in one pass")
