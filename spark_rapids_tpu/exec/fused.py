"""Whole-stage fusion execs.

One jitted XLA program per (stage signature, input shapes) covering a
maximal chain of device-side narrow ops — filters and projections — plus,
when the stage feeds a hash aggregate, the aggregate's per-batch update
pass.  Inside a fused stage filters never compact: they AND into a
selection mask that the terminal consumes (reductions mask by it; the
compact terminal performs one multi-operand sort).  This removes whole
kernel dispatches (each costs ~10-20ms of round-trip latency on a
tunnel-attached TPU) and all intermediate HBM materialization.

The reference dispatches one cuDF kernel per operator and cannot do this
(GpuProjectExec -> columnarEval chains, basicPhysicalOperators.scala:350);
whole-stage fusion is the structural advantage of tracing compilation, and
is this engine's analog of Spark's whole-stage codegen (which the
reference explicitly replaces with columnar execution).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (DeferredCount, DeviceColumn,
                                              rc_traceable)
from spark_rapids_tpu.expressions.base import EvalContext, Expression, TCol, \
    valid_array
from spark_rapids_tpu.plan.base import Exec, UnaryExec, closing_source


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()


#: ops are ('filter', condition) or ('project', [exprs])
StageOp = Tuple[str, object]


def _ops_signature(ops: Sequence[StageOp]) -> Tuple:
    sig = []
    for kind, payload in ops:
        if kind == "filter":
            sig.append(("F", payload.sql(), str(payload.data_type)))
        else:
            sig.append(("P", tuple((e.sql(), str(e.data_type))
                                   for e in payload)))
    return tuple(sig)


def _lits_desc(promoted) -> str:
    """Explain-only rendering of promoted-literal slot VALUES: the ops'
    sql() shows value-independent ``$litN`` placeholders (they key the
    program cache), so the concrete bindings surface here."""
    if not promoted:
        return ""
    return " lits[" + \
        ", ".join(f"$lit{p.slot}={p.value!r}" for p in promoted) + "]"


def _batch_signature(batch: ColumnarBatch) -> Tuple:
    from spark_rapids_tpu.columnar.encoding import (DictionaryColumn,
                                                    RleColumn)
    sig = []
    for c in batch.columns:
        enc = None
        if isinstance(c, DictionaryColumn):
            # codes plane, value-plane shapes ride the dictionary args;
            # the FINGERPRINT stays out — one executable per table/plane
            # SHAPE serves every dictionary and literal value
            enc = "dict"
        elif isinstance(c, RleColumn):
            enc = ("rle", c.logical_bucket)
        sig.append((str(c.data_type), tuple(c.data.shape),
                    c.lengths is not None, c.elem_valid is not None, enc))
    return tuple(sig)


def _trace_chain(ops, cols: List[TCol], sel, bucket, jnp, lit_args=None,
                 enc_tables=None):
    """Applies the filter/project chain to (cols, sel) in-trace.
    ``lit_args`` carries the runtime values of PromotedLiteral slots
    (plan/stages.py) so one compiled program serves every literal;
    ``enc_tables`` the dictionary lookup tables of code-space
    predicates (columnar/encoding.py DictContains)."""
    from spark_rapids_tpu.expressions.evaluator import tcol_to_device_column
    for kind, payload in ops:
        ctx = EvalContext(cols, "tpu", bucket)
        ctx.literal_args = lit_args
        ctx.enc_tables = enc_tables
        if kind == "filter":
            pred = payload.eval_tpu(ctx)
            keep = valid_array(pred, ctx)
            if not pred.is_scalar:
                keep = keep & pred.data
            else:
                keep = keep & jnp.asarray(pred.data).astype(bool)
            sel = sel & keep
        else:
            outs = []
            for e in payload:
                tc = e.eval_tpu(ctx)
                dc = tcol_to_device_column(tc, 0, bucket, jnp)
                outs.append(TCol(dc.data, dc.validity, e.data_type,
                                 lengths=dc.lengths,
                                 elem_valid=dc.elem_valid))
            cols = outs
    return cols, sel


def _cols_to_arrs(batch: ColumnarBatch):
    return [(c.data, c.validity, c.lengths, c.elem_valid)
            for c in batch.columns]


def _arrs_to_tcols(arrs, dtypes):
    return [TCol(d, v, dt, lengths=ln, elem_valid=ev)
            for (d, v, ln, ev), dt in zip(arrs, dtypes)]


class _PromotedLiteralsMixin:
    """Promoted-literal plumbing shared by the fused execs: slot values
    bind as runtime args of the compiled program (``_lit_args``) while
    plan-identity keys still carry the VALUES (``lit_key`` — two stages
    sharing one program are still different pipelines)."""

    def _init_promoted(self, promoted) -> None:
        #: PromotedLiteral slots in order (plan/stages.py); their values
        #: are runtime args of the compiled program, not part of its key
        self.promoted = list(promoted)
        self._lits = None

    def _lit_args(self) -> Tuple:
        if self._lits is None:
            from spark_rapids_tpu.plan.stages import physical_literal
            self._lits = tuple(physical_literal(p.value, p.data_type)
                               for p in self.promoted)
        return self._lits

    def lit_key(self) -> Tuple:
        return tuple((p.slot, repr(p.value)) for p in self.promoted)


class TpuFusedStageExec(UnaryExec, _PromotedLiteralsMixin):
    """Fused [Filter|Project]+ chain with a compact terminal."""

    is_device = True

    def __init__(self, ops: Sequence[StageOp], child: Exec, promoted=()):
        super().__init__(child)
        self.ops = list(ops)
        self._init_promoted(promoted)
        #: per-(batch encodings) translated op chains (encoding.py)
        self._enc_cache: dict = {}

    @property
    def schema(self) -> T.StructType:
        s = self.child.schema
        for kind, payload in self.ops:
            if kind == "project":
                from spark_rapids_tpu.exec.basic import _project_schema
                s = _project_schema(payload)
        return s

    def _out_names(self):
        from spark_rapids_tpu.expressions.evaluator import _out_names
        names = None
        for kind, payload in self.ops:
            if kind == "project":
                names = _out_names(payload)
        return names

    def execute_partition(self, pidx):
        from spark_rapids_tpu.exec import stage_compiler as SC
        pending = None
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                prog, args, enc = self._program(b)
                if SC.ASYNC_COMPILE and prog.needs_compile():
                    # background lower+compile; the one-batch look-ahead
                    # below overlaps it with the previous batch's
                    # downstream compute
                    prog.warm_async(*args)
                if pending is not None:
                    yield self._finish(*pending)
                    pending = None
                # defer only while a background compile is actually in
                # flight: in the steady state (program warm) an
                # unconditional hold would add a batch of latency and pin
                # an extra batch's device arrays per fused stage for zero
                # overlap benefit
                if prog.compiling():
                    pending = (prog, args, enc)
                else:
                    yield self._finish(prog, args, enc)
        if pending is not None:
            yield self._finish(*pending)

    def _program(self, b):
        import jax
        from spark_rapids_tpu.columnar import encoding as ENC
        jnp = _jx()
        enc = ENC.plan_fused_stage(self.ops, b, cache=self._enc_cache)
        ops = self.ops if enc is None else enc.ops
        key = (_ops_signature(self.ops), _batch_signature(b), b.bucket,
               None if enc is None else enc.sig)

        def build():
            bucket = b.bucket
            dtypes = [c.data_type for c in b.columns]
            plan = enc

            def run(arrs, rc, lits, enc_args):
                cols = _arrs_to_tcols(arrs, dtypes)
                if plan is not None:
                    cols = plan.prepare_cols(cols, enc_args, jnp)
                sel = jnp.arange(bucket, dtype=np.int32) < rc
                cols, sel = _trace_chain(ops, cols, sel, bucket, jnp,
                                         lits,
                                         None if plan is None
                                         else enc_args[0])
                # compact terminal: one multi-operand stable sort
                cnt = jnp.sum(sel)
                live = jnp.arange(bucket) < cnt
                flat, twod = [], []
                metas = []
                for c in cols:
                    is2d = getattr(c.data, "ndim", 1) > 1
                    (twod if is2d else flat).append(c.data)
                    flat.append(c.valid)
                    has_ln = c.lengths is not None
                    if has_ln:
                        flat.append(c.lengths)
                    has_ev = getattr(c, "elem_valid", None) is not None
                    if has_ev:
                        twod.append(c.elem_valid)
                    metas.append((is2d, has_ln, has_ev))
                rowpos = jnp.arange(bucket, dtype=np.int32)
                operands = ((~sel).astype(np.int8), rowpos) + tuple(flat)
                sorted_ops = jax.lax.sort(operands, num_keys=1,
                                          is_stable=True)
                perm = sorted_ops[1]
                fs = list(sorted_ops[2:])
                ts = [jnp.take(p, perm, axis=0) for p in twod]
                outs = []
                fi = ti = 0
                for (is2d, has_ln, has_ev) in metas:
                    if is2d:
                        d = ts[ti]
                        ti += 1
                    else:
                        d = fs[fi]
                        fi += 1
                    v = fs[fi] & live
                    fi += 1
                    ln = None
                    if has_ln:
                        ln = fs[fi]
                        fi += 1
                    ev = None
                    if has_ev:
                        ev = ts[ti]
                        ti += 1
                    outs.append((d, v, ln, ev))
                return outs, cnt

            return run
        from spark_rapids_tpu.exec.stage_compiler import get_or_build
        prog = get_or_build("fused.stage", key, build)
        # validity inside the trace comes from TCol.valid; bind real
        # planes (and the promoted literal values) here
        args = (_cols_to_arrs(b), rc_traceable(b.row_count),
                self._lit_args(),
                () if enc is None else enc.runtime_args(b))
        return prog, args, enc

    def _finish(self, prog, args, enc=None):
        outs, cnt = prog(*args)
        rc = DeferredCount(cnt)
        fields = self.schema.fields
        cols = []
        for i, ((d, v, ln, ev), f) in enumerate(zip(outs, fields)):
            dic = None if enc is None else enc.final_dicts[i]
            if dic is not None:
                # kept codes survived the compacting filter: decode is
                # deferred until (and unless) something needs values
                from spark_rapids_tpu.columnar.encoding import \
                    DictionaryColumn
                cols.append(DictionaryColumn(d, v, rc, f.data_type,
                                             None, None, dictionary=dic))
            else:
                cols.append(DeviceColumn(d, v, rc, f.data_type, ln, ev))
        return ColumnarBatch(cols, rc, self._out_names() or
                             [f.name for f in fields])

    def node_desc(self):
        parts = []
        for kind, payload in self.ops:
            if kind == "filter":
                parts.append(f"F[{payload.sql()}]")
            else:
                parts.append(f"P[{', '.join(e.sql() for e in payload)}]")
        return "TpuFusedStage(" + " -> ".join(parts) + ")" \
            + _lits_desc(self.promoted)


class TpuFusedAggExec(UnaryExec, _PromotedLiteralsMixin):
    """Fused [Filter|Project]* chain + hash-aggregate update pass.

    The chain and the aggregate's first (update) pass over each input batch
    run as ONE jit; filters contribute a selection mask consumed directly
    by the reductions — no compaction, no intermediate batches.  Merge and
    final passes reuse segmented_aggregate (tiny inputs).
    """

    is_device = True

    def __init__(self, ops: Sequence[StageOp], layout, mode, child: Exec,
                 promoted=()):
        super().__init__(child)
        self.ops = list(ops)
        self.layout = layout
        self.mode = mode
        self._init_promoted(promoted)
        #: per-(batch encodings) translated op chains (encoding.py)
        self._enc_cache: dict = {}

    @property
    def schema(self):
        from spark_rapids_tpu.exec.aggregate import PARTIAL
        return self.layout.buffer_schema if self.mode == PARTIAL else \
            self.layout.result_schema

    def _fused_update(self, b: ColumnarBatch) -> ColumnarBatch:
        from spark_rapids_tpu.columnar import encoding as ENC
        jnp = _jx()
        lay = self.layout
        nk0 = lay.num_keys
        all_upd = list(lay.update_input_exprs())
        enc = ENC.plan_fused_stage(self.ops, b, key_exprs=all_upd[:nk0],
                                   other_exprs=all_upd[nk0:],
                                   cache=self._enc_cache)
        ops = self.ops if enc is None else enc.ops
        # per-key Dictionary when the group key is a kept (code-space)
        # column; dictionary IDENTITY joins the program key — grouped
        # code outputs are only meaningful against their dictionary
        key_dicts = self._key_dicts(enc, all_upd[:nk0])
        key = (_ops_signature(self.ops), _batch_signature(b), b.bucket,
               tuple((e.sql(), str(e.data_type))
                     for e in lay.update_input_exprs()),
               tuple((o, k, cv, str(dt))
                     for o, k, cv, dt in lay.update_specs()),
               lay.num_keys,
               None if enc is None else enc.sig,
               tuple(None if d is None else d.fingerprint
                     for d in key_dicts))
        def build():
            from spark_rapids_tpu.expressions.evaluator import \
                tcol_to_device_column
            from spark_rapids_tpu.ops.agg_ops import (_GLOBAL_OUT_BUCKET,
                                                      global_agg_trace,
                                                      keyed_agg_trace)
            bucket = b.bucket
            dtypes = [c.data_type for c in b.columns]
            upd_exprs = list(lay.update_input_exprs())
            upd_specs = list(lay.update_specs())
            nk = lay.num_keys
            plan = enc
            kdicts = key_dicts

            def run(arrs, rc, lits, enc_args):
                cols = _arrs_to_tcols(arrs, dtypes)
                if plan is not None:
                    cols = plan.prepare_cols(cols, enc_args, jnp)
                sel = jnp.arange(bucket, dtype=np.int32) < rc
                cols, sel = _trace_chain(ops, cols, sel, bucket, jnp,
                                         lits,
                                         None if plan is None
                                         else enc_args[0])
                ctx = EvalContext(cols, "tpu", bucket)
                upd_cols = []
                for ki, e in enumerate(upd_exprs):
                    if ki < nk and kdicts[ki] is not None:
                        # kept dictionary key: GROUP BY THE CODES — an
                        # int32 plane instead of string word planes
                        from spark_rapids_tpu.columnar.encoding import \
                            _strip_alias
                        base = _strip_alias(e)
                        tc = cols[base.ordinal]
                        upd_cols.append(DeviceColumn(
                            tc.data.astype(np.int32), tc.valid, bucket,
                            T.INT))
                        continue
                    tc = e.eval_tpu(ctx)
                    dc = tcol_to_device_column(tc, 0, bucket, jnp)
                    upd_cols.append(DeviceColumn(dc.data, dc.validity,
                                                 bucket, e.data_type,
                                                 dc.lengths))
                if nk == 0:
                    outs = global_agg_trace(upd_cols, sel, upd_specs, jnp)
                    return outs, None
                return keyed_agg_trace(upd_cols, sel, nk, upd_specs,
                                       bucket, jnp)

            return run
        from spark_rapids_tpu.exec.stage_compiler import get_or_build
        fn = get_or_build("fused.agg_update", key, build)

        arrs = _cols_to_arrs(b)
        outs, ng = fn(arrs, rc_traceable(b.row_count), self._lit_args(),
                      () if enc is None else enc.runtime_args(b))
        lay = self.layout
        nk = lay.num_keys
        n = 1 if nk == 0 else DeferredCount(ng)
        names = [lay.key_name(i) for i in range(nk)] + \
            [lay.buffer_name(j) for j in range(len(lay.flat))]
        cols = []
        upd_exprs = list(lay.update_input_exprs())
        upd_specs = list(lay.update_specs())
        for j, (d, v, ln) in enumerate(outs):
            if j < nk:
                dt = upd_exprs[j].data_type
                if key_dicts[j] is not None:
                    from spark_rapids_tpu.columnar.encoding import \
                        DictionaryColumn
                    cols.append(DictionaryColumn(
                        d, v, n, dt, None, None,
                        dictionary=key_dicts[j]))
                    continue
            else:
                dt = upd_specs[j - nk][3]
                if ln is None and dt.np_dtype is not None and \
                        d.dtype != np.dtype(dt.np_dtype):
                    d = d.astype(dt.np_dtype)
            cols.append(DeviceColumn(d, v, n, dt, ln))
        return ColumnarBatch(cols, n, names)

    @staticmethod
    def _key_dicts(enc, key_exprs):
        """Per grouping key: the Dictionary when the key rides codes."""
        from spark_rapids_tpu.columnar.encoding import _strip_alias
        from spark_rapids_tpu.expressions.base import BoundReference
        out = []
        for e in key_exprs:
            dic = None
            if enc is not None:
                base = _strip_alias(e)
                if isinstance(base, BoundReference) and \
                        base.ordinal < len(enc.final_dicts):
                    dic = enc.final_dicts[base.ordinal]
            out.append(dic)
        return out

    def _merge_final_eligible(self, partials: List[ColumnarBatch]) -> bool:
        """The single-jit merge+final path needs in-trace concat: every
        partial must share one plane layout (same 2-D widths, no nested
        element-validity planes)."""
        sig0 = _batch_signature(partials[0])
        for b in partials[1:]:
            if _batch_signature(b) != sig0:
                return False
        return all(c.elem_valid is None
                   for b in partials for c in b.columns)

    def _merge_final_fused(self, partials: List[ColumnarBatch]):
        """ONE jit for the whole reduce side: in-trace concat of the
        partial buffers -> merge pass -> final expression eval.  Collapses
        three sequential dispatches (concat_batches, segmented_aggregate,
        final project) into one — on a tunnel-attached TPU each dispatch
        costs ~20ms of round-trip latency, so this halves the critical
        path of every aggregate query's last mile."""
        from spark_rapids_tpu.columnar.encoding import DictionaryColumn
        jnp = _jx()
        lay = self.layout
        nk = lay.num_keys
        merge_specs = list(lay.merge_specs())
        final_exprs = list(lay.final_exprs())
        # encoded key columns merge as code planes; align_batches already
        # guaranteed one fingerprint per position, and that IDENTITY is
        # part of the program key (grouped codes mean nothing without
        # their dictionary)
        enc_dicts = [c.dictionary if isinstance(c, DictionaryColumn)
                     else None for c in partials[0].columns]
        key = ("mergefinal", tuple(_batch_signature(b) for b in partials),
               tuple(b.bucket for b in partials), nk,
               tuple((o, k, cv, str(dt)) for o, k, cv, dt in merge_specs),
               tuple((e.sql(), str(e.data_type)) for e in final_exprs),
               tuple(None if d is None else d.fingerprint
                     for d in enc_dicts))
        def build():
            from spark_rapids_tpu.columnar.column import DeviceColumn
            from spark_rapids_tpu.expressions.evaluator import \
                tcol_to_device_column
            from spark_rapids_tpu.ops.agg_ops import (_GLOBAL_OUT_BUCKET,
                                                      global_agg_trace,
                                                      keyed_agg_trace)
            buckets = [b.bucket for b in partials]
            total = sum(buckets)
            # inside the trace encoded key columns are their int32 code
            # planes (the group/hash machinery must not see the logical
            # string type)
            in_dtypes = [T.INT if enc_dicts[ci] is not None
                         else c.data_type
                         for ci, c in enumerate(partials[0].columns)]

            def run(arrs_list, rcs):
                sel = jnp.concatenate(
                    [jnp.arange(bk, dtype=np.int32) < rcs[pi]
                     for pi, bk in enumerate(buckets)])
                cols = []
                for ci, dt in enumerate(in_dtypes):
                    d = jnp.concatenate(
                        [arrs_list[pi][ci][0] for pi in range(len(buckets))],
                        axis=0)
                    v = jnp.concatenate(
                        [arrs_list[pi][ci][1] for pi in range(len(buckets))])
                    lns = [arrs_list[pi][ci][2] for pi in range(len(buckets))]
                    ln = None if lns[0] is None else jnp.concatenate(lns)
                    cols.append(DeviceColumn(d, v, total, dt, ln))
                if nk == 0:
                    outs = global_agg_trace(cols, sel, merge_specs, jnp)
                    ng = None
                    out_bucket = _GLOBAL_OUT_BUCKET
                else:
                    outs, ng = keyed_agg_trace(cols, sel, nk, merge_specs,
                                               total, jnp)
                    out_bucket = total
                tcols = []
                for j, (d, v, ln) in enumerate(outs):
                    dt = in_dtypes[j] if j < nk else merge_specs[j - nk][3]
                    if ln is None and dt.np_dtype is not None and \
                            d.dtype != np.dtype(dt.np_dtype):
                        d = d.astype(dt.np_dtype)
                    tcols.append(TCol(d, v, dt, lengths=ln))
                ctx = EvalContext(tcols, "tpu", out_bucket)
                fouts = []
                for e in final_exprs:
                    tc = e.eval_tpu(ctx)
                    dc = tcol_to_device_column(tc, 0, out_bucket, jnp)
                    fouts.append((dc.data, dc.validity, dc.lengths,
                                  dc.elem_valid))
                return fouts, ng

            return run
        from spark_rapids_tpu.exec.stage_compiler import get_or_build
        fn = get_or_build("fused.agg_merge_final", key, build)

        arrs_list = [[(c.data, c.validity, c.lengths) for c in b.columns]
                     for b in partials]
        rcs = [rc_traceable(b.row_count) for b in partials]
        fouts, ng = fn(arrs_list, rcs)
        n = 1 if nk == 0 else DeferredCount(ng)
        from spark_rapids_tpu.expressions.evaluator import _out_names
        fields = self.layout.result_schema.fields
        cols = []
        for i, ((d, v, ln, ev), f) in enumerate(zip(fouts, fields)):
            if i < nk and enc_dicts[i] is not None:
                cols.append(DictionaryColumn(d, v, n, f.data_type,
                                             None, None,
                                             dictionary=enc_dicts[i]))
            else:
                cols.append(DeviceColumn(d, v, n, f.data_type, ln, ev))
        return ColumnarBatch(cols, n, _out_names(final_exprs) or
                             [f.name for f in fields])

    def execute_partition(self, pidx):
        from spark_rapids_tpu.exec.aggregate import COMPLETE, FINAL, PARTIAL
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.columnar import encoding as ENC
        lay = self.layout
        partials: List[ColumnarBatch] = []
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                partials.append(with_retry_no_split(
                    None, lambda: self._fused_update(b)))
        if len(partials) > 1 and any(ENC.batch_has_encoded(p)
                                     for p in partials):
            # grouped codes only combine against ONE dictionary per key
            # column; mismatched fingerprints decode before merging
            partials = ENC.align_batches(partials, site="agg-merge")
        if not partials:
            if lay.num_keys == 0 and self.mode in (COMPLETE, FINAL) and \
                    self.child.num_partitions == 1:
                from spark_rapids_tpu.exec.aggregate import \
                    CpuHashAggregateExec
                yield CpuHashAggregateExec(
                    lay.grouping, lay.aggs, self.mode,
                    self.child)._empty_reduction().to_device()
            return
        needs_merge = len(partials) > 1 or self.mode == FINAL
        if not needs_merge:
            merged_iter = iter(partials)
        else:
            from spark_rapids_tpu.exec.aggregate import \
                merge_partials_out_of_core
            from spark_rapids_tpu.memory.device_manager import \
                free_device_headroom
            from spark_rapids_tpu.memory.retry import (SplitAndRetryOOM,
                                                       maybe_inject_oom)
            from spark_rapids_tpu.memory.spillable import \
                SpillableColumnarBatch
            import spark_rapids_tpu.exec.aggregate as A
            eligible = self.mode != PARTIAL and \
                A.FORCE_REPARTITION_BELOW_DEPTH == 0 and \
                self._merge_final_eligible(partials)
            too_big = False
            if lay.num_keys > 0:
                budget = free_device_headroom(2)
                if budget is not None:
                    est = sum(p.sized_nbytes() for p in partials)
                    too_big = est > budget
            if (not eligible or too_big) and \
                    any(ENC.batch_has_encoded(p) for p in partials):
                # the out-of-core merge walks host tiers and the CPU
                # repartitioner: it needs values, not codes
                partials = [ENC.materialize_batch(p, site="agg-merge")
                            for p in partials]
            spills = [SpillableColumnarBatch.from_device(p)
                      for p in partials]
            partials = None  # only the spillable handles keep them alive
            if eligible and not too_big:
                def attempt():
                    maybe_inject_oom()
                    return self._merge_final_fused(
                        [sb.get_batch() for sb in spills])
                try:
                    out = with_retry_no_split(None, attempt)
                    for sb in spills:
                        sb.close()
                    yield out
                    return
                except SplitAndRetryOOM:
                    if lay.num_keys == 0:
                        raise
            merged_iter = merge_partials_out_of_core(lay, spills)
        names = [lay.key_name(i) for i in range(lay.num_keys)] + \
            [lay.buffer_name(j) for j in range(len(lay.flat))]
        for merged in merged_iter:
            if self.mode == PARTIAL:
                merged.names = list(names)
                yield merged
            elif lay.num_keys == 0 and merged.row_count == 0:
                from spark_rapids_tpu.exec.aggregate import \
                    CpuHashAggregateExec
                yield CpuHashAggregateExec(
                    lay.grouping, lay.aggs, self.mode,
                    self.child)._empty_reduction().to_device()
            else:
                # grouped dictionary keys pass through STILL ENCODED
                yield ENC.eval_exprs_keep_encoded(lay.final_exprs(),
                                                  merged)

    def node_desc(self):
        chain = "+".join("F" if k == "filter" else "P"
                         for k, _ in self.ops) or "-"
        return f"TpuFusedAgg[{chain}, keys={self.layout.num_keys}, " \
               f"mode={self.mode}]" + _lits_desc(self.promoted)
