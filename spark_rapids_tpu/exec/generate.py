"""Generate operator: explode / posexplode (+ _outer variants).

Reference: GpuGenerateExec.scala — GpuExplode/GpuPosExplode lower to cuDF
explode/explode_position (+outer).  TPU design: the array column already
lives as a padded rectangular plane, so explode is ONE device gather — the
output row for flat position p maps to (row = searchsorted(cum_lens, p),
within = p - cum_start(row)); repeated other-columns ride the same gather.
One host sync fetches the output row count (to size the output bucket),
matching the one-sync-per-batch discipline of filter/compact.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.expressions.base import BoundReference, Expression
from spark_rapids_tpu.plan.base import Exec, UnaryExec, closing_source


class CpuGenerateExec(UnaryExec):
    """explode(array_col): one output row per element; other columns are
    repeated.  ``outer`` keeps null/empty-array rows with a null element;
    ``position`` adds the element ordinal column (posexplode)."""

    def __init__(self, generator: Expression, child: Exec,
                 outer: bool = False, position: bool = False,
                 element_name: str = "col", pos_name: str = "pos"):
        super().__init__(child)
        dt = generator.data_type
        if not isinstance(dt, T.ArrayType):
            raise TypeError(f"explode needs an array input, got "
                            f"{dt.simple_name}")
        self.generator = generator
        self.outer = outer
        self.position = position
        self.element_name = element_name
        self.pos_name = pos_name

    @property
    def schema(self):
        fields = list(self.child.schema.fields)
        if self.position:
            fields.append(T.StructField(self.pos_name, T.INT, self.outer))
        fields.append(T.StructField(
            self.element_name, self.generator.data_type.element_type, True))
        return T.StructType(fields)

    def execute_partition(self, pidx):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        from spark_rapids_tpu.expressions.base import EvalContext, valid_array
        from spark_rapids_tpu.expressions.evaluator import host_batch_tcols
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                cols = host_batch_tcols(b)
                ctx = EvalContext(cols, "cpu", b.row_count)
                arr = self.generator.eval_cpu(ctx)
                valid = valid_array(arr, ctx)
                src_rows: List[int] = []
                poss: List[Optional[int]] = []
                elems: List = []
                for i in range(b.row_count):
                    lst = arr.data[i] if valid[i] else None
                    if lst:
                        for j, e in enumerate(lst):
                            src_rows.append(i)
                            poss.append(j)
                            elems.append(e)
                    elif self.outer:
                        src_rows.append(i)
                        poss.append(None)
                        elems.append(None)
                tab = pa.Table.from_batches([b.to_arrow()])
                taken = tab.take(pa.array(src_rows, type=pa.int64()))
                out_cols = [c.combine_chunks() if isinstance(c, pa.ChunkedArray)
                            else c for c in taken.columns]
                names = list(tab.schema.names)
                if self.position:
                    out_cols.append(pa.array(poss, type=pa.int32()))
                    names.append(self.pos_name)
                out_cols.append(pa.array(
                    elems, type=T.to_arrow(self.generator.data_type.element_type)))
                names.append(self.element_name)
                # from_arrays keeps duplicate names (the explode alias may
                # collide with a child column; a dict would silently drop one)
                yield batch_from_arrow(pa.Table.from_arrays(out_cols,
                                                            names=names))

    def node_desc(self):
        kind = "PosExplode" if self.position else "Explode"
        return f"Generate[{kind}{'Outer' if self.outer else ''}" \
               f"({self.generator.sql()})]"


class TpuGenerateExec(CpuGenerateExec):
    is_device = True

    def __init__(self, cpu: CpuGenerateExec):
        super().__init__(cpu.generator, cpu.children[0], cpu.outer,
                         cpu.position, cpu.element_name, cpu.pos_name)

    def execute_partition(self, pidx):
        import jax
        from spark_rapids_tpu.columnar.column import (DeviceColumn,
                                                      bucket_rows, _jnp)
        from spark_rapids_tpu.expressions.base import EvalContext, valid_array
        from spark_rapids_tpu.expressions.evaluator import device_batch_tcols
        from spark_rapids_tpu.ops.batch_ops import gather_batch
        jnp = _jnp()
        elem_dt = self.generator.data_type.element_type
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                cols = device_batch_tcols(b)
                ctx = EvalContext(cols, "tpu", b.bucket)
                arr = self.generator.eval_tpu(ctx)
                valid = valid_array(arr, ctx)
                rowpos = jnp.arange(b.bucket)
                live_row = valid & (rowpos < b.row_count)
                lens = jnp.where(live_row, arr.lengths, 0).astype(np.int64)
                if self.outer:
                    in_row = rowpos < b.row_count
                    fan = jnp.where(in_row & (lens == 0), 1, lens)
                else:
                    fan = lens
                cum = jnp.cumsum(fan)
                total = int(cum[-1])           # ONE sync: output size
                if total == 0:
                    continue
                out_bucket = bucket_rows(total)
                outpos = jnp.arange(out_bucket, dtype=np.int64)
                src = jnp.searchsorted(cum, outpos, side="right")
                src = jnp.clip(src, 0, b.bucket - 1)
                start = cum[src] - fan[src]
                within = outpos - start
                out_live = outpos < total
                # element plane gather
                w = arr.data.shape[1]
                safe_within = jnp.clip(within, 0, w - 1).astype(np.int64)
                elem = arr.data[src, safe_within]
                elem_ok = arr.elem_valid[src, safe_within] & \
                    (within < lens[src]) & out_live
                repeated = gather_batch(b, src, total, idx_valid=out_live)
                out_cols = list(repeated.columns)
                names = list(repeated.names)
                if self.position:
                    # outer-null fan rows have within==0 >= lens==0 -> null pos
                    pos_ok = out_live & (within < lens[src])
                    out_cols.append(DeviceColumn(
                        within.astype(np.int32), pos_ok, total, T.INT))
                    names.append(self.pos_name)
                out_cols.append(DeviceColumn(elem, elem_ok, total, elem_dt))
                names.append(self.element_name)
                yield ColumnarBatch(out_cols, total, names)

    def node_desc(self):
        return "Tpu" + super().node_desc()


# plan-rewrite registration (reference: GpuOverrides GenerateExec rule)
from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuGenerateExec,
              convert=lambda p, m: TpuGenerateExec(p),
              sig=TS.BASIC_WITH_ARRAYS,
              exprs_of=lambda p: [p.generator],
              desc="explode/posexplode via one device gather")
