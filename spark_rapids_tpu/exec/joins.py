"""Join execs: shuffled hash join, broadcast hash join, nested-loop join.

Reference: GpuShuffledHashJoinExec / GpuBroadcastHashJoinExecBase /
GpuBroadcastNestedLoopJoinExec over the common core GpuHashJoin
(org/apache/spark/sql/rapids/execution/GpuHashJoin.scala) + JoinGatherer.
The reference streams the probe side against a built hash table and
supports an extra non-equi ``condition`` evaluated per candidate pair (its
AST path); ours evaluates the condition as a fused XLA program over the
padded candidate-pair table (ops/join_ops.py).

Structure per partition (TPU path):
  build side  = concat of the build child's batches, sorted by key hash once
  probe side  = streamed; per batch: candidate ranges -> pair expand+verify
                -> optional condition -> finalize per join type
  right/full outer: build-row matched flags accumulate across probe batches;
                unmatched build rows are emitted after the stream drains
                (correct per-partition because the shuffle hash-partitions
                both sides by the same keys).

Sort-merge join: not built — the reference itself prefers converting SMJ to
shuffled hash join (GpuSortMergeJoinMeta.scala); we always plan hash joins.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (ColumnarBatch, HostColumnarBatch,
                                             batch_from_arrow,
                                             concat_host_batches)
from spark_rapids_tpu.expressions.base import EvalContext, Expression
from spark_rapids_tpu.ops import join_ops as J
from spark_rapids_tpu.ops import speculation
from spark_rapids_tpu.plan.base import BinaryExec, Exec

_PAIR_TYPES = (J.INNER, J.LEFT_OUTER, J.RIGHT_OUTER, J.FULL_OUTER, J.CROSS)

#: defaults for the build-side-swap knobs (spark.rapids.sql.join.
#: buildSideSwap.*); the convert-time values travel on each join
#: INSTANCE (conf must ride the plan, not the process — concurrent
#: sessions with different confs share these modules)
BUILD_SWAP_ENABLED = True
BUILD_SWAP_MAX_BYTES = 256 << 20

#: speculative-join verification headroom: candidate pairs are expanded
#: and verified over ``probe_bucket * HEADROOM`` so collision/null
#: candidates that verification rejects never flag overflow; the output
#: table stays at the probe bucket (post-verify pairs truncate back)
SPECULATIVE_PAIR_HEADROOM = 2


from spark_rapids_tpu.columnar.column import known_empty as _known_empty


def _normalize_how(how: str) -> str:
    h = how.lower().replace("_", "").replace(" ", "")
    return {
        "inner": J.INNER,
        "left": J.LEFT_OUTER, "leftouter": J.LEFT_OUTER,
        "right": J.RIGHT_OUTER, "rightouter": J.RIGHT_OUTER,
        "full": J.FULL_OUTER, "fullouter": J.FULL_OUTER, "outer": J.FULL_OUTER,
        "semi": J.LEFT_SEMI, "leftsemi": J.LEFT_SEMI,
        "anti": J.LEFT_ANTI, "leftanti": J.LEFT_ANTI,
        "cross": J.CROSS,
    }[h]


def expand_struct_key_pairs(left_keys, right_keys, null_safe=None):
    """Struct-CONSTRUCTOR key pairs -> field-wise NULL-SAFE pairs (Spark
    struct equality).  Shared by join construction AND the hash
    partitionings the planner builds above the join (both sides must
    shuffle by the same decomposed keys)."""
    from spark_rapids_tpu.expressions.collections import \
        CreateNamedStruct as _CNS
    ns_in = list(null_safe or [False] * len(list(left_keys)))
    lks, rks, nss = [], [], []
    for lk, rk, ns in zip(list(left_keys), list(right_keys), ns_in):
        if isinstance(lk, _CNS) and isinstance(rk, _CNS) and \
                len(lk.children) == len(rk.children):
            lks.extend(lk.children)
            rks.extend(rk.children)
            nss.extend([True] * len(lk.children))
        else:
            lks.append(lk)
            rks.append(rk)
            nss.append(ns)
    return lks, rks, nss


class _JoinBase(BinaryExec):
    """Shared schema/condition plumbing for all join execs."""

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], join_type: str,
                 condition: Optional[Expression], left: Exec, right: Exec,
                 null_safe: Optional[Sequence[bool]] = None):
        super().__init__(left, right)
        # struct-CONSTRUCTOR key pairs decompose into field-wise NULL-SAFE
        # pairs (Spark struct equality semantics; constructors are never
        # null themselves) — no device struct plane needed
        lks, rks, nss = expand_struct_key_pairs(left_keys, right_keys,
                                                null_safe)
        self.left_keys = lks
        self.right_keys = rks
        self.join_type = join_type
        self.condition = condition
        self.null_safe = tuple(nss)
        if len(self.left_keys) != len(self.right_keys):
            raise ValueError("left/right key counts differ")
        for lk, rk in zip(self.left_keys, self.right_keys):
            if str(lk.data_type) != str(rk.data_type):
                raise ValueError(
                    f"join key type mismatch: {lk.data_type} vs "
                    f"{rk.data_type}; add explicit casts")
            if lk.data_type.is_nested:
                raise TypeError(
                    f"equi-join key of type {lk.data_type.simple_name} "
                    "is not supported (arrays/structs/maps join as "
                    "payload, not keys)")

    @property
    def schema(self) -> T.StructType:
        ls, rs = self.left.schema, self.right.schema
        if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
            return ls
        lnull = self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER)
        rnull = self.join_type in (J.LEFT_OUTER, J.FULL_OUTER)
        fields = [T.StructField(f.name, f.data_type, f.nullable or lnull)
                  for f in ls.fields]
        fields += [T.StructField(f.name, f.data_type, f.nullable or rnull)
                   for f in rs.fields]
        return T.StructType(fields)

    @property
    def _out_names(self) -> List[str]:
        return self.schema.names

    def node_desc(self):
        keys = ", ".join(k.sql() for k in self.left_keys)
        cond = f", cond={self.condition.sql()}" if self.condition is not None \
            else ""
        return (f"{self.name}[{self.join_type}, keys=[{keys}]{cond}]")


# ---------------------------------------------------------------------------
# CPU core (the differential oracle): arrow hash join for the pair set,
# numpy for finalization
# ---------------------------------------------------------------------------

def _empty_host(schema: T.StructType) -> HostColumnarBatch:
    import pyarrow as pa
    arrays = [pa.array([], type=T.to_arrow(f.data_type))
              for f in schema.fields]
    return batch_from_arrow(pa.Table.from_arrays(arrays, names=schema.names))


def _concat_or_empty(batches: List[HostColumnarBatch],
                     schema: T.StructType) -> HostColumnarBatch:
    batches = [b for b in batches if b.row_count > 0]
    if not batches:
        return _empty_host(schema)
    return concat_host_batches(batches)


def _encode_key_array(hc, null_safe: bool):
    """HostColumn -> arrow array usable as an Acero hash-join key with Spark
    match semantics (NaN==NaN, -0.0==0.0 via bit canonicalization)."""
    import pyarrow as pa
    import pyarrow.compute as pc
    dt = hc.data_type
    arr = hc.arrow
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        bits = np.dtype(np.int32) if isinstance(dt, T.FloatType) \
            else np.dtype(np.int64)
        x = hc.data_np().copy()
        x[x == 0] = 0.0                       # -0.0 -> 0.0
        x[np.isnan(x)] = np.nan               # canonical NaN bits
        arr = pa.array(x.view(bits), mask=~hc.validity_np())
    if isinstance(dt, (T.DateType, T.TimestampType)):
        # equality on temporals == equality on their integer storage;
        # the null-safe filler below cannot be cast to temporal types
        storage = pa.int32() if isinstance(dt, T.DateType) else pa.int64()
        arr = arr.view(storage) if hasattr(arr, "view") else arr.cast(storage)
    if null_safe:
        nulls = pc.is_null(arr)
        if pa.types.is_string(arr.type) or pa.types.is_binary(arr.type):
            filler = pa.scalar("", type=arr.type)
        else:
            filler = pa.scalar(0, type=pa.int8()).cast(arr.type)
        return pc.coalesce(arr, filler), nulls
    return arr, None


def _cpu_key_pairs(lkb: HostColumnarBatch, rkb: HostColumnarBatch,
                   null_safe: Tuple[bool, ...]):
    """All key-equal (lidx, ridx) pairs via an arrow inner hash join."""
    import pyarrow as pa
    key_names: List[str] = []

    def key_table(kb, idx_name):
        arrays, names = [], []
        for i, c in enumerate(kb.columns):
            arr, nulls = _encode_key_array(c, null_safe[i])
            arrays.append(arr)
            names.append(f"k{i}")
            if nulls is not None:
                arrays.append(nulls)
                names.append(f"k{i}n")
        arrays.append(pa.array(np.arange(kb.row_count, dtype=np.int64)))
        names.append(idx_name)
        return pa.Table.from_arrays(arrays, names=names), \
            [n for n in names if n != idx_name]

    lt, key_names = key_table(lkb, "__lidx")
    rt, _ = key_table(rkb, "__ridx")
    joined = lt.join(rt, keys=key_names, join_type="inner")
    lidx = joined.column("__lidx").to_numpy(zero_copy_only=False)
    ridx = joined.column("__ridx").to_numpy(zero_copy_only=False)
    return lidx.astype(np.int64), ridx.astype(np.int64)


def _take_with_nulls(hb: HostColumnarBatch, idx: np.ndarray,
                     names: List[str]):
    """Arrow take where a negative index produces an all-null row."""
    import pyarrow as pa
    mask = idx < 0
    safe = np.where(mask, 0, idx)
    indices = pa.array(safe, mask=mask)
    tab = pa.Table.from_arrays([c.arrow for c in hb.columns],
                               names=[f"c{i}" for i in
                                      range(hb.num_columns)])
    taken = tab.take(indices)
    cols = [batch_from_arrow(taken).columns[i]
            for i in range(hb.num_columns)]
    return cols


def _cpu_assemble(left: HostColumnarBatch, right: HostColumnarBatch,
                  lmap: np.ndarray, rmap: np.ndarray,
                  names: List[str]) -> HostColumnarBatch:
    cols = _take_with_nulls(left, lmap, names) + \
        _take_with_nulls(right, rmap, names)
    return HostColumnarBatch(cols, len(lmap), names)


class _CpuJoinCore(_JoinBase):
    """Join over fully-materialized host sides (per partition)."""

    def _pair_condition_keep(self, left, right, lidx, ridx):
        from spark_rapids_tpu.expressions.evaluator import host_batch_tcols
        pair = _cpu_assemble(left, right, lidx, ridx,
                             [f"p{i}" for i in
                              range(left.num_columns + right.num_columns)])
        cols = host_batch_tcols(pair)
        ctx = EvalContext(cols, "cpu", pair.row_count)
        pred = self.condition.eval_cpu(ctx)
        if pred.is_scalar:
            ok = bool(pred.valid) and bool(pred.data)
            return np.full(len(lidx), ok)
        keep = np.asarray(pred.data, dtype=bool) & np.asarray(pred.valid)
        return keep[:len(lidx)]

    def _join_host(self, left: HostColumnarBatch,
                   right: HostColumnarBatch) -> HostColumnarBatch:
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_cpu
        jt = self.join_type
        nl, nr = left.row_count, right.row_count
        if jt == J.CROSS or not self.left_keys:
            lidx = np.repeat(np.arange(nl, dtype=np.int64), nr)
            ridx = np.tile(np.arange(nr, dtype=np.int64), nl)
        else:
            lkb = eval_exprs_cpu(self.left_keys, left,
                                 [f"k{i}" for i in
                                  range(len(self.left_keys))])
            rkb = eval_exprs_cpu(self.right_keys, right,
                                 [f"k{i}" for i in
                                  range(len(self.right_keys))])
            lidx, ridx = _cpu_key_pairs(lkb, rkb, self.null_safe)
        if self.condition is not None and len(lidx):
            keep = self._pair_condition_keep(left, right, lidx, ridx)
            lidx, ridx = lidx[keep], ridx[keep]
        names = self._out_names
        if jt in (J.INNER, J.CROSS):
            return _cpu_assemble(left, right, lidx, ridx, names)
        if jt in (J.LEFT_SEMI, J.LEFT_ANTI):
            matched = np.zeros(nl, dtype=bool)
            matched[lidx] = True
            rows = np.flatnonzero(matched if jt == J.LEFT_SEMI else ~matched)
            cols = _take_with_nulls(left, rows.astype(np.int64), names)
            return HostColumnarBatch(cols, len(rows), names)
        parts_l, parts_r = [lidx], [ridx]
        if jt in (J.LEFT_OUTER, J.FULL_OUTER):
            matched = np.zeros(nl, dtype=bool)
            matched[lidx] = True
            ul = np.flatnonzero(~matched).astype(np.int64)
            parts_l.append(ul)
            parts_r.append(np.full(len(ul), -1, dtype=np.int64))
        if jt in (J.RIGHT_OUTER, J.FULL_OUTER):
            matched = np.zeros(nr, dtype=bool)
            matched[ridx] = True
            ur = np.flatnonzero(~matched).astype(np.int64)
            parts_l.append(np.full(len(ur), -1, dtype=np.int64))
            parts_r.append(ur)
        lmap = np.concatenate(parts_l)
        rmap = np.concatenate(parts_r)
        return _cpu_assemble(left, right, lmap, rmap, names)


# ---------------------------------------------------------------------------
# TPU core
# ---------------------------------------------------------------------------

def _empty_device(schema: T.StructType) -> ColumnarBatch:
    return _empty_host(schema).to_device()


def _chain_then_close(consumed, it):
    """Replays already-sampled probe batches then continues the live
    stream; closing this generator early closes the underlying stream
    (the swap-sampling path must not strand a half-drained child)."""
    from spark_rapids_tpu.plan.base import close_iter
    try:
        yield from consumed
        yield from it
    finally:
        close_iter(it)


class _TpuJoinCore(_JoinBase):
    """Streamed probe vs built side on device (see module docstring)."""

    is_device = True

    #: conf-at-convert-time build-side-swap knobs
    #: (spark.rapids.sql.join.buildSideSwap.*); ``None`` falls back to
    #: the module defaults so directly-driven test execs keep working.
    #: Instance-threaded on purpose: per-query conf travels with the
    #: plan, never through process-global module state (concurrent
    #: sessions with different confs share this module)
    build_swap_enabled: Optional[bool] = None
    build_swap_max_bytes: Optional[int] = None

    def _augment_keys(self, batch: ColumnarBatch, keys,
                      enc_keys=None) -> ColumnarBatch:
        """Appends evaluated key columns; returns (augmented, ordinals).

        ``enc_keys`` (per key: Dictionary | None) marks keys that join
        in CODE SPACE: both sides carry the SAME dictionary, so equality
        on int32 codes is equality on values — the hash/probe machinery
        sees one int word instead of string word planes."""
        from spark_rapids_tpu.columnar import encoding as ENC
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        if not keys:
            return batch, ()
        enc_keys = enc_keys or [None] * len(keys)
        plain = [k for k, d in zip(keys, enc_keys) if d is None]
        kb_cols = iter(eval_exprs_tpu(plain, batch).columns) if plain \
            else iter(())
        key_cols = [ENC.codes_key_column(batch, k) if d is not None
                    else next(kb_cols)
                    for k, d in zip(keys, enc_keys)]
        aug = ColumnarBatch(list(batch.columns) + key_cols,
                            batch.row_count)
        ords = tuple(range(batch.num_columns,
                           batch.num_columns + len(keys)))
        return aug, ords

    def _condition_keep(self, probe_pay, build_pay, l_idx, r_idx, keep,
                        pair_bucket):
        """Applies the non-equi condition over the padded pair table."""
        from spark_rapids_tpu.expressions.base import valid_array
        from spark_rapids_tpu.expressions.evaluator import device_batch_tcols
        pair = J.gather_join_output(probe_pay, build_pay, l_idx, r_idx,
                                    pair_bucket)
        cols = device_batch_tcols(pair)
        ctx = EvalContext(cols, "tpu", pair.bucket)
        pred = self.condition.eval_tpu(ctx)
        ok = valid_array(pred, ctx)
        if pred.is_scalar:
            ok = ok & bool(pred.data)
        else:
            ok = ok & pred.data
        # pair table rows map 1:1 to pair positions (same bucket)
        return keep & ok[:keep.shape[0]]

    def _join_device(self, probe_batches: Iterator[ColumnarBatch],
                     build_batches: List[ColumnarBatch],
                     build_cache: Optional[dict] = None,
                     swapped: bool = False):
        """Yields output batches for one partition.  ``build_cache`` (dict)
        carries the concatenated/keyed/sorted build side across calls —
        broadcast joins pass a per-exec dict so the build work happens once
        for all probe partitions.

        ``swapped=True`` (inner equi-joins only): the PROBE stream is the
        RIGHT child and the build side the LEFT — the runtime build-side
        choice (reference: Spark/GpuShuffledHashJoinExec pick the smaller
        side to build; our planner joins in SQL order, which puts fact
        tables on the build side in star queries).  Output column order
        stays left-then-right via argument swap at gather time."""
        from spark_rapids_tpu.columnar import encoding as ENC
        from spark_rapids_tpu.ops.batch_ops import concat_batches
        jt = self.join_type
        names = self._out_names
        ls, rs = self.left.schema, self.right.schema
        probe_keys = self.right_keys if swapped else self.left_keys
        build_keys = self.left_keys if swapped else self.right_keys
        cache = build_cache if build_cache is not None else {}
        use_hash = bool(self.left_keys) and jt != J.CROSS
        if "build" in cache:
            build = cache["build"]
        else:
            build_batches = [ENC.materialize_rle_batch(b, site="join")
                             for b in build_batches
                             if not _known_empty(b.row_count)]
            build = concat_batches(build_batches) if build_batches else \
                _empty_device(ls if swapped else rs)
            # concat_batches passes a single input through unchanged —
            # never mutate it (it may be a shared/cached batch); rewrap
            # to drop names instead
            build = ColumnarBatch(build.columns, build.row_count)
            cache["build"] = build
        build_key_dicts = ENC.join_key_dicts(build, build_keys) \
            if use_hash else []
        # augmented build sides keyed by the code-space signature (one
        # per dictionary combination a probe stream presents), each with
        # its own string-width sub-cache
        aug_cache = cache.setdefault("aug", {})
        build_matched = None
        semi_anti = jt in (J.LEFT_SEMI, J.LEFT_ANTI)
        empty_right = ColumnarBatch([], 0) if semi_anti else None
        for probe in probe_batches:
            if _known_empty(probe.row_count):
                continue
            probe = ENC.materialize_rle_batch(probe, site="join")
            if use_hash:
                # a key joins in code space only when BOTH sides carry
                # the same dictionary; otherwise it falls back to value
                # comparison (the probe-side key eval materializes)
                probe_dicts = ENC.join_key_dicts(probe, probe_keys)
                enc_keys = [bd if (bd is not None and pd is not None and
                                   bd.fingerprint == pd.fingerprint)
                            else None
                            for bd, pd in zip(build_key_dicts,
                                              probe_dicts)]
                enc_sig = tuple(None if d is None else d.fingerprint
                                for d in enc_keys)
                entry = aug_cache.get(enc_sig)
                if entry is None:
                    entry = (self._augment_keys(build, build_keys,
                                                enc_keys), {})
                    aug_cache[enc_sig] = entry
                (build_aug, build_ords), built_by_widths = entry
                probe_aug, probe_ords = self._augment_keys(probe,
                                                           probe_keys,
                                                           enc_keys)
                pk = [probe_aug.columns[i] for i in probe_ords]
                wkey = tuple(J._n_value_words(c) for c in pk)
                built = built_by_widths.get(wkey)
                if built is None:
                    built = J.build_side(build_aug, build_ords, pk)
                    built_by_widths[wkey] = built
                lo, counts, offsets, total = J._probe_ranges(
                    [probe_aug.columns[i] for i in probe_ords], built)
                spec = speculation.active()
                if spec is not None:
                    # optimistic OUTPUT table = probe bucket (exact for
                    # the FK->PK joins that dominate star schemas: <=1
                    # build match per probe row), but candidates are
                    # expanded + verified over a HEADROOM window first:
                    # hash-collision / null-key candidates that
                    # verification rejects must not flag overflow (they
                    # used to trigger a silent full-query exact replay).
                    # Overflow is decided on the POST-VERIFY pair count
                    # against the probe bucket (below, after compact);
                    # only a candidate total beyond even the headroom
                    # window — unverifiable without a sizing sync —
                    # forces the replay directly
                    out_bucket = probe_aug.bucket
                    verify_bucket = out_bucket * SPECULATIVE_PAIR_HEADROOM
                    spec.add(total > verify_bucket)
                else:
                    total = int(total)       # the per-join sizing sync
                    out_bucket = J.bucket_rows(max(total, 1))
                    verify_bucket = out_bucket
                l_idx, r_idx, keep, pair_bucket = J._expand_verify(
                    probe_aug, probe_ords, built, self.null_safe, lo,
                    offsets, total, verify_bucket)
            else:
                l_idx, r_idx, keep, pair_bucket = J.cross_pairs(probe, build)
            probe_pay = probe
            build_pay = build
            if self.condition is not None:
                keep = self._condition_keep(probe_pay, build_pay, l_idx,
                                            r_idx, keep, pair_bucket)
            if jt in (J.RIGHT_OUTER, J.FULL_OUTER):
                bm = J.matched_flags(r_idx, keep, build.bucket)
                build_matched = bm if build_matched is None \
                    else build_matched | bm
            if semi_anti:
                flags = J.matched_flags(l_idx, keep, probe.bucket)
                if jt == J.LEFT_ANTI:
                    rows, n = J.unmatched_positions(flags, probe.row_count)
                else:
                    rows, n = J.unmatched_positions(~flags, probe.row_count)
                yield J.gather_join_output(probe_pay, empty_right,
                                           rows, None, n, names,
                                           out_bucket=probe.bucket)
                continue
            l, r, n = J.compact_pairs(l_idx, r_idx, keep)
            if use_hash and spec is not None and pair_bucket > out_bucket:
                # the post-verify overflow check: only REAL pairs (after
                # key verification AND the non-equi condition) must fit
                # the optimistic output bucket; the verified headroom
                # window then truncates back so output batches keep the
                # probe-bucket footprint
                from spark_rapids_tpu.columnar.column import (
                    DeferredCount as _DC, rc_traceable as _rt)
                from spark_rapids_tpu.columnar.column import _jnp as _j
                jnp = _j()
                nt = jnp.asarray(_rt(n))
                spec.add(nt > out_bucket)
                l, r = l[:out_bucket], r[:out_bucket]
                n = _DC(jnp.minimum(nt, out_bucket))
                pair_bucket = out_bucket
            if jt in (J.LEFT_OUTER, J.FULL_OUTER):
                flags = J.matched_flags(l_idx, keep, probe.bucket)
                ul, un = J.unmatched_positions(flags, probe.row_count)
                lmap, rmap, total_out, ob = J.concat_matched_unmatched(
                    l, r, n, ul, un)
                yield J.gather_join_output(probe_pay, build_pay, lmap, rmap,
                                           total_out, names, out_bucket=ob)
            elif swapped:
                # emit left-then-right: build side IS the left child here
                yield J.gather_join_output(build_pay, probe_pay, r, l, n,
                                           names, out_bucket=pair_bucket)
            else:
                yield J.gather_join_output(probe_pay, build_pay, l, r, n,
                                           names, out_bucket=pair_bucket)
        # outer-join: unmatched build rows after the probe stream drains
        if jt in (J.RIGHT_OUTER, J.FULL_OUTER):
            if build_matched is None:
                from spark_rapids_tpu.columnar.column import _jnp
                jnp = _jnp()
                build_matched = jnp.zeros(build.bucket, dtype=bool)
            ub, un = J.unmatched_positions(build_matched, build.row_count)
            probe_empty = _empty_device(ls)
            yield J.gather_join_output(probe_empty, build, None, ub, un,
                                       names, out_bucket=build.bucket)


# ---------------------------------------------------------------------------
# Concrete execs
# ---------------------------------------------------------------------------

def _check_copartitioned(join) -> None:
    """Partition i of the left side pairs with partition i of the right:
    the contract every producer of a shuffled join upholds — the eager
    exchanges, AQE's COORDINATED readers, and the distribution pass's
    elision (which only removes an exchange whose child provably
    delivers the same placement).  A count mismatch here means a pass
    broke that contract; failing loudly beats joining partition i
    against an unrelated partition i and returning silently wrong
    rows (plan/verify.py's distribution-consistency check is the
    observe-only twin of this guard)."""
    ln, rn = join.left.num_partitions, join.right.num_partitions
    if ln != rn:
        raise ValueError(
            f"{join.name} sides are not co-partitioned: left has {ln} "
            f"partition(s), right has {rn} — partition pairing would "
            "silently drop or mis-match rows")


class CpuShuffledHashJoinExec(_CpuJoinCore):
    """Both children hash-partitioned by the join keys; joins partition-wise
    (reference: GpuShuffledHashJoinExec)."""

    @property
    def num_partitions(self):
        return self.left.num_partitions

    def execute_partition(self, pidx):
        _check_copartitioned(self)
        left = _concat_or_empty(list(self.left.execute_partition(pidx)),
                                self.left.schema)
        right = _concat_or_empty(list(self.right.execute_partition(pidx)),
                                 self.right.schema)
        out = self._join_host(left, right)
        if out.row_count:
            yield out


class TpuShuffledHashJoinExec(_TpuJoinCore):
    @property
    def num_partitions(self):
        return self.left.num_partitions

    def _maybe_swapped(self, pidx):
        build = list(self.right.execute_partition(pidx))
        return self._maybe_swapped_with(build, pidx)

    def _maybe_swapped_with(self, build, pidx):
        """Runtime build-side choice for inner equi-joins: build on the
        smaller side (reference: GpuShuffledHashJoinExec's build side is
        planner-chosen by size; our SQL planner joins in source order,
        which would build on the FACT side in star queries — wrong both
        for memory and for the speculative pair sizing)."""
        bb = sum(b.nbytes() for b in build)
        if self.swap_enabled() and self.join_type == J.INNER and \
                self.condition is None and \
                self.left_keys and bb <= self.swap_max_bytes():
            # first-batch sampling: probe batches are pulled only until
            # their running bytes EXCEED the build side (probe provably
            # bigger -> no swap) or the stream ends first (whole probe is
            # smaller -> build on it).  Weighing the swap materializes at
            # most ~build-side bytes (itself <= buildSideSwap.maxBytes),
            # never the whole probe partition
            it = self.left.execute_partition(pidx)
            sampled = []
            pb = 0
            for b in it:
                sampled.append(b)
                pb += b.nbytes()
                if pb > bb:
                    break
            if pb <= bb:      # stream drained: full probe is the smaller side
                return iter(build), sampled, True
            return _chain_then_close(sampled, it), build, False
        return self.left.execute_partition(pidx), build, False

    def swap_enabled(self) -> bool:
        bs = self.build_swap_enabled
        return BUILD_SWAP_ENABLED if bs is None else bs

    def swap_max_bytes(self) -> int:
        mb = self.build_swap_max_bytes
        return BUILD_SWAP_MAX_BYTES if mb is None else mb

    def execute_partition(self, pidx):
        _check_copartitioned(self)
        probe, build, swapped = self._maybe_swapped(pidx)
        yield from self._join_device(probe, build, swapped=swapped)


class CpuBroadcastHashJoinExec(_CpuJoinCore):
    """Build side = every partition of the right child, materialized once
    (reference: GpuBroadcastHashJoinExecBase; the broadcast is a no-op
    in-process).  Right/full outer are not planned broadcast (the build side
    match flags would span probe partitions), matching Spark's rule that the
    broadcast side must not be the outer side."""

    @property
    def num_partitions(self):
        return self.left.num_partitions

    def _build_all(self):
        if getattr(self, "_built_host", None) is None:
            # concurrent probe tasks must not double-build; drop device
            # admission before blocking on the lock (the builder may need it)
            from spark_rapids_tpu.plan.base import release_semaphore_for_wait
            release_semaphore_for_wait()
            with self._exec_lock:
                if getattr(self, "_built_host", None) is None:
                    bs = []
                    for p in range(self.right.num_partitions):
                        bs.extend(self.right.execute_partition(p))
                    self._built_host = _concat_or_empty(bs,
                                                        self.right.schema)
        return self._built_host

    def execute_partition(self, pidx):
        left = _concat_or_empty(list(self.left.execute_partition(pidx)),
                                self.left.schema)
        out = self._join_host(left, self._build_all())
        if out.row_count:
            yield out


class TpuBroadcastHashJoinExec(_TpuJoinCore):
    @property
    def num_partitions(self):
        return self.left.num_partitions

    def execute_partition(self, pidx):
        # the build cache persists across probe partitions: the broadcast
        # side is concatenated, keyed, and hash-sorted exactly once; the
        # population is locked against concurrent probe tasks (admission
        # dropped first so the builder can acquire it)
        if getattr(self, "_build_cache", None) is None or \
                "batches" not in self._build_cache:
            from spark_rapids_tpu.plan.base import release_semaphore_for_wait
            release_semaphore_for_wait()
            with self._exec_lock:
                if getattr(self, "_build_cache", None) is None:
                    self._build_cache = {}
                if "batches" not in self._build_cache:
                    bs = []
                    for p in range(self.right.num_partitions):
                        bs.extend(self.right.execute_partition(p))
                    self._build_cache["batches"] = bs
        cache = self._build_cache
        yield from self._join_device(self.left.execute_partition(pidx),
                                     cache["batches"], cache)


class CpuBroadcastNestedLoopJoinExec(CpuBroadcastHashJoinExec):
    """Condition-only / cross joins (reference:
    GpuBroadcastNestedLoopJoinExecBase): no keys, every pair considered."""


class TpuBroadcastNestedLoopJoinExec(TpuBroadcastHashJoinExec):
    pass


# plan-rewrite registration (reference: GpuOverrides BroadcastHashJoinExec /
# ShuffledHashJoinExec / BroadcastNestedLoopJoinExec rules :4117-4260)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402


def _join_exprs(p: _JoinBase):
    out = list(p.left_keys) + list(p.right_keys)
    if p.condition is not None:
        out.append(p.condition)
    return out


from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402


def _tag_join_keys(m):
    TS.no_array_keys(list(m.plan.left_keys) + list(m.plan.right_keys), m,
                     "join key")


def _reg(cpu_cls, tpu_cls, desc):
    register_exec(
        cpu_cls,
        convert=lambda p, m: tpu_cls(p.left_keys, p.right_keys, p.join_type,
                                     p.condition, p.children[0],
                                     p.children[1], p.null_safe),
        sig=TS.BASIC_WITH_ARRAYS,
        exprs_of=_join_exprs,
        extra_tag=_tag_join_keys,
        desc=desc)


def _convert_shuffled(p, m):
    """Shuffled joins convert to the sub-partition-capable device join;
    below the size threshold it behaves exactly like the plain one."""
    from spark_rapids_tpu import config as C
    out = TpuSubPartitionHashJoinExec(p.left_keys, p.right_keys,
                                      p.join_type, p.condition,
                                      p.children[0], p.children[1],
                                      p.null_safe)
    out.subpartition_threshold = C.parse_bytes(
        m.conf.get(C.JOIN_SUBPARTITION_THRESHOLD.key))
    out.num_subpartitions = int(m.conf.get(C.JOIN_NUM_SUBPARTITIONS.key))
    # round-5 behavior knobs ride the INSTANCE (set from meta.conf at
    # convert time) — concurrent sessions must not race module globals
    out.build_swap_enabled = bool(
        m.conf.get(C.JOIN_BUILD_SWAP_ENABLED.key))
    out.build_swap_max_bytes = C.parse_bytes(
        m.conf.get(C.JOIN_BUILD_SWAP_MAX_BYTES.key))
    return out


register_exec(CpuShuffledHashJoinExec, convert=_convert_shuffled,
              sig=TS.BASIC_WITH_ARRAYS,
              exprs_of=_join_exprs,
              extra_tag=_tag_join_keys,
              desc="hash join over shuffled children (size-adaptive "
                   "sub-partitioning)")
_reg(CpuBroadcastHashJoinExec, TpuBroadcastHashJoinExec,
     "broadcast hash join")
_reg(CpuBroadcastNestedLoopJoinExec, TpuBroadcastNestedLoopJoinExec,
     "broadcast nested loop join")


# ---------------------------------------------------------------------------
# sub-partitioned join for oversized inputs (reference:
# GpuSubPartitionHashJoin.scala — when the build side cannot fit the memory
# budget, re-hash BOTH sides with a fresh seed into buckets and join each
# bucket pair independently; rows of one key land in exactly one bucket)
# ---------------------------------------------------------------------------

_SUBPART_SEED = 1999


def _subpartition_ids_device(batch, keys, k):
    from spark_rapids_tpu.columnar.column import _jnp
    from spark_rapids_tpu.expressions.evaluator import device_batch_tcols
    from spark_rapids_tpu.expressions.hashing import Murmur3Hash
    jnp = _jnp()
    ctx = EvalContext(device_batch_tcols(batch), "tpu", batch.bucket)
    h = Murmur3Hash(*keys, seed=_SUBPART_SEED).eval_tpu(ctx)
    r = h.data.astype(np.int32) % np.int32(k)
    return jnp.where(r < 0, r + k, r)


def _subpartition_device(batches, keys, k):
    """Splits device batches into k bucket lists by re-hash of the keys."""
    from spark_rapids_tpu.columnar.column import _jnp
    from spark_rapids_tpu.ops.batch_ops import compact_batch
    jnp = _jnp()
    buckets = [[] for _ in range(k)]
    for b in batches:
        pids = _subpartition_ids_device(b, keys, k)
        live = jnp.arange(b.bucket) < b.row_count
        for i in range(k):
            sub = compact_batch(b, (pids == i) & live)
            buckets[i].append(sub)
    return buckets


def _subpartition_host(batches, keys, k, schema):
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    from spark_rapids_tpu.expressions.evaluator import host_batch_tcols
    from spark_rapids_tpu.expressions.hashing import Murmur3Hash
    buckets = [[] for _ in range(k)]
    for hb in batches:
        ctx = EvalContext(host_batch_tcols(hb), "cpu", hb.row_count)
        h = Murmur3Hash(*keys, seed=_SUBPART_SEED).eval_cpu(ctx)
        pids = np.mod(h.data.astype(np.int64), k).astype(np.int64)
        tab = pa.Table.from_batches([hb.to_arrow()])
        for i in range(k):
            idx = np.flatnonzero(pids == i)
            if len(idx):
                buckets[i].append(
                    batch_from_arrow(tab.take(pa.array(idx))))
    return buckets


class _SubPartitionMixin:
    """Adds size-gated sub-partitioning to the shuffled joins."""

    subpartition_threshold: int = 1 << 30
    num_subpartitions: int = 16

    def _build_oversized(self, build_batches) -> bool:
        total = sum(b.nbytes() if hasattr(b, "nbytes") else 0
                    for b in build_batches)
        return total > self.subpartition_threshold


class CpuSubPartitionHashJoinExec(_SubPartitionMixin, CpuShuffledHashJoinExec):
    """Host variant (oracle): always joins through the bucket machinery."""

    def execute_partition(self, pidx):
        _check_copartitioned(self)
        left = list(self.left.execute_partition(pidx))
        right = list(self.right.execute_partition(pidx))
        if not self._build_oversized(right):
            lb = _concat_or_empty(left, self.left.schema)
            rb = _concat_or_empty(right, self.right.schema)
            out = self._join_host(lb, rb)
            if out.row_count:
                yield out
            return
        k = self.num_subpartitions
        lbuckets = _subpartition_host(left, self.left_keys, k,
                                      self.left.schema)
        rbuckets = _subpartition_host(right, self.right_keys, k,
                                      self.right.schema)
        for i in range(k):
            lb = _concat_or_empty(lbuckets[i], self.left.schema)
            rb = _concat_or_empty(rbuckets[i], self.right.schema)
            if lb.row_count == 0 and rb.row_count == 0:
                continue
            out = self._join_host(lb, rb)
            if out.row_count:
                yield out


class TpuSubPartitionHashJoinExec(_SubPartitionMixin, TpuShuffledHashJoinExec):
    def execute_partition(self, pidx):
        _check_copartitioned(self)
        build = list(self.right.execute_partition(pidx))
        if not self._build_oversized(build):
            probe, build, swapped = self._maybe_swapped_with(build, pidx)
            yield from self._join_device(probe, build, swapped=swapped)
            return
        k = self.num_subpartitions
        probe = list(self.left.execute_partition(pidx))
        lbuckets = _subpartition_device(probe, self.left_keys, k)
        rbuckets = _subpartition_device(build, self.right_keys, k)
        for i in range(k):
            yield from self._join_device(iter(lbuckets[i]), rbuckets[i])


register_exec(CpuSubPartitionHashJoinExec, convert=_convert_shuffled,
              sig=TS.BASIC_WITH_ARRAYS,
              exprs_of=_join_exprs,
              extra_tag=_tag_join_keys,
              desc="explicit sub-partitioned hash join")
