"""Asynchronous pipelined execution: bounded-depth prefetch boundaries.

The engine's iterator chains are pull-based and fully synchronous: when a
fused stage asks for its next batch, the scan decodes on the host, the
transfer pays the tunnel's large fixed cost (~80ms observed,
columnar/transfer.py), and only then does the TPU kernel dispatch — at any
instant two of the three resources (host CPU, tunnel, TPU) sit idle.
Theseus (PAPERS.md) shows a device query engine's wall-clock is dominated
by exactly this data-movement serialization and wins by overlapping I/O,
transfer and compute; this module is that overlap as a plan rewrite.

``PrefetchExec`` is a transparent unary node the planner inserts at
asynchrony-profitable boundaries (``insert_pipeline_prefetch``):

- host decode feeding ``HostToDeviceExec``  (decode N+1 while N transfers)
- transfer/shuffle output feeding device compute (ship N+1 while N computes,
  exploiting JAX async dispatch before deferred counts are forced)
- device compute feeding ``DeviceToHostExec`` (compute N+1 while N downloads)

Each ``execute_partition`` spins a ``PrefetchSpool``: ONE producer thread
drains the upstream generator into a bounded queue (depth AND in-flight
bytes bounded, ``spark.rapids.pipeline.*``) while the consumer pulls from
the queue.  The spool is memory-safe and failure-safe, not just fast:

- queued DEVICE batches register with the spill framework (lowest spill
  priority — in-flight prefetch is the most evictable data in the pool)
  and therefore count against the catalog's device-store budget;
- the producer runs under the consumer task's identity, so device
  admission is ONE shared hold released by the task-completion listener;
  a producer parked on backpressure keeps it (its consumer sibling is
  the thread draining the queue, so the task keeps progressing), which
  keeps ``concurrentGpuTasks`` honest while staying deadlock-free;
- a producer exception re-raises at the consumer with the ORIGINAL
  exception object (lineage intact), before any item was delivered when
  it struck before the first yield — so PR 3's task-retry/rerun machinery
  classifies and recovers it unchanged (fault point ``pipeline.prefetch``
  exercises exactly this path);
- consumer ``.close()`` (a limit short-circuiting, an abandoned fetch)
  stops the producer, closes every queued spillable, closes the upstream
  generator IN the producer thread, and joins it — early exit can neither
  leak spillables nor strand threads.

Stall-time and queue-depth metrics flow to the event bus
(``pipelineSpool`` events) and into the node's OpMetrics so
``explain(analyze=True)`` shows measured overlap per boundary; a
process-wide ledger (``pipeline_stats``) feeds bench.py's ``pipeline``
payload.
"""

from __future__ import annotations

import collections
import contextvars
import threading
import time
import weakref
from typing import Optional

from spark_rapids_tpu.plan.base import (Exec, UnaryExec,
                                        release_semaphore_for_wait)

#: conf-driven (plan/overrides.apply): spark.rapids.pipeline.*
PIPELINE_ENABLED = True
PIPELINE_DEPTH = 2
PIPELINE_MAX_BYTES = 256 << 20

_DONE = object()


class _SpoolError:
    """Producer-side failure in transit to the consumer (the original
    exception object travels so type/lineage survive re-raise)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


# ---------------------------------------------------------------------------
# process-wide ledger (bench.py's `pipeline` payload)
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()


def _zero_stats() -> dict:
    return {"spools": 0, "batches": 0, "bytes": 0,
            "producer_busy_s": 0.0, "producer_stall_s": 0.0,
            "consumer_stall_s": 0.0, "peak_depth": 0}


_STATS = _zero_stats()


def note_spool(spool: "PrefetchSpool") -> None:
    with _STATS_LOCK:
        _STATS["spools"] += 1
        _STATS["batches"] += spool.produced
        _STATS["bytes"] += spool.bytes_total
        _STATS["producer_busy_s"] += spool.producer_busy_s
        _STATS["producer_stall_s"] += spool.producer_stall_s
        _STATS["consumer_stall_s"] += spool.consumer_stall_s
        _STATS["peak_depth"] = max(_STATS["peak_depth"], spool.peak_depth)


def pipeline_stats() -> dict:
    """Snapshot with the derived overlap ratio: the fraction of upstream
    production time hidden from the consumer.  Fully serial execution has
    the consumer waiting out every producer second (ratio 0); perfect
    overlap has the consumer never waiting (ratio 1)."""
    with _STATS_LOCK:
        out = dict(_STATS)
    busy = out["producer_busy_s"]
    out["overlap_ratio"] = round(
        max(0.0, 1.0 - out["consumer_stall_s"] / busy), 4) if busy > 0 \
        else 0.0
    for k in ("producer_busy_s", "producer_stall_s", "consumer_stall_s"):
        out[k] = round(out[k], 6)
    return out


def reset_pipeline_stats() -> None:
    global _STATS
    with _STATS_LOCK:
        _STATS = _zero_stats()


#: live (unfinished) spools, for the resource sampler's point-in-time
#: queue-depth gauge; weak so a dropped spool never leaks through here
_LIVE_SPOOLS: "weakref.WeakSet" = weakref.WeakSet()


def live_spool_stats() -> dict:
    """Read-only snapshot of in-flight prefetch spools (sampler gauge).
    Depth reads race the producers by design — a sample is a sample."""
    spools = 0
    queued = 0
    queued_bytes = 0
    for s in list(_LIVE_SPOOLS):
        if s._finished:
            continue
        spools += 1
        queued += s._depth
        queued_bytes += s._bytes
    return {"spools": spools, "queued_batches": queued,
            "queued_bytes": queued_bytes}


# ---------------------------------------------------------------------------
# the spool
# ---------------------------------------------------------------------------

class PrefetchSpool:
    """Bounded producer/consumer spool over one upstream generator.

    The producer thread starts lazily at the first consumer pull (plan
    setup must not spawn threads) inside a COPY of the consumer's context
    (the active QueryExecution and speculation scope propagate, exactly
    like the task pool's ``ctx.copy().run``) and under the consumer
    task's id/metrics, so semaphore holds and pressure events attribute
    to — and are released with — the owning task.
    """

    #: contract flag the runtime plan verifier (plan/verify.py,
    #: ``spark.rapids.debug.planCheck``) asserts: ``_wrap`` registers
    #: every queued DEVICE batch with the spill framework (owned=False,
    #: lowest priority).  A refactor that drops the registration must
    #: flip this — and thereby fail every armed run — instead of
    #: silently pinning unevictable device memory in spool queues.
    QUEUED_DEVICE_BATCHES_SPILLABLE = True

    def __init__(self, source_fn, depth: int, max_bytes: int,
                 boundary: str):
        self._source_fn = source_fn
        self.depth = max(1, int(depth))
        self.max_bytes = max(1, int(max_bytes))
        self.boundary = boundary
        self._q: collections.deque = collections.deque()
        from spark_rapids_tpu.aux.lockorder import tracked_condition
        self._cond = tracked_condition("spool")
        self._depth = 0
        self._bytes = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._finished = False
        # stats
        self.produced = 0
        self.bytes_total = 0
        self.producer_busy_s = 0.0
        self.producer_stall_s = 0.0
        self.consumer_stall_s = 0.0
        self.peak_depth = 0
        # consumer task identity, adopted by the producer thread
        from spark_rapids_tpu.memory.retry import task_context
        tc = task_context()
        self._task_id = tc.task_id
        self._task_metrics = tc.metrics
        _LIVE_SPOOLS.add(self)

    # -- producer ------------------------------------------------------------
    def _start(self) -> None:
        ctx = contextvars.copy_context()
        t = threading.Thread(target=ctx.run, args=(self._produce,),
                             name=f"tpu-prefetch-{self.boundary}",
                             daemon=True)
        self._thread = t
        t.start()

    def _wrap(self, item):
        """(payload, spillable, nbytes): a device batch registers with the
        catalog so the spill framework can move it (and its bytes count
        against the device-store budget); a registration that itself hits
        pool pressure falls back to the raw batch — prefetch must relieve
        pressure, never amplify it."""
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        if isinstance(item, ColumnarBatch) and item.columns:
            nb = item.sized_nbytes()
            from spark_rapids_tpu.memory.device_manager import get_runtime
            if get_runtime() is not None:
                from spark_rapids_tpu.memory.catalog import SpillPriority
                from spark_rapids_tpu.memory.retry import RetryOOM
                from spark_rapids_tpu.memory.spillable import \
                    SpillableColumnarBatch
                try:
                    # owned=False: the arrays may be shared with upstream
                    # caches and are handed straight to the consumer — the
                    # catalog may spill (copy out + drop ref) but never
                    # .delete() them
                    spill = SpillableColumnarBatch.from_device(
                        item, priority=SpillPriority.INPUT_FROM_SHUFFLE,
                        owned=False)
                    return (None, spill, nb)
                except RetryOOM:
                    pass
            return (item, None, nb)
        nbf = getattr(item, "nbytes", None)
        return (item, None, nbf() if callable(nbf) else 0)

    @staticmethod
    def _close_entry(entry) -> None:
        spill = entry[1]
        if spill is not None:
            try:
                spill.close()
            except Exception:   # noqa: BLE001 - cleanup must not mask
                pass

    def _produce(self) -> None:
        # adopt the consumer task's identity: semaphore acquires in this
        # thread key to the task and release with it (run_task's finally),
        # and the arbiter tracks this thread under the task so the
        # deadlock detector sees the task's FULL thread set
        from spark_rapids_tpu.memory.arbiter import get_arbiter
        from spark_rapids_tpu.memory.retry import task_context
        tc = task_context()
        tc.task_id = self._task_id
        tc.metrics = self._task_metrics
        arb = get_arbiter()
        adopted = arb.adopt_thread(self._task_id)
        src = None
        try:
            from spark_rapids_tpu.aux.faults import maybe_fire
            maybe_fire("pipeline.prefetch")
            src = self._source_fn()
            while not self._stop:
                t0 = time.monotonic()
                try:
                    item = next(src)
                except StopIteration:
                    break
                self.producer_busy_s += time.monotonic() - t0
                entry = self._wrap(item)
                if not self._put(entry):
                    self._close_entry(entry)
                    break
                arb.note_progress(self._task_id)
        except BaseException as e:   # noqa: BLE001 - re-raised by consumer
            with self._cond:
                self._q.append(_SpoolError(e))
                self._cond.notify_all()
        finally:
            if adopted:
                arb.drop_thread(self._task_id)
            if src is not None:
                # the producer owns the upstream generator: closing it HERE
                # (never from the consumer thread, which would race a
                # running frame) propagates early exit all the way up
                try:
                    src.close()
                except BaseException:   # noqa: BLE001
                    pass
            if self._task_id is None:
                # no owning task: semaphore holds acquired under this
                # thread's identity have no completion listener to release
                # them — drop them now
                from spark_rapids_tpu.memory.device_manager import \
                    get_runtime
                rt = get_runtime()
                if rt is not None:
                    rt.semaphore.release_all()
            with self._cond:
                self._q.append(_DONE)
                self._cond.notify_all()

    def _put(self, entry) -> bool:
        from spark_rapids_tpu.memory.arbiter import TaskState, get_arbiter
        arb = get_arbiter()
        nb = entry[2]
        with self._cond:
            # admit at least one item regardless of its size, else a
            # batch larger than the byte budget would deadlock the spool.
            # NO semaphore release while backpressured: the device hold
            # is keyed by the task id this producer SHARES with its
            # consumer, and that consumer is the thread draining this
            # very queue — the task keeps progressing, and a whole-task
            # release would strip admission from a sibling mid-kernel
            # (over-admitting past concurrentGpuTasks)
            t0 = arb.wait_cancellable(
                self._cond,
                lambda: not self._stop and (
                    self._depth >= self.depth or
                    (self._depth > 0
                     and self._bytes + nb > self.max_bytes)),
                TaskState.BLOCKED_ON_SPOOL, slice_s=0.1,
                task_id=self._task_id)
            if t0 is not None:
                self.producer_stall_s += time.monotonic() - t0
            if self._stop:
                return False
            self._q.append(entry)
            self._depth += 1
            self._bytes += nb
            self.produced += 1
            self.bytes_total += nb
            self.peak_depth = max(self.peak_depth, self._depth)
            self._cond.notify_all()
            return True

    # -- consumer ------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        from spark_rapids_tpu.memory.arbiter import TaskState, get_arbiter
        arb = get_arbiter()
        if self._thread is None:
            self._start()
        with self._cond:
            def _on_first_wait():
                if self._task_id is None:
                    # untasked caller (direct-exec tests): the producer
                    # acquires under its OWN thread identity and could
                    # block on this thread's hold — drop it while
                    # waiting.  Tasked callers share one hold with the
                    # producer, so waiting with it held is deadlock-free
                    # and keeps admission honest.
                    release_semaphore_for_wait()

            # waiting on our own producer: a tracked blocked state (the
            # producer may itself be parked on an allocation — the task
            # is then FULLY blocked and must count toward deadlock
            # detection)
            t0 = arb.wait_cancellable(
                self._cond, lambda: not self._q,
                TaskState.BLOCKED_ON_SPOOL, slice_s=0.1,
                task_id=self._task_id, on_first_wait=_on_first_wait)
            if t0 is not None:
                self.consumer_stall_s += time.monotonic() - t0
            entry = self._q.popleft()
            if entry is _DONE:
                self._q.append(_DONE)   # repeated next() stays terminal
                self._finish()
                raise StopIteration
            if isinstance(entry, _SpoolError):
                exc = entry.exc
            else:
                exc = None
                self._depth -= 1
                self._bytes -= entry[2]
                self._cond.notify_all()
        if exc is not None:
            self._finish()
            raise exc
        payload, spill, _nb = entry
        if spill is not None:
            try:
                payload = spill.get_batch()
            finally:
                spill.close()
        arb.note_progress(self._task_id)    # spool handoff = task progress
        self._reacquire_admission(payload)
        return payload

    def _reacquire_admission(self, payload) -> None:
        """Dequeue is the owning task's device-section boundary: admission
        the producer legitimately dropped while blocked in an upstream
        wait (the exchange releases before materializing so map tasks can
        run) is re-acquired HERE, closing the over-admission window at
        the next batch instead of leaving the task computing unadmitted
        for its remainder.  Only inside a real task — its completion
        listener releases the hold; an untasked caller (direct-exec
        tests) must not pin a permit under a thread identity nothing
        releases."""
        if self._task_id is None:
            return
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        if not isinstance(payload, ColumnarBatch):
            return
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        if rt is not None:
            rt.semaphore.acquire_if_necessary()

    def close(self) -> None:
        """Idempotent early-exit teardown: stop the producer, release every
        queued spillable, join the thread.  Safe to call after normal
        exhaustion (everything is already drained)."""
        with self._cond:
            self._stop = True
            pending = [e for e in self._q
                       if e is not _DONE and not isinstance(e, _SpoolError)]
            self._q.clear()
            self._depth = 0
            self._bytes = 0
            self._cond.notify_all()
        for e in pending:
            self._close_entry(e)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            # the producer may be mid-pull on a slow upstream; it checks
            # the stop flag right after and exits.  The join bound keeps a
            # wedged upstream from hanging the consumer's close.
            t.join(timeout=10.0)
        self._finish()

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        note_spool(self)
        from spark_rapids_tpu.aux.events import emit
        emit("pipelineSpool", boundary=self.boundary,
             batches=self.produced,
             producer_busy_s=round(self.producer_busy_s, 6),
             producer_stall_s=round(self.producer_stall_s, 6),
             consumer_stall_s=round(self.consumer_stall_s, 6),
             peak_depth=self.peak_depth)


# ---------------------------------------------------------------------------
# the exec + planner pass
# ---------------------------------------------------------------------------

class PrefetchExec(UnaryExec):
    """Transparent pipelining boundary: schema/partitioning/device-ness all
    mirror the child; execution interposes a PrefetchSpool."""

    def __init__(self, child: Exec, boundary: str,
                 depth: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        super().__init__(child)
        self.boundary = boundary
        self.depth = depth
        self.max_bytes = max_bytes
        # instance attr shadows the class default so transitions/markers
        # see the wrapped tier
        self.is_device = child.is_device

    def execute_partition(self, pidx):
        depth = self.depth if self.depth is not None else PIPELINE_DEPTH
        mb = self.max_bytes if self.max_bytes is not None \
            else PIPELINE_MAX_BYTES
        spool = PrefetchSpool(
            lambda: self.child.execute_partition(pidx), depth, mb,
            self.boundary)
        try:
            # PEP 380: closing this generator close()s the spool via the
            # delegation protocol; the finally covers error paths too
            yield from spool
        finally:
            spool.close()
            self._note_metrics(spool)

    def _note_metrics(self, spool: PrefetchSpool) -> None:
        """Folds spool stats into this node's OpMetrics so the span tree
        (explain(analyze=True)) shows per-boundary overlap."""
        ms = getattr(self, "metrics", None)
        if not isinstance(ms, dict):
            return
        from spark_rapids_tpu.aux.metrics import MetricLevel, OpMetric

        def metric(name: str) -> OpMetric:
            m = ms.get(name)
            if m is None:
                m = ms[name] = OpMetric(name, MetricLevel.MODERATE)
            return m

        metric("producerStallTime").add(round(spool.producer_stall_s, 6))
        metric("consumerStallTime").add(round(spool.consumer_stall_s, 6))
        pk = metric("peakQueueDepth")
        pk.value = max(pk.value, spool.peak_depth)

    def node_desc(self):
        d = self.depth if self.depth is not None else PIPELINE_DEPTH
        return f"Prefetch[{self.boundary}, depth={d}]"


def insert_pipeline_prefetch(plan: Exec) -> Exec:
    """Planner pass (runs LAST, after reuse/adaptive): wraps the
    asynchrony-profitable boundaries in PrefetchExec.  Identity-memoized —
    a node shared by several parents (ReuseExchange, CTE collapse) must
    map to ONE rewritten node or the sharing silently splits into
    per-parent copies that each re-materialize their shuffle."""
    from spark_rapids_tpu.exec.adaptive import AdaptiveShuffleReaderExec
    from spark_rapids_tpu.exec.basic import (CpuInMemoryScanExec,
                                             DeviceToHostExec,
                                             HostToDeviceExec,
                                             TpuCoalesceBatchesExec)
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec

    def boundary_for(c: Exec) -> Optional[str]:
        if isinstance(c, (HostToDeviceExec, TpuCoalesceBatchesExec)):
            return "transfer"
        if isinstance(c, (CpuShuffleExchangeExec,
                          AdaptiveShuffleReaderExec)):
            return "shuffle"
        if isinstance(c, CpuInMemoryScanExec) and c.is_device:
            # device-resident scan: the producer pays the (first-action)
            # upload and cache assembly while the consumer computes
            return "upload"
        return None

    memo: dict = {}

    def visit(node: Exec) -> Exec:
        hit = memo.get(id(node))
        if hit is not None:
            return hit
        kids = [visit(c) for c in node.children]
        if isinstance(node, PrefetchExec):
            pass   # already a boundary: never stack spools
        elif isinstance(node, HostToDeviceExec):
            if not isinstance(kids[0], PrefetchExec):
                kids = [PrefetchExec(kids[0], "decode")]
        elif isinstance(node, DeviceToHostExec):
            if not isinstance(kids[0], PrefetchExec):
                kids = [PrefetchExec(kids[0], "d2h")]
        elif node.is_device and not isinstance(
                node, (TpuCoalesceBatchesExec, AdaptiveShuffleReaderExec)):
            # (the coalescer and the adaptive reader introspect their
            # direct child — the spool goes ABOVE them, never inside)
            kids = [PrefetchExec(c, b)
                    if not isinstance(c, PrefetchExec)
                    and (b := boundary_for(c)) is not None else c
                    for c in kids]
        if kids != node.children:
            # mutate IN PLACE (like instrument_plan): this pass runs on
            # the per-action executed tree, and a with_children copy here
            # would split identities other passes pinned — the adaptive
            # readers' coordinated specs reference the in-tree exchange
            # instances, and reuse/CTE sharing is by identity
            node.children = kids
        memo[id(node)] = node
        return node

    return visit(plan)
