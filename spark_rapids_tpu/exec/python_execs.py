"""Pandas-exec family: vectorized python over arrow batches.

Reference: execution/python/ (14 files) — GpuMapInPandasExec,
GpuFlatMapGroupsInPandasExec, GpuArrowEvalPythonExec: the engine batches
columnar data, hands it to python over Arrow, and reads arrow back.  Here
the hand-off is in-process (pandas <-> arrow), host tier with honest
tagging — the data-movement architecture (batch -> arrow -> python ->
arrow -> batch) is the same."""

from __future__ import annotations

from typing import Callable, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import batch_from_arrow
from spark_rapids_tpu.plan.base import Exec, UnaryExec


def _to_pandas(b):
    import pyarrow as pa
    hb = b.to_host() if hasattr(b, "bucket") else b
    return pa.Table.from_batches([hb.to_arrow()]).to_pandas()


def _from_pandas(pdf, schema: T.StructType):
    import pyarrow as pa
    arrays = {}
    for f in schema.fields:
        if f.name not in pdf.columns:
            raise ValueError(f"pandas UDF result is missing column "
                             f"{f.name!r} (declared schema: "
                             f"{schema.simple_name})")
        arrays[f.name] = pa.array(pdf[f.name],
                                  type=T.to_arrow(f.data_type))
    return batch_from_arrow(pa.table(arrays))


class CpuMapInPandasExec(UnaryExec):
    """df.map_in_pandas(fn, schema): fn(pandas.DataFrame) ->
    pandas.DataFrame per batch (reference GpuMapInPandasExec)."""

    def __init__(self, fn: Callable, out_schema: T.StructType, child: Exec):
        super().__init__(child)
        self.fn = fn
        self._schema = out_schema

    @property
    def schema(self):
        return self._schema

    def execute_partition(self, pidx):
        for b in self.child.execute_partition(pidx):
            pdf = self.fn(_to_pandas(b))
            yield _from_pandas(pdf, self._schema)

    def node_desc(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class CpuFlatMapGroupsInPandasExec(UnaryExec):
    """group_by(keys).apply_in_pandas(fn, schema): child is already
    hash-partitioned by the keys; each group's rows become one pandas
    DataFrame handed to fn (reference GpuFlatMapGroupsInPandasExec)."""

    def __init__(self, key_names: Sequence[str], fn: Callable,
                 out_schema: T.StructType, child: Exec):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self._schema = out_schema

    @property
    def schema(self):
        return self._schema

    def execute_partition(self, pidx):
        import pandas as pd
        frames = [_to_pandas(b) for b in self.child.execute_partition(pidx)]
        if not frames:
            return
        pdf = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0]
        if not len(pdf):
            return
        for _key, group in pdf.groupby(self.key_names, dropna=False,
                                       sort=True):
            out = self.fn(group.reset_index(drop=True))
            if out is not None and len(out):
                yield _from_pandas(out, self._schema)

    def node_desc(self):
        return (f"FlatMapGroupsInPandas[{', '.join(self.key_names)}; "
                f"{getattr(self.fn, '__name__', 'fn')}]")


# host tier: registered so tagging reports the honest reason
from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402


def _host_only(meta):
    meta.will_not_work("pandas execs run on the host tier "
                       "(arrow hand-off to python)")


register_exec(CpuMapInPandasExec, convert=lambda p, m: p,
              sig=TS.BASIC_WITH_ARRAYS, extra_tag=_host_only,
              desc="vectorized python over arrow batches")
register_exec(CpuFlatMapGroupsInPandasExec, convert=lambda p, m: p,
              sig=TS.BASIC_WITH_ARRAYS, extra_tag=_host_only,
              desc="grouped pandas apply over arrow batches")
