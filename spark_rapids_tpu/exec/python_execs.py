"""Pandas-exec family: vectorized python over arrow batches.

Reference: execution/python/ (14 files) — GpuMapInPandasExec,
GpuFlatMapGroupsInPandasExec, GpuArrowEvalPythonExec: the engine batches
columnar data, hands it to python over Arrow, and reads arrow back.  Here
the hand-off is in-process (pandas <-> arrow), host tier with honest
tagging — the data-movement architecture (batch -> arrow -> python ->
arrow -> batch) is the same."""

from __future__ import annotations

from typing import Callable, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import batch_from_arrow
from spark_rapids_tpu.plan.base import Exec, UnaryExec, closing_source


def _to_pandas(b):
    import pyarrow as pa
    hb = b.to_host() if hasattr(b, "bucket") else b
    return pa.Table.from_batches([hb.to_arrow()]).to_pandas()


def _from_pandas(pdf, schema: T.StructType):
    import pyarrow as pa
    arrays = {}
    for f in schema.fields:
        if f.name not in pdf.columns:
            raise ValueError(f"pandas UDF result is missing column "
                             f"{f.name!r} (declared schema: "
                             f"{schema.simple_name})")
        arrays[f.name] = pa.array(pdf[f.name],
                                  type=T.to_arrow(f.data_type))
    return batch_from_arrow(pa.table(arrays))


class CpuMapInPandasExec(UnaryExec):
    """df.map_in_pandas(fn, schema): fn(pandas.DataFrame) ->
    pandas.DataFrame per batch (reference GpuMapInPandasExec)."""

    def __init__(self, fn: Callable, out_schema: T.StructType, child: Exec):
        super().__init__(child)
        self.fn = fn
        self._schema = out_schema

    @property
    def schema(self):
        return self._schema

    def execute_partition(self, pidx):
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                pdf = self.fn(_to_pandas(b))
                yield _from_pandas(pdf, self._schema)

    def node_desc(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class CpuFlatMapGroupsInPandasExec(UnaryExec):
    """group_by(keys).apply_in_pandas(fn, schema): child is already
    hash-partitioned by the keys; each group's rows become one pandas
    DataFrame handed to fn (reference GpuFlatMapGroupsInPandasExec)."""

    def __init__(self, key_names: Sequence[str], fn: Callable,
                 out_schema: T.StructType, child: Exec):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self._schema = out_schema

    @property
    def schema(self):
        return self._schema

    def execute_partition(self, pidx):
        import pandas as pd
        frames = [_to_pandas(b) for b in self.child.execute_partition(pidx)]
        if not frames:
            return
        pdf = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0]
        if not len(pdf):
            return
        for _key, group in pdf.groupby(self.key_names, dropna=False,
                                       sort=True):
            out = self.fn(group.reset_index(drop=True))
            if out is not None and len(out):
                yield _from_pandas(out, self._schema)

    def node_desc(self):
        return (f"FlatMapGroupsInPandas[{', '.join(self.key_names)}; "
                f"{getattr(self.fn, '__name__', 'fn')}]")


def _eval_inputs_pandas(exprs, b):
    """Evaluates input expressions on the host and returns pandas Series."""
    from spark_rapids_tpu.expressions.base import Alias
    from spark_rapids_tpu.expressions.evaluator import eval_exprs_cpu
    hb = b.to_host() if hasattr(b, "bucket") else b
    named = [Alias(e, f"u{i}") for i, e in enumerate(exprs)]
    out = eval_exprs_cpu(named, hb)
    import pyarrow as pa
    return [pa.Table.from_batches([out.to_arrow()]).column(i).to_pandas()
            for i in range(len(exprs))]


class CpuArrowEvalPythonExec(UnaryExec):
    """Scalar pandas UDFs inside a projection: ``udfs`` is
    [(name, fn, input_exprs, dtype)] with ``fn(*pandas.Series) ->
    pandas.Series`` per batch; appends one column per UDF (reference:
    GpuArrowEvalPythonExec — batch -> arrow -> python -> arrow)."""

    def __init__(self, udfs, child: Exec):
        super().__init__(child)
        self.udfs = list(udfs)

    @property
    def schema(self):
        fields = list(self.child.schema.fields)
        for name, _fn, _ins, dtype in self.udfs:
            fields.append(T.StructField(name, dtype, True))
        return T.StructType(fields)

    def execute_partition(self, pidx):
        import pyarrow as pa
        with closing_source(self.child.execute_partition(pidx)) as it:
            for b in it:
                hb = b.to_host() if hasattr(b, "bucket") else b
                tab = pa.Table.from_batches([hb.to_arrow()])
                # ONE host eval pass for every UDF's inputs (k separate
                # passes would re-materialize the batch per UDF)
                all_ins = [e for _n, _f, ins, _d in self.udfs for e in ins]
                series = _eval_inputs_pandas(all_ins, hb) if all_ins else []
                off = 0
                for name, fn, ins, dtype in self.udfs:
                    args = series[off:off + len(ins)]
                    off += len(ins)
                    res = fn(*args)
                    tab = tab.append_column(
                        name, pa.array(res, type=T.to_arrow(dtype)))
                yield batch_from_arrow(tab)

    def node_desc(self):
        return "ArrowEvalPython[%s]" % ", ".join(n for n, *_ in self.udfs)


class CpuAggregateInPandasExec(UnaryExec):
    """Grouped pandas-UDF aggregation: ``fn(*pandas.Series) -> scalar``
    per group; child is hash-partitioned by the keys; yields one row per
    group: keys + one column per UDF (reference:
    GpuAggregateInPandasExec)."""

    def __init__(self, key_names: Sequence[str], udfs, child: Exec):
        super().__init__(child)
        self.key_names = list(key_names)
        self.udfs = list(udfs)

    @property
    def schema(self):
        child = self.child.schema
        fields = [f for f in child.fields if f.name in self.key_names]
        for name, _fn, _ins, dtype in self.udfs:
            fields.append(T.StructField(name, dtype, True))
        return T.StructType(fields)

    def execute_partition(self, pidx):
        import pandas as pd
        import pyarrow as pa
        frames = [_to_pandas(b) for b in self.child.execute_partition(pidx)]
        if not frames:
            return
        pdf = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0]
        if not len(pdf):
            return
        rows = {k: [] for k in self.key_names}
        outs = {name: [] for name, *_ in self.udfs}
        for key_vals, group in pdf.groupby(self.key_names, dropna=False,
                                           sort=True):
            if not isinstance(key_vals, tuple):
                key_vals = (key_vals,)
            for k, v in zip(self.key_names, key_vals):
                rows[k].append(None if pd.isna(v) else v)
            for name, fn, ins, _dtype in self.udfs:
                args = [group[self._in_name(e)].reset_index(drop=True)
                        for e in ins]
                outs[name].append(fn(*args))
        sch = self.schema
        arrays = {}
        for f in sch.fields:
            src = rows.get(f.name, outs.get(f.name))
            arrays[f.name] = pa.array(src, type=T.to_arrow(f.data_type))
        yield batch_from_arrow(pa.table(arrays))

    def _in_name(self, e) -> str:
        name = getattr(e, "ref_name", None)
        if name:
            return name
        raise ValueError("agg_in_pandas inputs must be plain columns")

    def node_desc(self):
        return "AggregateInPandas[%s]" % ", ".join(n for n, *_ in self.udfs)


class CpuWindowInPandasExec(UnaryExec):
    """Pandas UDF over the whole window partition (UNBOUNDED frame):
    ``fn(*pandas.Series) -> scalar`` per partition group, broadcast to the
    group's rows as an appended column (reference: GpuWindowInPandasExec
    whole-partition frame).  Output rows come grouped by key."""

    def __init__(self, key_names: Sequence[str], udfs, child: Exec):
        super().__init__(child)
        self.key_names = list(key_names)
        self.udfs = list(udfs)

    @property
    def schema(self):
        fields = list(self.child.schema.fields)
        for name, _fn, _ins, dtype in self.udfs:
            fields.append(T.StructField(name, dtype, True))
        return T.StructType(fields)

    def execute_partition(self, pidx):
        import pandas as pd
        import pyarrow as pa
        frames = [_to_pandas(b) for b in self.child.execute_partition(pidx)]
        if not frames:
            return
        pdf = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0]
        if not len(pdf):
            return
        pieces = []
        for _key, group in pdf.groupby(self.key_names, dropna=False,
                                       sort=True):
            g = group.reset_index(drop=True)
            for name, fn, ins, _dtype in self.udfs:
                args = [g[self._in_name(e)] for e in ins]
                g[name] = fn(*args)
            pieces.append(g)
        out = pd.concat(pieces, ignore_index=True)
        sch = self.schema
        arrays = {f.name: pa.array(out[f.name], type=T.to_arrow(f.data_type))
                  for f in sch.fields}
        yield batch_from_arrow(pa.table(arrays))

    _in_name = CpuAggregateInPandasExec._in_name

    def node_desc(self):
        return "WindowInPandas[%s]" % ", ".join(n for n, *_ in self.udfs)


class CpuFlatMapCoGroupsInPandasExec(Exec):
    """Co-grouped pandas apply: both children hash-partitioned by their
    keys; per key ``fn(left_pdf, right_pdf) -> pdf`` (either side may be
    empty) (reference: GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left_keys: Sequence[str], right_keys: Sequence[str],
                 fn: Callable, out_schema: T.StructType,
                 left: Exec, right: Exec):
        super().__init__([left, right])
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = out_schema

    @property
    def schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, pidx):
        import pandas as pd

        def side(child, keys):
            frames = [_to_pandas(b) for b in child.execute_partition(pidx)]
            if not frames:
                return {}
            pdf = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
                else frames[0]
            if not len(pdf):
                return {}
            return {k if isinstance(k, tuple) else (k,):
                    g.reset_index(drop=True)
                    for k, g in pdf.groupby(keys, dropna=False, sort=True)}

        lgroups = side(self.children[0], self.left_keys)
        rgroups = side(self.children[1], self.right_keys)
        lcols = [f.name for f in self.children[0].schema.fields]
        rcols = [f.name for f in self.children[1].schema.fields]
        lempty = pd.DataFrame(columns=lcols)
        rempty = pd.DataFrame(columns=rcols)
        keys = sorted(set(lgroups) | set(rgroups),
                      key=lambda t: tuple((v is None, v) for v in t))
        for k in keys:
            out = self.fn(lgroups.get(k, lempty), rgroups.get(k, rempty))
            if out is not None and len(out):
                yield _from_pandas(out, self._schema)

    def node_desc(self):
        return (f"FlatMapCoGroupsInPandas[{', '.join(self.left_keys)}; "
                f"{getattr(self.fn, '__name__', 'fn')}]")


# host tier: registered so tagging reports the honest reason
from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402


def _host_only(meta):
    meta.will_not_work("pandas execs run on the host tier "
                       "(arrow hand-off to python)")


for _cls, _desc in (
        (CpuMapInPandasExec, "vectorized python over arrow batches"),
        (CpuFlatMapGroupsInPandasExec,
         "grouped pandas apply over arrow batches"),
        (CpuArrowEvalPythonExec, "scalar pandas UDFs in projections"),
        (CpuAggregateInPandasExec, "grouped pandas-UDF aggregation"),
        (CpuWindowInPandasExec, "pandas UDF over window partitions"),
        (CpuFlatMapCoGroupsInPandasExec,
         "co-grouped pandas apply over arrow batches")):
    register_exec(_cls, convert=lambda p, m: p,
                  sig=TS.BASIC_WITH_ARRAYS, extra_tag=_host_only,
                  desc=_desc, host_only=True)
