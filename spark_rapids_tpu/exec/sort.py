"""Sort operator.

Reference: GpuSortExec.scala (633 LoC) — full/partial sort with out-of-core
merge; SortUtils.scala lowers to cuDF sortOrder+gather.  Here the device
path is ops.sort_ops (one fused lax.sort); the per-partition iterator
coalesces input batches and sorts once (the reference's full-sort path
similarly concatenates-then-sorts, spilling when pressured — our spill hook
is the memory catalog, wired by the exec when batches exceed budget).

Global total order = RangePartitioning exchange below this exec (planner's
job), matching Spark's SortExec(global=true) requiring range-partitioned
input.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_host_batches
from spark_rapids_tpu.expressions.base import BoundReference, Expression
from spark_rapids_tpu.plan.base import Exec, UnaryExec


@dataclasses.dataclass(frozen=True)
class SortSpec:
    """Sort key at the expression level (Spark SortOrder)."""
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: asc->first, desc->last

    @property
    def effective_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _split_keys(specs: Sequence[SortSpec], n_cols: int):
    """Maps sort specs onto batch ordinals; non-reference keys are appended
    as projected columns after the originals."""
    from spark_rapids_tpu.ops.sort_ops import SortOrder
    extra: List[Expression] = []
    orders: List[SortOrder] = []
    for s in specs:
        if isinstance(s.expr, BoundReference):
            orders.append(SortOrder(s.expr.ordinal, s.ascending,
                                    s.effective_nulls_first))
        else:
            orders.append(SortOrder(n_cols + len(extra), s.ascending,
                                    s.effective_nulls_first))
            extra.append(s.expr)
    return orders, extra


def host_sort_batch(b, specs: Sequence[SortSpec]):
    """Stable host sort of one concatenated batch; iterative stable pandas
    sort (general per-key null placement).  Shared by CpuSortExec and
    CpuTakeOrderedAndProjectExec."""
    import numpy as np
    import pyarrow as pa
    import pandas as pd
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                        tcol_to_host_column)
    from spark_rapids_tpu.expressions.base import EvalContext
    keys = []
    cols = host_batch_tcols(b)
    ctx = EvalContext(cols, "cpu", b.row_count)
    for s in specs:
        kc = tcol_to_host_column(s.expr.eval_cpu(ctx), b.row_count)
        keys.append(kc.arrow)
    perm = np.arange(b.row_count)

    def key_series(arr):
        # floats: pandas conflates NaN with NA; map to IEEE-sortable
        # ints (NaN > +inf, Spark order) keeping true nulls as NA
        if pa.types.is_floating(arr.type):
            isnull = arr.is_null().to_numpy(zero_copy_only=False)
            v = arr.fill_null(0).to_numpy(zero_copy_only=False)
            v = np.where(v == 0.0, 0.0, v)  # -0.0 -> 0.0
            v = np.where(np.isnan(v), np.nan, v)
            u = v.astype(np.float64).view(np.uint64)
            sign = np.uint64(1) << np.uint64(63)
            key = np.where(u & sign != 0, u ^ ~np.uint64(0), u | sign)
            ser = pd.Series(key, dtype="UInt64")
            ser[isnull] = pd.NA
            return ser
        if pa.types.is_integer(arr.type):
            # plain to_pandas() promotes nullable int64 to float64,
            # corrupting values above 2^53 — keep exact via nullable Int64
            isnull = arr.is_null().to_numpy(zero_copy_only=False)
            v = arr.fill_null(0).to_numpy(zero_copy_only=False)
            ser = pd.Series(v.astype(np.int64), dtype="Int64")
            ser[isnull] = pd.NA
            return ser
        return pd.Series(arr.to_pandas())

    for s, arr in zip(reversed(list(specs)), reversed(keys)):
        ser = key_series(arr.take(pa.array(perm)))
        na = "first" if s.effective_nulls_first else "last"
        idx = ser.sort_values(kind="stable", ascending=s.ascending,
                              na_position=na).index.to_numpy()
        perm = perm[idx]
    tab = pa.Table.from_batches([b.to_arrow()]).take(pa.array(perm))
    return batch_from_arrow(tab)


def device_sort_batch(b: ColumnarBatch, specs: Sequence[SortSpec]
                      ) -> ColumnarBatch:
    """Device sort of one batch (reference: SortUtils computeSortedTable).
    Sort-key prep is fused: non-reference keys evaluate IN-TRACE inside
    the single sort+gather program (ops/sort_ops.sort_gather_batch) — no
    key projection dispatch, no key materialization, no separate
    per-column gather."""
    from spark_rapids_tpu.columnar.encoding import shadow_sort_batch
    from spark_rapids_tpu.ops.sort_ops import sort_gather_batch
    from spark_rapids_tpu.memory.retry import with_retry_no_split
    # encoded prep: a dictionary SORT KEY rides its codes only when the
    # dictionary is value-sorted (codes are order-isomorphic), else it
    # materializes; payload dictionary columns gather as int planes and
    # re-wrap, staying encoded through the sort
    b, rewrap = shadow_sort_batch(b, specs)
    orders, extra = _split_keys(specs, b.num_columns)
    return rewrap(with_retry_no_split(
        None, lambda: sort_gather_batch(b, orders, extra)))


class CpuSortExec(UnaryExec):
    """Per-partition host sort."""

    def __init__(self, specs: Sequence[SortSpec], child: Exec,
                 global_sort: bool = False):
        super().__init__(child)
        self.specs = list(specs)
        self.global_sort = global_sort

    def execute_partition(self, pidx):
        batches = list(self.child.execute_partition(pidx))
        if not batches:
            return
        yield host_sort_batch(concat_host_batches(batches), self.specs)

    def node_desc(self):
        ks = ", ".join(f"{s.expr.sql()} {'ASC' if s.ascending else 'DESC'}"
                       for s in self.specs)
        return f"Sort[{ks}]"


#: test hook: force the external (sorted-runs + merge) path
FORCE_OUT_OF_CORE_SORT = False
#: observability: bumped once per external-sort merge pass
EXTERNAL_SORT_EVENTS = 0
#: rows per output batch of the external merge (bounds device residency
#: of any single downstream batch)
_MERGE_OUT_ROWS = 1 << 20


def _word_bytes(w: "np.ndarray", n: int):
    """One order word -> big-endian unsigned bytes (order preserved)."""
    import numpy as np
    if w.dtype == np.bool_:
        u = w.astype(np.uint8)
    elif w.dtype.kind == "i":
        bits = w.dtype.itemsize * 8
        ut = np.dtype(f"uint{bits}")
        u = (w.view(ut) ^ ut.type(1 << (bits - 1)))
    else:
        u = w
    be = np.ascontiguousarray(u.astype(u.dtype.newbyteorder(">")))
    return be.view(np.uint8).reshape(n, u.dtype.itemsize)


def merge_key_bytes(hb, specs: Sequence[SortSpec],
                    string_widths: Optional[dict] = None) -> "np.ndarray":
    """Per-row packed key bytes whose plain bytewise order == the SQL sort
    order (host mirror of the device sortable-words normalization).  All
    runs of one merge must pass the same ``string_widths`` so their word
    counts agree."""
    import numpy as np
    from spark_rapids_tpu.expressions.base import EvalContext
    from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                        tcol_to_host_column)
    from spark_rapids_tpu.ops.sort_ops import SortOrder, host_order_words
    n = hb.row_count
    ctx = EvalContext(host_batch_tcols(hb), "cpu", n)
    planes = []
    for i, s in enumerate(specs):
        kc = tcol_to_host_column(s.expr.eval_cpu(ctx), n)
        order = SortOrder(0, s.ascending, s.effective_nulls_first)
        width = (string_widths or {}).get(i)
        for w in host_order_words(kc, order, string_width=width):
            planes.append(_word_bytes(np.asarray(w), n))
    packed = np.concatenate(planes, axis=1) if planes else \
        np.zeros((n, 1), dtype=np.uint8)
    return packed.reshape(n, -1).view(f"|S{packed.shape[1]}").ravel()


def probe_string_widths(host_batches, specs: Sequence[SortSpec]) -> dict:
    """Max string rectangle width per string sort key across all runs."""
    import pyarrow as pa
    from spark_rapids_tpu.expressions.base import EvalContext
    from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                        tcol_to_host_column)
    widths: dict = {}
    for hb in host_batches:
        ctx = EvalContext(host_batch_tcols(hb), "cpu", hb.row_count)
        for i, s in enumerate(specs):
            if not isinstance(s.expr.data_type, (T.StringType,
                                                 T.BinaryType)):
                continue
            kc = tcol_to_host_column(s.expr.eval_cpu(ctx), hb.row_count)
            arr = kc.arrow
            lens = pa.compute.binary_length(arr)
            mx = pa.compute.max(lens).as_py() or 0
            widths[i] = max(widths.get(i, 1), int(mx), 1)
    return widths


class TpuSortExec(UnaryExec):
    """Device sort (reference: GpuSortExec.scala:633 full-sort path with
    the out-of-core discipline).

    Fast path: concat every input batch and sort once on device.  When
    the estimated working set exceeds the free-pool headroom (or a
    SplitAndRetryOOM surfaces), falls back to an EXTERNAL sort: inputs
    group into device-budget-sized chunks, each chunk sorts on device
    into a sorted run that is staged to spillable host memory, and the
    runs merge by their packed order-word keys (numpy stable sort over
    pre-sorted runs — C-speed, host tier), streaming device batches of
    ``_MERGE_OUT_ROWS`` back out.  Device residency stays bounded by one
    chunk + one output batch.
    """

    is_device = True

    def __init__(self, specs: Sequence[SortSpec], child: Exec,
                 global_sort: bool = False):
        super().__init__(child)
        self.specs = list(specs)
        self.global_sort = global_sort

    def _run_budget(self):
        from spark_rapids_tpu.memory.device_manager import \
            free_device_headroom
        # sort materializes the permuted copy -> 4x headroom
        return free_device_headroom(4)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.memory.retry import (SplitAndRetryOOM,
                                                   maybe_inject_oom,
                                                   with_retry_no_split)
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
        from spark_rapids_tpu.ops import concat_batches
        spills = [SpillableColumnarBatch.from_device(b)
                  for b in self.child.execute_partition(pidx)]
        if not spills:
            return
        budget = self._run_budget()
        est = sum(sb.sized_nbytes for sb in spills)
        fits = not FORCE_OUT_OF_CORE_SORT and \
            (budget is None or est <= budget)
        if fits:
            def attempt():
                maybe_inject_oom()
                bs = [sb.get_batch() for sb in spills]
                big = concat_batches(bs) if len(bs) > 1 else bs[0]
                return device_sort_batch(big, self.specs)
            try:
                out = with_retry_no_split(None, attempt)
                for sb in spills:
                    sb.close()
                yield out
                return
            except SplitAndRetryOOM:
                pass  # the input must be processed in pieces
        yield from self._external_sort(spills, budget)

    def _external_sort(self, spills, budget):
        """Sorted runs -> spillable host staging -> packed-key merge."""
        import numpy as np
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        from spark_rapids_tpu.exec.basic import upload_batches
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
        from spark_rapids_tpu.ops import concat_batches
        global EXTERNAL_SORT_EVENTS
        EXTERNAL_SORT_EVENTS += 1
        run_budget = budget if budget and budget > 0 else 64 << 20
        # ---- build device-sorted runs, staged to spillable host memory ----
        runs: List[SpillableColumnarBatch] = []
        group: List[SpillableColumnarBatch] = []
        group_bytes = 0

        def flush_group():
            nonlocal group, group_bytes
            if not group:
                return
            bs = [sb.get_batch() for sb in group]
            big = concat_batches(bs) if len(bs) > 1 else bs[0]
            sorted_run = with_retry_no_split(
                None, lambda: device_sort_batch(big, self.specs))
            hb = sorted_run.to_host()
            for sb in group:
                sb.close()
            runs.append(SpillableColumnarBatch.from_host(hb))
            group, group_bytes = [], 0

        for sb in spills:
            if group and group_bytes + sb.sized_nbytes > run_budget:
                flush_group()
            group.append(sb)
            group_bytes += sb.sized_nbytes
        flush_group()
        # ---- merge runs by packed order-word keys ----
        host_runs = [r.get_host_batch() for r in runs]
        widths = probe_string_widths(host_runs, self.specs)
        keys = np.concatenate([merge_key_bytes(hb, self.specs, widths)
                               for hb in host_runs])
        order = np.argsort(keys, kind="stable")  # stable: run order on ties
        tab = pa.Table.from_batches([hb.to_arrow() for hb in host_runs])
        names = host_runs[0].names
        for r in runs:
            r.close()
        total = tab.num_rows
        out_host = []
        for off in range(0, total, _MERGE_OUT_ROWS):
            idx = order[off:off + _MERGE_OUT_ROWS]
            piece = batch_from_arrow(tab.take(pa.array(idx)))
            piece.names = names
            out_host.append(piece)
        yield from upload_batches(out_host)

    def node_desc(self):
        ks = ", ".join(f"{s.expr.sql()} {'ASC' if s.ascending else 'DESC'}"
                       for s in self.specs)
        return f"TpuSort[{ks}]"


# plan-rewrite registration (reference: GpuOverrides SortExec rule :4210)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

from spark_rapids_tpu.plan import typechecks as _TS  # noqa: E402

register_exec(CpuSortExec,
              convert=lambda p, m: TpuSortExec(p.specs, p.children[0],
                                               p.global_sort),
              sig=_TS.BASIC_WITH_ARRAYS,
              exprs_of=lambda p: [s.expr for s in p.specs],
              extra_tag=lambda m: _TS.no_array_keys(
                  [s.expr for s in m.plan.specs], m, "sort key"),
              desc="device sort (fused lax.sort over sortable key words)")
