"""Sort operator.

Reference: GpuSortExec.scala (633 LoC) — full/partial sort with out-of-core
merge; SortUtils.scala lowers to cuDF sortOrder+gather.  Here the device
path is ops.sort_ops (one fused lax.sort); the per-partition iterator
coalesces input batches and sorts once (the reference's full-sort path
similarly concatenates-then-sorts, spilling when pressured — our spill hook
is the memory catalog, wired by the exec when batches exceed budget).

Global total order = RangePartitioning exchange below this exec (planner's
job), matching Spark's SortExec(global=true) requiring range-partitioned
input.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_host_batches
from spark_rapids_tpu.expressions.base import BoundReference, Expression
from spark_rapids_tpu.plan.base import Exec, UnaryExec


@dataclasses.dataclass(frozen=True)
class SortSpec:
    """Sort key at the expression level (Spark SortOrder)."""
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: asc->first, desc->last

    @property
    def effective_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _split_keys(specs: Sequence[SortSpec], n_cols: int):
    """Maps sort specs onto batch ordinals; non-reference keys are appended
    as projected columns after the originals."""
    from spark_rapids_tpu.ops.sort_ops import SortOrder
    extra: List[Expression] = []
    orders: List[SortOrder] = []
    for s in specs:
        if isinstance(s.expr, BoundReference):
            orders.append(SortOrder(s.expr.ordinal, s.ascending,
                                    s.effective_nulls_first))
        else:
            orders.append(SortOrder(n_cols + len(extra), s.ascending,
                                    s.effective_nulls_first))
            extra.append(s.expr)
    return orders, extra


def host_sort_batch(b, specs: Sequence[SortSpec]):
    """Stable host sort of one concatenated batch; iterative stable pandas
    sort (general per-key null placement).  Shared by CpuSortExec and
    CpuTakeOrderedAndProjectExec."""
    import numpy as np
    import pyarrow as pa
    import pandas as pd
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                        tcol_to_host_column)
    from spark_rapids_tpu.expressions.base import EvalContext
    keys = []
    cols = host_batch_tcols(b)
    ctx = EvalContext(cols, "cpu", b.row_count)
    for s in specs:
        kc = tcol_to_host_column(s.expr.eval_cpu(ctx), b.row_count)
        keys.append(kc.arrow)
    perm = np.arange(b.row_count)

    def key_series(arr):
        # floats: pandas conflates NaN with NA; map to IEEE-sortable
        # ints (NaN > +inf, Spark order) keeping true nulls as NA
        if pa.types.is_floating(arr.type):
            isnull = arr.is_null().to_numpy(zero_copy_only=False)
            v = arr.fill_null(0).to_numpy(zero_copy_only=False)
            v = np.where(v == 0.0, 0.0, v)  # -0.0 -> 0.0
            v = np.where(np.isnan(v), np.nan, v)
            u = v.astype(np.float64).view(np.uint64)
            sign = np.uint64(1) << np.uint64(63)
            key = np.where(u & sign != 0, u ^ ~np.uint64(0), u | sign)
            ser = pd.Series(key, dtype="UInt64")
            ser[isnull] = pd.NA
            return ser
        if pa.types.is_integer(arr.type):
            # plain to_pandas() promotes nullable int64 to float64,
            # corrupting values above 2^53 — keep exact via nullable Int64
            isnull = arr.is_null().to_numpy(zero_copy_only=False)
            v = arr.fill_null(0).to_numpy(zero_copy_only=False)
            ser = pd.Series(v.astype(np.int64), dtype="Int64")
            ser[isnull] = pd.NA
            return ser
        return pd.Series(arr.to_pandas())

    for s, arr in zip(reversed(list(specs)), reversed(keys)):
        ser = key_series(arr.take(pa.array(perm)))
        na = "first" if s.effective_nulls_first else "last"
        idx = ser.sort_values(kind="stable", ascending=s.ascending,
                              na_position=na).index.to_numpy()
        perm = perm[idx]
    tab = pa.Table.from_batches([b.to_arrow()]).take(pa.array(perm))
    return batch_from_arrow(tab)


def device_sort_batch(b: ColumnarBatch, specs: Sequence[SortSpec]
                      ) -> ColumnarBatch:
    """Device sort of one batch, projecting non-reference keys as needed
    (reference: SortUtils computeSortedTable)."""
    from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
    from spark_rapids_tpu.expressions.base import Alias, BoundReference as BR
    from spark_rapids_tpu.ops.sort_ops import sort_batch
    from spark_rapids_tpu.memory.retry import with_retry_no_split
    n_cols = b.num_columns
    orders, extra = _split_keys(specs, n_cols)
    if extra:
        names = b.names or [f"c{i}" for i in range(n_cols)]
        proj = [Alias(BR(i, c.data_type, True), names[i])
                for i, c in enumerate(b.columns)]
        keys = [Alias(e, f"__sortkey{i}") for i, e in enumerate(extra)]
        aug = eval_exprs_tpu(proj + keys, b)
    else:
        aug = b
    out = with_retry_no_split(None, lambda: sort_batch(aug, orders))
    if extra:
        out = out.select(list(range(n_cols)))
    return out


class CpuSortExec(UnaryExec):
    """Per-partition host sort."""

    def __init__(self, specs: Sequence[SortSpec], child: Exec,
                 global_sort: bool = False):
        super().__init__(child)
        self.specs = list(specs)
        self.global_sort = global_sort

    def execute_partition(self, pidx):
        batches = list(self.child.execute_partition(pidx))
        if not batches:
            return
        yield host_sort_batch(concat_host_batches(batches), self.specs)

    def node_desc(self):
        ks = ", ".join(f"{s.expr.sql()} {'ASC' if s.ascending else 'DESC'}"
                       for s in self.specs)
        return f"Sort[{ks}]"


class TpuSortExec(UnaryExec):
    """Device sort (reference: GpuSortExec full-sort path)."""

    is_device = True

    def __init__(self, specs: Sequence[SortSpec], child: Exec,
                 global_sort: bool = False):
        super().__init__(child)
        self.specs = list(specs)
        self.global_sort = global_sort

    def execute_partition(self, pidx):
        from spark_rapids_tpu.ops import concat_batches
        batches = list(self.child.execute_partition(pidx))
        if not batches:
            return
        yield device_sort_batch(concat_batches(batches), self.specs)

    def node_desc(self):
        ks = ", ".join(f"{s.expr.sql()} {'ASC' if s.ascending else 'DESC'}"
                       for s in self.specs)
        return f"TpuSort[{ks}]"


# plan-rewrite registration (reference: GpuOverrides SortExec rule :4210)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

from spark_rapids_tpu.plan import typechecks as _TS  # noqa: E402

register_exec(CpuSortExec,
              convert=lambda p, m: TpuSortExec(p.specs, p.children[0],
                                               p.global_sort),
              sig=_TS.BASIC_WITH_ARRAYS,
              exprs_of=lambda p: [s.expr for s in p.specs],
              extra_tag=lambda m: _TS.no_array_keys(
                  [s.expr for s in m.plan.specs], m, "sort key"),
              desc="device sort (fused lax.sort over sortable key words)")
