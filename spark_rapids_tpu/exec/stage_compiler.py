"""StageCompiler: the process-wide executable cache behind every jitted
stage program.

The engine's end-to-end deficit lives in the query path around the
kernels, not in the kernels (ROADMAP item 1): per-operator dispatch and —
worse — re-tracing/re-compiling programs the process has already built.
Every jitted stage program (fused filter/project/agg chains, join
build/probe/pair phases, sort permutations, window frames, transfer
pack/unpack...) is obtained through ONE helper here, keyed by its
(op-signature, batch schema, row bucket) and backed by a two-tier cache:

- **tier 1 — process executable cache**: a bounded LRU of jitted
  callables with hit/miss/evict/trace counters.  The python trace
  function of every program is wrapped with a trace counter, so "the
  second run of an identical query performs zero new traces" is an
  assertable fact, not a hope.
- **tier 2 — JAX persistent compilation cache** (conf
  ``spark.rapids.sql.compile.cacheDir``): compiled XLA executables
  survive process restarts; a cold process re-traces (cheap) but loads
  machine code from disk instead of re-compiling (expensive — tens of
  seconds per program on a tunnel-attached TPU).

Optional background compilation (conf ``spark.rapids.sql.compile.async``):
``warm_async`` lowers + compiles a program on a daemon pool thread while
the caller overlaps other work (the fused stage exec runs a one-batch
look-ahead so a new program's compile overlaps the previous batch's
compute), mirroring the PR-4 pipeline's producer/consumer overlap at the
compiler layer.

Reference analog: the reference pays JIT cost in cuDF kernel launches and
avoids it via pre-built kernels; a tracing-compiler engine must instead
manage program identity explicitly — this module is that manager.
"""

from __future__ import annotations

import collections
import hashlib
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["get_or_build", "stats", "reset_stats", "clear",
           "set_max_programs", "set_persistent_cache_dir", "StageProgram",
           "jaxpr_signatures"]

#: synced from ``spark.rapids.sql.compile.async`` by the planner
ASYNC_COMPILE = False

#: synced from ``spark.rapids.audit.ledger`` by the planner: record a
#: per-program audit ledger row (``stageProgram`` event, schema v3) at
#: every build, so the offline auditor (tools/audit) sees every cached
#: executable.  The row carries signatures/shapes/fingerprints ONLY —
#: never jaxpr objects or buffers, so audit state pins no device memory.
#: Recording only happens when a sink that will STORE the row is live
#: (the query's event-log file sink, or a process-global sink) — the
#: audit is an offline tool over event logs, and paying the per-build
#: analysis for a row that dies in the per-query ring buffer would tax
#: every sink-less session (~10% on compile-heavy suites) for nothing.
AUDIT_LEDGER = True

#: consts at or under this many bytes get a content fingerprint (one
#: host read at build time); larger consts record shape/dtype only —
#: the auditor treats any large const as promotion-suspect on its own
CONST_FP_MAX_BYTES = 1 << 20

#: cache keys recorded into the ledger are capped at this many repr
#: chars (key provenance is for storm diagnosis, not reconstruction)
KEY_REPR_MAX = 600

_LOCK = threading.RLock()
_PROGRAMS: "collections.OrderedDict[Tuple, StageProgram]" = \
    collections.OrderedDict()
_MAX_PROGRAMS = 4096

_STATS = {
    "hits": 0,          # tier-1 lookups that found a live program
    "misses": 0,        # lookups that had to build a new program
    "evictions": 0,     # programs dropped by the LRU bound
    "traces": 0,        # python trace-function executions (per jax trace)
    "compiles": 0,      # first dispatches that built a new executable
    "async_compiles": 0,  # programs compiled on the background pool
    "async_failures": 0,  # background compiles that raised (jit fallback)
    "compile_s": 0.0,   # seconds spent in first-dispatch trace+compile
    "ledger_rows": 0,   # stageProgram audit rows emitted
    "ledger_errors": 0,  # ledger recordings that raised (audit never
                         # fails the query; nonzero = blind audit spots)
}
#: last background-compile error (stats(); None = healthy)
_ASYNC_ERROR = [None]
_TRACES_BY_KIND: Dict[str, int] = {}

#: persistent (tier-2) cache state; dir None = disabled
_DISK = {"dir": None, "error": None}

_POOL = None
_POOL_LOCK = threading.Lock()


class _DaemonPool:
    """Two daemon worker threads + a queue.  NOT a ThreadPoolExecutor:
    since 3.9 its (non-daemon) workers are joined at interpreter exit, so
    an in-flight XLA compile — tens of seconds, or forever on a dead TPU
    tunnel — would block shutdown.  A background compile is disposable;
    daemon threads let the process exit mid-compile."""

    def __init__(self, workers: int = 2):
        import queue
        self._q = queue.Queue()
        for i in range(workers):
            threading.Thread(target=self._loop, daemon=True,
                             name=f"tpu-compile-{i}").start()

    def _loop(self):
        while True:
            fut, fn = self._q.get()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered to the
                fut.set_exception(e)     # joining __call__, never lost

    def submit(self, fn):
        from concurrent.futures import Future
        fut = Future()
        self._q.put((fut, fn))
        return fut


def _compile_pool():
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = _DaemonPool()
        return _POOL


def _key_hash(key) -> str:
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


class StageProgram:
    """One cached jitted program.  Callable; measures its first dispatch
    (trace + compile + first execution) and emits a ``stageCompile``
    event so the profiler can attribute compilation separately from
    steady-state compute."""

    __slots__ = ("kind", "key_hash", "key_repr", "_fn", "_lock",
                 "_dispatched", "_warm_future", "_compiled", "_drifted")

    def __init__(self, kind: str, key, fn):
        self.kind = kind
        self.key_hash = _key_hash(key)
        #: key provenance for the audit ledger (bounded repr: enough to
        #: diagnose which component over-discriminates in a recompile
        #: storm, never the whole structure)
        self.key_repr = repr(key)[:KEY_REPR_MAX]
        self._fn = fn
        self._lock = threading.Lock()
        self._dispatched = False
        self._warm_future = None
        self._compiled = None
        self._drifted = False

    # -- async (AOT) path ----------------------------------------------------
    def needs_compile(self) -> bool:
        return not (self._dispatched or self._compiled is not None
                    or self._warm_future is not None)

    def compiling(self) -> bool:
        """True while a background compile is in flight (cleared when a
        ``__call__`` joins it)."""
        return self._warm_future is not None

    def warm_async(self, *args) -> bool:
        """Lower + compile off the critical path on the daemon pool.  The
        next ``__call__`` joins the in-flight future, so foreground work
        never duplicates the compile.  Returns True if a warm was
        scheduled."""
        with self._lock:
            if not self.needs_compile():
                return False

            def work():
                t0 = time.perf_counter()
                traced = self._fn.trace(*args)
                lowered = traced.lower()
                compiled = lowered.compile()
                dt = time.perf_counter() - t0
                self._note_compiled(dt, tier="aot")
                with _LOCK:
                    _STATS["async_compiles"] += 1
                _record_ledger(self, traced, lowered)
                return compiled

            # the pool's daemon threads carry no contextvars: run the
            # work inside a COPY of the caller's context (the spool
            # pattern) so the stageCompile/stageProgram events route to
            # the caller's query sinks — without it every async-built
            # program would silently vanish from the audit ledger
            import contextvars
            ctx = contextvars.copy_context()
            self._warm_future = _compile_pool().submit(
                lambda: ctx.run(work))
            return True

    def _note_compiled(self, dt: float, tier: str) -> None:
        with _LOCK:
            _STATS["compiles"] += 1
            _STATS["compile_s"] += dt
        from spark_rapids_tpu.aux.events import emit
        emit("stageCompile", stage_kind=self.kind, key=self.key_hash,
             duration_s=round(dt, 6), tier=tier,
             disk_cache=_DISK["dir"] is not None)

    # -- dispatch ------------------------------------------------------------
    def __call__(self, *args):
        fut = self._warm_future
        if fut is not None:
            try:
                compiled = fut.result()
            except Exception as e:  # noqa: BLE001 — AOT is an optimization;
                compiled = None      # the jit path below is always correct,
                # but a silently-failing async tier must be visible in
                # stats(), or async=true degrades to sync with no evidence
                with _LOCK:
                    _STATS["async_failures"] += 1
                    _ASYNC_ERROR[0] = f"{type(e).__name__}: {e}"[:160]
            with self._lock:
                self._warm_future = None
                if compiled is not None:
                    self._compiled = compiled
                    self._dispatched = True
                # on a failed background compile, first-dispatch stays
                # unclaimed: the fallback jit compile below must be timed
                # and counted like any cold compile, not happen invisibly
        if self._compiled is not None:
            try:
                return self._compiled(*args)
            except (TypeError, ValueError):
                # arg-signature drift only (an int row count where the
                # lowering saw a device scalar): route THIS call through
                # the jit dispatcher, which traces and caches the
                # variant.  The compiled executable is KEPT — exact-
                # signature calls stay on it, and dropping it would make
                # jit re-compile the original signature from scratch the
                # next time it recurs (one full wasted compile per
                # drifting program).  The first drift is timed and
                # counted like any cold compile so it can't leak into
                # steady-state metrics.  Genuine runtime errors (device
                # OOM...) must propagate to retry/arbitration, not
                # silently re-execute the program.
                t0 = time.perf_counter()
                out = self._fn(*args)
                with self._lock:
                    first_drift = not self._drifted
                    self._drifted = True
                if first_drift:
                    self._note_compiled(time.perf_counter() - t0,
                                        tier="jit")
                return out
        first = False
        if not self._dispatched:
            # claim first-dispatch under the lock: concurrent partitions
            # hitting a fresh program must produce ONE compile record
            with self._lock:
                if not self._dispatched:
                    self._dispatched = True
                    first = True
        if first:
            t0 = time.perf_counter()
            traced = lowered = compiled = None
            if _ledger_active():
                # first dispatch goes through the AOT pipeline so the
                # audit ledger sees the jaxpr + cost analysis of the
                # exact program being cached, with ONE trace (the same
                # count the jit dispatch would pay) and no duplicate
                # compile.  Any AOT-surface failure falls back to the
                # plain jit dispatch, which is always correct.
                try:
                    traced = self._fn.trace(*args)
                    lowered = traced.lower()
                    compiled = lowered.compile()
                except Exception:  # noqa: BLE001 — audit is best-effort
                    traced = lowered = compiled = None
                    with _LOCK:
                        _STATS["ledger_errors"] += 1
            if compiled is not None:
                self._compiled = compiled
                out = compiled(*args)
                self._note_compiled(time.perf_counter() - t0, tier="jit")
                _record_ledger(self, traced, lowered)
                return out
            out = self._fn(*args)
            self._note_compiled(time.perf_counter() - t0, tier="jit")
            return out
        return self._fn(*args)


def _counting(kind: str, fn: Callable) -> Callable:
    """Wraps a trace function so every ACTUAL jax trace (including
    signature-variant retraces inside one jit wrapper) counts."""
    def traced(*args, **kwargs):
        with _LOCK:
            _STATS["traces"] += 1
            _TRACES_BY_KIND[kind] = _TRACES_BY_KIND.get(kind, 0) + 1
        return fn(*args, **kwargs)
    traced.__name__ = getattr(fn, "__name__", "run") + f"[{kind}]"
    return traced


# ---------------------------------------------------------------------------
# audit ledger (schema v3 ``stageProgram`` rows; consumed by tools/audit)
# ---------------------------------------------------------------------------

#: memory addresses inside param reprs (callables, array views) would
#: make structural signatures unstable across processes
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _ledger_active() -> bool:
    """True when a recorded row would actually be STORED: the active
    query carries a durable (file) sink, or process-global sinks exist
    (out-of-query builds route there).  A query's ring buffer alone
    does not count — it is discarded at query end."""
    if not AUDIT_LEDGER:
        return False
    from spark_rapids_tpu.aux import events as EV
    q = EV.active_query()
    if q is not None:
        return bool(getattr(q, "_sinks", None))
    return bool(EV._GLOBAL_SINKS)


def _literal_cls():
    from jax.core import Literal
    return Literal


def _sub_jaxprs(val) -> List:
    """Open jaxprs nested inside an eqn param (pjit's ``jaxpr``, scan's
    branches...), whatever container they arrive in."""
    import jax
    if isinstance(val, jax.core.Jaxpr):
        return [val]
    if isinstance(val, jax.core.ClosedJaxpr):
        return [val.jaxpr]
    if isinstance(val, (tuple, list)):
        out = []
        for v in val:
            out.extend(_sub_jaxprs(v))
        return out
    return []


def _walk_eqns(jaxpr, exact: List, norm: List, prims: set) -> None:
    lit_cls = _literal_cls()
    for eqn in jaxpr.eqns:
        prims.add(eqn.primitive.name)
        ins_exact, ins_norm = [], []
        for v in eqn.invars:
            short = v.aval.str_short()
            if isinstance(v, lit_cls):
                # the exact signature keeps the baked value, the
                # normalized one keeps only its type: N keys collapsing
                # onto one normalized signature while their exact
                # signatures differ IS the missed-literal-promotion
                # storm the auditor hunts
                ins_exact.append(f"lit({v.val!r}):{short}")
                ins_norm.append(f"lit:{short}")
            else:
                ins_exact.append(short)
                ins_norm.append(short)
        params = []
        for k in sorted(eqn.params):
            val = eqn.params[k]
            subs = _sub_jaxprs(val)
            if subs:
                for sj in subs:
                    _walk_eqns(sj, exact, norm, prims)
                params.append((k, "<jaxpr>"))
            else:
                params.append((k, _ADDR_RE.sub("0x", repr(val))))
        rec = (eqn.primitive.name, tuple(params),
               tuple(o.aval.str_short() for o in eqn.outvars))
        exact.append((rec, tuple(ins_exact)))
        norm.append((rec, tuple(ins_norm)))


def jaxpr_signatures(jaxpr) -> Tuple[str, str, List[str], int]:
    """(struct_sig, norm_sig, primitives, eqn count) of an OPEN jaxpr.

    ``struct_sig`` hashes the full structure including inline literal
    VALUES; ``norm_sig`` replaces every literal value with its type, so
    programs differing only in baked scalars collapse onto one
    signature — the clustering key of the auditor's recompile-storm and
    baked-constant passes.  Const buffers never participate: constvars
    contribute only their avals."""
    exact: List = []
    norm: List = []
    prims: set = set()
    _walk_eqns(jaxpr, exact, norm, prims)
    frame = (tuple(v.aval.str_short() for v in jaxpr.invars),
             tuple(v.aval.str_short() for v in jaxpr.constvars),
             tuple(v.aval.str_short() for v in jaxpr.outvars))

    def h(parts) -> str:
        return hashlib.sha1(repr((frame, parts)).encode()).hexdigest()[:16]

    return h(exact), h(norm), sorted(prims), len(exact)


def _const_records(consts) -> List[Dict]:
    """Shape/dtype/nbytes + content fingerprint per jaxpr const.  The
    fingerprint is a hash of the VALUE (one bounded host read at build
    time) so the auditor can tell 'same table baked everywhere' from
    'a different table baked per key'; the buffer itself is read and
    immediately dropped — ledger rows hold primitives only."""
    import numpy as np
    out = []
    for c in consts:
        shape = tuple(getattr(c, "shape", ()))
        dtype = str(getattr(c, "dtype", type(c).__name__))
        nbytes = int(getattr(c, "nbytes", 0) or 0)
        if 0 < nbytes <= CONST_FP_MAX_BYTES:
            try:
                fp = hashlib.sha1(
                    np.asarray(c).tobytes()).hexdigest()[:16]
            except Exception:  # noqa: BLE001 — unreadable const: shape-only
                fp = "unreadable"
        else:
            fp = "large"
        out.append({"shape": list(shape), "dtype": dtype,
                    "nbytes": nbytes, "fp": fp})
    return out


def _record_ledger(prog: StageProgram, traced, lowered) -> None:
    """Emits the program's ``stageProgram`` audit row.  Never raises —
    a failed recording counts in ``ledger_errors`` (a blind audit spot
    must be visible in stats, not silent)."""
    if traced is None or not _ledger_active():
        return
    try:
        closed = traced.jaxpr
        struct_sig, norm_sig, prims, n_eqns = jaxpr_signatures(closed.jaxpr)
        in_avals = [v.aval for v in closed.jaxpr.invars]
        out_avals = [v.aval for v in closed.jaxpr.outvars]
        flops = bytes_accessed = None
        try:
            ca = lowered.cost_analysis()
            if isinstance(ca, dict):
                if ca.get("flops") is not None:
                    flops = float(ca["flops"])
                if ca.get("bytes accessed") is not None:
                    bytes_accessed = float(ca["bytes accessed"])
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            pass
        args_sig = [a.str_short() for a in in_avals]
        payload = {
            "stage_kind": prog.kind,
            "key": prog.key_hash,
            "key_repr": prog.key_repr,
            "struct_sig": struct_sig,
            "norm_sig": norm_sig,
            "primitives": prims,
            "eqns": n_eqns,
            "consts": _const_records(closed.consts),
            "n_args": len(args_sig),
            "args": args_sig[:64],
            "in_dtypes": sorted({str(getattr(a, "dtype", "?"))
                                 for a in in_avals}),
            "out_dtypes": sorted({str(getattr(a, "dtype", "?"))
                                  for a in out_avals}),
            "flops": flops,
            "bytes_accessed": bytes_accessed,
        }
        from spark_rapids_tpu.aux.events import emit
        emit("stageProgram", **payload)
        with _LOCK:
            _STATS["ledger_rows"] += 1
    except Exception:  # noqa: BLE001 — audit must never fail the query
        with _LOCK:
            _STATS["ledger_errors"] += 1


def get_or_build(kind: str, key: Tuple,
                 build: Callable[[], Callable]) -> StageProgram:
    """THE lookup every jit site uses.  ``build()`` runs only on a miss
    and returns the raw python trace function; this helper owns jitting,
    trace counting, LRU bounding and the program wrapper."""
    full_key = (kind, key)
    with _LOCK:
        prog = _PROGRAMS.get(full_key)
        if prog is not None:
            _STATS["hits"] += 1
            _PROGRAMS.move_to_end(full_key)
            return prog
        _STATS["misses"] += 1
    # build outside the lock: expression tree walks can be slow and must
    # not serialize unrelated task threads; a racing double-build is
    # harmless (the FIRST insert wins, the loser's wrapper is discarded,
    # both programs are correct)
    import jax
    prog = StageProgram(kind, full_key, jax.jit(_counting(kind, build())))
    with _LOCK:
        existing = _PROGRAMS.get(full_key)
        if existing is not None:
            # the race loser's lookup was really a hit: reclassify its
            # recorded miss so hits+misses stays equal to lookups
            _STATS["misses"] -= 1
            _STATS["hits"] += 1
            return existing
        _PROGRAMS[full_key] = prog
        while len(_PROGRAMS) > _MAX_PROGRAMS:
            _PROGRAMS.popitem(last=False)
            _STATS["evictions"] += 1
    return prog


# ---------------------------------------------------------------------------
# stats / maintenance
# ---------------------------------------------------------------------------

def stats() -> Dict:
    with _LOCK:
        out = dict(_STATS)
        out["programs"] = len(_PROGRAMS)
        out["max_programs"] = _MAX_PROGRAMS
        out["traces_by_kind"] = dict(_TRACES_BY_KIND)
        out["disk_cache_dir"] = _DISK["dir"]
        out["disk_cache_error"] = _DISK["error"]
        out["async_error"] = _ASYNC_ERROR[0]
        return out


def reset_stats() -> None:
    """Zeroes the counters (tests / bench phase boundaries); live
    programs stay cached."""
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "compile_s" else 0
        _TRACES_BY_KIND.clear()
        _ASYNC_ERROR[0] = None


def clear() -> None:
    """Drops every cached program (tests; also releases the compiled
    executables' device handles)."""
    with _LOCK:
        _PROGRAMS.clear()


def set_max_programs(n: int) -> None:
    global _MAX_PROGRAMS
    with _LOCK:
        _MAX_PROGRAMS = max(1, int(n))
        while len(_PROGRAMS) > _MAX_PROGRAMS:
            _PROGRAMS.popitem(last=False)
            _STATS["evictions"] += 1


def set_persistent_cache_dir(path: Optional[str]) -> None:
    """Tier 2: point JAX's persistent compilation cache at ``path`` so
    compiled executables survive across queries AND sessions (conf
    ``spark.rapids.sql.compile.cacheDir``).  Thresholds drop to zero so
    every stage program persists — on a tunnel-attached TPU even small
    programs cost a round trip to rebuild.  Empty/None disables."""
    path = (path or "").strip() or None
    if path == _DISK["dir"]:
        return
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        if path is not None:
            for k, v in (("jax_persistent_cache_min_compile_time_secs", 0),
                         ("jax_persistent_cache_min_entry_size_bytes", -1)):
                try:
                    jax.config.update(k, v)
                except (AttributeError, ValueError):
                    pass    # older jax: keep its defaults
        _DISK["dir"] = path
        _DISK["error"] = None
    except Exception as e:  # noqa: BLE001 — the disk tier is optional;
        # a bad dir must not fail the query path
        _DISK["error"] = f"{type(e).__name__}: {e}"[:160]
