"""StageCompiler: the process-wide executable cache behind every jitted
stage program.

The engine's end-to-end deficit lives in the query path around the
kernels, not in the kernels (ROADMAP item 1): per-operator dispatch and —
worse — re-tracing/re-compiling programs the process has already built.
Every jitted stage program (fused filter/project/agg chains, join
build/probe/pair phases, sort permutations, window frames, transfer
pack/unpack...) is obtained through ONE helper here, keyed by its
(op-signature, batch schema, row bucket) and backed by a two-tier cache:

- **tier 1 — process executable cache**: a bounded LRU of jitted
  callables with hit/miss/evict/trace counters.  The python trace
  function of every program is wrapped with a trace counter, so "the
  second run of an identical query performs zero new traces" is an
  assertable fact, not a hope.
- **tier 2 — JAX persistent compilation cache** (conf
  ``spark.rapids.sql.compile.cacheDir``): compiled XLA executables
  survive process restarts; a cold process re-traces (cheap) but loads
  machine code from disk instead of re-compiling (expensive — tens of
  seconds per program on a tunnel-attached TPU).

Optional background compilation (conf ``spark.rapids.sql.compile.async``):
``warm_async`` lowers + compiles a program on a daemon pool thread while
the caller overlaps other work (the fused stage exec runs a one-batch
look-ahead so a new program's compile overlaps the previous batch's
compute), mirroring the PR-4 pipeline's producer/consumer overlap at the
compiler layer.

Reference analog: the reference pays JIT cost in cuDF kernel launches and
avoids it via pre-built kernels; a tracing-compiler engine must instead
manage program identity explicitly — this module is that manager.
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Callable, Dict, Optional, Tuple

__all__ = ["get_or_build", "stats", "reset_stats", "clear",
           "set_max_programs", "set_persistent_cache_dir", "StageProgram"]

#: synced from ``spark.rapids.sql.compile.async`` by the planner
ASYNC_COMPILE = False

_LOCK = threading.RLock()
_PROGRAMS: "collections.OrderedDict[Tuple, StageProgram]" = \
    collections.OrderedDict()
_MAX_PROGRAMS = 4096

_STATS = {
    "hits": 0,          # tier-1 lookups that found a live program
    "misses": 0,        # lookups that had to build a new program
    "evictions": 0,     # programs dropped by the LRU bound
    "traces": 0,        # python trace-function executions (per jax trace)
    "compiles": 0,      # first dispatches that built a new executable
    "async_compiles": 0,  # programs compiled on the background pool
    "async_failures": 0,  # background compiles that raised (jit fallback)
    "compile_s": 0.0,   # seconds spent in first-dispatch trace+compile
}
#: last background-compile error (stats(); None = healthy)
_ASYNC_ERROR = [None]
_TRACES_BY_KIND: Dict[str, int] = {}

#: persistent (tier-2) cache state; dir None = disabled
_DISK = {"dir": None, "error": None}

_POOL = None
_POOL_LOCK = threading.Lock()


class _DaemonPool:
    """Two daemon worker threads + a queue.  NOT a ThreadPoolExecutor:
    since 3.9 its (non-daemon) workers are joined at interpreter exit, so
    an in-flight XLA compile — tens of seconds, or forever on a dead TPU
    tunnel — would block shutdown.  A background compile is disposable;
    daemon threads let the process exit mid-compile."""

    def __init__(self, workers: int = 2):
        import queue
        self._q = queue.Queue()
        for i in range(workers):
            threading.Thread(target=self._loop, daemon=True,
                             name=f"tpu-compile-{i}").start()

    def _loop(self):
        while True:
            fut, fn = self._q.get()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered to the
                fut.set_exception(e)     # joining __call__, never lost

    def submit(self, fn):
        from concurrent.futures import Future
        fut = Future()
        self._q.put((fut, fn))
        return fut


def _compile_pool():
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = _DaemonPool()
        return _POOL


def _key_hash(key) -> str:
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


class StageProgram:
    """One cached jitted program.  Callable; measures its first dispatch
    (trace + compile + first execution) and emits a ``stageCompile``
    event so the profiler can attribute compilation separately from
    steady-state compute."""

    __slots__ = ("kind", "key_hash", "_fn", "_lock", "_dispatched",
                 "_warm_future", "_compiled")

    def __init__(self, kind: str, key, fn):
        self.kind = kind
        self.key_hash = _key_hash(key)
        self._fn = fn
        self._lock = threading.Lock()
        self._dispatched = False
        self._warm_future = None
        self._compiled = None

    # -- async (AOT) path ----------------------------------------------------
    def needs_compile(self) -> bool:
        return not (self._dispatched or self._compiled is not None
                    or self._warm_future is not None)

    def compiling(self) -> bool:
        """True while a background compile is in flight (cleared when a
        ``__call__`` joins it)."""
        return self._warm_future is not None

    def warm_async(self, *args) -> bool:
        """Lower + compile off the critical path on the daemon pool.  The
        next ``__call__`` joins the in-flight future, so foreground work
        never duplicates the compile.  Returns True if a warm was
        scheduled."""
        with self._lock:
            if not self.needs_compile():
                return False

            def work():
                t0 = time.perf_counter()
                compiled = self._fn.lower(*args).compile()
                dt = time.perf_counter() - t0
                self._note_compiled(dt, tier="aot")
                with _LOCK:
                    _STATS["async_compiles"] += 1
                return compiled

            self._warm_future = _compile_pool().submit(work)
            return True

    def _note_compiled(self, dt: float, tier: str) -> None:
        with _LOCK:
            _STATS["compiles"] += 1
            _STATS["compile_s"] += dt
        from spark_rapids_tpu.aux.events import emit
        emit("stageCompile", stage_kind=self.kind, key=self.key_hash,
             duration_s=round(dt, 6), tier=tier,
             disk_cache=_DISK["dir"] is not None)

    # -- dispatch ------------------------------------------------------------
    def __call__(self, *args):
        fut = self._warm_future
        if fut is not None:
            try:
                compiled = fut.result()
            except Exception as e:  # noqa: BLE001 — AOT is an optimization;
                compiled = None      # the jit path below is always correct,
                # but a silently-failing async tier must be visible in
                # stats(), or async=true degrades to sync with no evidence
                with _LOCK:
                    _STATS["async_failures"] += 1
                    _ASYNC_ERROR[0] = f"{type(e).__name__}: {e}"[:160]
            with self._lock:
                self._warm_future = None
                if compiled is not None:
                    self._compiled = compiled
                    self._dispatched = True
                # on a failed background compile, first-dispatch stays
                # unclaimed: the fallback jit compile below must be timed
                # and counted like any cold compile, not happen invisibly
        if self._compiled is not None:
            try:
                return self._compiled(*args)
            except (TypeError, ValueError):
                # arg-signature drift only (an int row count where the
                # lowering saw a device scalar): fall back to the jit
                # dispatcher, which traces a variant — timed and counted
                # like any cold compile so it can't leak into steady-
                # state metrics.  Genuine runtime errors (device OOM...)
                # must propagate to retry/arbitration, not silently
                # re-execute the program.
                self._compiled = None
                t0 = time.perf_counter()
                out = self._fn(*args)
                self._note_compiled(time.perf_counter() - t0, tier="jit")
                return out
        first = False
        if not self._dispatched:
            # claim first-dispatch under the lock: concurrent partitions
            # hitting a fresh program must produce ONE compile record
            with self._lock:
                if not self._dispatched:
                    self._dispatched = True
                    first = True
        if first:
            t0 = time.perf_counter()
            out = self._fn(*args)
            self._note_compiled(time.perf_counter() - t0, tier="jit")
            return out
        return self._fn(*args)


def _counting(kind: str, fn: Callable) -> Callable:
    """Wraps a trace function so every ACTUAL jax trace (including
    signature-variant retraces inside one jit wrapper) counts."""
    def traced(*args, **kwargs):
        with _LOCK:
            _STATS["traces"] += 1
            _TRACES_BY_KIND[kind] = _TRACES_BY_KIND.get(kind, 0) + 1
        return fn(*args, **kwargs)
    traced.__name__ = getattr(fn, "__name__", "run") + f"[{kind}]"
    return traced


def get_or_build(kind: str, key: Tuple,
                 build: Callable[[], Callable]) -> StageProgram:
    """THE lookup every jit site uses.  ``build()`` runs only on a miss
    and returns the raw python trace function; this helper owns jitting,
    trace counting, LRU bounding and the program wrapper."""
    full_key = (kind, key)
    with _LOCK:
        prog = _PROGRAMS.get(full_key)
        if prog is not None:
            _STATS["hits"] += 1
            _PROGRAMS.move_to_end(full_key)
            return prog
        _STATS["misses"] += 1
    # build outside the lock: expression tree walks can be slow and must
    # not serialize unrelated task threads; a racing double-build is
    # harmless (the FIRST insert wins, the loser's wrapper is discarded,
    # both programs are correct)
    import jax
    prog = StageProgram(kind, full_key, jax.jit(_counting(kind, build())))
    with _LOCK:
        existing = _PROGRAMS.get(full_key)
        if existing is not None:
            # the race loser's lookup was really a hit: reclassify its
            # recorded miss so hits+misses stays equal to lookups
            _STATS["misses"] -= 1
            _STATS["hits"] += 1
            return existing
        _PROGRAMS[full_key] = prog
        while len(_PROGRAMS) > _MAX_PROGRAMS:
            _PROGRAMS.popitem(last=False)
            _STATS["evictions"] += 1
    return prog


# ---------------------------------------------------------------------------
# stats / maintenance
# ---------------------------------------------------------------------------

def stats() -> Dict:
    with _LOCK:
        out = dict(_STATS)
        out["programs"] = len(_PROGRAMS)
        out["max_programs"] = _MAX_PROGRAMS
        out["traces_by_kind"] = dict(_TRACES_BY_KIND)
        out["disk_cache_dir"] = _DISK["dir"]
        out["disk_cache_error"] = _DISK["error"]
        out["async_error"] = _ASYNC_ERROR[0]
        return out


def reset_stats() -> None:
    """Zeroes the counters (tests / bench phase boundaries); live
    programs stay cached."""
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "compile_s" else 0
        _TRACES_BY_KIND.clear()
        _ASYNC_ERROR[0] = None


def clear() -> None:
    """Drops every cached program (tests; also releases the compiled
    executables' device handles)."""
    with _LOCK:
        _PROGRAMS.clear()


def set_max_programs(n: int) -> None:
    global _MAX_PROGRAMS
    with _LOCK:
        _MAX_PROGRAMS = max(1, int(n))
        while len(_PROGRAMS) > _MAX_PROGRAMS:
            _PROGRAMS.popitem(last=False)
            _STATS["evictions"] += 1


def set_persistent_cache_dir(path: Optional[str]) -> None:
    """Tier 2: point JAX's persistent compilation cache at ``path`` so
    compiled executables survive across queries AND sessions (conf
    ``spark.rapids.sql.compile.cacheDir``).  Thresholds drop to zero so
    every stage program persists — on a tunnel-attached TPU even small
    programs cost a round trip to rebuild.  Empty/None disables."""
    path = (path or "").strip() or None
    if path == _DISK["dir"]:
        return
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        if path is not None:
            for k, v in (("jax_persistent_cache_min_compile_time_secs", 0),
                         ("jax_persistent_cache_min_entry_size_bytes", -1)):
                try:
                    jax.config.update(k, v)
                except (AttributeError, ValueError):
                    pass    # older jax: keep its defaults
        _DISK["dir"] = path
        _DISK["error"] = None
    except Exception as e:  # noqa: BLE001 — the disk tier is optional;
        # a bad dir must not fail the query path
        _DISK["error"] = f"{type(e).__name__}: {e}"[:160]
