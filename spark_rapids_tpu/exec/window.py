"""Window exec: ranking, offset, and frame aggregations over partitions.

Reference: window/GpuWindowExec.scala + GpuWindowExecMeta (673) pick among
batched running / double-pass / bounded algorithms; GpuWindowExpression.scala
lowers frames to cuDF rolling/scan aggs.  Our device path fuses the whole
spec group into ONE XLA program (sort + boundaries + every window column,
ops/window_ops.py); the CPU path is a deliberately-simple python oracle
(sort with a comparator, per-group loops) for differential testing.

Contract (like Spark's WindowExec): the child is hash-partitioned by the
partition keys (the session layer inserts the exchange) and this exec
concatenates each partition to one batch before computing.  Output rows are
in (partition, order) sorted order.
"""

from __future__ import annotations

import functools
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.window_exprs import (Lag, Lead, NTile,
                                                       DenseRank, Rank,
                                                       RowNumber,
                                                       WindowExpression)
from spark_rapids_tpu.ops.window_ops import MAX_UNROLLED_FRAME
from spark_rapids_tpu.plan.base import Exec, UnaryExec, closing_source


class LoweredWindow:
    """One window output column lowered to a kernel func spec.

    ``func`` holds a placeholder -1 where the value ordinal goes (filled in
    by the exec once input columns are laid out)."""

    def __init__(self, func: Tuple, inputs: List[Expression],
                 dtype: T.DataType):
        self.func = func
        self.inputs = inputs
        self.dtype = dtype


def lower_window_expr(wexpr: WindowExpression) -> LoweredWindow:
    from spark_rapids_tpu.expressions import aggregates as AG
    from spark_rapids_tpu.expressions.cast import Cast
    f = wexpr.function
    if isinstance(f, RowNumber):
        return LoweredWindow(("row_number",), [], T.INT)
    if isinstance(f, Rank):
        return LoweredWindow(("rank",), [], T.INT)
    if isinstance(f, DenseRank):
        return LoweredWindow(("dense_rank",), [], T.INT)
    if isinstance(f, NTile):
        return LoweredWindow(("ntile", f.n), [], T.INT)
    if isinstance(f, (Lag, Lead)):
        from spark_rapids_tpu.expressions.base import Literal
        off = f.offset * f.direction
        dflt = None
        if f.default is not None:
            if not isinstance(f.default, Literal) or isinstance(
                    f.children[0].data_type, (T.StringType, T.BinaryType)):
                raise NotImplementedError(
                    "lag/lead default must be a scalar literal over a "
                    "non-string column")
            if str(f.default.data_type) != str(f.children[0].data_type):
                raise TypeError(
                    f"lag/lead default type {f.default.data_type} does not "
                    f"match column type {f.children[0].data_type}; cast "
                    "the default explicitly")
            dflt = f.default.value
        return LoweredWindow(("offset", -1, off, dflt), [f.children[0]],
                             f.data_type)
    if isinstance(f, AG.AggregateFunction):
        frame = wexpr.spec.effective_frame()
        lo = None if frame.lo_unbounded else int(frame.lo)
        hi = None if frame.hi_unbounded else int(frame.hi)
        fk = frame.kind
        if fk == "range" and not (lo is None and hi in (None, 0)):
            raise NotImplementedError(
                "bounded RANGE frames are not supported; use ROWS BETWEEN "
                "(Spark's value-based RANGE frames need a single numeric "
                "order key)")
        child = f.children[0] if f.children else None
        if isinstance(f, AG.Sum):
            dt = f.data_type
            return LoweredWindow(("agg", "sum", -1, fk, lo, hi, True),
                                 [Cast(child, dt)], dt)
        if isinstance(f, AG.Count):
            from spark_rapids_tpu.expressions.base import Literal
            count_all = isinstance(child, Literal) and \
                child.value is not None        # count(*) counts every row
            return LoweredWindow(("agg", "count", -1, fk, lo, hi,
                                  not count_all), [child], T.LONG)
        if isinstance(f, AG.Average):
            return LoweredWindow(("agg", "mean", -1, fk, lo, hi, True),
                                 [Cast(child, T.DOUBLE)], T.DOUBLE)
        if isinstance(f, AG.Min):
            return LoweredWindow(("agg", "min", -1, fk, lo, hi, True),
                                 [child], f.data_type)
        if isinstance(f, AG.Max):
            return LoweredWindow(("agg", "max", -1, fk, lo, hi, True),
                                 [child], f.data_type)
    raise NotImplementedError(f"window function {f!r}")


def device_unsupported_reason(wexpr: WindowExpression) -> Optional[str]:
    """Why this window expression cannot run on device (meta tagging;
    reference: GpuWindowExpressionMeta.tagExprForGpu)."""
    try:
        low = lower_window_expr(wexpr)
    except NotImplementedError as e:
        return str(e)
    if low.func[0] != "agg":
        return None
    _, agg, _, fk, lo, hi, _ = low.func
    if agg in ("min", "max"):
        if low.inputs and isinstance(low.inputs[0].data_type,
                                     (T.StringType, T.BinaryType,
                                      T.BooleanType)):
            return "string/boolean min/max window frames not on device yet"
        if lo is not None and hi is not None and \
                (hi - lo + 1) > MAX_UNROLLED_FRAME:
            return (f"bounded min/max frame wider than "
                    f"{MAX_UNROLLED_FRAME} rows")
        if lo is not None and hi is None:
            if lo != 0:
                return "min/max over (N preceding/following, unbounded)"
        if lo is None and hi is not None and hi != 0:
            return "min/max over (unbounded, N following)"
    return None


class CpuWindowExec(UnaryExec):
    """window_cols: [(output_name, WindowExpression)] sharing one
    partition/order spec; appends one column per entry."""

    def __init__(self, window_cols: List[Tuple[str, WindowExpression]],
                 child: Exec):
        super().__init__(child)
        self.window_cols = list(window_cols)
        self.spec = window_cols[0][1].spec
        self.lowered = [lower_window_expr(w) for _, w in window_cols]

    @property
    def schema(self) -> T.StructType:
        fields = list(self.child.schema.fields)
        for (name, w), low in zip(self.window_cols, self.lowered):
            fields.append(T.StructField(name, low.dtype, True))
        return T.StructType(fields)

    def node_desc(self):
        cols = ", ".join(w.sql() for _, w in self.window_cols)
        return f"{self.name}[{cols}]"

    # -- CPU oracle ---------------------------------------------------------
    def execute_partition(self, pidx):
        from spark_rapids_tpu.columnar.batch import batch_from_pydict
        from spark_rapids_tpu.exec.joins import _concat_or_empty
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_cpu
        batch = _concat_or_empty(list(self.child.execute_partition(pidx)),
                                 self.child.schema)
        if batch.row_count == 0:
            return
        n = batch.row_count
        pvals = self._col_lists(eval_exprs_cpu(
            self.spec.partition_exprs, batch,
            [f"p{i}" for i in range(len(self.spec.partition_exprs))]))
        ovals = self._col_lists(eval_exprs_cpu(
            [e for e, _, _ in self.spec.order_specs], batch,
            [f"o{i}" for i in range(len(self.spec.order_specs))]))
        # sort rows: partition keys (any order groups them), then order keys
        dirs = [(a, nf) for _, a, nf in self.spec.order_specs]
        idx = sorted(range(n), key=functools.cmp_to_key(
            lambda i, j: self._cmp(pvals, ovals, dirs, i, j)))
        # group boundaries
        groups: List[List[int]] = []
        for k, i in enumerate(idx):
            if k == 0 or any(
                    self._cmp_val(c[i], c[idx[k - 1]], True, True) != 0
                    for c in pvals):       # NaN == NaN, like the device
                groups.append([])
            groups[-1].append(i)
        # evaluate inputs per lowered func
        in_cols = []
        for low in self.lowered:
            vals = self._col_lists(eval_exprs_cpu(
                low.inputs, batch,
                [f"v{i}" for i in range(len(low.inputs))])) \
                if low.inputs else []
            in_cols.append(vals[0] if vals else None)
        outs: List[List] = [[None] * n for _ in self.lowered]
        for g in groups:
            okeys = [[c[i] for c in ovals] for i in g]
            for li, low in enumerate(self.lowered):
                self._cpu_one(low, g, okeys, in_cols[li], outs[li], dirs)
        # assemble: payload rows in sorted order + window cols
        import pyarrow as pa
        tab = pa.Table.from_arrays(
            [c.arrow for c in batch.columns],
            names=[f"c{i}" for i in range(batch.num_columns)])
        taken = tab.take(pa.array(np.asarray(idx, dtype=np.int64)))
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        payload = batch_from_arrow(taken)
        cols = list(payload.columns)
        names = list(batch.names or payload.names)
        for (name, _), low, out in zip(self.window_cols, self.lowered,
                                       outs):
            ordered = [out[i] for i in idx]
            from spark_rapids_tpu.columnar.column import HostColumn
            cols.append(HostColumn(pa.array(
                ordered, type=T.to_arrow(low.dtype)), low.dtype))
            names.append(name)
        yield HostColumnarBatch(cols, n, names)

    @staticmethod
    def _col_lists(hb: HostColumnarBatch) -> List[List]:
        return [c.to_pylist() for c in hb.columns]

    @staticmethod
    def _cmp_val(a, b, ascending, nulls_first):
        if a is None or b is None:
            if a is None and b is None:
                return 0
            first = -1 if nulls_first else 1
            return first if a is None else -first
        an = isinstance(a, float) and math.isnan(a)
        bn = isinstance(b, float) and math.isnan(b)
        if an or bn:                 # Spark: NaN sorts greatest
            c = 0 if an and bn else (1 if an else -1)
        else:
            c = 0 if a == b else (1 if a > b else -1)
        return c if ascending else -c

    @classmethod
    def _peers(cls, okeys, i, j, dirs):
        """Order-key equality with Spark semantics (NaN == NaN)."""
        return all(cls._cmp_val(a, b, asc, nf) == 0
                   for a, b, (asc, nf) in zip(okeys[i], okeys[j], dirs))

    def _cmp(self, pvals, ovals, dirs, i, j):
        for c in pvals:
            r = self._cmp_val(c[i], c[j], True, True)
            if r:
                return r
        for c, (a, nf) in zip(ovals, dirs):
            r = self._cmp_val(c[i], c[j], a, nf)
            if r:
                return r
        return 0

    def _cpu_one(self, low: LoweredWindow, g: List[int], okeys, vals, out,
                 dirs):
        kind = low.func[0]
        cnt = len(g)
        if kind == "row_number":
            for k, i in enumerate(g):
                out[i] = k + 1
            return
        if kind in ("rank", "dense_rank"):
            rank = drank = 0
            for k, i in enumerate(g):
                if k == 0 or not self._peers(okeys, k, k - 1, dirs):
                    rank = k + 1
                    drank += 1
                out[i] = rank if kind == "rank" else drank
            return
        if kind == "ntile":
            ntiles = low.func[1]
            base, rem = cnt // ntiles, cnt % ntiles
            pos = 0
            for t in range(ntiles):
                size = base + (1 if t < rem else 0)
                for _ in range(size):
                    if pos < cnt:
                        out[g[pos]] = t + 1
                        pos += 1
            return
        if kind == "offset":
            off, dflt = low.func[2], low.func[3]
            for k, i in enumerate(g):
                j = k + off
                out[i] = vals[g[j]] if 0 <= j < cnt else dflt
            return
        _, agg, _, fk, lo, hi, cvo = low.func
        # peer-group end (RANGE frames include peers of the current row):
        # scan backward keeping the end of each equal-okey run
        peer_end = [0] * cnt
        k = cnt - 1
        while k >= 0:
            j = k
            while j > 0 and self._peers(okeys, j - 1, k, dirs):
                j -= 1
            for m in range(j, k + 1):
                peer_end[m] = k
            k = j - 1
        for k, i in enumerate(g):
            if fk == "range":
                a = 0 if lo is None else None
                b = peer_end[k] if hi == 0 else cnt - 1 if hi is None \
                    else None
            else:
                a = 0 if lo is None else max(0, k + lo)
                b = cnt - 1 if hi is None else min(cnt - 1, k + hi)
            window = [vals[g[m]] for m in range(a, b + 1)] if a <= b else []
            if agg == "count":
                out[i] = len(window) if not cvo else \
                    len([v for v in window if v is not None])
                continue
            wv = [v for v in window if v is not None]
            if not wv:
                out[i] = None
                continue
            if agg == "sum":
                out[i] = type(wv[0])(np.sum(np.asarray(wv)).item()) \
                    if not isinstance(wv[0], float) else float(np.sum(wv))
            elif agg == "mean":
                out[i] = float(np.sum(wv) / len(wv))
            elif agg in ("min", "max"):
                # Spark NaN-greatest: min skips NaN unless all-NaN; max is
                # NaN when any NaN present (python min/max would propagate
                # NaN position-dependently)
                nan = [v for v in wv
                       if isinstance(v, float) and math.isnan(v)]
                real = [v for v in wv
                        if not (isinstance(v, float) and math.isnan(v))]
                if agg == "min":
                    out[i] = min(real) if real else float("nan")
                else:
                    out[i] = float("nan") if nan else max(wv)


#: test hook: force the batched running-window path
FORCE_RUNNING_WINDOW = False
#: observability: bumped once per running-window (batched) pass
RUNNING_WINDOW_EVENTS = 0

FORCE_BOUNDED_WINDOW = False
#: observability: bumped once per bounded-window (batched) pass
BOUNDED_WINDOW_EVENTS = 0

#: largest preceding+following row span the batched bounded path carries
#: between chunks; wider frames concat the whole partition
BOUNDED_WINDOW_MAX_SPAN = 4096


def _bounded_span(lowered: List[LoweredWindow]):
    """(max_preceding, max_following) when every window column is a
    fixed-bound ROWS-frame aggregate or a lag/lead — the shapes whose
    chunked evaluation needs only a (P+F)-row tail carried between
    batches (reference: GpuBatchedBoundedWindowExec.scala).  None when
    any column needs more (running/rank shapes go through
    _running_windows; everything else concats the partition)."""
    P = F = 0
    for low in lowered:
        k = low.func[0]
        if k == "offset":
            off = low.func[2]
            P = max(P, max(0, -off))
            F = max(F, max(0, off))
            continue
        if k == "agg":
            _, _agg, _, fk, lo, hi, _cvo = low.func
            if fk == "rows" and lo is not None and hi is not None:
                P = max(P, max(0, -lo))
                F = max(F, max(0, hi))
                continue
        return None
    if P + F == 0 or P + F > BOUNDED_WINDOW_MAX_SPAN:
        return None
    return P, F


def _running_eligible(lowered: List[LoweredWindow]) -> bool:
    """True when every window column is a running computation over
    (UNBOUNDED PRECEDING, CURRENT ROW) ROWS frames or a rank-family
    function — the shapes whose state is a fixed-size carry (reference:
    GpuRunningWindowExec.scala:220 isRunningWindow)."""
    for low in lowered:
        k = low.func[0]
        if k in ("row_number", "rank", "dense_rank"):
            continue
        if k == "agg":
            _, agg, _, fk, lo, hi, _cvo = low.func
            if agg in ("sum", "count", "min", "max") and fk == "rows" and \
                    lo is None and hi == 0:
                continue
        return False
    return True


class _HandoffBatchesScan(Exec):
    """Feeds already-produced device batches to a wrapping exec,
    DESTRUCTIVELY: each yielded batch is dropped from the list, so once
    the consumer has registered it (TpuSortExec wraps every input batch
    spillable immediately), this scan no longer pins it — the catalog
    can spill the whole input under pressure."""

    is_device = True

    def __init__(self, batches: List[ColumnarBatch], schema: T.StructType):
        super().__init__([])
        self._batches = batches
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def execute_partition(self, pidx):
        while self._batches:
            yield self._batches.pop(0)


class TpuWindowExec(CpuWindowExec):
    is_device = True

    def _funcs_with_ordinals(self, np_: int):
        pkeys = self.spec.partition_exprs
        okeys = [e for e, _, _ in self.spec.order_specs]
        extra = list(pkeys) + list(okeys)
        val_base = np_ + len(extra)
        funcs = []
        next_val = val_base
        for low in self.lowered:
            f = list(low.func)
            if low.inputs:
                f[f.index(-1)] = next_val
                next_val += len(low.inputs)
            funcs.append(tuple(f))
        return funcs, extra

    def _window_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Fused sort + window over ONE batch (the whole-partition path)."""
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        from spark_rapids_tpu.ops.window_ops import compute_windows
        np_ = batch.num_columns
        pkeys = self.spec.partition_exprs
        funcs, extra = self._funcs_with_ordinals(np_)
        all_inputs = [x for low in self.lowered for x in low.inputs]
        aug_cols = list(batch.columns)
        if extra or all_inputs:
            kb = eval_exprs_tpu(extra + all_inputs, batch)
            aug_cols += list(kb.columns)
        aug = ColumnarBatch(aug_cols, batch.row_count)
        order_specs = [(np_ + len(pkeys) + i, a, nf)
                       for i, (_, a, nf) in
                       enumerate(self.spec.order_specs)]
        out = compute_windows(aug, np_, len(pkeys), order_specs, funcs,
                              [low.dtype for low in self.lowered])
        out.names = list(batch.names or
                         [f.name for f in self.child.schema.fields]) + \
            [name for name, _ in self.window_cols]
        return out

    def _batch_budget(self):
        from spark_rapids_tpu.memory.device_manager import \
            free_device_headroom
        return free_device_headroom(4)

    def execute_partition(self, pidx):
        from spark_rapids_tpu.ops.batch_ops import concat_batches
        batches = [b for b in self.child.execute_partition(pidx)
                   if b.row_count]
        if not batches:
            return
        if _running_eligible(self.lowered):
            # even ONE oversized batch profits: the sort stage below
            # chunks its output under the same pressure, and the carry
            # then bounds this exec to one chunk at a time
            budget = self._batch_budget()
            est = sum(b.nbytes() for b in batches)
            if FORCE_RUNNING_WINDOW or (budget is not None and
                                        est > budget):
                yield from self._running_windows(batches)
                batches = None   # handed off — nothing pinned here
                return
        span = _bounded_span(self.lowered)
        if span is not None:
            budget = self._batch_budget()
            est = sum(b.nbytes() for b in batches)
            if FORCE_BOUNDED_WINDOW or (budget is not None and
                                        est > budget):
                yield from self._bounded_windows(batches, *span)
                batches = None
                return
        yield self._window_one(concat_batches(batches))

    def _running_windows(self, batches: List[ColumnarBatch]):
        """Batched running windows (reference: GpuRunningWindowExec.scala:220
        GpuRunningWindowIterator — fixed-size carry state across batches).

        The input is first globally sorted by (partition keys, order keys)
        through TpuSortExec — whose own out-of-core path bounds device
        residency — then each sorted batch runs the fused per-batch window
        kernel and a carry fix-up: rows continuing the previous batch's
        last partition get their running aggregates/ranks shifted by the
        carried state, and the state advances from the batch's last row.
        The full partition is never resident at once.
        """
        global RUNNING_WINDOW_EVENTS
        RUNNING_WINDOW_EVENTS += 1
        from spark_rapids_tpu.exec.sort import SortSpec, TpuSortExec
        scan = _HandoffBatchesScan(batches, self.child.schema)
        specs = [SortSpec(e, True, True) for e in self.spec.partition_exprs]
        specs += [SortSpec(e, a, nf if nf is not None else None)
                  for e, a, nf in self.spec.order_specs]
        sorter = TpuSortExec(specs, scan)
        carry = None
        with closing_source(sorter.execute_partition(0)) as it:
            for sorted_batch in it:
                out = self._window_one(sorted_batch)
                out, carry = self._apply_carry(out, carry)
                yield out

    def _bounded_windows(self, batches: List[ColumnarBatch], P: int,
                         F: int):
        """Chunked fixed-bound ROWS frames (reference:
        GpuBatchedBoundedWindowExec.scala — carry max(preceding) rows of
        context plus the last max(following) rows whose frames were
        incomplete, instead of concatenating the partition).

        Overlap re-computation scheme: each sorted chunk is prepended with
        the previous chunk's (P+F)-row tail, the fused per-batch window
        kernel runs over the combined batch (partition-segmented, so
        context rows from an earlier partition never pollute), and only
        rows whose frames lie fully inside the combined batch are emitted:
        positions [carried - held, rc - F).  The final chunk's trailing
        rows are complete by definition and flush at stream end.  All
        cursors are device scalars — no per-chunk host sync."""
        global BOUNDED_WINDOW_EVENTS
        BOUNDED_WINDOW_EVENTS += 1
        from spark_rapids_tpu.columnar.column import (DeferredCount,
                                                      bucket_rows, _jnp,
                                                      rc_traceable)
        from spark_rapids_tpu.exec.sort import SortSpec, TpuSortExec
        from spark_rapids_tpu.ops.batch_ops import (compact_batch,
                                                    concat_batches,
                                                    gather_batch)
        jnp = _jnp()
        span = P + F
        scan = _HandoffBatchesScan(batches, self.child.schema)
        specs = [SortSpec(e, True, True) for e in self.spec.partition_exprs]
        specs += [SortSpec(e, a, nf if nf is not None else None)
                  for e, a, nf in self.spec.order_specs]
        sorter = TpuSortExec(specs, scan)
        carry = None          # (P+F)-row tail batch of the prev combined
        skip_t = None         # device scalar: rows of carry already emitted
        last = None           # (windowed combined, rc_t, skip_t) to flush
        with closing_source(sorter.execute_partition(0)) as it:
            for sb in it:
                combined = sb if carry is None else concat_batches([carry, sb])
                out = self._window_one(combined)
                rc_t = jnp.asarray(rc_traceable(out.row_count), dtype=np.int64)
                skip = jnp.zeros((), np.int64) if skip_t is None else skip_t
                pos = jnp.arange(out.bucket, dtype=np.int64)
                emit_hi = jnp.maximum(rc_t - F, skip)
                emitted = compact_batch(out, (pos >= skip) & (pos < emit_hi))
                emitted.names = out.names
                yield emitted
                # tail for the next chunk: last min(rc, span) rows of combined
                carried_t = jnp.minimum(rc_t, span)
                idx = jnp.maximum(rc_t - span, 0) + \
                    jnp.arange(bucket_rows(span), dtype=np.int64)
                carry = gather_batch(
                    combined, jnp.minimum(idx, jnp.maximum(rc_t - 1, 0)),
                    DeferredCount(carried_t))
                carry.names = combined.names
                # of the carried rows, the last min(F, rc) were NOT emitted
                skip_t = carried_t - jnp.minimum(jnp.asarray(F, np.int64),
                                                 rc_t - skip)
                last = (out, rc_t, emit_hi)
        if last is not None:
            out, rc_t, emit_hi = last
            # flush: the final chunk's trailing rows' frames are complete
            pos = jnp.arange(out.bucket, dtype=np.int64)
            tail = compact_batch(out, (pos >= emit_hi) & (pos < rc_t))
            tail.names = out.names
            yield tail

    def _apply_carry(self, out: ColumnarBatch, carry):
        """Adjusts the leading rows of ``out`` (those continuing the
        previous batch's last partition group) by the carried running
        state, and extracts the new carry from the last row."""
        import jax
        from spark_rapids_tpu.columnar.column import (DeferredCount,
                                                      DeviceColumn, _jnp,
                                                      rc_traceable)
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        jnp = _jnp()
        n_payload = out.num_columns - len(self.lowered)
        pkeys = list(self.spec.partition_exprs)
        okeys = [e for e, _, _ in self.spec.order_specs]
        kb = eval_exprs_tpu(pkeys + okeys, out)
        key_cols = list(kb.columns)
        win_cols = list(out.columns[n_payload:])
        sig = (tuple((str(c.data_type), tuple(c.data.shape),
                      c.lengths is not None) for c in key_cols),
               tuple((str(c.data_type), tuple(c.data.shape))
                     for c in win_cols),
               tuple(low.func[:2] for low in self.lowered),
               len(pkeys), out.bucket, carry is None)
        def build():
            return _make_running_fixup(
                [c.data_type for c in key_cols], len(pkeys),
                [low.func for low in self.lowered],
                [c.data_type for c in win_cols], out.bucket,
                first=carry is None)
        from spark_rapids_tpu.exec.stage_compiler import get_or_build
        fn = get_or_build("window.running_fixup", sig, build)
        key_arrs = [(c.data, c.validity, c.lengths) for c in key_cols]
        win_arrs = [(c.data, c.validity) for c in win_cols]
        fixed, new_carry = fn(key_arrs, win_arrs,
                              rc_traceable(out.row_count), carry)
        rc = out.row_count
        n = rc if isinstance(rc, int) else DeferredCount(rc_traceable(rc))
        cols = list(out.columns[:n_payload])
        for (d, v), c in zip(fixed, win_cols):
            cols.append(DeviceColumn(d, v, n, c.data_type, c.lengths))
        return ColumnarBatch(cols, out.row_count, out.names), new_carry


def _spark_minmax(agg: str, a, b, jnp, dt):
    """Two-value combine with Spark NaN-greatest float semantics."""
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        na, nb = jnp.isnan(a), jnp.isnan(b)
        if agg == "min":       # NaN is largest: prefer the non-NaN side
            return jnp.where(na, b, jnp.where(nb, a, jnp.minimum(a, b)))
        return jnp.where(na, a, jnp.where(nb, b, jnp.maximum(a, b)))
    return jnp.minimum(a, b) if agg == "min" else jnp.maximum(a, b)


def _make_running_fixup(key_dtypes, n_pkeys: int, funcs, win_dtypes,
                        bucket: int, first: bool):
    """Builds the traced carry fix-up: adjusts running outputs for rows
    continuing the carried partition group and extracts the next carry
    from the batch's last row.  One jit per signature."""
    import numpy as np_

    def run(key_arrs, win_arrs, rc, carry):
        from spark_rapids_tpu.columnar.column import DeviceColumn, _jnp
        from spark_rapids_tpu.ops.agg_ops import _masked_group_words
        jnp = _jnp()
        inrow = jnp.arange(bucket) < rc
        last = jnp.clip(rc - 1, 0, bucket - 1)
        kcols = [DeviceColumn(d, v, bucket, dt, ln)
                 for (d, v, ln), dt in zip(key_arrs, key_dtypes)]
        pw, ow = [], []
        for i, c in enumerate(kcols):
            (pw if i < n_pkeys else ow).extend(_masked_group_words(c, jnp))

        def eq_words(words, carried):
            # string keys produce ONE PACKED WORD PER 7 CHARS of the
            # batch's char rectangle, so two batches of one stream can
            # disagree on word count; the packing 0-fills beyond the
            # string length, so the missing trailing words are exactly
            # zero — extend the shorter side with zeros instead of
            # silently truncating the comparison (zip would)
            import itertools
            eq = jnp.ones(bucket, dtype=bool)
            for w, cw in itertools.zip_longest(words, carried):
                if w is None:
                    eq = eq & (cw == 0 if cw.ndim == 0
                               else jnp.all(cw == 0))
                    continue
                if cw is None:
                    if w.ndim == 1:
                        eq = eq & (w == 0)
                    else:
                        eq = eq & jnp.all(w == 0, axis=-1)
                    continue
                if w.ndim == 1:
                    eq = eq & (w == cw)
                else:
                    if cw.shape[0] != w.shape[1]:
                        width = max(cw.shape[0], w.shape[1])
                        cw = jnp.pad(cw, (0, width - cw.shape[0]))
                        w = jnp.pad(w, ((0, 0), (0, width - w.shape[1])))
                    eq = eq & jnp.all(w == cw[None, :], axis=-1)
            return eq

        def cum_all(mask):
            return jnp.cumprod(mask.astype(np_.int32)).astype(bool)

        zero = jnp.asarray(0, dtype=np_.int64)
        if first:
            prefix = jnp.zeros(bucket, dtype=bool)
            same_peer = prefix
            c_rows = c_rank = c_dense = zero
            c_aggs = []
        else:
            prefix = cum_all(eq_words(pw, carry["pw"]) & inrow) & inrow
            same_peer = cum_all(eq_words(pw, carry["pw"]) &
                                eq_words(ow, carry["ow"]) & inrow) & prefix
            c_rows, c_rank = carry["rows"], carry["rank"]
            c_dense = carry["dense"]
            c_aggs = carry["aggs"]
        cont = same_peer[0]       # first row continues the carried peers

        fixed = []
        new_aggs = []
        ai = 0
        rank_last = dense_last = zero
        for fi, (f, dt) in enumerate(zip(funcs, win_dtypes)):
            d, v = win_arrs[fi]
            kind = f[0]
            if kind == "row_number":
                d2 = jnp.where(prefix, d + c_rows.astype(d.dtype), d)
                v2 = v
            elif kind == "rank":
                d2 = jnp.where(same_peer, c_rank.astype(d.dtype),
                               jnp.where(prefix,
                                         d + c_rows.astype(d.dtype), d))
                v2 = v
                rank_last = d2[last].astype(np_.int64)
            elif kind == "dense_rank":
                adj = c_dense - cont.astype(np_.int64)
                d2 = jnp.where(prefix, d + adj.astype(d.dtype), d)
                v2 = v
                dense_last = d2[last].astype(np_.int64)
            else:
                agg = f[1]
                if first:
                    d2, v2 = d, v
                else:
                    acc_d, acc_v = c_aggs[2 * ai], c_aggs[2 * ai + 1]
                    acc_d = acc_d.astype(d.dtype)
                    if agg == "count":
                        d2 = jnp.where(prefix, d + acc_d, d)
                        v2 = v
                    elif agg == "sum":
                        base = jnp.where(v, d, jnp.zeros_like(d))
                        addend = jnp.where(acc_v, acc_d,
                                           jnp.zeros_like(acc_d))
                        d2 = jnp.where(prefix, base + addend, d)
                        v2 = v | (prefix & acc_v)
                    else:  # min / max
                        comb = _spark_minmax(agg, d, acc_d, jnp, dt)
                        pick = jnp.where(v & acc_v, comb,
                                         jnp.where(v, d, acc_d))
                        d2 = jnp.where(prefix, pick, d)
                        v2 = v | (prefix & acc_v)
                new_aggs.append(d2[last])
                new_aggs.append(v2[last])
                ai += 1
            fixed.append((d2, v2))
        # next carry: rows of the batch's last partition group (+ the
        # carried rows when the whole batch continued one group)
        eql = eq_words(pw, [w[last] for w in pw])
        count_last = jnp.sum((eql & inrow).astype(np_.int64))
        rows_new = count_last + jnp.where(prefix[last], c_rows, zero)
        new_carry = {
            "pw": [w[last] for w in pw],
            "ow": [w[last] for w in ow],
            "rows": rows_new,
            "rank": rank_last,
            "dense": dense_last,
            "aggs": new_aggs,
        }
        return fixed, new_carry

    return run


# plan-rewrite registration (reference: GpuOverrides WindowExec rule +
# GpuWindowExecMeta tagging)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402


def _window_tag(meta):
    p = meta.plan
    for _, w in p.window_cols:
        reason = device_unsupported_reason(w)
        if reason:
            meta.will_not_work(reason)


register_exec(
    CpuWindowExec,
    convert=lambda p, m: TpuWindowExec(p.window_cols, p.children[0]),
    exprs_of=lambda p: (list(p.spec.partition_exprs) +
                        [e for e, _, _ in p.spec.order_specs] +
                        [x for low in p.lowered for x in low.inputs]),
    extra_tag=_window_tag,
    desc="window functions (fused sort + segmented scans)")
