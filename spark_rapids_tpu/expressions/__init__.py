"""Expression layer: SQL expressions with dual TPU/CPU backends.

Reference counterparts (SURVEY.md §2.6 "Cast & expressions" row):
``GpuExpression.columnarEval`` over cuDF ColumnVectors — here ``eval_tpu``
building jax ops over (data, validity) pairs, so an entire projection/filter
expression tree traces into ONE fused XLA program (a structural advantage
over the reference's kernel-at-a-time cuDF dispatch).

``eval_cpu`` is an independent numpy/pyarrow implementation used both as the
CPU fallback execution path and as the differential-test oracle (the
reference's oracle is Spark-on-CPU; SURVEY.md §4).
"""

from spark_rapids_tpu.expressions.base import (  # noqa: F401
    Expression, Literal, BoundReference, AttributeReference, Alias, TCol,
    bind_references, lit, col)
from spark_rapids_tpu.expressions import arithmetic  # noqa: F401
from spark_rapids_tpu.expressions import predicates  # noqa: F401
from spark_rapids_tpu.expressions import conditional  # noqa: F401
from spark_rapids_tpu.expressions import mathexprs  # noqa: F401
from spark_rapids_tpu.expressions import cast  # noqa: F401
from spark_rapids_tpu.expressions import strings  # noqa: F401
from spark_rapids_tpu.expressions import datetime_exprs  # noqa: F401
from spark_rapids_tpu.expressions import hashing  # noqa: F401
from spark_rapids_tpu.expressions import bitwise  # noqa: F401
