"""Declarative aggregate functions.

Reference: aggregate/aggregateFunctions.scala (2025 LoC) — each function
declares input projections, an update aggregation, a merge aggregation, and
a final ("evaluate") projection; GpuAggregateExec pipelines these through
cuDF groupby.

TPU-first redesign: every aggregate lowers to a small set of *segmented
reduction kinds* (sum/min/max/first/last/count over sorted segments —
ops/agg_ops.py) instead of cuDF's hash groupby.  A function contributes:

- ``inputs()``: expressions evaluated against the child batch (pre-step)
- ``buffers()``: (name, dtype, update_kind, merge_kind) partial columns
- ``evaluate(refs)``: final expression over the merged buffers

count/sum/avg/variance compose buffers algebraically (reference: e.g.
GpuAverage = sum+count); min/max/first/last map 1:1.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (BoundReference, Expression,
                                               Literal, TCol)


@dataclasses.dataclass(frozen=True)
class BufferSpec:
    name: str
    dtype: T.DataType
    update_kind: str   # segmented reduction over the input column
    merge_kind: str    # segmented reduction over partial buffers
    input_ordinal: int = 0      # which of inputs() feeds the update
    count_valid_only: bool = True


class AggregateFunction(Expression):
    """Base; children are the raw input expressions."""

    foldable = False   # never constant-fold aggregation/window context
    is_aggregate = True
    #: variable-length state: plan in COMPLETE mode after a key shuffle
    #: (Spark's ObjectHashAggregate pattern), no partial/merge stages
    requires_complete = False

    @property
    def nullable(self) -> bool:
        return True

    def inputs(self) -> List[Expression]:
        """Pre-step projections (default: the children)."""
        return list(self.children)

    def buffers(self) -> List[BufferSpec]:
        raise NotImplementedError

    def evaluate(self, refs: List[Expression]) -> Expression:
        """Final projection over buffer refs (order matches buffers())."""
        raise NotImplementedError

    # NOTE: decimal128 buffer gating lives in exec/aggregate.py
    # (_tag_aggregate), which sees the full buffer layout.

    def sql(self):
        args = ", ".join(c.sql() for c in self.children)
        return f"{type(self).__name__.lower()}({args})"


def _sum_result_type(dt: T.DataType) -> T.DataType:
    if isinstance(dt, T.DecimalType):
        return T.DecimalType(min(38, dt.precision + 10), dt.scale)
    if isinstance(dt, (T.DoubleType, T.FloatType)):
        return T.DOUBLE
    return T.LONG


class Sum(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return _sum_result_type(self.children[0].data_type)

    def buffers(self):
        return [BufferSpec("sum", self.data_type, "sum", "sum"),
                BufferSpec("cnt", T.LONG, "count", "sum")]

    def evaluate(self, refs):
        # Spark: sum of empty/all-null group is NULL, not 0
        from spark_rapids_tpu.expressions.conditional import If
        from spark_rapids_tpu.expressions.predicates import GreaterThan
        return If(GreaterThan(refs[1], Literal(0, T.LONG)),
                  refs[0], Literal(None, self.data_type))


class Count(AggregateFunction):
    """count(expr) — non-null count; count(lit(1)) == count(*)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def buffers(self):
        count_all = isinstance(self.children[0], Literal) and \
            self.children[0].value is not None
        return [BufferSpec("cnt", T.LONG, "count", "sum",
                           count_valid_only=not count_all)]

    def evaluate(self, refs):
        return refs[0]


class Min(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return self.children[0].data_type

    def buffers(self):
        return [BufferSpec("min", self.data_type, "min", "min")]

    def evaluate(self, refs):
        return refs[0]


class Max(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return self.children[0].data_type

    def buffers(self):
        return [BufferSpec("max", self.data_type, "max", "max")]

    def evaluate(self, refs):
        return refs[0]


class Average(AggregateFunction):
    """reference: GpuAverage — sum+count buffers, final divide."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        dt = self.children[0].data_type
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(min(38, dt.precision + 4),
                                 min(dt.scale + 4, 38))
        return T.DOUBLE

    def inputs(self):
        from spark_rapids_tpu.expressions.cast import Cast
        dt = self.children[0].data_type
        if isinstance(dt, T.DecimalType):
            return [self.children[0]]
        return [Cast(self.children[0], T.DOUBLE)]

    def buffers(self):
        sdt = T.DOUBLE if not isinstance(self.children[0].data_type,
                                         T.DecimalType) else \
            _sum_result_type(self.children[0].data_type)
        return [BufferSpec("sum", sdt, "sum", "sum"),
                BufferSpec("cnt", T.LONG, "count", "sum")]

    def evaluate(self, refs):
        from spark_rapids_tpu.expressions.arithmetic import Divide
        from spark_rapids_tpu.expressions.cast import Cast
        from spark_rapids_tpu.expressions.conditional import If
        from spark_rapids_tpu.expressions.predicates import GreaterThan
        div = Divide(Cast(refs[0], T.DOUBLE), Cast(refs[1], T.DOUBLE))
        if isinstance(self.data_type, T.DecimalType):
            div = Cast(div, self.data_type)
        return If(GreaterThan(refs[1], Literal(0, T.LONG)),
                  div, Literal(None, self.data_type))


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    @property
    def data_type(self):
        return self.children[0].data_type

    def buffers(self):
        kind = "first_valid" if self.ignore_nulls else "first"
        return [BufferSpec("first", self.data_type, kind, kind)]

    def evaluate(self, refs):
        return refs[0]


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    @property
    def data_type(self):
        return self.children[0].data_type

    def buffers(self):
        kind = "last_valid" if self.ignore_nulls else "last"
        return [BufferSpec("last", self.data_type, kind, kind)]

    def evaluate(self, refs):
        return refs[0]


class _CentralMoment(AggregateFunction):
    """Variance family via (count, mean, M2) — numerically-stable merge
    (Chan et al.), the same decomposition cuDF's groupby VAR/STD uses."""

    ddof = 1

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return T.DOUBLE

    def inputs(self):
        from spark_rapids_tpu.expressions.cast import Cast
        return [Cast(self.children[0], T.DOUBLE)]

    def buffers(self):
        # m2 update/merge are special kinds handled by the kernel
        return [BufferSpec("cnt", T.DOUBLE, "count", "m2_cnt"),
                BufferSpec("mean", T.DOUBLE, "mean", "m2_mean"),
                BufferSpec("m2", T.DOUBLE, "m2", "m2_m2")]

    def _final(self, refs):
        raise NotImplementedError

    def evaluate(self, refs):
        return self._final(refs)


class VarianceSamp(_CentralMoment):
    def _final(self, refs):
        from spark_rapids_tpu.expressions.arithmetic import Divide, Subtract
        from spark_rapids_tpu.expressions.conditional import If
        from spark_rapids_tpu.expressions.predicates import GreaterThan
        n, m2 = refs[0], refs[2]
        return If(GreaterThan(n, Literal(1.0, T.DOUBLE)),
                  Divide(m2, Subtract(n, Literal(1.0, T.DOUBLE))),
                  Literal(None, T.DOUBLE))


class VariancePop(_CentralMoment):
    def _final(self, refs):
        from spark_rapids_tpu.expressions.arithmetic import Divide
        from spark_rapids_tpu.expressions.conditional import If
        from spark_rapids_tpu.expressions.predicates import GreaterThan
        n, m2 = refs[0], refs[2]
        return If(GreaterThan(n, Literal(0.0, T.DOUBLE)),
                  Divide(m2, n), Literal(None, T.DOUBLE))


class StddevSamp(_CentralMoment):
    def _final(self, refs):
        from spark_rapids_tpu.expressions.mathexprs import Sqrt
        return Sqrt(VarianceSamp(self.children[0])._final(refs))


class StddevPop(_CentralMoment):
    def _final(self, refs):
        from spark_rapids_tpu.expressions.mathexprs import Sqrt
        return Sqrt(VariancePop(self.children[0])._final(refs))


@dataclasses.dataclass
class AggregateExpression:
    """An aggregate + its output name (Alias analog for agg results)."""
    func: AggregateFunction
    out_name: str


# ---------------------------------------------------------------------------
# collection + percentile aggregates (reference: GpuCollectList/GpuCollectSet
# in aggregateFunctions.scala; GpuPercentile/GpuApproximatePercentile via the
# JNI Histogram/t-digest kernels).  These need variable-length state, so
# they plan in COMPLETE mode (shuffle raw rows by key first — Spark's
# ObjectHashAggregate pattern) and run on the host tier until segmented
# list-state kernels land on device.
# ---------------------------------------------------------------------------

class CollectList(AggregateFunction):
    requires_complete = True

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type)

    def buffers(self):
        return [BufferSpec("list", self.data_type, "list", "list")]

    def evaluate(self, refs):
        return refs[0]


class CollectSet(AggregateFunction):
    requires_complete = True

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type, contains_null=False)

    def buffers(self):
        return [BufferSpec("set", self.data_type, "distinct", "distinct")]

    def evaluate(self, refs):
        return refs[0]


class CountDistinct(AggregateFunction):
    """count(DISTINCT x): a distinct-set buffer (COMPLETE-mode planning,
    Spark's ObjectHashAggregate pattern) sized at final.  Spark rewrites
    distinct aggregates with Expand (RewriteDistinctAggregates); this
    engine's complete pass reaches the same results — nulls are ignored
    and the count is never null (reference: the cuDF collect-set-backed
    distinct path, aggregateFunctions.scala)."""

    requires_complete = True

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self) -> bool:
        return False

    def sql(self):
        return f"count(DISTINCT {self.children[0].sql()})"

    def buffers(self):
        set_t = T.ArrayType(self.children[0].data_type,
                            contains_null=False)
        return [BufferSpec("set", set_t, "distinct", "distinct")]

    def evaluate(self, refs):
        from spark_rapids_tpu.expressions.cast import Cast
        from spark_rapids_tpu.expressions.collections import Size
        from spark_rapids_tpu.expressions.conditional import Greatest
        from spark_rapids_tpu.expressions.base import Literal
        # size() of a null set is -1 (Spark legacy default, never null);
        # an empty/all-null group must count 0
        return Greatest(Cast(Size(refs[0]), T.LONG), Literal(0, T.LONG))


class Percentile(AggregateFunction):
    """Exact percentile with Spark's 1-based-rank linear interpolation."""

    requires_complete = True

    def __init__(self, child: Expression, percentage):
        super().__init__([child])
        self.percentages = list(percentage) \
            if isinstance(percentage, (list, tuple)) else [percentage]
        self.scalar = not isinstance(percentage, (list, tuple))
        for p in self.percentages:
            if not (0.0 <= float(p) <= 1.0):
                raise ValueError(f"percentage {p} out of [0, 1]")

    @property
    def data_type(self):
        return T.DOUBLE if self.scalar else T.ArrayType(T.DOUBLE)

    def buffers(self):
        return [BufferSpec("vals", T.ArrayType(self.children[0].data_type),
                           "list", "list")]

    def evaluate(self, refs):
        return _PercentileFromList(refs[0], self.percentages, self.scalar)


class ApproximatePercentile(Percentile):
    """approx_percentile: the reference runs a t-digest JNI kernel; here the
    collected values are reduced exactly (a strictly more accurate answer
    for the same contract — the accuracy argument is accepted and
    ignored)."""

    def __init__(self, child: Expression, percentage, accuracy: int = 10000):
        super().__init__(child, percentage)
        self.accuracy = accuracy


class _PercentileFromList(Expression):
    """Final projection for Percentile: per-group sorted interpolation over
    the collected array buffer (host tier)."""

    def __init__(self, child, percentages, scalar: bool):
        super().__init__([child])
        self.percentages = [float(p) for p in percentages]
        self.scalar = scalar

    @property
    def data_type(self):
        return T.DOUBLE if self.scalar else T.ArrayType(T.DOUBLE)

    def tpu_supported(self, conf):
        return "percentile finalization is host tier"

    def eval_cpu(self, ctx):
        import numpy as np
        from spark_rapids_tpu.expressions.base import valid_array
        tc = self.children[0].eval(ctx)
        valid = valid_array(tc, ctx)
        n = ctx.row_count
        out = np.empty(n, dtype=object)
        ok = np.zeros(n, dtype=bool)
        for i in range(n):
            vals = tc.data[i] if valid[i] else None
            nums = sorted(float(v) for v in (vals or []) if v is not None)
            if not nums:
                out[i] = None
                continue
            res = [_interp(nums, p) for p in self.percentages]
            out[i] = res[0] if self.scalar else res
            ok[i] = True
        if self.scalar:
            dense = np.zeros(n, dtype=np.float64)
            for i in range(n):
                if ok[i]:
                    dense[i] = out[i]
            return TCol(dense, ok, T.DOUBLE)
        return TCol(out, ok, self.data_type)

    eval_tpu = eval_cpu


def _interp(sorted_vals, p: float) -> float:
    """Spark Percentile: rank = 1 + p*(n-1), linear interpolation."""
    n = len(sorted_vals)
    pos = p * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac
