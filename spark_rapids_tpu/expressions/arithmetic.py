"""Arithmetic expressions (reference: org/apache/spark/sql/rapids/
arithmetic.scala — GpuAdd/GpuSubtract/GpuMultiply/GpuDivide/GpuRemainder/
GpuPmod/GpuIntegralDivide/GpuUnaryMinus/GpuAbs...).

Semantics follow Spark non-ANSI mode: integer overflow wraps; division and
remainder by zero yield NULL (not an error).  Divide on integral/float
operands returns double (Spark true division).

TPU note: these are pure elementwise jnp ops; when evaluated under the
projection jit they fuse with neighbors into one XLA kernel.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               both_valid, jnp, materialize,
                                               valid_array)


class BinaryExpr(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def sql(self):
        return f"({self.left.sql()} {self.symbol} {self.right.sql()})"


def _coerce(c: TCol, dtype: T.DataType, ctx: EvalContext, xp):
    """Casts a numeric TCol to the result dtype (cheap numeric widen only)."""
    nd = dtype.np_dtype
    if c.is_scalar:
        if c.data is None:
            return TCol.scalar(None, dtype)
        v = c.data
        if nd is not None:
            if hasattr(v, "aval"):
                # promoted-literal scalar: a traced 0-d array, cast
                # in-trace (np.type() would force a host conversion)
                v = v.astype(nd)
            else:
                v = nd.type(v)
        return TCol.scalar(v, dtype)
    data = c.data
    if nd is not None and data.dtype != nd:
        data = data.astype(nd)
    return TCol(data, c.valid, dtype)


class BinaryArithmetic(BinaryExpr):
    """Shared scaffolding: numeric coercion, null propagation, wrap-on-overflow."""

    null_on_zero_divisor = False
    decimal_op: str = ""   # "add"/"sub"/"mul"/"div"/"rem"/"pmod"

    def _decimal_operands(self):
        """(left_dt, right_dt) when this op runs in decimal space (at least
        one decimal operand, the other decimal/integral), else None."""
        from spark_rapids_tpu.expressions import decimal_math as DM
        lt, rt = self.left.data_type, self.right.data_type
        if not (isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType)):
            return None
        if lt.is_floating or rt.is_floating:
            return None   # Spark promotes decimal+fractional to double
        if DM.as_decimal_type(lt) is None or DM.as_decimal_type(rt) is None:
            raise TypeError(
                f"cannot apply {self.name} to {lt.simple_name} and "
                f"{rt.simple_name}: cast the non-numeric side explicitly")
        return lt, rt

    @property
    def data_type(self) -> T.DataType:
        ops = self._decimal_operands()
        if ops is not None and self.decimal_op:
            from spark_rapids_tpu.expressions import decimal_math as DM
            return DM.binary_result_type(self.decimal_op, *ops)
        return T.common_type(self.left.data_type, self.right.data_type)

    def tpu_supported(self, conf):
        ops = self._decimal_operands()
        if ops is not None:
            if not self.decimal_op:
                return f"decimal {self.name} not supported on device"
            from spark_rapids_tpu.expressions import decimal_math as DM
            return DM.device_supported(self.decimal_op, *ops)
        return None

    def _apply(self, a, b, xp):
        raise NotImplementedError

    def _eval(self, ctx: EvalContext, xp) -> TCol:
        rt = self.data_type
        ops = self._decimal_operands()
        if ops is not None and self.decimal_op:
            from spark_rapids_tpu.expressions import decimal_math as DM
            ltc = self.left.eval(ctx)
            rtc = self.right.eval(ctx)
            if ctx.backend == "cpu":
                return DM.cpu_binary_eval(self.decimal_op, ltc, rtc, rt, ctx)
            return DM.tpu_binary_eval(self.decimal_op, ltc, rtc, rt, ctx, xp)
        if isinstance(self.left.data_type, T.DecimalType) or \
                isinstance(self.right.data_type, T.DecimalType):
            # decimal + fractional: promote the decimal side to double
            from spark_rapids_tpu.expressions import decimal_math as DM
            a = self.left.eval(ctx)
            b = self.right.eval(ctx)
            if isinstance(a.dtype, T.DecimalType):
                a = DM.decimal_to_double(a, ctx, xp)
            if isinstance(b.dtype, T.DecimalType):
                b = DM.decimal_to_double(b, ctx, xp)
            a = _coerce(a, rt, ctx, xp)
            b = _coerce(b, rt, ctx, xp)
            return self._finish_eval(a, b, rt, ctx, xp)
        a = _coerce(self.left.eval(ctx), rt, ctx, xp)
        b = _coerce(self.right.eval(ctx), rt, ctx, xp)
        return self._finish_eval(a, b, rt, ctx, xp)

    def _finish_eval(self, a, b, rt, ctx, xp) -> TCol:
        valid = both_valid(a, b, ctx)
        if a.is_scalar and b.is_scalar:
            if not valid or (self.null_on_zero_divisor and not b.data):
                return TCol.scalar(None, rt)
            out = self._apply(np.asarray(a.data), np.asarray(b.data), np)
            return TCol.scalar(out[()], rt)
        ad = materialize(a, ctx, rt.np_dtype)
        bd = materialize(b, ctx, rt.np_dtype)
        if self.null_on_zero_divisor:
            zero = bd == 0
            valid = valid & ~zero  # at least one input is an array here
            bd = xp.where(zero, xp.ones_like(bd), bd)  # avoid div warnings
        out = self._apply(ad, bd, xp)
        return TCol(out, valid, rt)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)


class Add(BinaryArithmetic):
    symbol = "+"
    decimal_op = "add"

    def _apply(self, a, b, xp):
        return a + b


class Subtract(BinaryArithmetic):
    symbol = "-"
    decimal_op = "sub"

    def _apply(self, a, b, xp):
        return a - b


class Multiply(BinaryArithmetic):
    symbol = "*"
    decimal_op = "mul"

    def _apply(self, a, b, xp):
        return a * b


class Divide(BinaryArithmetic):
    """Spark Divide: double result — except decimal/decimal, which stays
    decimal per DecimalPrecision; x/0 -> NULL (non-ANSI)."""
    symbol = "/"
    decimal_op = "div"

    @property
    def data_type(self):
        ops = self._decimal_operands()
        if ops is not None:
            from spark_rapids_tpu.expressions import decimal_math as DM
            return DM.binary_result_type("div", *ops)
        return T.DOUBLE

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        return a / b


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: long result, x div 0 -> NULL."""
    symbol = "div"
    decimal_op = "idiv"

    @property
    def data_type(self):
        return T.LONG

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        # exact int64 truncate-toward-zero (Spark/Java semantics); plain
        # floor-div then adjust when signs differ and division is inexact.
        # (a/b via float would lose precision past 2^53.)  Zero divisors were
        # already replaced by 1 and nulled in _eval.
        q = a // b
        inexact = (a - q * b) != 0
        adjust = inexact & ((a < 0) ^ (b < 0))
        return (q + adjust).astype(np.int64)


class Remainder(BinaryArithmetic):
    """Spark %: sign follows the dividend (fmod); x%0 -> NULL."""
    symbol = "%"
    decimal_op = "rem"

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        return xp.fmod(a, b)


class Pmod(BinaryArithmetic):
    """Positive modulus (reference GpuPmod)."""
    symbol = "pmod"
    decimal_op = "pmod"

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        r = xp.fmod(a, b)
        return xp.where(r < 0, r + xp.abs(b), r)


class UnaryExpr(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def child(self):
        return self.children[0]


class UnaryMinus(UnaryExpr):
    @property
    def data_type(self):
        return self.child.data_type

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        dt = c.dtype
        if isinstance(dt, T.DecimalType):
            from spark_rapids_tpu.expressions import decimal_math as DM
            if ctx.backend == "cpu":
                vals, valid = DM.unscaled_py(c, ctx)
                out = np.empty(ctx.row_count, dtype=object)
                for i in range(ctx.row_count):
                    out[i] = -vals[i]
                return DM.result_tcol_py(out, valid, dt, ctx)
            hi, lo, valid = DM.device_parts(c, ctx, xp)
            hi, lo = DM.widen_to_128(hi, lo, xp)
            nh, nl = DM.neg128(hi, lo, xp)
            return DM.pack_result(nh, nl, valid, dt, ctx, xp)
        if c.is_scalar:
            return TCol.scalar(None if c.data is None else -c.data, c.dtype)
        return TCol(-c.data, c.valid, c.dtype)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Abs(UnaryExpr):
    @property
    def data_type(self):
        return self.child.data_type

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        dt = c.dtype
        if isinstance(dt, T.DecimalType):
            from spark_rapids_tpu.expressions import decimal_math as DM
            if ctx.backend == "cpu":
                vals, valid = DM.unscaled_py(c, ctx)
                out = np.empty(ctx.row_count, dtype=object)
                for i in range(ctx.row_count):
                    out[i] = abs(vals[i])
                return DM.result_tcol_py(out, valid, dt, ctx)
            hi, lo, valid = DM.device_parts(c, ctx, xp)
            hi, lo = DM.widen_to_128(hi, lo, xp)
            ah, al = DM.abs128(hi, lo, xp)
            return DM.pack_result(ah, al, valid, dt, ctx, xp)
        if c.is_scalar:
            return TCol.scalar(None if c.data is None else abs(c.data), c.dtype)
        return TCol(xp.abs(c.data), c.valid, c.dtype)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)
