"""Arithmetic expressions (reference: org/apache/spark/sql/rapids/
arithmetic.scala — GpuAdd/GpuSubtract/GpuMultiply/GpuDivide/GpuRemainder/
GpuPmod/GpuIntegralDivide/GpuUnaryMinus/GpuAbs...).

Semantics follow Spark non-ANSI mode: integer overflow wraps; division and
remainder by zero yield NULL (not an error).  Divide on integral/float
operands returns double (Spark true division).

TPU note: these are pure elementwise jnp ops; when evaluated under the
projection jit they fuse with neighbors into one XLA kernel.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               both_valid, jnp, materialize,
                                               valid_array)


class BinaryExpr(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def sql(self):
        return f"({self.left.sql()} {self.symbol} {self.right.sql()})"


def _coerce(c: TCol, dtype: T.DataType, ctx: EvalContext, xp):
    """Casts a numeric TCol to the result dtype (cheap numeric widen only)."""
    nd = dtype.np_dtype
    if c.is_scalar:
        if c.data is None:
            return TCol.scalar(None, dtype)
        v = c.data
        if nd is not None:
            v = nd.type(v)
        return TCol.scalar(v, dtype)
    data = c.data
    if nd is not None and data.dtype != nd:
        data = data.astype(nd)
    return TCol(data, c.valid, dtype)


class BinaryArithmetic(BinaryExpr):
    """Shared scaffolding: numeric coercion, null propagation, wrap-on-overflow."""

    null_on_zero_divisor = False

    @property
    def data_type(self) -> T.DataType:
        return T.common_type(self.left.data_type, self.right.data_type)

    def tpu_supported(self, conf):
        if isinstance(self.data_type, T.DecimalType):
            return "decimal arithmetic not yet on device"
        return None

    def _apply(self, a, b, xp):
        raise NotImplementedError

    def _eval(self, ctx: EvalContext, xp) -> TCol:
        rt = self.data_type
        a = _coerce(self.left.eval(ctx), rt, ctx, xp)
        b = _coerce(self.right.eval(ctx), rt, ctx, xp)
        valid = both_valid(a, b, ctx)
        if a.is_scalar and b.is_scalar:
            if not valid or (self.null_on_zero_divisor and not b.data):
                return TCol.scalar(None, rt)
            out = self._apply(np.asarray(a.data), np.asarray(b.data), np)
            return TCol.scalar(out[()], rt)
        ad = materialize(a, ctx, rt.np_dtype)
        bd = materialize(b, ctx, rt.np_dtype)
        if self.null_on_zero_divisor:
            zero = bd == 0
            valid = valid & ~zero  # at least one input is an array here
            bd = xp.where(zero, xp.ones_like(bd), bd)  # avoid div warnings
        out = self._apply(ad, bd, xp)
        return TCol(out, valid, rt)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)


class Add(BinaryArithmetic):
    symbol = "+"

    def _apply(self, a, b, xp):
        return a + b


class Subtract(BinaryArithmetic):
    symbol = "-"

    def _apply(self, a, b, xp):
        return a - b


class Multiply(BinaryArithmetic):
    symbol = "*"

    def _apply(self, a, b, xp):
        return a * b


class Divide(BinaryArithmetic):
    """Spark Divide: result is double; x/0 -> NULL (non-ANSI)."""
    symbol = "/"

    @property
    def data_type(self):
        return T.DOUBLE

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        return a / b


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: long result, x div 0 -> NULL."""
    symbol = "div"

    @property
    def data_type(self):
        return T.LONG

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        # exact int64 truncate-toward-zero (Spark/Java semantics); plain
        # floor-div then adjust when signs differ and division is inexact.
        # (a/b via float would lose precision past 2^53.)  Zero divisors were
        # already replaced by 1 and nulled in _eval.
        q = a // b
        inexact = (a - q * b) != 0
        adjust = inexact & ((a < 0) ^ (b < 0))
        return (q + adjust).astype(np.int64)


class Remainder(BinaryArithmetic):
    """Spark %: sign follows the dividend (fmod); x%0 -> NULL."""
    symbol = "%"

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        return xp.fmod(a, b)


class Pmod(BinaryArithmetic):
    """Positive modulus (reference GpuPmod)."""
    symbol = "pmod"

    @property
    def null_on_zero_divisor(self):
        return True

    def _apply(self, a, b, xp):
        r = xp.fmod(a, b)
        return xp.where(r < 0, r + xp.abs(b), r)


class UnaryExpr(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def child(self):
        return self.children[0]


class UnaryMinus(UnaryExpr):
    @property
    def data_type(self):
        return self.child.data_type

    def tpu_supported(self, conf):
        if isinstance(self.data_type, T.DecimalType):
            return "decimal negate not yet on device"
        return None

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            return TCol.scalar(None if c.data is None else -c.data, c.dtype)
        return TCol(-c.data, c.valid, c.dtype)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Abs(UnaryExpr):
    @property
    def data_type(self):
        return self.child.data_type

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            return TCol.scalar(None if c.data is None else abs(c.data), c.dtype)
        return TCol(xp.abs(c.data), c.valid, c.dtype)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)
