"""Expression base classes and evaluation contexts.

Reference: the GpuExpression hierarchy (sql-plugin/.../GpuExpressions.scala)
and Spark Catalyst's Expression tree.  Two evaluation paths:

- ``eval_tpu(ctx)``: builds jax ops on ``TCol`` values.  Called inside a
  traced function, so the whole tree compiles into one XLA program and XLA
  fuses everything (TPU-first whole-stage fusion).
- ``eval_cpu(ctx)``: independent numpy/pyarrow implementation with the same
  SQL semantics; the CPU fallback path and the differential-test oracle.

Value representations:
- TPU: ``TCol(data, valid, dtype, lengths)`` of jax arrays.  Strings are
  uint8[bucket, width] + lengths.  Scalars use ``is_scalar=True`` with
  python/0-d values (broadcast lazily by kernels).
- CPU: ``TCol`` of numpy arrays; strings are object arrays of ``str``.

SQL null semantics: every value carries ``valid``; kernels must propagate
nulls per-operator (null-propagating by default; Kleene logic for AND/OR).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T


def jnp():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()


@dataclasses.dataclass
class TCol:
    """A columnar value during evaluation (device or host backend)."""
    data: Any
    valid: Any                 # bool array, or True/False for scalars
    dtype: T.DataType
    lengths: Any = None        # string/array columns (device rep)
    is_scalar: bool = False
    elem_valid: Any = None     # array columns only (device rep)

    @staticmethod
    def scalar(value, dtype: T.DataType) -> "TCol":
        return TCol(value, value is not None, dtype, is_scalar=True)

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, (T.StringType, T.BinaryType))


class EvalContext:
    """Holds the input columns for BoundReference + backend selector.

    ``row_count`` is the PHYSICAL length of the column arrays — the padded
    bucket on the device backend, the logical row count on the CPU backend.
    Kernels always produce physical-length outputs; the exec layer tracks the
    logical count and masks padding via validity.
    """

    __slots__ = ("cols", "backend", "row_count", "lambda_bindings",
                 "elem_plane", "literal_args", "enc_tables")

    def __init__(self, cols: Sequence[TCol], backend: str, row_count: int):
        self.cols = list(cols)
        self.backend = backend  # "tpu" | "cpu"
        self.row_count = row_count
        self.lambda_bindings = {}  # name -> TCol (higher-order functions)
        #: True while evaluating a lambda body over an [n, w] element plane
        #: (scalars then densify to [n, 1] so they broadcast either way)
        self.elem_plane = False
        #: runtime values for PromotedLiteral slots (plan/stages.py) when
        #: evaluating inside a parameterized fused-stage trace
        self.literal_args = None
        #: device bool lookup tables for code-space dictionary predicates
        #: (columnar/encoding.py DictContains slots)
        self.enc_tables = None


class Expression:
    """Base expression node."""

    #: False for expressions that must not be constant-folded even over
    #: all-literal children (aggregation/window context dependence).
    foldable: bool = True
    #: False for expressions whose value differs per evaluation (rand,
    #: uuid, monotonically_increasing_id).  fold_constants refuses to fold
    #: these regardless of ``foldable`` — any new non-deterministic
    #: expression MUST set this or it would silently fold to one literal.
    deterministic: bool = True

    def __init__(self, children: Sequence["Expression"] = ()):
        self.children: List[Expression] = list(children)

    # -- static info --------------------------------------------------------
    @property
    def data_type(self) -> T.DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children) if self.children else True

    @property
    def name(self) -> str:
        return type(self).__name__

    def sql(self) -> str:
        args = ", ".join(c.sql() for c in self.children)
        return f"{self.name}({args})"

    # -- evaluation ---------------------------------------------------------
    def eval(self, ctx: EvalContext) -> TCol:
        if ctx.backend == "tpu":
            return self.eval_tpu(ctx)
        return self.eval_cpu(ctx)

    def eval_tpu(self, ctx: EvalContext) -> TCol:
        raise NotImplementedError(f"{self.name}.eval_tpu")

    def eval_cpu(self, ctx: EvalContext) -> TCol:
        raise NotImplementedError(f"{self.name}.eval_cpu")

    # -- planner hooks ------------------------------------------------------
    def tpu_supported(self, conf) -> Optional[str]:
        """None if supported on device; else a reason string (used by the
        meta layer to tag fallback, reference RapidsMeta.willNotWorkOnGpu)."""
        return None

    def transform_up(self, fn: Callable[["Expression"], "Expression"]) -> "Expression":
        node = self.with_children([c.transform_up(fn) for c in self.children])
        return fn(node)

    def with_children(self, children: List["Expression"]) -> "Expression":
        if not self.children and not children:
            return self
        import copy
        node = copy.copy(self)
        node.children = list(children)
        return node

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def over(self, spec) -> "Expression":
        """agg_function.over(window_spec) -> WindowExpression (valid for
        aggregate functions; ranking functions override on their class)."""
        from spark_rapids_tpu.expressions.window_exprs import (
            WindowExpression, _to_spec)
        if not getattr(self, "is_aggregate", False):
            raise TypeError(f"{self.name} cannot be used as a window "
                            "function")
        return WindowExpression(self, _to_spec(spec))

    def collect(self, pred) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    def __repr__(self):
        return self.sql()

    # -- pyspark-Column-style operator sugar --------------------------------
    @staticmethod
    def _wrap(v) -> "Expression":
        return v if isinstance(v, Expression) else lit(v)

    def _bin(self, other, cls, flip=False):
        a, b = Expression._wrap(other), self
        if not flip:
            a, b = b, a
        return cls(a, b)

    def __add__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Add
        return self._bin(o, Add)

    def __radd__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Add
        return self._bin(o, Add, True)

    def __sub__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Subtract
        return self._bin(o, Subtract)

    def __rsub__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Subtract
        return self._bin(o, Subtract, True)

    def __mul__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Multiply
        return self._bin(o, Multiply)

    def __rmul__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Multiply
        return self._bin(o, Multiply, True)

    def __truediv__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Divide
        return self._bin(o, Divide)

    def __rtruediv__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Divide
        return self._bin(o, Divide, True)

    def __mod__(self, o):
        from spark_rapids_tpu.expressions.arithmetic import Remainder
        return self._bin(o, Remainder)

    def __neg__(self):
        from spark_rapids_tpu.expressions.arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __lt__(self, o):
        from spark_rapids_tpu.expressions.predicates import LessThan
        return self._bin(o, LessThan)

    def __le__(self, o):
        from spark_rapids_tpu.expressions.predicates import LessThanOrEqual
        return self._bin(o, LessThanOrEqual)

    def __gt__(self, o):
        from spark_rapids_tpu.expressions.predicates import GreaterThan
        return self._bin(o, GreaterThan)

    def __ge__(self, o):
        from spark_rapids_tpu.expressions.predicates import GreaterThanOrEqual
        return self._bin(o, GreaterThanOrEqual)

    def __eq__(self, o):
        from spark_rapids_tpu.expressions.predicates import EqualTo
        return self._bin(o, EqualTo)

    def __ne__(self, o):
        from spark_rapids_tpu.expressions.predicates import NotEqual
        return self._bin(o, NotEqual)

    __hash__ = object.__hash__  # __eq__ builds an expression, not a bool

    def __bool__(self):
        raise ValueError(
            "Cannot convert an Expression to a bool: use '&' for AND, '|' "
            "for OR, '~' for NOT, and avoid chained comparisons "
            "(a < col < b)")

    def __and__(self, o):
        from spark_rapids_tpu.expressions.predicates import And
        return self._bin(o, And)

    def __or__(self, o):
        from spark_rapids_tpu.expressions.predicates import Or
        return self._bin(o, Or)

    def __invert__(self):
        from spark_rapids_tpu.expressions.predicates import Not
        return Not(self)


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Literal(Expression):
    def __init__(self, value, dtype: Optional[T.DataType] = None):
        super().__init__()
        self.value = value
        self._dtype = dtype or _infer_literal_type(value)

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    def sql(self):
        return repr(self.value)

    def _as_tcol(self) -> TCol:
        return TCol.scalar(self.value, self._dtype)

    def eval_tpu(self, ctx):
        return self._as_tcol()

    def eval_cpu(self, ctx):
        return self._as_tcol()


class BoundReference(Expression):
    def __init__(self, ordinal: int, dtype: T.DataType, nullable: bool = True,
                 ref_name: str = ""):
        super().__init__()
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable
        self.ref_name = ref_name

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    def sql(self):
        return self.ref_name or f"input[{self.ordinal}]"

    def eval_tpu(self, ctx):
        return ctx.cols[self.ordinal]

    eval_cpu = eval_tpu


class AttributeReference(Expression):
    """Named column reference, resolved to BoundReference at bind time."""

    def __init__(self, ref_name: str):
        super().__init__()
        self.ref_name = ref_name

    @property
    def data_type(self):
        raise TypeError(f"unresolved attribute {self.ref_name!r}")

    def sql(self):
        return self.ref_name


class Alias(Expression):
    def __init__(self, child: Expression, alias_name: str):
        super().__init__([child])
        self.alias_name = alias_name

    @property
    def data_type(self):
        return self.children[0].data_type

    @property
    def nullable(self):
        return self.children[0].nullable

    def sql(self):
        return f"{self.children[0].sql()} AS {self.alias_name}"

    def eval_tpu(self, ctx):
        return self.children[0].eval(ctx)

    eval_cpu = eval_tpu


def _infer_literal_type(value) -> T.DataType:
    import datetime
    import decimal
    import numpy as _np
    if value is None:
        return T.NULL
    if isinstance(value, _np.generic):
        return T.from_numpy_dtype(value.dtype)
    if isinstance(value, bool):
        return T.BOOLEAN
    if isinstance(value, int):
        return T.INT if -(2**31) <= value < 2**31 else T.LONG
    if isinstance(value, float):
        return T.DOUBLE
    if isinstance(value, str):
        return T.STRING
    if isinstance(value, bytes):
        return T.BINARY
    if isinstance(value, decimal.Decimal):
        sign, digits, exp = value.as_tuple()
        scale = max(0, -exp)
        return T.DecimalType(max(len(digits), scale + 1), scale)
    if isinstance(value, datetime.datetime):
        return T.TIMESTAMP
    if isinstance(value, datetime.date):
        return T.DATE
    raise TypeError(f"cannot infer literal type of {value!r}")


# ---------------------------------------------------------------------------
# Binding & helpers
# ---------------------------------------------------------------------------

def bind_references(expr: Expression, schema: T.StructType) -> Expression:
    """Resolves AttributeReference names to ordinals (reference:
    GpuBindReferences.bindGpuReferences)."""

    def fix(node: Expression) -> Expression:
        if isinstance(node, AttributeReference):
            i = schema.field_index(node.ref_name)
            f = schema.fields[i]
            return BoundReference(i, f.data_type, f.nullable, f.name)
        if hasattr(node, "_sync_var_types"):
            # higher-order fns type their lambda variables once the array
            # child is resolved (the vars are shared leaf instances)
            node._sync_var_types()
        return node

    return expr.transform_up(fix)


def fold_constants(expr: Expression) -> Expression:
    """Evaluates deterministic all-literal subtrees once on the host and
    replaces them with Literals (Spark's ConstantFolding logical rule),
    and simplifies struct CONSTRUCTOR forms so they never need a device
    struct plane (Spark's SimplifyExtractValueOps + struct-equality
    expansion):

    - ``struct(a, b).a``             -> ``a``
    - ``struct(a, b) = struct(c, d)`` -> ``a <=> c AND b <=> d``
      (struct equality is field-wise NULL-SAFE in Spark; the constructor
      itself is never null, so no outer null term is needed)

    First-order device win: ``cast('2000-08-23' as date)`` inside a filter
    otherwise drags the whole operator to host because string->date casts
    are host-only; folded to a DATE literal the comparison stays on device.
    """
    from spark_rapids_tpu.expressions.evaluator import tcol_to_host_column

    def fix(n: Expression) -> Expression:
        simplified = _simplify_struct_node(n)
        if simplified is not None:
            return simplified
        if (isinstance(n, (Literal, Alias)) or not n.children or
                not n.foldable or not n.deterministic or
                not all(isinstance(c, Literal) for c in n.children)):
            return n
        try:
            tc = n.eval_cpu(EvalContext([], "cpu", 1))
            v = tcol_to_host_column(tc, 1).arrow[0].as_py()
            return Literal(v, n.data_type)
        except Exception:  # noqa: BLE001 — any eval failure (overflow,
            # arrow conversion, host-only op) defers to runtime, where the
            # engine's own error surfaces; folding is an optimization and
            # must never turn a runnable plan into a planning error
            return n

    return expr.transform_up(fix)


def _simplify_struct_node(n: Expression):
    """Struct-constructor simplifications (see fold_constants docstring).
    Returns the replacement or None."""
    from spark_rapids_tpu.expressions.collections import (CreateNamedStruct,
                                                          GetStructField)
    from spark_rapids_tpu.expressions import predicates as PR
    if isinstance(n, GetStructField) and \
            isinstance(n.children[0], CreateNamedStruct):
        st = n.children[0]
        # SQL identifiers resolve case-insensitively (Spark default)
        want = n.field_name.lower()
        for nm, child in zip(st.names, st.children):
            if nm.lower() == want:
                return child
        return None   # unknown field: defer to GetStructField's own error
    if isinstance(n, PR.EqualTo):
        l, r = n.children
        if isinstance(l, CreateNamedStruct) and \
                isinstance(r, CreateNamedStruct) and \
                len(l.children) == len(r.children):
            out = None
            for lc, rc in zip(l.children, r.children):
                term = PR.EqualNullSafe(lc, rc)
                out = term if out is None else PR.And(out, term)
            return out
    return None


def col(name: str) -> AttributeReference:
    return AttributeReference(name)


def lit(value, dtype: Optional[T.DataType] = None) -> Literal:
    return Literal(value, dtype)


# -- broadcast/validity helpers shared by kernels ---------------------------

def both_valid(a: TCol, b: TCol, ctx: EvalContext):
    """Combined validity of two inputs; returns array or scalar bool."""
    av, bv = a.valid, b.valid
    if a.is_scalar and b.is_scalar:
        return bool(av) and bool(bv)
    xp = jnp() if ctx.backend == "tpu" else np
    if a.is_scalar:
        return bv if av else xp.zeros_like(bv)
    if b.is_scalar:
        return av if bv else xp.zeros_like(av)
    return av & bv


def all_valid(cols: Sequence[TCol], ctx: EvalContext):
    out = cols[0]
    acc = out.valid
    for c in cols[1:]:
        nxt = TCol(None, acc, out.dtype)
        acc = both_valid(nxt, c, ctx)
    return acc


def to_physical_scalar(v):
    """Date/timestamp python objects -> the physical int representation
    kernels compute on (micros since epoch / days since epoch); any other
    value passes through.  Shared by ``materialize`` (baked constants) and
    plan/stages.physical_literal (promoted runtime args) — the two MUST
    produce identical values or promoted-vs-baked programs diverge."""
    import datetime as _dt
    if isinstance(v, _dt.datetime):
        import calendar
        return int(calendar.timegm(v.utctimetuple())) * 1_000_000 \
            + v.microsecond
    if isinstance(v, _dt.date):
        return (v - _dt.date(1970, 1, 1)).days
    return v


def materialize(c: TCol, ctx: EvalContext, np_dtype=None) -> Any:
    """Densifies a scalar TCol to a full column when a kernel needs arrays."""
    xp = jnp() if ctx.backend == "tpu" else np
    if not c.is_scalar:
        return c.data
    dt = np_dtype or (c.dtype.np_dtype or np.dtype(object))
    shape = (ctx.row_count, 1) if ctx.elem_plane else (ctx.row_count,)
    if c.data is None:
        if dt == np.dtype(object):
            return np.full(shape, None, dtype=object)
        return xp.zeros(shape, dtype=dt)
    if dt == np.dtype(object):
        return np.full(shape, c.data, dtype=object)
    # date/timestamp literals carry python objects; kernels want the
    # physical int representation
    v = to_physical_scalar(c.data)
    return xp.full(shape, v, dtype=dt)


def valid_array(c: TCol, ctx: EvalContext):
    xp = jnp() if ctx.backend == "tpu" else np
    if not c.is_scalar:
        return c.valid
    shape = (ctx.row_count, 1) if ctx.elem_plane else (ctx.row_count,)
    return xp.full(shape, bool(c.valid), dtype=bool)
