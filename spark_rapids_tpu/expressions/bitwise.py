"""Bitwise expressions (reference: org/apache/spark/sql/rapids/bitwise.scala —
GpuBitwiseAnd/Or/Xor/Not, GpuShiftLeft/Right/RightUnsigned)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import TCol, both_valid, jnp, materialize
from spark_rapids_tpu.expressions.arithmetic import (BinaryArithmetic,
                                                     UnaryExpr)


class BitwiseAnd(BinaryArithmetic):
    symbol = "&"

    def _apply(self, a, b, xp):
        return a & b


class BitwiseOr(BinaryArithmetic):
    symbol = "|"

    def _apply(self, a, b, xp):
        return a | b


class BitwiseXor(BinaryArithmetic):
    symbol = "^"

    def _apply(self, a, b, xp):
        return a ^ b


class BitwiseNot(UnaryExpr):
    @property
    def data_type(self):
        return self.child.data_type

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            return TCol.scalar(None if c.data is None else ~c.data, c.dtype)
        return TCol(~c.data, c.valid, c.dtype)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class _Shift(BinaryArithmetic):
    """Java shift semantics: shift amount masked to the value's bit width."""

    @property
    def data_type(self):
        return self.left.data_type

    def _mask(self):
        return 63 if isinstance(self.left.data_type, T.LongType) else 31

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        dt = self.data_type
        if a.is_scalar and b.is_scalar:
            if not valid:
                return TCol.scalar(None, dt)
            out = self._apply(np.asarray(a.data), np.asarray(b.data), np)
            return TCol.scalar(out[()].item(), dt)
        ad = materialize(a, ctx, dt.np_dtype)
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol(self._apply(ad, bd, xp), valid, dt)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class ShiftLeft(_Shift):
    symbol = "<<"

    def _apply(self, a, b, xp):
        return a << (b & self._mask())


class ShiftRight(_Shift):
    symbol = ">>"

    def _apply(self, a, b, xp):
        return a >> (b & self._mask())


class ShiftRightUnsigned(_Shift):
    symbol = ">>>"

    def _apply(self, a, b, xp):
        shift = b & self._mask()
        if isinstance(self.left.data_type, T.LongType):
            u = a.astype(np.uint64) >> shift.astype(np.uint64)
            return u.astype(np.int64)
        u = a.astype(np.uint32) >> shift.astype(np.uint32)
        return u.astype(np.int32)
