"""Bloom filter build + probe (join pruning).

Reference: the JNI ``BloomFilter`` kernels (SURVEY.md §2.16) backing
Spark's runtime bloom-filter join pruning (BloomFilterAggregate /
BloomFilterMightContain).

Layout: a power-of-two bit array packed in uint32 words.  Positions come
from double hashing (h1 + i*h2) of the murmur3 of the value — the build is
hash + scatter-OR, the probe is gather + AND: both pure elementwise device
work."""

from __future__ import annotations

from typing import Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (EvalContext, Expression, TCol,
                                               jnp, valid_array)
from spark_rapids_tpu.expressions.hashing import murmur3_col


class BloomFilter:
    """Immutable once built; ``might_contain`` has no false negatives."""

    def __init__(self, bits_words: np.ndarray, num_hashes: int):
        self.words = np.asarray(bits_words, dtype=np.uint32)
        self.num_bits = len(self.words) * 32
        assert self.num_bits & (self.num_bits - 1) == 0, \
            "bloom bit count must be a power of two"
        self.num_hashes = num_hashes

    # -- construction --------------------------------------------------------
    @staticmethod
    def empty(num_bits: int = 1 << 20, num_hashes: int = 3) -> "BloomFilter":
        if num_bits & (num_bits - 1):
            raise ValueError("num_bits must be a power of two")
        return BloomFilter(np.zeros(num_bits // 32, dtype=np.uint32),
                           num_hashes)

    @staticmethod
    def build(df, column, num_bits: int = 1 << 20,
              num_hashes: int = 3) -> "BloomFilter":
        """Builds from a DataFrame column (one pass over the executed
        plan; device batches hash on device, the small word array folds on
        host)."""
        from spark_rapids_tpu.expressions.base import bind_references, col
        bf = BloomFilter.empty(num_bits, num_hashes)
        expr = bind_references(
            col(column) if isinstance(column, str) else column, df.schema)
        plan = df._executed_plan()
        for b in plan.execute_all():
            if hasattr(b, "bucket"):
                hb = b.to_host()
            else:
                hb = b
            from spark_rapids_tpu.expressions.evaluator import host_batch_tcols
            ctx = EvalContext(host_batch_tcols(hb), "cpu", hb.row_count)
            tc = expr.eval_cpu(ctx)
            bf._add_host(tc, ctx)
        return bf

    def _positions(self, tc: TCol, ctx, xp):
        dt = tc.dtype
        h1 = murmur3_col(tc, dt, np.uint32(0x9747B28C), ctx, xp) \
            .astype(np.uint32)
        h2 = murmur3_col(tc, dt, np.uint32(0x85EBCA6B), ctx, xp) \
            .astype(np.uint32) | np.uint32(1)
        mask = np.uint32(self.num_bits - 1)
        return [((h1 + np.uint32(i) * h2) & mask)
                for i in range(self.num_hashes)]

    def _add_host(self, tc: TCol, ctx) -> None:
        valid = np.asarray(valid_array(tc, ctx))
        for pos in self._positions(tc, ctx, np):
            p = np.asarray(pos)[valid]
            np.bitwise_or.at(self.words, p >> 5,
                             np.uint32(1) << (p & np.uint32(31)))

    # -- probe ---------------------------------------------------------------
    def might_contain_kernel(self, tc: TCol, ctx, xp):
        """bool array: True unless definitely absent."""
        words = xp.asarray(self.words)
        out = None
        for pos in self._positions(tc, ctx, xp):
            w = xp.take(words, (pos >> np.uint32(5)).astype(np.int32))
            bit = (w >> (pos & np.uint32(31))) & np.uint32(1)
            hit = bit != 0
            out = hit if out is None else (out & hit)
        return out

    @property
    def saturation(self) -> float:
        return float(np.unpackbits(self.words.view(np.uint8)).mean())


class BloomMightContain(Expression):
    """might_contain(bloom, value): null-in-null-out probe expression
    (reference: GpuBloomFilterMightContain)."""

    def __init__(self, bloom: BloomFilter, child: Expression):
        super().__init__([child])
        self.bloom = bloom

    @property
    def data_type(self):
        return T.BOOLEAN

    def sql(self):
        return (f"might_contain(bloom[{self.bloom.num_bits}b], "
                f"{self.children[0].sql()})")

    def _eval(self, ctx, xp):
        tc = self.children[0].eval(ctx)
        out = self.bloom.might_contain_kernel(tc, ctx, xp)
        return TCol(out, valid_array(tc, ctx), T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_expr  # noqa: E402

register_expr(BloomMightContain, TS.ALL_BASIC)
