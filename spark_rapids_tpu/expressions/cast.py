"""Cast expression and the castability matrix.

Reference: ``GpuCast.scala`` (1903 LoC) + ``CastStrings`` JNI + the
``CastChecks`` table (TypeChecks.scala:1277).  Spark non-ANSI semantics:
invalid string parses yield NULL; float->int saturates at the target range
(Java semantics) with NaN -> 0.

Device support notes (TPU-first):
- numeric<->numeric, bool<->numeric, date<->timestamp: pure jnp, fuse freely.
- int->string and string->int run on device with digit kernels over the
  padded string rectangle (vectorizes on VPU lanes).
- float<->string and timestamp/date<->string parse/format on host (tagged via
  ``tpu_supported``), mirroring the reference's choice to keep the hairiest
  string casts behind flags (docs/compatibility.md "%g formatting" caveats).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               jnp, materialize, valid_array)

_SECONDS_TO_MICROS = 1_000_000
_DAY_MICROS = 86_400 * _SECONDS_TO_MICROS


class Cast(Expression):
    def __init__(self, child: Expression, to: T.DataType):
        super().__init__([child])
        self.to = to

    @property
    def child(self):
        return self.children[0]

    @property
    def data_type(self):
        return self.to

    def sql(self):
        return f"CAST({self.child.sql()} AS {self.to.simple_name})"

    def with_children(self, children):
        return Cast(children[0], self.to)

    # -- planner tagging ----------------------------------------------------
    def tpu_supported(self, conf):
        src, dst = self.child.data_type, self.to
        if isinstance(src, T.NullType):
            return None
        if isinstance(src, T.DecimalType) and \
                isinstance(dst, T.DecimalType) and \
                src.scale == dst.scale and dst.precision >= src.precision:
            # same-scale precision widening: pure limb sign-extension,
            # never overflows (the decimal sum buffer cast)
            return None
        if src.is_numeric and dst.is_numeric and not (
                isinstance(src, T.DecimalType) or isinstance(dst, T.DecimalType)):
            return None
        if isinstance(src, T.BooleanType) or isinstance(dst, T.BooleanType):
            return None
        if isinstance(src, (T.DateType, T.TimestampType)) and \
                isinstance(dst, (T.DateType, T.TimestampType)):
            return None
        if src.is_integral and isinstance(dst, T.StringType):
            return None
        if isinstance(src, T.StringType) and dst.is_integral:
            return None
        if src == dst:
            return None
        return f"cast {src} -> {dst} runs on host"

    # -- evaluation ---------------------------------------------------------
    def _eval(self, ctx: EvalContext, xp) -> TCol:
        c = self.child.eval(ctx)
        src, dst = self.child.data_type, self.to
        if src == dst or isinstance(dst, T.NullType):
            return c
        if c.is_scalar:
            return self._cast_scalar(c, src, dst)
        if isinstance(src, T.NullType):
            nd = dst.np_dtype or np.dtype(object)
            if ctx.backend == "tpu" and isinstance(dst, (T.StringType, T.BinaryType)):
                z = xp.zeros((ctx.row_count, 8), dtype=np.uint8)
                zl = xp.zeros(ctx.row_count, dtype=np.int32)
                return TCol(z, xp.zeros(ctx.row_count, dtype=bool), dst, lengths=zl)
            data = (np.full(ctx.row_count, None, dtype=object)
                    if nd == np.dtype(object)
                    else xp.zeros(ctx.row_count, dtype=nd))
            return TCol(data, xp.zeros(ctx.row_count, dtype=bool), dst)
        if ctx.backend == "tpu":
            return self._cast_device(c, src, dst, ctx, xp)
        return self._cast_host(c, src, dst, ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)

    # -- scalar -------------------------------------------------------------
    def _cast_scalar(self, c: TCol, src, dst) -> TCol:
        if not c.valid or c.data is None:
            return TCol.scalar(None, dst)
        v = c.data
        out = _cast_py_value(v, src, dst)
        return TCol.scalar(out, dst)

    # -- device kernels -----------------------------------------------------
    def _cast_device(self, c: TCol, src, dst, ctx, xp) -> TCol:
        if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType) \
                and src.scale == dst.scale and \
                dst.precision >= src.precision:
            # same-scale widening: decimal64 -> [hi=sign, lo] limbs; a
            # decimal128 source already carries the target layout
            if src.is_decimal128 == dst.is_decimal128:
                return TCol(c.data, c.valid, dst)
            lo = c.data.astype(np.int64)
            hi = xp.right_shift(lo, np.int64(63))   # arithmetic: sign
            return TCol(xp.stack([hi, lo], axis=1), c.valid, dst)
        if src.is_numeric and dst.is_numeric:
            return TCol(_numeric_cast_dev(c.data, src, dst, xp), c.valid, dst)
        if isinstance(src, T.BooleanType) and dst.is_numeric:
            return TCol(c.data.astype(dst.np_dtype), c.valid, dst)
        if src.is_numeric and isinstance(dst, T.BooleanType):
            return TCol(c.data != 0, c.valid, dst)
        if isinstance(src, T.BooleanType) and isinstance(dst, T.StringType):
            return _bool_to_string_dev(c, ctx, xp)
        if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
            days = xp.floor_divide(c.data, _DAY_MICROS).astype(np.int32)
            return TCol(days, c.valid, dst)
        if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
            return TCol(c.data.astype(np.int64) * _DAY_MICROS, c.valid, dst)
        if src.is_numeric and isinstance(dst, T.TimestampType):
            micros = (c.data.astype(np.float64) * _SECONDS_TO_MICROS) \
                if src.is_floating else (c.data.astype(np.int64) * _SECONDS_TO_MICROS)
            return TCol(xp.asarray(micros).astype(np.int64), c.valid, dst)
        if isinstance(src, T.TimestampType) and dst.is_numeric:
            secs = xp.floor_divide(c.data, _SECONDS_TO_MICROS)
            return TCol(_numeric_cast_dev(secs, T.LONG, dst, xp), c.valid, dst)
        if src.is_integral and isinstance(dst, T.StringType):
            return _int_to_string_dev(c, dst, xp)
        if isinstance(src, T.StringType) and dst.is_integral:
            return _string_to_int_dev(c, dst, xp)
        raise NotImplementedError(f"device cast {src} -> {dst}")

    # -- host path (oracle + fallback for hairy casts) ----------------------
    def _cast_host(self, c: TCol, src, dst, ctx) -> TCol:
        data, valid = c.data, valid_array(c, ctx)
        n = len(valid)
        if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType) \
                and src.scale == dst.scale and \
                dst.precision >= src.precision:
            # same-scale widening on the host: EXACT unscaled ints — the
            # generic numeric branch below would route through float64
            # and corrupt values past 2^53 (host decimal128 repr = object
            # array of python ints; decimal64 = int64)
            if dst.is_decimal128 and not src.is_decimal128:
                out = np.empty(n, dtype=object)
                for i in range(n):
                    out[i] = int(data[i]) if valid[i] else 0
                return TCol(out, c.valid, dst)
            return TCol(data, c.valid, dst)
        if src.is_numeric and dst.is_numeric:
            return TCol(_numeric_cast_dev(data, src, dst, np), c.valid, dst)
        if isinstance(src, T.BooleanType) and dst.is_numeric:
            return TCol(data.astype(dst.np_dtype), c.valid, dst)
        if src.is_numeric and isinstance(dst, T.BooleanType):
            return TCol(data != 0, c.valid, dst)
        if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
            return TCol(np.floor_divide(data, _DAY_MICROS).astype(np.int32),
                        c.valid, dst)
        if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
            return TCol(data.astype(np.int64) * _DAY_MICROS, c.valid, dst)
        if src.is_numeric and isinstance(dst, T.TimestampType):
            return TCol((data.astype(np.float64) * _SECONDS_TO_MICROS)
                        .astype(np.int64), c.valid, dst)
        if isinstance(src, T.TimestampType) and dst.is_numeric:
            secs = np.floor_divide(data, _SECONDS_TO_MICROS)
            return TCol(_numeric_cast_dev(secs, T.LONG, dst, np), c.valid, dst)
        if isinstance(dst, T.StringType):
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = _cast_py_value(_host_value(data, i, src), src, dst) \
                    if valid[i] else None
            return TCol(out, valid, dst)
        if isinstance(src, T.StringType):
            import datetime as _dt
            out_nd = dst.np_dtype or np.dtype(object)
            out = np.zeros(n, dtype=out_nd)
            ok = np.zeros(n, dtype=bool)
            for i in range(n):
                if not valid[i] or data[i] is None:
                    continue
                v = _cast_py_value(data[i], src, dst)
                if v is None:
                    continue
                # parsed python dates/timestamps land in their PHYSICAL
                # int representation (the CPU backend's convention)
                if isinstance(v, _dt.datetime):
                    import calendar
                    v = int(calendar.timegm(v.utctimetuple())) \
                        * 1_000_000 + v.microsecond
                elif isinstance(v, _dt.date):
                    v = (v - _dt.date(1970, 1, 1)).days
                out[i] = v
                ok[i] = True
            return TCol(out, ok, dst)
        raise NotImplementedError(f"host cast {src} -> {dst}")


def _host_value(data, i, src):
    return data[i]


def _numeric_cast_dev(data, src: T.DataType, dst: T.DataType, xp):
    nd = dst.np_dtype
    if src.is_floating and dst.is_integral:
        # Java semantics: NaN -> 0, saturate at target bounds, trunc toward 0
        info = np.iinfo(nd)
        x = xp.nan_to_num(data, nan=0.0, posinf=float(info.max),
                          neginf=float(info.min))
        x = xp.clip(xp.trunc(x), float(info.min), float(info.max))
        return x.astype(nd)
    return data.astype(nd)


def _cast_py_value(v, src: T.DataType, dst: T.DataType):
    """Python-level single-value cast (scalars + host string paths)."""
    import datetime
    if isinstance(dst, T.StringType):
        if isinstance(src, T.BooleanType):
            return "true" if v else "false"
        if isinstance(src, T.FloatType) or isinstance(src, T.DoubleType):
            return _format_float(float(v))
        if isinstance(src, T.DateType):
            if isinstance(v, (int, np.integer)):
                v = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
            return v.isoformat()
        if isinstance(src, T.TimestampType):
            if isinstance(v, (int, np.integer)):
                v = (datetime.datetime(1970, 1, 1) +
                     datetime.timedelta(microseconds=int(v)))
            s = v.strftime("%Y-%m-%d %H:%M:%S")
            if v.microsecond:
                s += f".{v.microsecond:06d}".rstrip("0")
            return s
        if isinstance(src, T.DecimalType):
            return str(v)
        return str(v)
    if isinstance(src, T.StringType):
        s = str(v).strip()
        try:
            if isinstance(dst, T.BooleanType):
                low = s.lower()
                if low in ("t", "true", "y", "yes", "1"):
                    return True
                if low in ("f", "false", "n", "no", "0"):
                    return False
                return None
            if dst.is_integral:
                if not s:
                    return None
                # Spark accepts trailing .0 forms via decimal parse
                iv = int(s, 10) if _INT_RE.match(s) else None
                if iv is None:
                    return None
                info = np.iinfo(dst.np_dtype)
                return iv if info.min <= iv <= info.max else None
            if dst.is_floating:
                return float(s)
            if isinstance(dst, T.DateType):
                return _parse_spark_date(s)
            if isinstance(dst, T.TimestampType):
                return _parse_spark_timestamp(s)
            if isinstance(dst, T.DecimalType):
                import decimal
                return decimal.Decimal(s)
        except (ValueError, ArithmeticError):
            return None
    if src.is_numeric and dst.is_numeric:
        arr = _numeric_cast_dev(np.asarray(v), src, dst, np)
        out = arr[()]
        return out.item() if hasattr(out, "item") else out
    if isinstance(src, T.BooleanType) and dst.is_numeric:
        return dst.np_dtype.type(1 if v else 0).item()
    if src.is_numeric and isinstance(dst, T.BooleanType):
        return bool(v)
    if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
        return int(v) // _DAY_MICROS if isinstance(v, (int, np.integer)) \
            else v.date()
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        if isinstance(v, (int, np.integer)):
            return int(v) * _DAY_MICROS
        if isinstance(v, datetime.datetime):
            return v
        # datetime.date -> midnight UTC; returning the date unchanged would
        # materialize as DAYS inside a micros-typed timestamp column
        return datetime.datetime(v.year, v.month, v.day,
                                 tzinfo=datetime.timezone.utc)
    if src.is_numeric and isinstance(dst, T.TimestampType):
        return int(float(v) * _SECONDS_TO_MICROS)
    if isinstance(src, T.TimestampType) and dst.is_numeric:
        return int(v) // _SECONDS_TO_MICROS
    raise NotImplementedError(f"scalar cast {src} -> {dst}")


def _parse_spark_date(s: str):
    """Spark date cast accepts [y]yyyy-[m]m-[d]d (+ optional trailing
    time/junk after the date, which Spark truncates)."""
    import datetime
    m = _DATE_RE.match(s)
    if not m:
        return None
    return datetime.date(int(m.group(1)), int(m.group(2)),
                         int(m.group(3)))


def _parse_spark_timestamp(s: str):
    """yyyy-[m]m-[d]d[ T][h]h:[m]m:[s]s[.fraction] (Spark cast subset)."""
    import datetime
    m = _TS_RE.match(s)
    if not m:
        d = _parse_spark_date(s)
        if d is None:
            return None
        return datetime.datetime(d.year, d.month, d.day,
                                 tzinfo=datetime.timezone.utc)
    frac = (m.group(7) or "").ljust(6, "0")[:6]
    return datetime.datetime(int(m.group(1)), int(m.group(2)),
                             int(m.group(3)), int(m.group(4)),
                             int(m.group(5)), int(m.group(6)),
                             int(frac or 0),
                             tzinfo=datetime.timezone.utc)


import re  # noqa: E402

_DATE_RE = re.compile(r"^(\d{4})-(\d{1,2})-(\d{1,2})(?:[ T].*)?$")
_TS_RE = re.compile(
    r"^(\d{4})-(\d{1,2})-(\d{1,2})[ T](\d{1,2}):(\d{1,2}):(\d{1,2})"
    r"(?:\.(\d{1,6}))?\s*(?:Z|UTC)?$")

_INT_RE = re.compile(r"^[+-]?\d+$")


def _format_float(f: float) -> str:
    """Approximates Java Double.toString (documented deviation like the
    reference's castFloatToString, docs/compatibility.md)."""
    import math
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "Infinity" if f > 0 else "-Infinity"
    if f == int(f) and abs(f) < 1e16:
        return f"{f:.1f}"
    return repr(f)


# ---------------------------------------------------------------------------
# Device string cast kernels
# ---------------------------------------------------------------------------

_MAX_INT_DIGITS = 20  # int64 min has 19 digits + sign


def _int_to_string_dev(c: TCol, dst, xp) -> TCol:
    """int -> decimal string, fully vectorized digit extraction."""
    v = c.data.astype(np.int64)
    neg = v < 0
    # magnitude as uint64 (handles int64 min without overflow)
    mag = xp.where(neg, (~v).astype(np.uint64) + np.uint64(1),
                   v.astype(np.uint64))
    pows = np.power(np.uint64(10), np.arange(_MAX_INT_DIGITS - 1, -1, -1,
                                             dtype=np.uint64),
                    dtype=np.uint64)
    digits = (mag[:, None] // pows[None, :]) % np.uint64(10)  # [n, 20] MSD first
    nonzero = digits != 0
    has_any = xp.any(nonzero, axis=1)
    first = xp.argmax(nonzero, axis=1)
    ndig = xp.where(has_any, _MAX_INT_DIGITS - first, 1)
    total_len = ndig + neg.astype(np.int32)
    width = _MAX_INT_DIGITS + 1
    # output position j takes digit at (first + j - neg_offset)
    j = xp.arange(width)[None, :]
    src_idx = first[:, None] + j - neg[:, None].astype(np.int32)
    src_idx_c = xp.clip(src_idx, 0, _MAX_INT_DIGITS - 1)
    gathered = xp.take_along_axis(digits.astype(np.uint8), src_idx_c, axis=1)
    chars = gathered + np.uint8(ord("0"))
    chars = xp.where((j == 0) & neg[:, None], np.uint8(ord("-")), chars)
    in_range = j < total_len[:, None]
    chars = xp.where(in_range, chars, np.uint8(0))
    return TCol(chars, c.valid, dst, lengths=total_len.astype(np.int32))


def _bool_to_string_dev(c: TCol, ctx, xp) -> TCol:
    tmpl_true = np.frombuffer(b"true\x00\x00\x00\x00", dtype=np.uint8)
    tmpl_false = np.frombuffer(b"false\x00\x00\x00", dtype=np.uint8)
    chars = xp.where(c.data[:, None], xp.asarray(tmpl_true)[None, :],
                     xp.asarray(tmpl_false)[None, :])
    lens = xp.where(c.data, 4, 5).astype(np.int32)
    return TCol(chars, c.valid, T.STRING, lengths=lens)


def _string_to_int_dev(c: TCol, dst, xp) -> TCol:
    """string -> integer parse with NULL on invalid, vectorized.

    Handles optional leading +/-, ASCII digits, surrounding spaces.  Overflow
    beyond int64 is not detected (wraps), matching our non-ANSI contract.
    """
    chars = c.data
    lens = c.lengths
    n, w = chars.shape
    pos = xp.arange(w)[None, :]
    in_len = pos < lens[:, None]
    is_space = (chars == 32) | (chars == 9)
    # strip: leading spaces before sign/digits, trailing spaces after
    non_space = (~is_space) & in_len
    any_ns = xp.any(non_space, axis=1)
    start = xp.argmax(non_space, axis=1)
    # last non-space: argmax over reversed
    rev_ns = non_space[:, ::-1]
    last = w - 1 - xp.argmax(rev_ns, axis=1)
    sign_char = xp.take_along_axis(chars, start[:, None], axis=1)[:, 0]
    neg = sign_char == ord("-")
    signed = neg | (sign_char == ord("+"))
    dstart = start + signed.astype(np.int32)
    is_digit = (chars >= ord("0")) & (chars <= ord("9"))
    in_num = (pos >= dstart[:, None]) & (pos <= last[:, None])
    valid_parse = any_ns & (last >= dstart) & \
        xp.all(is_digit | ~in_num, axis=1)
    digit_vals = xp.where(in_num & is_digit, (chars - ord("0")).astype(np.int64),
                          xp.zeros_like(chars, dtype=np.int64))
    # place value: 10^(last - pos) for positions within the number
    exp = xp.clip(last[:, None] - pos, 0, _MAX_INT_DIGITS - 1)
    pows = np.power(np.int64(10), np.arange(_MAX_INT_DIGITS, dtype=np.int64))
    place = xp.asarray(pows)[exp]
    total = xp.sum(digit_vals * place * in_num, axis=1)
    total = xp.where(neg, -total, total)
    valid = c.valid & valid_parse
    info = np.iinfo(dst.np_dtype)
    if dst.np_dtype != np.dtype(np.int64):
        in_range = (total >= info.min) & (total <= info.max)
        valid = valid & in_range
    return TCol(total.astype(dst.np_dtype), valid, dst)
