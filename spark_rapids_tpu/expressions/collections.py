"""Collection expressions: arrays, structs, maps, higher-order functions.

Reference: collectionOperations.scala (GpuSize/GpuArrayContains/GpuSortArray/
GpuSlice/GpuElementAt...), complexTypeCreator.scala (GpuCreateArray/
GpuCreateNamedStruct/GpuCreateMap), complexTypeExtractors.scala
(GpuGetStructField/GpuGetArrayItem), higherOrderFunctions.scala
(GpuArrayTransform/GpuArrayExists/GpuArrayFilter/GpuArrayAggregate).

TPU design: arrays of fixed-width scalars live as a padded rectangular plane
(values [bucket, w] + lengths + element validity) — see DeviceColumn — so
every array kernel below is pure elementwise/segmented jnp math over 2-D
arrays and fuses into the surrounding XLA program.  The SAME kernel bodies
serve the CPU oracle: the host backend rectangularizes the python lists,
runs the numpy twin, and re-raggedizes.  Struct and map compute stays on the
host tier (honest fallback tagging, as the reference does for types cuDF
cannot represent).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (EvalContext, Expression, TCol,
                                               jnp, valid_array)


# ---------------------------------------------------------------------------
# rectangular <-> ragged bridges (CPU backend)
# ---------------------------------------------------------------------------

def _elem_np(elem: T.DataType):
    from spark_rapids_tpu.columnar.column import _elem_np_dtype
    return _elem_np_dtype(elem)


def _rect_cpu(tc: TCol, ctx: EvalContext):
    """Object-array-of-lists -> (vals [n, w], lens, elem_valid) numpy."""
    n = ctx.row_count
    dt = tc.dtype
    assert isinstance(dt, T.ArrayType)
    if tc.is_scalar:
        lst = [tc.data if tc.valid else None] * n
    else:
        lst = [tc.data[i] for i in range(n)]
    lens = np.zeros(n, dtype=np.int32)
    for i, v in enumerate(lst):
        if v is not None:
            lens[i] = len(v)
    w = max(1, int(lens.max()) if n else 1)
    edt = _elem_np(dt.element_type) or np.dtype(object)
    vals = np.zeros((n, w), dtype=edt) if edt != np.dtype(object) \
        else np.empty((n, w), dtype=object)
    ev = np.zeros((n, w), dtype=bool)
    for i, v in enumerate(lst):
        if v is None:
            continue
        for j, e in enumerate(v):
            if e is not None:
                vals[i, j] = _to_phys(e, dt.element_type)
                ev[i, j] = True
    return vals, lens, ev


def _to_phys(v, elem: T.DataType):
    import datetime
    if isinstance(elem, T.DateType) and isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(elem, T.TimestampType) and isinstance(v, datetime.datetime):
        import calendar
        return int(calendar.timegm(v.utctimetuple())) * 1_000_000 \
            + v.microsecond
    return v


def _from_phys(v, elem: T.DataType):
    import datetime
    if elem.np_dtype is None and not isinstance(
            elem, (T.DateType, T.TimestampType)):
        return v   # host-only element types (strings/nested) pass through
    if isinstance(elem, T.DateType):
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
    if isinstance(elem, T.TimestampType):
        return (datetime.datetime(1970, 1, 1,
                                  tzinfo=datetime.timezone.utc)
                + datetime.timedelta(microseconds=int(v)))
    if isinstance(elem, T.BooleanType):
        return bool(v)
    if isinstance(elem, (T.FloatType, T.DoubleType)):
        return float(v)
    return int(v)


def _ragged_cpu(vals, lens, ev, valid, dt: T.ArrayType):
    """(vals, lens, elem_valid) -> object array of python lists."""
    n = len(lens)
    out = np.empty(n, dtype=object)
    for i in range(n):
        if not valid[i]:
            out[i] = None
            continue
        out[i] = [(_from_phys(vals[i, j], dt.element_type)
                   if ev[i, j] else None) for j in range(int(lens[i]))]
    return out


def _array_parts(tc: TCol, ctx: EvalContext):
    """(vals, lens, elem_valid, row_valid) in the backend's array module."""
    if ctx.backend == "tpu":
        return tc.data, tc.lengths, tc.elem_valid, valid_array(tc, ctx)
    vals, lens, ev = _rect_cpu(tc, ctx)
    return vals, lens, ev, valid_array(tc, ctx)


def _array_result(vals, lens, ev, valid, dt: T.ArrayType, ctx: EvalContext
                  ) -> TCol:
    if ctx.backend == "tpu":
        return TCol(vals, valid, dt, lengths=lens, elem_valid=ev)
    return TCol(_ragged_cpu(vals, np.asarray(lens), np.asarray(ev),
                            np.asarray(valid), dt), valid, dt)


def _xp(ctx):
    return jnp() if ctx.backend == "tpu" else np


def _positions(xp, shape):
    """[n, w] matrix of element ordinals (iota over the element axis)."""
    return xp.broadcast_to(xp.arange(shape[1], dtype=np.int32), shape)


def _scalar_or_col(tc: TCol, ctx, xp, np_dtype):
    from spark_rapids_tpu.expressions.base import materialize
    return materialize(tc, ctx, np_dtype)[:, None] if not tc.is_scalar \
        else (xp.zeros((ctx.row_count, 1), dtype=np_dtype) + (
            tc.data if tc.valid else 0))


# ---------------------------------------------------------------------------
# basic array expressions
# ---------------------------------------------------------------------------

class _ArrayExpr(Expression):
    """Base: first child must be an array."""

    def _check_array_child(self) -> Optional[str]:
        dt = self.children[0].data_type
        if not isinstance(dt, T.ArrayType):
            return f"{self.name} needs an array input, got {dt.simple_name}"
        return None

    def tpu_supported(self, conf):
        from spark_rapids_tpu.columnar.column import is_device_array_type
        r = self._check_array_child()
        if r is not None:
            return r
        if not is_device_array_type(self.children[0].data_type):
            return ("array element type "
                    f"{self.children[0].data_type.element_type.simple_name} "
                    "is host-only")
        return None

    def eval_tpu(self, ctx):
        return self._eval(ctx)

    def eval_cpu(self, ctx):
        return self._eval(ctx)


class Size(_ArrayExpr):
    """size(arr): element count; -1 for null input (Spark legacy default,
    reference GpuSize)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def data_type(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def _eval(self, ctx):
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        if ctx.backend == "cpu" and not tc.is_scalar:
            # lengths come straight off the lists; no rectangularization
            valid = valid_array(tc, ctx)
            out = np.full(ctx.row_count, -1, dtype=np.int32)
            for i in range(ctx.row_count):
                if valid[i] and tc.data[i] is not None:
                    out[i] = len(tc.data[i])
            return TCol(out, np.ones(ctx.row_count, dtype=bool), T.INT)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        out = xp.where(valid, xp.asarray(lens, dtype=np.int32),
                       np.int32(-1))
        return TCol(out, xp.ones(ctx.row_count, dtype=bool), T.INT)


class GetArrayItem(_ArrayExpr):
    """arr[i]: 0-based ordinal; null when out of bounds or element null
    (reference GpuGetArrayItem)."""

    def __init__(self, child, ordinal):
        super().__init__([child, ordinal])
        self._one_based = False

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def _eval(self, ctx):
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        idx_tc = self.children[1].eval(ctx)
        idx = _scalar_or_col(idx_tc, ctx, xp, np.dtype(np.int64))[:, 0]
        idx_valid = valid_array(idx_tc, ctx)
        lens64 = xp.asarray(lens, dtype=np.int64)
        if self._one_based:
            # element_at: 1-based, negative counts from the end, 0 errors
            eff = xp.where(idx > 0, idx - 1, lens64 + idx)
        else:
            eff = idx
        in_bounds = (eff >= 0) & (eff < lens64)
        safe = xp.clip(eff, 0, max(1, vals.shape[1]) - 1).astype(np.int64)
        data = xp.take_along_axis(vals, safe[:, None], axis=1)[:, 0]
        evv = xp.take_along_axis(ev, safe[:, None], axis=1)[:, 0]
        ok = valid & idx_valid & in_bounds & evv
        # both backends use the physical fixed-width repr for elements
        return TCol(data, ok, self.data_type)


class ElementAt(GetArrayItem):
    """element_at(arr, i): 1-based, negative from end (reference
    GpuElementAt; non-ANSI null-on-out-of-bounds semantics)."""

    def __init__(self, child, ordinal):
        super().__init__(child, ordinal)
        self._one_based = True


class ArrayContains(_ArrayExpr):
    """array_contains(arr, v) with Spark's three-valued result: true when
    found; null when not found but the array has null elements (or inputs
    are null); false otherwise (reference GpuArrayContains)."""

    def __init__(self, child, value):
        super().__init__([child, value])

    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx):
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        v_tc = self.children[1].eval(ctx)
        v = _scalar_or_col(v_tc, ctx, xp, vals.dtype)
        v_valid = valid_array(v_tc, ctx)
        pos = _positions(xp, vals.shape)
        in_len = pos < xp.asarray(lens, dtype=np.int32)[:, None]
        hit = (vals == v) & ev & in_len
        found = hit.any(axis=1)
        has_null_elem = ((~ev) & in_len).any(axis=1)
        out_valid = valid & v_valid & (found | ~has_null_elem)
        return TCol(found, out_valid, T.BOOLEAN)


class _ArrayMinMax(_ArrayExpr):
    is_max = False

    def __init__(self, child):
        super().__init__([child])

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def _eval(self, ctx):
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        if ctx.backend == "cpu" and vals.dtype == np.dtype(object):
            # host-only element types: per-row python min/max
            out = np.empty(ctx.row_count, dtype=object)
            ok = np.zeros(ctx.row_count, dtype=bool)
            for i in range(ctx.row_count):
                if not valid[i]:
                    continue
                live_vals = [vals[i, j] for j in range(int(lens[i]))
                             if ev[i, j]]
                if live_vals:
                    out[i] = max(live_vals) if self.is_max else min(live_vals)
                    ok[i] = True
            return TCol(out, ok, self.data_type)
        pos = _positions(xp, vals.shape)
        live = ev & (pos < xp.asarray(lens, dtype=np.int32)[:, None])
        any_live = live.any(axis=1)
        fdt = vals.dtype
        if self.is_max:
            neutral = np.finfo(fdt).min if fdt.kind == "f" else \
                (np.iinfo(fdt).min if fdt.kind in "iu" else False)
            masked = xp.where(live, vals, xp.asarray(neutral, dtype=fdt))
            agg = masked.max(axis=1)
        else:
            neutral = np.finfo(fdt).max if fdt.kind == "f" else \
                (np.iinfo(fdt).max if fdt.kind in "iu" else True)
            masked = xp.where(live, vals, xp.asarray(neutral, dtype=fdt))
            agg = masked.min(axis=1)
        ok = valid & any_live
        return TCol(agg, ok, self.data_type)


class ArrayMin(_ArrayMinMax):
    is_max = False


class ArrayMax(_ArrayMinMax):
    is_max = True


class SortArray(_ArrayExpr):
    """sort_array(arr, asc): per-row element sort; nulls first when
    ascending, last when descending (Spark semantics; reference
    GpuSortArray)."""

    def __init__(self, child, ascending=None):
        from spark_rapids_tpu.expressions.base import Literal
        if ascending is None:
            ascending = Literal(True, T.BOOLEAN)
        super().__init__([child, ascending])

    @property
    def data_type(self):
        return self.children[0].data_type

    def _extra_check(self):
        from spark_rapids_tpu.expressions.base import Literal
        if not isinstance(self.children[1], Literal):
            return "sort_array order must be a literal boolean"
        return None

    def tpu_supported(self, conf):
        return super().tpu_supported(conf) or self._extra_check()

    def _eval(self, ctx):
        xp = _xp(ctx)
        asc = bool(self.children[1].value)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        if ctx.backend == "cpu" and vals.dtype == np.dtype(object):
            # host-only element types: per-row python sort, Spark null
            # placement (nulls first asc, last desc)
            out = np.empty(ctx.row_count, dtype=object)
            for i in range(ctx.row_count):
                if not valid[i]:
                    out[i] = None
                    continue
                row = [vals[i, j] if ev[i, j] else None
                       for j in range(int(lens[i]))]
                nn = sorted([v for v in row if v is not None], reverse=not asc)
                nulls = [None] * (len(row) - len(nn))
                out[i] = nulls + nn if asc else nn + nulls
            return TCol(out, valid, self.data_type)
        pos = _positions(xp, vals.shape)
        in_len = pos < xp.asarray(lens, dtype=np.int32)[:, None]
        live = ev & in_len
        fdt = vals.dtype
        big = np.finfo(fdt).max if fdt.kind == "f" else \
            (np.iinfo(fdt).max if fdt.kind in "iu" else True)
        small = np.finfo(fdt).min if fdt.kind == "f" else \
            (np.iinfo(fdt).min if fdt.kind in "iu" else False)
        if asc:
            # nulls first: nulls -> -inf tier, padding -> +inf tier
            key = xp.where(live, vals, xp.asarray(small, dtype=fdt))
            key = xp.where(in_len & ~ev, xp.asarray(small, dtype=fdt), key)
            key = xp.where(~in_len, xp.asarray(big, dtype=fdt), key)
            tier = xp.where(live, 1, xp.where(in_len, 0, 2))
        else:
            key = xp.where(live, vals, xp.asarray(big, dtype=fdt))
            tier = xp.where(live, 0, xp.where(in_len, 1, 2))
        # lexicographic (tier, key): sort by key then stable-sort by tier
        order = xp.argsort(key, axis=1, stable=True)
        if not asc:
            order = order[:, ::-1]
        t2 = xp.take_along_axis(tier, order, axis=1)
        order2 = xp.argsort(t2, axis=1, stable=True)
        final = xp.take_along_axis(order, order2, axis=1)
        nv = xp.take_along_axis(vals, final, axis=1)
        ne = xp.take_along_axis(live, final, axis=1)
        return _array_result(nv, lens, ne, valid, self.data_type, ctx)


class Slice(_ArrayExpr):
    """slice(arr, start, length): 1-based start, negative from end
    (reference GpuSlice)."""

    def __init__(self, child, start, length):
        super().__init__([child, start, length])

    @property
    def data_type(self):
        return self.children[0].data_type

    def _eval(self, ctx):
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        st_tc = self.children[1].eval(ctx)
        ln_tc = self.children[2].eval(ctx)
        start = _scalar_or_col(st_tc, ctx, xp, np.dtype(np.int64))
        length = _scalar_or_col(ln_tc, ctx, xp, np.dtype(np.int64))
        lens64 = xp.asarray(lens, dtype=np.int64)[:, None]
        # Spark ArraySlice.semanticSlice: a resolved start outside
        # [0, len) yields an EMPTY array (no clamping), so all kept rows
        # have 0 <= eff < len and the gather is front-aligned
        eff = xp.where(start > 0, start - 1, lens64 + start)
        take = xp.clip(length, 0, None)
        in_range = (eff >= 0) & (eff < lens64)
        new_len = xp.where(in_range[:, 0],
                           xp.minimum(take[:, 0], lens64[:, 0] - eff[:, 0]),
                           0).astype(np.int32)
        pos = _positions(xp, vals.shape).astype(np.int64)
        src = xp.clip(pos + xp.where(in_range, eff, 0), 0,
                      vals.shape[1] - 1)
        in_slice = pos < new_len[:, None]
        nv = xp.take_along_axis(vals, src, axis=1)
        ne = xp.take_along_axis(ev, src, axis=1) & in_slice
        # start=0 or negative length -> null row (Spark errors in ANSI;
        # null here, like non-ANSI out-of-range element_at)
        ok = valid & valid_array(st_tc, ctx) & valid_array(ln_tc, ctx) \
            & (start[:, 0] != 0) & (length[:, 0] >= 0)
        return _array_result(nv, new_len, ne, ok, self.data_type, ctx)


class CreateArray(Expression):
    """array(e1, ..., en) from scalar columns (reference GpuCreateArray)."""

    def __init__(self, *exprs):
        super().__init__(list(exprs))
        if not exprs:
            raise ValueError("array() needs at least one element")

    @property
    def data_type(self):
        dt = self.children[0].data_type
        for c in self.children[1:]:
            dt = T.common_type(dt, c.data_type)
        return T.ArrayType(dt)

    @property
    def nullable(self):
        return False

    def tpu_supported(self, conf):
        from spark_rapids_tpu.columnar.column import is_device_array_type
        if not is_device_array_type(self.data_type):
            return (f"array of {self.data_type.element_type.simple_name} "
                    "is host-only")
        return None

    def _eval(self, ctx):
        from spark_rapids_tpu.expressions.cast import Cast
        xp = _xp(ctx)
        out_dt = self.data_type
        edt = _elem_np(out_dt.element_type)
        cols = []
        for c in self.children:
            if c.data_type != out_dt.element_type:
                c = Cast(c, out_dt.element_type)
            cols.append(c.eval(ctx))
        n = ctx.row_count
        vals = xp.stack([_scalar_or_col(tc, ctx, xp, edt)[:, 0]
                         for tc in cols], axis=1)
        ev = xp.stack([valid_array(tc, ctx) for tc in cols], axis=1)
        lens = xp.full(n, len(cols), dtype=np.int32)
        valid = xp.ones(n, dtype=bool)
        return _array_result(vals, lens, ev, valid, out_dt, ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx)

    def eval_cpu(self, ctx):
        return self._eval(ctx)


class ArrayRepeat(_ArrayExpr):
    """array_repeat(v, n) (reference GpuArrayRepeat)."""

    def __init__(self, value, count):
        Expression.__init__(self, [value, count])

    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type)

    def _check_array_child(self):
        return None

    def tpu_supported(self, conf):
        from spark_rapids_tpu.columnar.column import is_device_array_type
        from spark_rapids_tpu.expressions.base import Literal
        if not is_device_array_type(self.data_type):
            return "array element type is host-only"
        if not isinstance(self.children[1], Literal):
            # the element-plane width is a compile-time shape on the device
            return "array_repeat count must be a literal on the device"
        return None

    def _eval(self, ctx):
        from spark_rapids_tpu.expressions.base import Literal
        xp = _xp(ctx)
        v_tc = self.children[0].eval(ctx)
        n_tc = self.children[1].eval(ctx)
        edt = _elem_np(self.data_type.element_type)
        v = _scalar_or_col(v_tc, ctx, xp, edt)
        cnt = _scalar_or_col(n_tc, ctx, xp, np.dtype(np.int64))[:, 0]
        cnt = xp.clip(cnt, 0, None)
        if isinstance(self.children[1], Literal):
            w = max(1, int(self.children[1].value or 0))
        else:
            w = max(1, int(np.max(np.asarray(cnt))) if ctx.row_count else 1)
        from spark_rapids_tpu.columnar.column import bucket_strlen
        w = bucket_strlen(w)
        pos = xp.broadcast_to(xp.arange(w, dtype=np.int64),
                              (ctx.row_count, w))
        in_len = pos < cnt[:, None]
        vals = xp.broadcast_to(v.astype(edt), (ctx.row_count, w))
        ev = in_len & valid_array(v_tc, ctx)[:, None]
        valid = valid_array(n_tc, ctx)
        return _array_result(vals, cnt.astype(np.int32), ev, valid,
                             self.data_type, ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx)

    eval_cpu = eval_tpu


# ---------------------------------------------------------------------------
# higher-order functions (lambda over the element plane)
# ---------------------------------------------------------------------------

class LambdaVariable(Expression):
    """Named lambda parameter; bound by the enclosing HOF during eval.
    On the device the binding is the full [bucket, w] element plane, so the
    lambda body's elementwise ops fuse over every element at once.

    The dtype is resolved lazily (``typer``) because the HOF's array child
    may still be an unresolved attribute when the lambda body is built."""

    def __init__(self, var_name: str, dtype=None):
        super().__init__()
        self.var_name = var_name
        self._dtype = dtype

    @property
    def data_type(self):
        if self._dtype is None:
            raise TypeError(f"lambda variable {self.var_name} not yet "
                            "typed (bind the enclosing HOF first)")
        return self._dtype

    def sql(self):
        return self.var_name

    def eval_tpu(self, ctx):
        return ctx.lambda_bindings[self.var_name]

    eval_cpu = eval_tpu


def _lambda_ctx(ctx: EvalContext, bindings) -> EvalContext:
    """Context for evaluating a lambda body over the element plane: outer
    column references are lifted to [n, 1] so they broadcast against the
    [n, w] element matrices."""
    xp = _xp(ctx)
    lifted = []
    for c in ctx.cols:
        if c.is_scalar or c.lengths is not None or \
                getattr(c.data, "ndim", 1) != 1:
            lifted.append(c)
        else:
            lifted.append(TCol(c.data[:, None],
                               c.valid if isinstance(c.valid, bool)
                               else c.valid[:, None], c.dtype))
    out = EvalContext(lifted, ctx.backend, ctx.row_count)
    out.lambda_bindings = dict(getattr(ctx, "lambda_bindings", {}))
    out.lambda_bindings.update(bindings)
    out.elem_plane = True
    # promoted-literal slots (plan/stages.py) must survive into the lambda
    # body: the compiled program is cached under a value-independent key,
    # so a dropped binding would bake the FIRST query's constant into a
    # program later queries share
    out.literal_args = getattr(ctx, "literal_args", None)
    return out


class _HigherOrderFn(_ArrayExpr):
    """fn(arr, lambda): children = [array, body]; the body references the
    SHARED LambdaVariable instances ``self.var``/``self.idx_var`` (leaves
    survive tree rewrites untouched, so typing them after reference binding
    reaches the rebound body too)."""

    def __init__(self, child, body_fn):
        import inspect
        super().__init__([child])
        self.var = LambdaVariable("x")
        self.idx_var = LambdaVariable("i", T.INT)
        n_params = len(inspect.signature(body_fn).parameters)
        body = body_fn(self.var, self.idx_var) if n_params >= 2 \
            else body_fn(self.var)
        from spark_rapids_tpu.expressions.base import Expression as E
        if not isinstance(body, E):
            raise TypeError("lambda must build an Expression")
        self.children.append(body)
        self._sync_var_types()

    @property
    def body(self) -> Expression:
        return self.children[1]

    def _sync_var_types(self):
        try:
            dt = self.children[0].data_type
        except TypeError:
            return  # still unresolved; synced again after binding
        if isinstance(dt, T.ArrayType):
            self.var._dtype = dt.element_type

    def tpu_supported(self, conf):
        self._sync_var_types()
        r = super().tpu_supported(conf)
        if r is not None:
            return r
        # the body must be elementwise-safe (no strings/nested inside)
        bad = self.body.collect(
            lambda e: isinstance(e.data_type, (T.StringType, T.BinaryType))
            if not isinstance(e, LambdaVariable) and _has_dtype(e) else False)
        if bad:
            return "lambda body with string ops is host-only"
        return None

    def _body_parts(self, ctx):
        self._sync_var_types()
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        pos = _positions(xp, vals.shape)
        in_len = pos < xp.asarray(lens, dtype=np.int32)[:, None]
        x = TCol(vals, ev & in_len, self.var.data_type)
        i = TCol(pos, in_len, T.INT)
        bctx = _lambda_ctx(ctx, {self.var.var_name: x,
                                 self.idx_var.var_name: i})
        body = self.body.eval(bctx)
        return xp, vals, lens, ev, valid, in_len, body, bctx


def _has_dtype(e):
    try:
        e.data_type
        return True
    except Exception:
        return False


class ArrayTransform(_HigherOrderFn):
    """transform(arr, x -> body) (reference GpuArrayTransform)."""

    @property
    def data_type(self):
        self._sync_var_types()
        return T.ArrayType(self.body.data_type)

    def tpu_supported(self, conf):
        from spark_rapids_tpu.columnar.column import is_device_array_type
        return super().tpu_supported(conf) or (
            None if is_device_array_type(self.data_type)
            else "transform result element type is host-only")

    def _eval(self, ctx):
        xp, vals, lens, ev, valid, in_len, body, _ = self._body_parts(ctx)
        edt = _elem_np(self.body.data_type)
        bd = body.data if not body.is_scalar else \
            xp.zeros(vals.shape, dtype=edt) + (body.data or 0)
        bv = body.valid if not body.is_scalar else \
            xp.full(vals.shape, bool(body.valid))
        if getattr(bd, "ndim", 1) == 1:   # body ignored the element: lift
            bd = xp.broadcast_to(bd[:, None], vals.shape)
            bv = xp.broadcast_to(xp.asarray(bv)[:, None], vals.shape) \
                if getattr(bv, "ndim", 0) == 1 else bv
        return _array_result(bd.astype(edt), lens, bv & in_len, valid,
                             self.data_type, ctx)


class ArrayExists(_HigherOrderFn):
    """exists(arr, x -> pred) (reference GpuArrayExists; 3VL)."""

    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx):
        xp, vals, lens, ev, valid, in_len, body, _ = self._body_parts(ctx)
        pd = body.data & body.valid & in_len
        found = pd.any(axis=1)
        null_pred = (~body.valid) & in_len
        has_null = null_pred.any(axis=1)
        ok = valid & (found | ~has_null)
        return TCol(found, ok, T.BOOLEAN)


class ArrayForAll(_HigherOrderFn):
    """forall(arr, x -> pred)."""

    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx):
        xp, vals, lens, ev, valid, in_len, body, _ = self._body_parts(ctx)
        # Spark 3VL: any genuine false -> false; else any null pred -> null;
        # else true
        genuine_false = (in_len & body.valid & ~body.data).any(axis=1)
        has_null = (in_len & ~body.valid).any(axis=1)
        ok = valid & (genuine_false | ~has_null)
        return TCol(~genuine_false & ~has_null, ok, T.BOOLEAN)


class ArrayFilter(_HigherOrderFn):
    """filter(arr, x -> pred) (reference GpuArrayFilter): keep elements
    where pred is true, compacting within the row."""

    @property
    def data_type(self):
        return self.children[0].data_type

    def _eval(self, ctx):
        xp, vals, lens, ev, valid, in_len, body, _ = self._body_parts(ctx)
        keep = body.data & body.valid & in_len
        # stable within-row compaction: argsort on ~keep
        order = xp.argsort(~keep, axis=1, stable=True)
        nv = xp.take_along_axis(vals, order, axis=1)
        ne = xp.take_along_axis(ev & keep, order, axis=1)
        new_len = keep.sum(axis=1).astype(np.int32)
        return _array_result(nv, new_len, ne, valid, self.data_type, ctx)


class ArrayAggregate(Expression):
    """aggregate(arr, zero, (acc, x) -> merge [, acc -> finish])
    (reference GpuArrayAggregate).  The merge body is applied element by
    element with a statically unrolled loop over the padded width — each
    step is one fused elementwise op over the batch."""

    def __init__(self, child, zero, merge_fn, finish_fn=None):
        super().__init__([child])
        zero = zero if isinstance(zero, Expression) else _lit(zero)
        self.acc_var = LambdaVariable("acc")
        self.x_var = LambdaVariable("x")
        merge = merge_fn(self.acc_var, self.x_var)
        finish = None if finish_fn is None else finish_fn(self.acc_var)
        self.has_finish = finish is not None
        self.children += [zero, merge] + ([finish] if finish is not None
                                          else [])
        self._sync_var_types()

    @property
    def zero(self) -> Expression:
        return self.children[1]

    @property
    def merge(self) -> Expression:
        return self.children[2]

    @property
    def finish(self) -> Optional[Expression]:
        return self.children[3] if self.has_finish else None

    def _sync_var_types(self):
        try:
            dt = self.children[0].data_type
            if isinstance(dt, T.ArrayType):
                self.x_var._dtype = dt.element_type
        except TypeError:
            pass
        try:
            self.acc_var._dtype = self.zero.data_type
        except TypeError:
            pass

    @property
    def data_type(self):
        self._sync_var_types()
        fin = self.finish
        return fin.data_type if fin is not None else self.merge.data_type

    def tpu_supported(self, conf):
        from spark_rapids_tpu.columnar.column import is_device_array_type
        self._sync_var_types()
        dt = self.children[0].data_type
        if not isinstance(dt, T.ArrayType):
            return f"aggregate needs an array input, got {dt.simple_name}"
        if not is_device_array_type(dt):
            return "array element type is host-only"
        return None

    def _eval(self, ctx):
        self._sync_var_types()
        xp = _xp(ctx)
        tc = self.children[0].eval(ctx)
        vals, lens, ev, valid = _array_parts(tc, ctx)
        pos = _positions(xp, vals.shape)
        in_len = pos < xp.asarray(lens, dtype=np.int32)[:, None]
        zero = self.zero.eval(ctx)
        from spark_rapids_tpu.expressions.base import materialize
        acc_d = materialize(zero, ctx,
                            _elem_np(self.zero.data_type))
        acc_v = valid_array(zero, ctx)
        w = vals.shape[1]
        for k in range(w):
            x = TCol(vals[:, k], ev[:, k], self.x_var.data_type)
            acc = TCol(acc_d, acc_v, self.acc_var.data_type)
            # acc/x are 1-D planes like ordinary columns: plain bindings,
            # no [n, 1] lifting (that is only for the [n, w] element HOFs)
            bctx = EvalContext(ctx.cols, ctx.backend, ctx.row_count)
            bctx.lambda_bindings = {"acc": acc, "x": x}
            bctx.literal_args = getattr(ctx, "literal_args", None)
            nxt = self.merge.eval(bctx)
            from spark_rapids_tpu.expressions.base import materialize as mat
            nd = mat(nxt, bctx, _elem_np(self.zero.data_type)) \
                if nxt.is_scalar else nxt.data
            nv = valid_array(nxt, bctx)
            step = in_len[:, k]
            acc_d = xp.where(step, nd, acc_d)
            acc_v = xp.where(step, nv, acc_v)
        out = TCol(acc_d, acc_v & valid, self.zero.data_type)
        if self.finish is not None:
            # acc is an ordinary 1-D column: plain bindings, no lifting
            bctx = EvalContext(ctx.cols, ctx.backend, ctx.row_count)
            bctx.lambda_bindings = {"acc": out}
            bctx.literal_args = getattr(ctx, "literal_args", None)
            out = self.finish.eval(bctx)
        return out

    def eval_tpu(self, ctx):
        return self._eval(ctx)

    eval_cpu = eval_tpu


def _lit(v):
    from spark_rapids_tpu.expressions.base import Literal
    return Literal(v)


# ---------------------------------------------------------------------------
# struct & map expressions (host tier)
# ---------------------------------------------------------------------------

class GetStructField(Expression):
    """struct.field (reference GpuGetStructField).  Host tier: structs have
    no device plane yet."""

    def __init__(self, child, field_name: str):
        super().__init__([child])
        self.field_name = field_name

    @property
    def data_type(self):
        dt = self.children[0].data_type
        if not isinstance(dt, T.StructType):
            raise TypeError(f"GetStructField on {dt.simple_name}")
        return dt.fields[dt.field_index(self.field_name)].data_type

    def sql(self):
        return f"{self.children[0].sql()}.{self.field_name}"

    def tpu_supported(self, conf):
        return "struct field access is host-only"

    def eval_cpu(self, ctx):
        tc = self.children[0].eval(ctx)
        n = ctx.row_count
        valid = valid_array(tc, ctx)
        out = np.empty(n, dtype=object)
        ok = np.zeros(n, dtype=bool)
        for i in range(n):
            if valid[i] and tc.data[i] is not None:
                v = tc.data[i].get(self.field_name)
                out[i] = v
                ok[i] = v is not None
        return _obj_result(out, ok, self.data_type)

    eval_tpu = eval_cpu


class CreateNamedStruct(Expression):
    """named_struct(n1, e1, ...) (reference GpuCreateNamedStruct)."""

    def __init__(self, names: Sequence[str], exprs: Sequence[Expression]):
        super().__init__(list(exprs))
        self.names = list(names)

    @property
    def data_type(self):
        return T.StructType([
            T.StructField(n, e.data_type, e.nullable)
            for n, e in zip(self.names, self.children)])

    @property
    def nullable(self):
        return False

    def tpu_supported(self, conf):
        return "struct construction is host-only"

    def eval_cpu(self, ctx):
        n = ctx.row_count
        vals = [self.children[i].eval(ctx) for i in range(len(self.children))]
        vas = [valid_array(tc, ctx) for tc in vals]
        out = np.empty(n, dtype=object)
        for i in range(n):
            row = {}
            for nm, tc, va in zip(self.names, vals, vas):
                d = tc.data if tc.is_scalar else tc.data[i]
                row[nm] = _pyval(d, va[i], tc.dtype)
            out[i] = row
        return TCol(out, np.ones(n, dtype=bool), self.data_type)

    eval_tpu = eval_cpu


class CreateMap(Expression):
    """map(k1, v1, ...) (reference GpuCreateMap). Host tier."""

    def __init__(self, *kv):
        if len(kv) % 2 or not kv:
            raise ValueError("map() needs key/value pairs")
        super().__init__(list(kv))

    @property
    def data_type(self):
        kt = self.children[0].data_type
        vt = self.children[1].data_type
        for i in range(2, len(self.children), 2):
            kt = T.common_type(kt, self.children[i].data_type)
            vt = T.common_type(vt, self.children[i + 1].data_type)
        return T.MapType(kt, vt)

    @property
    def nullable(self):
        return False

    def tpu_supported(self, conf):
        return "map construction is host-only"

    def eval_cpu(self, ctx):
        n = ctx.row_count
        ks = [self.children[i].eval(ctx)
              for i in range(0, len(self.children), 2)]
        vs = [self.children[i].eval(ctx)
              for i in range(1, len(self.children), 2)]
        kvas = [valid_array(tc, ctx) for tc in ks]
        vvas = [valid_array(tc, ctx) for tc in vs]
        out = np.empty(n, dtype=object)
        for i in range(n):
            pairs = []
            for ktc, vtc, kva, vva in zip(ks, vs, kvas, vvas):
                k = _pyval(ktc.data if ktc.is_scalar else ktc.data[i],
                           kva[i], ktc.dtype)
                v = _pyval(vtc.data if vtc.is_scalar else vtc.data[i],
                           vva[i], vtc.dtype)
                if k is None:
                    raise ValueError("map keys cannot be null")
                pairs.append((k, v))
            out[i] = pairs
        return TCol(out, np.ones(n, dtype=bool), self.data_type)

    eval_tpu = eval_cpu


class MapKeys(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type.key_type,
                           contains_null=False)

    def tpu_supported(self, conf):
        return "map ops are host-only"

    def eval_cpu(self, ctx):
        return _map_part(self, ctx, 0)

    eval_tpu = eval_cpu


class MapValues(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type.value_type)

    def tpu_supported(self, conf):
        return "map ops are host-only"

    def eval_cpu(self, ctx):
        return _map_part(self, ctx, 1)

    eval_tpu = eval_cpu


def _map_part(expr, ctx, part):
    tc = expr.children[0].eval(ctx)
    n = ctx.row_count
    valid = valid_array(tc, ctx)
    out = np.empty(n, dtype=object)
    for i in range(n):
        if valid[i] and tc.data[i] is not None:
            entries = tc.data[i]
            if isinstance(entries, dict):
                entries = list(entries.items())
            out[i] = [e[part] for e in entries]
        else:
            out[i] = None
    return TCol(out, valid, expr.data_type)


def _pyval(v, ok, dt):
    if not ok or v is None:
        return None
    if hasattr(v, "item"):
        v = v.item()
    return v


def _obj_result(out, ok, dt):
    """Struct-field extraction results (python values from to_pylist) back
    to the CPU backend's physical representations."""
    ok2 = ok & np.array([v is not None for v in out], dtype=bool)
    if isinstance(dt, (T.DateType, T.TimestampType)):
        dense = np.zeros(len(out), dtype=_elem_np(dt))
        for i, v in enumerate(out):
            if ok2[i]:
                dense[i] = _to_phys(v, dt)
        return TCol(dense, ok2, dt)
    if dt.np_dtype is not None:
        dense = np.zeros(len(out), dtype=dt.np_dtype)
        for i, v in enumerate(out):
            if ok2[i]:
                dense[i] = v
        return TCol(dense, ok2, dt)
    return TCol(out, ok2, dt)
