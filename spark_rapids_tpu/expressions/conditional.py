"""Conditional expressions (reference: conditionalExpressions.scala —
GpuIf/GpuCaseWhen; nullExpressions.scala — GpuCoalesce/GpuNvl;
GpuGreatest/GpuLeast in GpuOverrides registrations).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               jnp, materialize, valid_array)


def _result_type(exprs) -> T.DataType:
    out = None
    for e in exprs:
        dt = e.data_type
        if out is None or isinstance(out, T.NullType):
            out = dt
        elif not isinstance(dt, T.NullType):
            out = T.common_type(out, dt)
    return out or T.NULL


def _widen_strings(a: TCol, b: TCol, xp):
    """Pads two device string rectangles to a common width."""
    wa, wb = a.data.shape[1], b.data.shape[1]
    w = max(wa, wb)
    ad = xp.pad(a.data, ((0, 0), (0, w - wa))) if wa < w else a.data
    bd = xp.pad(b.data, ((0, 0), (0, w - wb))) if wb < w else b.data
    return ad, bd


def select(cond, a: TCol, b: TCol, ctx: EvalContext, xp, dtype) -> TCol:
    """Row-wise select: cond ? a : b with validity merge (vectorized)."""
    if isinstance(dtype, (T.StringType, T.BinaryType)) and ctx.backend == "tpu":
        from spark_rapids_tpu.expressions.predicates import _densify_string
        a = _densify_string(a, ctx, xp)
        b = _densify_string(b, ctx, xp)
        ad, bd = _widen_strings(a, b, xp)
        data = xp.where(cond[:, None], ad, bd)
        lengths = xp.where(cond, a.lengths, b.lengths)
        valid = xp.where(cond, valid_array(a, ctx), valid_array(b, ctx))
        return TCol(data, valid, dtype, lengths=lengths)
    if ctx.backend == "tpu" and isinstance(dtype, T.DecimalType) and \
            dtype.is_decimal128:
        # [n, 2] hi/lo limb planes; scalar branches (NULL, literals)
        # densify to limb planes too
        def _limbs(c: TCol):
            if c.is_scalar:
                if c.data is None:
                    return xp.zeros((ctx.row_count, 2), dtype=np.int64)
                import decimal as _dec
                v = c.data
                if isinstance(v, _dec.Decimal):
                    # high-precision context: the default 28-digit one
                    # would silently round wide literals
                    cx = _dec.Context(prec=60)
                    v = int(v.scaleb(dtype.scale, context=cx)
                            .to_integral_value(context=cx))
                u = int(v) % (1 << 128)
                hi = (u >> 64) - (1 << 64 if (u >> 64) >= (1 << 63) else 0)
                lo = (u & ((1 << 64) - 1))
                lo = lo - (1 << 64) if lo >= (1 << 63) else lo
                row = xp.asarray([hi, lo], dtype=np.int64)
                return xp.broadcast_to(row, (ctx.row_count, 2))
            d = c.data
            if getattr(d, "ndim", 1) == 1:   # narrower decimal: widen
                lo = d.astype(np.int64)
                return xp.stack([xp.right_shift(lo, np.int64(63)), lo],
                                axis=1)
            return d
        ad, bd = _limbs(a), _limbs(b)
        data = xp.where(cond[:, None], ad, bd)
        valid = xp.where(cond, valid_array(a, ctx), valid_array(b, ctx))
        return TCol(data, valid, dtype)
    nd = dtype.np_dtype if not isinstance(dtype, (T.StringType, T.BinaryType)) \
        else np.dtype(object)
    ad = materialize(_cast_tcol(a, dtype), ctx, nd)
    bd = materialize(_cast_tcol(b, dtype), ctx, nd)
    data = xp.where(cond, ad, bd) if nd != np.dtype(object) else \
        np.where(cond, ad, bd)
    valid = xp.where(cond, valid_array(a, ctx), valid_array(b, ctx))
    return TCol(data, valid, dtype)


def _cast_tcol(c: TCol, dtype: T.DataType) -> TCol:
    """Numeric widen of an evaluated TCol to the select's result type."""
    if c.dtype == dtype or c.is_string or dtype.np_dtype is None:
        return c
    if c.is_scalar:
        v = c.data
        return TCol.scalar(None if v is None else dtype.np_dtype.type(v), dtype)
    if c.data.dtype != dtype.np_dtype:
        return TCol(c.data.astype(dtype.np_dtype), c.valid, dtype)
    return c


class If(Expression):
    def __init__(self, predicate, a, b):
        super().__init__([predicate, a, b])

    @property
    def data_type(self):
        return _result_type(self.children[1:])

    def sql(self):
        p, a, b = self.children
        return f"if({p.sql()}, {a.sql()}, {b.sql()})"

    def _eval(self, ctx, xp):
        p = self.children[0].eval(ctx)
        a = self.children[1].eval(ctx)
        b = self.children[2].eval(ctx)
        dt = self.data_type
        if p.is_scalar:
            chosen = a if (p.valid and p.data) else b
            if chosen.is_scalar:
                return TCol.scalar(chosen.data if chosen.valid else None, dt)
            return _cast_tcol(chosen, dt)
        cond = p.data & valid_array(p, ctx)  # null predicate -> else branch
        return select(cond, a, b, ctx, xp, dt)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 [WHEN p2 THEN v2]... [ELSE e] END.

    Evaluates as a right-fold of If selects — on TPU every branch is computed
    and blended with `where` (branchless, XLA-friendly); the reference's cuDF
    path similarly computes all branches for columnar CASE WHEN.
    """

    def __init__(self, branches, else_value=None):
        from spark_rapids_tpu.expressions.base import Literal
        self.branches = [(p, v) for p, v in branches]
        self.else_value = else_value if else_value is not None else Literal(None)
        kids = [e for pv in self.branches for e in pv] + [self.else_value]
        super().__init__(kids)

    def with_children(self, children):
        n = len(self.branches)
        branches = [(children[2 * i], children[2 * i + 1]) for i in range(n)]
        return CaseWhen(branches, children[-1])

    @property
    def data_type(self):
        return _result_type([v for _, v in self.branches] + [self.else_value])

    def _eval(self, ctx, xp):
        expr = self.else_value
        for p, v in reversed(self.branches):
            expr = If(p, v, expr)
        return expr.eval(ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Coalesce(Expression):
    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def data_type(self):
        return _result_type(self.children)

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.predicates import IsNotNull
        expr = self.children[-1]
        for c in reversed(self.children[:-1]):
            expr = If(IsNotNull(c), c, expr)
        return expr.eval(ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class AtLeastNNonNulls(Expression):
    """True when at least n of the inputs are non-null (and non-NaN for
    floats) — Spark's DataFrame.na.drop predicate (reference
    GpuAtLeastNNonNulls in nullExpressions)."""

    def __init__(self, n: int, *exprs):
        super().__init__(list(exprs))
        self.n = n

    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.base import valid_array
        count = xp.zeros(ctx.row_count, dtype=np.int32)
        for c in self.children:
            tc = c.eval(ctx)
            ok = valid_array(tc, ctx)
            if isinstance(tc.dtype, (T.FloatType, T.DoubleType)):
                if tc.is_scalar:
                    import math
                    nanfree = not (tc.data is not None
                                   and math.isnan(float(tc.data)))
                    ok = ok & nanfree
                else:
                    ok = ok & ~xp.isnan(tc.data)
            count = count + ok.astype(np.int32)
        return TCol(count >= self.n, xp.ones(ctx.row_count, dtype=bool),
                    T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN else a (reference GpuNaNvl)."""

    def __init__(self, a, b):
        super().__init__([a, b])

    @property
    def data_type(self):
        return T.DOUBLE

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.predicates import IsNan
        return If(IsNan(self.children[0]), self.children[1],
                  self.children[0]).eval(ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class _MinMaxN(Expression):
    """greatest/least: null-skipping n-ary extremum (NaN loses to numbers in
    Spark's greatest? No — Spark treats NaN as largest; we follow that)."""

    take_max = True

    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def data_type(self):
        return _result_type(self.children)

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.predicates import (GreaterThan,
                                                             LessThan, IsNull)
        dt = self.data_type
        cols = [c.eval(ctx) for c in self.children]
        # all-scalar fast path
        if all(c.is_scalar for c in cols):
            vals = [c.data for c in cols if c.valid and c.data is not None]
            if not vals:
                return TCol.scalar(None, dt)
            return TCol.scalar(max(vals) if self.take_max else min(vals), dt)
        nd = dt.np_dtype
        acc_data = None
        acc_valid = None
        for c in cols:
            d = materialize(_cast_tcol(c, dt), ctx, nd)
            v = valid_array(c, ctx)
            if acc_data is None:
                acc_data, acc_valid = d, v
                continue
            if nd is not None and nd.kind == "f":
                # Spark orders NaN as largest: max prefers NaN, min avoids it
                if self.take_max:
                    better = (d > acc_data) | xp.isnan(d)
                else:
                    better = (d < acc_data) | xp.isnan(acc_data)
            else:
                better = (d > acc_data) if self.take_max else (d < acc_data)
            take_new = v & (~acc_valid | better)
            acc_data = xp.where(take_new, d, acc_data)
            acc_valid = acc_valid | v
        return TCol(acc_data, acc_valid, dt)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)


class Greatest(_MinMaxN):
    take_max = True


class Least(_MinMaxN):
    take_max = False
