"""Date/time expressions (reference: datetimeExpressions.scala — GpuYear/
GpuMonth/GpuDayOfMonth/GpuHour/GpuMinute/GpuSecond, GpuDateAdd/GpuDateSub/
GpuDateDiff, GpuLastDay, GpuDayOfWeek/GpuDayOfYear/GpuQuarter,
GpuUnixTimestamp family; TimeZoneDB.scala for non-UTC).

Device kernels derive civil fields from epoch days with pure integer
arithmetic (Euclidean-affine days->y/m/d conversion), so they trace into the
fused XLA program.  All timestamps are UTC micros; non-UTC session timezones
are a later milestone (the reference gates non-UTC behind GpuTimeZoneDB).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, TCol, both_valid,
                                               jnp, materialize, valid_array)
from spark_rapids_tpu.expressions.arithmetic import BinaryExpr, UnaryExpr

_DAY_MICROS = 86_400_000_000


def _civil_from_days(days, xp):
    """Epoch days -> (year, month, day); branch-free integer algorithm
    (public-domain civil-calendar arithmetic), valid for +-32k years."""
    z = days + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = xp.floor_divide(doe - xp.floor_divide(doe, 1460)
                          + xp.floor_divide(doe, 36524)
                          - xp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + xp.floor_divide(yoe, 4)
                 - xp.floor_divide(yoe, 100))                # [0, 365]
    mp = xp.floor_divide(5 * doy + 2, 153)                   # [0, 11]
    d = doy - xp.floor_divide(153 * mp + 2, 5) + 1           # [1, 31]
    m = mp + xp.where(mp < 10, 3, -9)                        # [1, 12]
    year = y + (m <= 2)
    return year.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


def _days_from_civil(y, m, d, xp):
    y = y - (m <= 2)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + xp.where(m > 2, -3, 9)
    doy = xp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + xp.floor_divide(yoe, 4) - xp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _to_days(c: TCol, src: T.DataType, xp):
    if isinstance(src, T.DateType):
        return c.data.astype(np.int64)
    return xp.floor_divide(c.data, _DAY_MICROS)


class _DateField(UnaryExpr):
    """Extracts a civil field from a date/timestamp column."""

    @property
    def data_type(self):
        return T.INT

    def _field(self, y, m, d, days, xp):
        raise NotImplementedError

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        src = self.child.data_type
        if c.is_scalar:
            import datetime
            v = c.data if c.valid else None
            if v is None:
                return TCol.scalar(None, T.INT)
            if isinstance(v, (int, np.integer)):
                days = np.asarray(int(v) if isinstance(src, T.DateType)
                                  else int(v) // _DAY_MICROS)
            else:
                epoch = datetime.date(1970, 1, 1)
                dd = v.date() if isinstance(v, datetime.datetime) else v
                days = np.asarray((dd - epoch).days)
            y, m, d = _civil_from_days(days, np)
            return TCol.scalar(int(self._field(y, m, d, days, np)[()]), T.INT)
        days = _to_days(c, src, xp)
        y, m, d = _civil_from_days(days, xp)
        return TCol(self._field(y, m, d, days, xp).astype(np.int32),
                    c.valid, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Year(_DateField):
    def _field(self, y, m, d, days, xp):
        return y


class Month(_DateField):
    def _field(self, y, m, d, days, xp):
        return m


class DayOfMonth(_DateField):
    def _field(self, y, m, d, days, xp):
        return d


class Quarter(_DateField):
    def _field(self, y, m, d, days, xp):
        return xp.floor_divide(m - 1, 3) + 1


class DayOfWeek(_DateField):
    """1 = Sunday ... 7 = Saturday (Spark semantics)."""

    def _field(self, y, m, d, days, xp):
        return ((days + 4) % 7 + 1).astype(np.int32)


class WeekDay(_DateField):
    """0 = Monday ... 6 = Sunday."""

    def _field(self, y, m, d, days, xp):
        return ((days + 3) % 7).astype(np.int32)


class DayOfYear(_DateField):
    def _field(self, y, m, d, days, xp):
        jan1 = _days_from_civil(y, xp.ones_like(m), xp.ones_like(d), xp)
        return (days - jan1 + 1).astype(np.int32)


class LastDay(UnaryExpr):
    """Last day of the month as a date (reference GpuLastDay)."""

    @property
    def data_type(self):
        return T.DATE

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        days = _to_days(c, self.child.data_type, xp)
        y, m, _ = _civil_from_days(days, xp)
        ny = xp.where(m == 12, y + 1, y)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = _days_from_civil(ny, nm, xp.ones_like(nm), xp)
        return TCol((first_next - 1).astype(np.int32), c.valid, T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class _TimeField(UnaryExpr):
    divisor = 1
    modulus = 60

    @property
    def data_type(self):
        return T.INT

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        micros = c.data
        # Euclidean mod keeps pre-epoch timestamps correct
        day_micros = micros - xp.floor_divide(micros, _DAY_MICROS) * _DAY_MICROS
        secs = xp.floor_divide(day_micros, 1_000_000)
        out = xp.floor_divide(secs, self.divisor) % self.modulus
        return TCol(out.astype(np.int32), c.valid, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Hour(_TimeField):
    divisor = 3600
    modulus = 24


class Minute(_TimeField):
    divisor = 60
    modulus = 60


class Second(_TimeField):
    divisor = 1
    modulus = 60


class DateAdd(BinaryExpr):
    symbol = "date_add"

    @property
    def data_type(self):
        return T.DATE

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        ad = materialize(a, ctx, np.dtype(np.int32))
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol((ad + bd).astype(np.int32), valid, T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class DateSub(DateAdd):
    symbol = "date_sub"

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        ad = materialize(a, ctx, np.dtype(np.int32))
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol((ad - bd).astype(np.int32), valid, T.DATE)


class DateDiff(BinaryExpr):
    symbol = "datediff"

    @property
    def data_type(self):
        return T.INT

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        ad = materialize(a, ctx, np.dtype(np.int32))
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol((ad - bd).astype(np.int32), valid, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class UnixTimestampFromTs(UnaryExpr):
    """to_unix_timestamp on a timestamp column -> long seconds."""

    @property
    def data_type(self):
        return T.LONG

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        return TCol(xp.floor_divide(c.data, 1_000_000), c.valid, T.LONG)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


# ---------------------------------------------------------------------------
# volume datetime functions (reference: datetimeExpressions.scala —
# GpuAddMonths, GpuMonthsBetween, GpuNextDay, GpuTruncDate/Timestamp,
# GpuDateFormatClass via the strftime-ish path)
# ---------------------------------------------------------------------------

class AddMonths(BinaryExpr):
    """add_months(date, n): day clamps to the target month's end (Spark
    semantics: add_months('2024-01-31', 1) -> '2024-02-29')."""

    symbol = "add_months"

    @property
    def data_type(self):
        return T.DATE

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        days = materialize(a, ctx, np.dtype(np.int32)).astype(np.int64)
        months = materialize(b, ctx, np.dtype(np.int32)).astype(np.int64)
        y, m, d = _civil_from_days(days, xp)
        total = (y.astype(np.int64) * 12 + (m.astype(np.int64) - 1)
                 + months)
        ny = xp.floor_divide(total, 12)
        nm = total - ny * 12 + 1
        # clamp the day to the target month's length
        mlen = _month_len(ny, nm, xp)
        nd = xp.minimum(d.astype(np.int64), mlen)
        out = _days_from_civil(ny, nm, nd, xp)
        return TCol(out.astype(np.int32), valid, T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


def _month_len(y, m, xp):
    lengths = xp.asarray(
        np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                 dtype=np.int64))
    base = xp.take(lengths, (m - 1).astype(np.int32))
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return xp.where((m == 2) & leap, 29, base)


class MonthsBetween(BinaryExpr):
    """months_between(end, start): whole months + day-fraction over 31,
    rounded to 8 places; full double precision (Spark semantics on
    dates)."""

    symbol = "months_between"

    @property
    def data_type(self):
        return T.DOUBLE

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        d1 = materialize(a, ctx, np.dtype(np.int32)).astype(np.int64)
        d2 = materialize(b, ctx, np.dtype(np.int32)).astype(np.int64)
        y1, m1, dd1 = _civil_from_days(d1, xp)
        y2, m2, dd2 = _civil_from_days(d2, xp)
        ml1 = _month_len(y1.astype(np.int64), m1.astype(np.int64), xp)
        ml2 = _month_len(y2.astype(np.int64), m2.astype(np.int64), xp)
        whole = (y1.astype(np.int64) - y2.astype(np.int64)) * 12 \
            + (m1.astype(np.int64) - m2.astype(np.int64))
        both_last = (dd1 == ml1) & (dd2 == ml2)
        same_day = dd1 == dd2
        frac = (dd1.astype(np.float64) - dd2.astype(np.float64)) / 31.0
        out = xp.where(both_last | same_day, whole.astype(np.float64),
                       whole.astype(np.float64) + frac)
        out = xp.round(out * 1e8) / 1e8
        return TCol(out, valid, T.DOUBLE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class NextDay(Expression):
    """next_day(date, 'mon'..'sun'): the next date strictly after ``date``
    falling on the given weekday (literal weekday, like the reference)."""

    _DAYS = {"mon": 0, "tue": 1, "wed": 2, "thu": 3, "fri": 4, "sat": 5,
             "sun": 6, "monday": 0, "tuesday": 1, "wednesday": 2,
             "thursday": 3, "friday": 4, "saturday": 5, "sunday": 6}

    def __init__(self, child, day_of_week: str):
        super().__init__([child])
        key = str(day_of_week).strip().lower()
        if key not in self._DAYS:
            raise ValueError(f"unknown weekday {day_of_week!r}")
        self.target = self._DAYS[key]   # 0 = Monday

    @property
    def data_type(self):
        return T.DATE

    def sql(self):
        return f"next_day({self.children[0].sql()}, {self.target})"

    def _eval(self, ctx, xp):
        c = self.children[0].eval(ctx)
        days = materialize(c, ctx, np.dtype(np.int32)).astype(np.int64)
        # 1970-01-01 was a Thursday (weekday 3, Monday=0)
        wd = (days + 3) % 7
        delta = (self.target - wd) % 7
        delta = xp.where(delta == 0, 7, delta)
        return TCol((days + delta).astype(np.int32),
                    valid_array(c, ctx), T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class TruncDate(Expression):
    """trunc(date, fmt): floor to year/quarter/month/week (Spark trunc)."""

    _FMTS = ("year", "yyyy", "yy", "quarter", "month", "mon", "mm", "week")

    def __init__(self, child, fmt: str):
        super().__init__([child])
        self.fmt = str(fmt).lower()
        if self.fmt not in self._FMTS:
            raise ValueError(f"unsupported trunc format {fmt!r}")

    @property
    def data_type(self):
        return T.DATE

    def sql(self):
        return f"trunc({self.children[0].sql()}, '{self.fmt}')"

    def _eval(self, ctx, xp):
        c = self.children[0].eval(ctx)
        days = materialize(c, ctx, np.dtype(np.int32)).astype(np.int64)
        y, m, d = _civil_from_days(days, xp)
        y64, m64 = y.astype(np.int64), m.astype(np.int64)
        if self.fmt in ("year", "yyyy", "yy"):
            out = _days_from_civil(y64, xp.ones_like(m64),
                                   xp.ones_like(m64), xp)
        elif self.fmt == "quarter":
            qm = ((m64 - 1) // 3) * 3 + 1
            out = _days_from_civil(y64, qm, xp.ones_like(m64), xp)
        elif self.fmt in ("month", "mon", "mm"):
            out = _days_from_civil(y64, m64, xp.ones_like(m64), xp)
        else:   # week: Monday
            wd = (days + 3) % 7
            out = days - wd
        return TCol(out.astype(np.int32), valid_array(c, ctx), T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class DateFormat(Expression):
    """date_format(ts/date, pattern) for the common Spark pattern letters
    (yyyy MM dd HH mm ss): builds the digits with integer math on the
    device byte plane — no host round trip."""

    _SUPPORTED = "yMdHms-: /."

    def __init__(self, child, pattern: str):
        super().__init__([child])
        self.pattern = pattern
        self._segs = self._parse(pattern)

    @staticmethod
    def _parse(pattern):
        segs = []
        i = 0
        while i < len(pattern):
            ch = pattern[i]
            if ch in "yMdHms":
                j = i
                while j < len(pattern) and pattern[j] == ch:
                    j += 1
                width = j - i
                # fixed-width fields only: the device byte plane is
                # static-shaped (Spark's single-letter forms are
                # variable-width -> host-formatting territory)
                if ch == "y" and width not in (2, 4):
                    raise ValueError("year pattern must be yy or yyyy")
                if ch != "y" and width != 2:
                    raise ValueError(
                        f"pattern field {ch * width!r} must be "
                        f"{ch * 2!r} (fixed two-digit)")
                segs.append((ch, width))
                i = j
            else:
                if ch not in "-: /.":
                    raise ValueError(
                        f"unsupported date_format pattern char {ch!r}")
                segs.append(("lit", ch))
                i += 1
        return segs

    @property
    def data_type(self):
        return T.STRING

    def sql(self):
        return f"date_format({self.children[0].sql()}, '{self.pattern}')"

    def _eval(self, ctx, xp):
        c = self.children[0].eval(ctx)
        if isinstance(c.dtype, T.DateType):
            days = materialize(c, ctx, np.dtype(np.int32)).astype(np.int64)
            secs = xp.zeros_like(days)
        else:
            us = materialize(c, ctx, np.dtype(np.int64))
            days = xp.floor_divide(us, _DAY_MICROS)
            secs = xp.floor_divide(us - days * _DAY_MICROS, 1_000_000)
        y, m, d = _civil_from_days(days, xp)
        fields = {"y": y.astype(np.int64), "M": m.astype(np.int64),
                  "d": d.astype(np.int64),
                  "H": secs // 3600, "m": (secs // 60) % 60,
                  "s": secs % 60}
        n = ctx.row_count
        cols = []
        for seg in self._segs:
            if seg[0] == "lit":
                cols.append(("lit", seg[1]))
            else:
                ch, width = seg
                v = fields["m" if ch == "m" else ch]
                if ch == "y" and width == 2:
                    v = v % 100
                cols.append(("num", v, max(width, 1)))
        total_w = sum(len(s[1]) if s[0] == "lit" else s[2] for s in cols)
        out = xp.zeros((n, total_w), dtype=np.uint8)
        off = 0
        for s in cols:
            if s[0] == "lit":
                if hasattr(out, "at"):
                    out = out.at[:, off].set(ord(s[1]))
                else:
                    out[:, off] = ord(s[1])
                off += 1
            else:
                _tag, v, width = s
                for k in range(width):
                    digit = (v // (10 ** (width - 1 - k))) % 10
                    byte = (digit + ord("0")).astype(np.uint8)
                    if hasattr(out, "at"):
                        out = out.at[:, off + k].set(byte)
                    else:
                        out[:, off + k] = byte
                off += width
        lens = xp.full(n, total_w, dtype=np.int32)
        return TCol(out, valid_array(c, ctx), T.STRING, lengths=lens)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        # CPU backend: python strftime-equivalent via the same integer
        # math (object array output, matching the CPU string convention)
        import numpy as _np
        tc = self._eval(ctx, _np)
        chars, lens = tc.data, tc.lengths
        out = _np.empty(ctx.row_count, dtype=object)
        valid = _np.asarray(tc.valid)
        for i in range(ctx.row_count):
            out[i] = bytes(chars[i][:lens[i]]).decode() if valid[i] else None
        return TCol(out, tc.valid, T.STRING)
