"""Date/time expressions (reference: datetimeExpressions.scala — GpuYear/
GpuMonth/GpuDayOfMonth/GpuHour/GpuMinute/GpuSecond, GpuDateAdd/GpuDateSub/
GpuDateDiff, GpuLastDay, GpuDayOfWeek/GpuDayOfYear/GpuQuarter,
GpuUnixTimestamp family; TimeZoneDB.scala for non-UTC).

Device kernels derive civil fields from epoch days with pure integer
arithmetic (Euclidean-affine days->y/m/d conversion), so they trace into the
fused XLA program.  All timestamps are UTC micros; non-UTC session timezones
are a later milestone (the reference gates non-UTC behind GpuTimeZoneDB).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, TCol, both_valid,
                                               jnp, materialize, valid_array)
from spark_rapids_tpu.expressions.arithmetic import BinaryExpr, UnaryExpr

_DAY_MICROS = 86_400_000_000


def _civil_from_days(days, xp):
    """Epoch days -> (year, month, day); branch-free integer algorithm
    (public-domain civil-calendar arithmetic), valid for +-32k years."""
    z = days + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = xp.floor_divide(doe - xp.floor_divide(doe, 1460)
                          + xp.floor_divide(doe, 36524)
                          - xp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + xp.floor_divide(yoe, 4)
                 - xp.floor_divide(yoe, 100))                # [0, 365]
    mp = xp.floor_divide(5 * doy + 2, 153)                   # [0, 11]
    d = doy - xp.floor_divide(153 * mp + 2, 5) + 1           # [1, 31]
    m = mp + xp.where(mp < 10, 3, -9)                        # [1, 12]
    year = y + (m <= 2)
    return year.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


def _days_from_civil(y, m, d, xp):
    y = y - (m <= 2)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + xp.where(m > 2, -3, 9)
    doy = xp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + xp.floor_divide(yoe, 4) - xp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _to_days(c: TCol, src: T.DataType, xp):
    if isinstance(src, T.DateType):
        return c.data.astype(np.int64)
    return xp.floor_divide(c.data, _DAY_MICROS)


class _DateField(UnaryExpr):
    """Extracts a civil field from a date/timestamp column."""

    @property
    def data_type(self):
        return T.INT

    def _field(self, y, m, d, days, xp):
        raise NotImplementedError

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        src = self.child.data_type
        if c.is_scalar:
            import datetime
            v = c.data if c.valid else None
            if v is None:
                return TCol.scalar(None, T.INT)
            if isinstance(v, (int, np.integer)):
                days = np.asarray(int(v) if isinstance(src, T.DateType)
                                  else int(v) // _DAY_MICROS)
            else:
                epoch = datetime.date(1970, 1, 1)
                dd = v.date() if isinstance(v, datetime.datetime) else v
                days = np.asarray((dd - epoch).days)
            y, m, d = _civil_from_days(days, np)
            return TCol.scalar(int(self._field(y, m, d, days, np)[()]), T.INT)
        days = _to_days(c, src, xp)
        y, m, d = _civil_from_days(days, xp)
        return TCol(self._field(y, m, d, days, xp).astype(np.int32),
                    c.valid, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Year(_DateField):
    def _field(self, y, m, d, days, xp):
        return y


class Month(_DateField):
    def _field(self, y, m, d, days, xp):
        return m


class DayOfMonth(_DateField):
    def _field(self, y, m, d, days, xp):
        return d


class Quarter(_DateField):
    def _field(self, y, m, d, days, xp):
        return xp.floor_divide(m - 1, 3) + 1


class DayOfWeek(_DateField):
    """1 = Sunday ... 7 = Saturday (Spark semantics)."""

    def _field(self, y, m, d, days, xp):
        return ((days + 4) % 7 + 1).astype(np.int32)


class WeekDay(_DateField):
    """0 = Monday ... 6 = Sunday."""

    def _field(self, y, m, d, days, xp):
        return ((days + 3) % 7).astype(np.int32)


class DayOfYear(_DateField):
    def _field(self, y, m, d, days, xp):
        jan1 = _days_from_civil(y, xp.ones_like(m), xp.ones_like(d), xp)
        return (days - jan1 + 1).astype(np.int32)


class LastDay(UnaryExpr):
    """Last day of the month as a date (reference GpuLastDay)."""

    @property
    def data_type(self):
        return T.DATE

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        days = _to_days(c, self.child.data_type, xp)
        y, m, _ = _civil_from_days(days, xp)
        ny = xp.where(m == 12, y + 1, y)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = _days_from_civil(ny, nm, xp.ones_like(nm), xp)
        return TCol((first_next - 1).astype(np.int32), c.valid, T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class _TimeField(UnaryExpr):
    divisor = 1
    modulus = 60

    @property
    def data_type(self):
        return T.INT

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        micros = c.data
        # Euclidean mod keeps pre-epoch timestamps correct
        day_micros = micros - xp.floor_divide(micros, _DAY_MICROS) * _DAY_MICROS
        secs = xp.floor_divide(day_micros, 1_000_000)
        out = xp.floor_divide(secs, self.divisor) % self.modulus
        return TCol(out.astype(np.int32), c.valid, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Hour(_TimeField):
    divisor = 3600
    modulus = 24


class Minute(_TimeField):
    divisor = 60
    modulus = 60


class Second(_TimeField):
    divisor = 1
    modulus = 60


class DateAdd(BinaryExpr):
    symbol = "date_add"

    @property
    def data_type(self):
        return T.DATE

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        ad = materialize(a, ctx, np.dtype(np.int32))
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol((ad + bd).astype(np.int32), valid, T.DATE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class DateSub(DateAdd):
    symbol = "date_sub"

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        ad = materialize(a, ctx, np.dtype(np.int32))
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol((ad - bd).astype(np.int32), valid, T.DATE)


class DateDiff(BinaryExpr):
    symbol = "datediff"

    @property
    def data_type(self):
        return T.INT

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        ad = materialize(a, ctx, np.dtype(np.int32))
        bd = materialize(b, ctx, np.dtype(np.int32))
        return TCol((ad - bd).astype(np.int32), valid, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class UnixTimestampFromTs(UnaryExpr):
    """to_unix_timestamp on a timestamp column -> long seconds."""

    @property
    def data_type(self):
        return T.LONG

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        return TCol(xp.floor_divide(c.data, 1_000_000), c.valid, T.LONG)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)
