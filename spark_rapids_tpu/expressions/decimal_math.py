"""Decimal arithmetic: Spark DecimalPrecision result types + exact kernels.

Reference: decimalExpressions.scala + the JNI ``DecimalUtils`` 128-bit
kernels (SURVEY.md §2.16) and GpuDecimalMultiply/GpuDecimalDivide in
arithmetic.scala.  Semantics follow Spark non-ANSI mode: overflow -> NULL,
divide-by-zero -> NULL, HALF_UP rounding.

TPU design: decimal64 (precision <= 18) is plain int64 lane math.
decimal128 lives as [n, 2] int64 (hi, lo-bits) columns; kernels split each
lane into four 32-bit limbs (held in int64 lanes so carries fit), run
schoolbook add/mul/divmod-by-small, and rejoin — all elementwise jnp ops
that fuse into the surrounding XLA program.  The device handles:

- add/sub/negate/abs at any precision (incl. 128-bit, with 10^d rescale)
- multiply when the UNADJUSTED result fits 38 digits (64x64->128 limbs)
- divide when the scaled numerator fits in 64 bits

Everything else is tagged host-only (the CPU oracle computes with python
ints, always exact).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (EvalContext, TCol, jnp,
                                               valid_array)

D = T.DecimalType
MAX_P = D.MAX_PRECISION          # 38
MAX_LONG = D.MAX_LONG_DIGITS     # 18
_MASK32 = np.int64(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# Spark DecimalPrecision result types (allowPrecisionLoss=true defaults)
# ---------------------------------------------------------------------------

def _bounded(precision: int, scale: int) -> D:
    """Spark DecimalType.adjustPrecisionScale."""
    if precision <= MAX_P:
        return D(precision, scale)
    digits = precision - scale
    min_scale = min(scale, 6)
    adj_scale = max(MAX_P - digits, min_scale)
    return D(MAX_P, adj_scale)


def add_result_type(a: D, b: D) -> D:
    scale = max(a.scale, b.scale)
    digits = max(a.precision - a.scale, b.precision - b.scale)
    return _bounded(digits + scale + 1, scale)


def mul_result_type(a: D, b: D) -> D:
    return _bounded(a.precision + b.precision + 1, a.scale + b.scale)


def div_result_type(a: D, b: D) -> D:
    scale = max(6, a.scale + b.precision + 1)
    digits = a.precision - a.scale + b.scale
    return _bounded(digits + scale, scale)


def rem_result_type(a: D, b: D) -> D:
    scale = max(a.scale, b.scale)
    digits = min(a.precision - a.scale, b.precision - b.scale)
    return _bounded(digits + scale, scale)


def as_decimal_type(dt: T.DataType) -> Optional[D]:
    """The decimal view of an operand (Spark's integral->decimal widening)."""
    if isinstance(dt, D):
        return dt
    if isinstance(dt, T.ByteType):
        return D(3, 0)
    if isinstance(dt, T.ShortType):
        return D(5, 0)
    if isinstance(dt, T.IntegerType):
        return D(10, 0)
    if isinstance(dt, T.LongType):
        return D(19, 0)
    return None


# ---------------------------------------------------------------------------
# 32-bit limb helpers (device decimal128); numpy twin drives the CPU checks
# ---------------------------------------------------------------------------

def _u64(x, xp):
    if xp is np:
        return np.asarray(x).view(np.uint64)
    import jax
    return jax.lax.bitcast_convert_type(x, np.uint64)


def _i64(x, xp):
    if xp is np:
        return np.asarray(x).view(np.int64)
    import jax
    return jax.lax.bitcast_convert_type(x, np.int64)


def split128(hi, lo, xp):
    """(hi int64, lo bits int64) -> 4 unsigned 32-bit limbs in int64 lanes,
    little-endian, two's-complement (negative numbers stay wrapped)."""
    lo_u = _u64(lo, xp)
    hi_u = _u64(hi, xp)
    m = np.uint64(0xFFFFFFFF)
    l0 = _i64((lo_u & m), xp)
    l1 = _i64((lo_u >> np.uint64(32)), xp)
    l2 = _i64((hi_u & m), xp)
    l3 = _i64((hi_u >> np.uint64(32)), xp)
    return [l0, l1, l2, l3]


def join128(limbs, xp):
    """4 normalized limbs -> (hi, lo-bits) int64."""
    lo = _i64(_u64(limbs[0], xp) | (_u64(limbs[1], xp) << np.uint64(32)), xp)
    hi = _i64(_u64(limbs[2], xp) | (_u64(limbs[3], xp) << np.uint64(32)), xp)
    return hi, lo


def _normalize(limbs, xp):
    """Propagates carries so every limb is in [0, 2^32); returns (limbs,
    carry-out) — carry-out nonzero means 128-bit overflow (for unsigned
    magnitude math)."""
    out = []
    carry = xp.zeros_like(limbs[0])
    for l in limbs:
        s = l + carry
        out.append(s & _MASK32)
        carry = s >> np.int64(32)
    return out, carry


def neg128(hi, lo, xp):
    """Two's-complement negate."""
    limbs = split128(hi, lo, xp)
    inv = [(~l) & _MASK32 for l in limbs]
    inv[0] = inv[0] + 1
    norm, _ = _normalize(inv, xp)
    return join128(norm, xp)


def is_neg128(hi):
    return hi < 0


def abs128(hi, lo, xp):
    nh, nl = neg128(hi, lo, xp)
    neg = hi < 0
    return xp.where(neg, nh, hi), xp.where(neg, nl, lo)


def add128(ah, al, bh, bl, xp):
    """Signed 128-bit add; returns (hi, lo, overflow)."""
    a = split128(ah, al, xp)
    b = split128(bh, bl, xp)
    s = [x + y for x, y in zip(a, b)]
    norm, _ = _normalize(s, xp)
    hi, lo = join128(norm, xp)
    # signed overflow: operands same sign, result differs
    ovf = ((ah < 0) == (bh < 0)) & ((hi < 0) != (ah < 0))
    return hi, lo, ovf


def mul128_small(hi, lo, mult_limbs, xp):
    """|x| * m for a non-negative 128-bit magnitude and a python-int
    multiplier decomposed into 32-bit limbs; returns (hi, lo, overflow)."""
    a = split128(hi, lo, xp)
    acc = [xp.zeros_like(a[0]) for _ in range(5)]
    ovf = xp.zeros_like(hi < 0)
    for j, m in enumerate(mult_limbs):
        if m == 0:
            continue
        m64 = np.int64(m)          # m < 2^32
        for i in range(4):
            k = i + j
            if k >= 4:
                # any contribution past 128 bits is overflow
                ovf = ovf | (a[i] != 0)
                continue
            # limb product < 2^64 would not fit signed int64; split the
            # 32-bit limb into 16-bit halves so partials stay exact
            p_lo = a[i] * (m64 & np.int64(0xFFFF))
            p_hi = a[i] * (m64 >> np.int64(16))
            acc[k] = acc[k] + (p_lo & _MASK32) \
                + ((p_hi & np.int64(0xFFFF)) << np.int64(16))
            spill = (p_lo >> np.int64(32)) + (p_hi >> np.int64(16))
            if k + 1 >= 4:
                ovf = ovf | (spill != 0)
            else:
                acc[k + 1] = acc[k + 1] + spill
    norm, carry = _normalize(acc[:4], xp)
    ovf = ovf | (carry != 0) | (acc[4] != 0)
    hi2, lo2 = join128(norm, xp)
    # magnitude math: a negative (signed) result bit means > 2^127-1
    ovf = ovf | (hi2 < 0)
    return hi2, lo2, ovf


def _mul32(a, b):
    """Exact 32x32 -> 64 product of unsigned limbs held in int64 lanes,
    returned as (low32, high32) — the naive a*b can reach ~2^64 and wrap
    signed int64, so the product is assembled from 16-bit halves."""
    a0, a1 = a & np.int64(0xFFFF), a >> np.int64(16)
    b0, b1 = b & np.int64(0xFFFF), b >> np.int64(16)
    mid = a0 * b1 + a1 * b0                      # < 2^33
    low = a0 * b0 + ((mid & np.int64(0xFFFF)) << np.int64(16))  # < 2^33
    high = a1 * b1 + (mid >> np.int64(16)) + (low >> np.int64(32))
    return low & _MASK32, high


def divmod128_small(hi, lo, div: int, xp):
    """|x| divmod d for a non-negative 128-bit magnitude and a python int
    divisor 0 < d < 2^31; long division over the four limbs."""
    limbs = split128(hi, lo, xp)
    d = np.int64(div)
    q = []
    rem = xp.zeros_like(limbs[0])
    for l in reversed(limbs):
        cur = (rem << np.int64(32)) | l
        q.append(cur // d)
        rem = cur % d
    q = list(reversed(q))
    qh, ql = join128([x & _MASK32 for x in q], xp)
    return qh, ql, rem


def cmp128_const(hi, lo, bound: int, xp):
    """|x| > bound (non-negative magnitudes), bound a python int < 2^127."""
    bh = np.int64(bound >> 64)
    bl = np.int64((bound & ((1 << 64) - 1)) - (1 << 64)) \
        if (bound & ((1 << 64) - 1)) >= (1 << 63) else \
        np.int64(bound & ((1 << 64) - 1))
    gt_hi = hi > bh
    eq_hi = hi == bh
    gt_lo = _u64(lo, xp) > _u64(xp.zeros_like(lo) + bl, xp)
    return gt_hi | (eq_hi & gt_lo)


def _pow10_limbs(d: int):
    v = 10 ** d
    return [(v >> (32 * i)) & 0xFFFFFFFF for i in range(4)]


def rescale128_up(hi, lo, d: int, xp):
    """x * 10^d (signed), returns (hi, lo, overflow)."""
    if d == 0:
        return hi, lo, xp.zeros_like(hi < 0)
    ah, al = abs128(hi, lo, xp)
    mh, ml, ovf = mul128_small(ah, al, _pow10_limbs(d), xp)
    nh, nl = neg128(mh, ml, xp)
    neg = hi < 0
    return xp.where(neg, nh, mh), xp.where(neg, nl, ml), ovf


def div128_pow10_half_up(hi, lo, d: int, xp):
    """round_half_up(x / 10^d) (signed)."""
    if d == 0:
        return hi, lo
    ah, al = abs128(hi, lo, xp)
    q_h, q_l = ah, al
    # divide in <=9-digit chunks (divisor must fit in 31 bits)
    rem_scale = 1
    remainders = xp.zeros_like(hi)
    left = d
    while left > 0:
        step = min(left, 9)
        dv = 10 ** step
        q_h, q_l, r = divmod128_small(q_h, q_l, dv, xp)
        remainders = remainders + r * np.int64(rem_scale)
        rem_scale *= dv
        left -= step
    # HALF_UP: remainder*2 >= divisor -> bump (remainder < 10^d <= 10^38
    # may exceed int64 when d > 18 — compare in float is unsafe; instead
    # compare against half-divisor chunkwise is overkill: d > 18 implies
    # dropping >18 digits, only the top chunk matters for the half test)
    if d <= 18:
        bump = 2 * remainders >= np.int64(10 ** d)
    else:
        # remainder tracked exactly only while it fits; for d>18 divide is
        # host-only (guarded by callers), keep a defensive floor here
        bump = xp.zeros_like(hi < 0)
    b_limbs = split128(q_h, q_l, xp)
    b_limbs[0] = b_limbs[0] + bump.astype(np.int64)
    norm, _ = _normalize(b_limbs, xp)
    q_h, q_l = join128(norm, xp)
    nh, nl = neg128(q_h, q_l, xp)
    neg = hi < 0
    return xp.where(neg, nh, q_h), xp.where(neg, nl, q_l)


# ---------------------------------------------------------------------------
# TCol plumbing
# ---------------------------------------------------------------------------

def unscaled_py(tc: TCol, ctx: EvalContext) -> Tuple[np.ndarray, np.ndarray]:
    """CPU backend: (object array of python unscaled ints, validity)."""
    import decimal as dec
    n = ctx.row_count
    valid = valid_array(tc, ctx)
    out = np.empty(n, dtype=object)
    dt = tc.dtype
    if tc.is_scalar:
        v = _scalar_unscaled(tc)
        for i in range(n):
            out[i] = v
        return out, valid
    if isinstance(dt, D) and dt.is_decimal128:
        # data is already a python-int object array (signed unscaled)
        for i in range(n):
            out[i] = int(tc.data[i]) if valid[i] else 0
        return out, valid
    arr = np.asarray(tc.data)
    for i in range(n):
        out[i] = int(arr[i]) if valid[i] else 0
    return out, valid


def _scalar_unscaled(tc: TCol) -> int:
    import decimal as dec
    if tc.data is None:
        return 0
    dt = tc.dtype
    if isinstance(tc.data, dec.Decimal):
        scale = dt.scale if isinstance(dt, D) else 0
        return int(tc.data.scaleb(scale).to_integral_value())
    return int(tc.data)


def result_tcol_py(vals: np.ndarray, valid, rt: D, ctx) -> TCol:
    """Python ints -> the CPU physical repr of the result type, nulling
    overflow (Spark non-ANSI)."""
    n = ctx.row_count
    bound = 10 ** rt.precision
    ok = np.asarray(valid).copy()
    if rt.is_decimal128:
        out = np.empty(n, dtype=object)
        for i in range(n):
            v = vals[i]
            if abs(v) >= bound:
                ok[i] = False
                out[i] = 0
            else:
                out[i] = v
        return TCol(out, ok, rt)
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        v = vals[i]
        if abs(v) >= bound:
            ok[i] = False
        else:
            out[i] = v
    return TCol(out, ok, rt)


def device_parts(tc: TCol, ctx: EvalContext, xp):
    """Device backend: ((hi, lo) limbs or (None, lo64), validity); integral
    operands present as decimal(p, 0) in int64."""
    valid = valid_array(tc, ctx)
    dt = tc.dtype
    if tc.is_scalar:
        v = _scalar_unscaled(tc)
        if isinstance(dt, D) and dt.is_decimal128:
            hi = xp.full(ctx.row_count, np.int64(v >> 64))
            lo = xp.full(ctx.row_count,
                         np.int64((v & ((1 << 64) - 1)) - (1 << 64)
                                  if (v & ((1 << 64) - 1)) >= (1 << 63)
                                  else v & ((1 << 64) - 1)))
            return hi, lo, valid
        return None, xp.full(ctx.row_count, np.int64(v)), valid
    if isinstance(dt, D) and dt.is_decimal128:
        return tc.data[:, 0], tc.data[:, 1], valid
    return None, xp.asarray(tc.data).astype(np.int64), valid


def widen_to_128(hi, lo, xp):
    if hi is not None:
        return hi, lo
    return xp.where(lo < 0, np.int64(-1), np.int64(0)), lo


def pack_result(hi, lo, valid, rt: D, ctx, xp) -> TCol:
    """Device (hi, lo) -> result column with overflow nulling."""
    bound = 10 ** rt.precision - 1
    ah, al = abs128(hi, lo, xp)
    ovf = cmp128_const(ah, al, bound, xp)
    ok = valid & ~ovf
    if rt.is_decimal128:
        return TCol(xp.stack([hi, lo], axis=1), ok, rt)
    return TCol(lo, ok, rt)


# ---------------------------------------------------------------------------
# High-level op evaluation (called from arithmetic.py when decimals involved)
# ---------------------------------------------------------------------------

def binary_result_type(op: str, lt: T.DataType, rt: T.DataType) -> D:
    a, b = as_decimal_type(lt), as_decimal_type(rt)
    if a is None or b is None:
        raise TypeError(f"decimal {op} on non-decimal operands {lt}, {rt}")
    if op in ("add", "sub"):
        return add_result_type(a, b)
    if op == "mul":
        return mul_result_type(a, b)
    if op == "div":
        return div_result_type(a, b)
    if op in ("rem", "pmod", "idiv"):
        # idiv's column type is LONG; the decimal view only drives gating
        return rem_result_type(a, b)
    raise ValueError(op)


def device_supported(op: str, lt: T.DataType, rt_: T.DataType) -> Optional[str]:
    """None when the device kernels handle this op/type combo exactly;
    reason string otherwise (tagging -> host fallback, like the reference
    gates DECIMAL128 ops per JNI kernel availability)."""
    a, b = as_decimal_type(lt), as_decimal_type(rt_)
    out = binary_result_type(op, lt, rt_)
    if op in ("add", "sub"):
        if max(a.scale, b.scale) - out.scale > 18:
            return "decimal add/sub scale reduction beyond 18 is host tier"
        return None   # 128-bit add with rescale covers the rest
    if op == "mul":
        raw_scale = a.scale + b.scale
        if a.precision <= MAX_LONG and b.precision <= MAX_LONG and \
                out.scale == raw_scale:
            return None
        if out.scale != raw_scale and raw_scale - out.scale <= 18 and \
                a.precision <= MAX_LONG and b.precision <= MAX_LONG:
            return None  # 64x64->128 then one rounded pow10 divide
        return (f"decimal multiply {a.simple_name} x {b.simple_name} "
                "needs >128-bit intermediates (host tier)")
    if op == "div":
        d = out.scale + b.scale - a.scale
        if a.precision + d <= MAX_LONG and b.precision <= MAX_LONG:
            return None  # scaled numerator fits int64
        return (f"decimal divide {a.simple_name} / {b.simple_name} "
                "needs 128-bit division (host tier)")
    if op in ("rem", "pmod", "idiv"):
        s = max(a.scale, b.scale)
        if a.precision + (s - a.scale) <= MAX_LONG and \
                b.precision + (s - b.scale) <= MAX_LONG:
            return None  # aligned operands fit int64
        return f"decimal {op} at this precision is host tier"
    return f"decimal {op} not implemented"


def cpu_binary_eval(op: str, left: TCol, right: TCol, out: D,
                    ctx: EvalContext) -> TCol:
    """Exact python-int oracle for every decimal op."""
    a, b = as_decimal_type(left.dtype), as_decimal_type(right.dtype)
    av, avalid = unscaled_py(left, ctx)
    bv, bvalid = unscaled_py(right, ctx)
    n = ctx.row_count
    valid = np.asarray(avalid & bvalid).copy()
    vals = np.empty(n, dtype=object)
    for i in range(n):
        vals[i] = 0
        if not valid[i]:
            continue
        x, y = av[i], bv[i]
        if op in ("add", "sub"):
            s_max = max(a.scale, b.scale)
            x *= 10 ** (s_max - a.scale)
            y *= 10 ** (s_max - b.scale)
            r = x + y if op == "add" else x - y
            vals[i] = _round_half_up(r, s_max - out.scale)
        elif op == "mul":
            raw = x * y                      # scale a.scale + b.scale
            vals[i] = _round_half_up(raw, a.scale + b.scale - out.scale)
        elif op == "div":
            if y == 0:
                valid[i] = False
                continue
            d = out.scale + b.scale - a.scale
            vals[i] = _div_half_up(x * 10 ** d, y)
        elif op in ("rem", "pmod", "idiv"):
            if y == 0:
                valid[i] = False
                continue
            s = max(a.scale, b.scale)
            xs = x * 10 ** (s - a.scale)
            ys = y * 10 ** (s - b.scale)
            if op == "idiv":
                q = abs(xs) // abs(ys)
                vals[i] = -q if (xs < 0) != (ys < 0) else q
                continue
            r = math_fmod(xs, ys)
            if op == "pmod" and r < 0:
                r += abs(ys)
            vals[i] = _round_half_up(r, s - out.scale)
    if op == "idiv":
        # long result (Spark IntegralDivide), overflow -> null
        ok = np.asarray(valid).copy()
        dense = np.zeros(ctx.row_count, dtype=np.int64)
        for i in range(ctx.row_count):
            if ok[i]:
                if abs(vals[i]) > (1 << 63) - 1:
                    ok[i] = False
                else:
                    dense[i] = vals[i]
        return TCol(dense, ok, T.LONG)
    return result_tcol_py(vals, valid, out, ctx)


def math_fmod(x: int, y: int) -> int:
    """Java % (sign follows dividend) on ints."""
    r = abs(x) % abs(y)
    return -r if x < 0 else r


def _round_half_up(v: int, drop_digits: int) -> int:
    if drop_digits <= 0:
        return v * 10 ** (-drop_digits)
    return _div_half_up(v, 10 ** drop_digits)


def _div_half_up(num: int, den: int) -> int:
    sign = -1 if (num < 0) != (den < 0) else 1
    num, den = abs(num), abs(den)
    return sign * ((2 * num + den) // (2 * den))


def tpu_binary_eval(op: str, left: TCol, right: TCol, out: D,
                    ctx: EvalContext, xp) -> TCol:
    """Device kernels for the combos device_supported() admits."""
    a, b = as_decimal_type(left.dtype), as_decimal_type(right.dtype)
    ah, al, avalid = device_parts(left, ctx, xp)
    bh, bl, bvalid = device_parts(right, ctx, xp)
    valid = avalid & bvalid
    if op in ("add", "sub"):
        # exact sum at s_max = max(s1, s2); when _bounded reduced the
        # result scale below s_max, round HALF_UP afterwards (BigDecimal
        # semantics)
        s_max = max(a.scale, b.scale)
        ah, al = widen_to_128(ah, al, xp)
        bh, bl = widen_to_128(bh, bl, xp)
        ah, al, ovf1 = rescale128_up(ah, al, s_max - a.scale, xp)
        bh, bl, ovf2 = rescale128_up(bh, bl, s_max - b.scale, xp)
        if op == "sub":
            bh, bl = neg128(bh, bl, xp)
        rh, rl, ovf3 = add128(ah, al, bh, bl, xp)
        if out.scale < s_max:
            rh, rl = div128_pow10_half_up(rh, rl, s_max - out.scale, xp)
        return pack_result(rh, rl, valid & ~ovf1 & ~ovf2 & ~ovf3, out,
                           ctx, xp)
    if op == "mul":
        # both operands fit int64: 64x64 -> 128 via 32-bit limb products
        neg = (al < 0) != (bl < 0)
        x = xp.abs(al)
        y = xp.abs(bl)
        x_l, x_h = x & _MASK32, x >> np.int64(32)
        y_l, y_h = y & _MASK32, y >> np.int64(32)
        ll_lo, ll_hi = _mul32(x_l, y_l)
        lh_lo, lh_hi = _mul32(x_l, y_h)
        hl_lo, hl_hi = _mul32(x_h, y_l)
        hh_lo, hh_hi = _mul32(x_h, y_h)
        acc0 = ll_lo
        acc1 = ll_hi + lh_lo + hl_lo
        acc2 = lh_hi + hl_hi + hh_lo
        acc3 = hh_hi
        norm, carry = _normalize([acc0, acc1, acc2, acc3], xp)
        rh, rl = join128(norm, xp)
        drop = a.scale + b.scale - out.scale
        if drop > 0:
            rh, rl = div128_pow10_half_up(rh, rl, drop, xp)
        nh, nl = neg128(rh, rl, xp)
        rh = xp.where(neg, nh, rh)
        rl = xp.where(neg, nl, rl)
        return pack_result(rh, rl, valid & (carry == 0), out, ctx, xp)
    if op == "div":
        d = out.scale + b.scale - a.scale
        num = al * np.int64(10 ** d)     # guarded: fits int64
        den = bl
        zero = den == 0
        den = xp.where(zero, np.int64(1), den)
        sign = xp.where((num < 0) != (den < 0), np.int64(-1), np.int64(1))
        q = (2 * xp.abs(num) + xp.abs(den)) // (2 * xp.abs(den))
        rl = sign * q
        rh = xp.where(rl < 0, np.int64(-1), np.int64(0))
        return pack_result(rh, rl, valid & ~zero, out, ctx, xp)
    if op in ("rem", "pmod", "idiv"):
        s = max(a.scale, b.scale)
        xs = al * np.int64(10 ** (s - a.scale))
        ys = bl * np.int64(10 ** (s - b.scale))
        zero = ys == 0
        ys = xp.where(zero, np.int64(1), ys)
        if op == "idiv":
            q = xp.abs(xs) // xp.abs(ys)
            q = xp.where((xs < 0) != (ys < 0), -q, q)
            return TCol(q, valid & ~zero, T.LONG)
        r = xp.abs(xs) % xp.abs(ys)
        r = xp.where(xs < 0, -r, r)
        if op == "pmod":
            r = xp.where(r < 0, r + xp.abs(ys), r)
        drop = s - out.scale
        if drop > 0:
            sign = xp.where(r < 0, np.int64(-1), np.int64(1))
            p10 = np.int64(10 ** drop)
            r = sign * ((2 * xp.abs(r) + p10) // (2 * p10))
        rh = xp.where(r < 0, np.int64(-1), np.int64(0))
        return pack_result(rh, r, valid & ~zero, out, ctx, xp)
    raise ValueError(op)


def decimal_to_double(tc: TCol, ctx: EvalContext, xp) -> TCol:
    """decimal -> double (for decimal+float promotions)."""
    dt = tc.dtype
    assert isinstance(dt, D)
    if ctx.backend == "cpu":
        vals, valid = unscaled_py(tc, ctx)
        out = np.zeros(ctx.row_count, dtype=np.float64)
        for i in range(ctx.row_count):
            out[i] = float(vals[i]) / (10.0 ** dt.scale)
        return TCol(out, valid, T.DOUBLE)
    hi, lo, valid = device_parts(tc, ctx, xp)
    if hi is None:
        out = lo.astype(np.float64) / (10.0 ** dt.scale)
    else:
        out = (hi.astype(np.float64) * np.float64(2.0 ** 64)
               + _u64(lo, xp).astype(np.float64)) / (10.0 ** dt.scale)
    return TCol(out, valid, T.DOUBLE)


# ---------------------------------------------------------------------------
# Comparisons (used by BinaryComparison when decimals are involved)
# ---------------------------------------------------------------------------

def compare_involved(lt: T.DataType, rt: T.DataType) -> bool:
    """True when the comparison must run in decimal space (both sides
    decimal or integral; fractional partners promote to double instead;
    anything else needs an explicit cast)."""
    if not (isinstance(lt, D) or isinstance(rt, D)):
        return False
    return as_decimal_type(lt) is not None and \
        as_decimal_type(rt) is not None


def compare_supported(lt: T.DataType, rt: T.DataType) -> Optional[str]:
    a, b = as_decimal_type(lt), as_decimal_type(rt)
    s = max(a.scale, b.scale)
    if max(a.precision + (s - a.scale), b.precision + (s - b.scale)) <= MAX_P:
        return None
    return "decimal comparison at this scale mix is host tier"


def compare(left: TCol, right: TCol, ctx: EvalContext, xp):
    """Returns an int8/int array of -1/0/1 per row (nulls handled by the
    caller's validity)."""
    a, b = as_decimal_type(left.dtype), as_decimal_type(right.dtype)
    s = max(a.scale, b.scale)
    if ctx.backend == "cpu":
        av, _ = unscaled_py(left, ctx)
        bv, _ = unscaled_py(right, ctx)
        out = np.zeros(ctx.row_count, dtype=np.int8)
        da, db = 10 ** (s - a.scale), 10 ** (s - b.scale)
        for i in range(ctx.row_count):
            x, y = av[i] * da, bv[i] * db
            out[i] = (x > y) - (x < y)
        return out
    ah, al, _ = device_parts(left, ctx, xp)
    bh, bl, _ = device_parts(right, ctx, xp)
    da, db = s - a.scale, s - b.scale
    if ah is None and bh is None and \
            a.precision + da <= MAX_LONG and b.precision + db <= MAX_LONG:
        x = al * np.int64(10 ** da)
        y = bl * np.int64(10 ** db)
        return (xp.asarray(x > y, dtype=np.int8)
                - xp.asarray(x < y, dtype=np.int8))
    ah, al = widen_to_128(ah, al, xp)
    bh, bl = widen_to_128(bh, bl, xp)
    ah, al, _o1 = rescale128_up(ah, al, da, xp)
    bh, bl, _o2 = rescale128_up(bh, bl, db, xp)
    # signed 128-bit compare: hi signed, lo unsigned
    lt_ = (ah < bh) | ((ah == bh) & (_u64(al, xp) < _u64(bl, xp)))
    gt_ = (ah > bh) | ((ah == bh) & (_u64(al, xp) > _u64(bl, xp)))
    return xp.asarray(gt_, dtype=np.int8) - xp.asarray(lt_, dtype=np.int8)
