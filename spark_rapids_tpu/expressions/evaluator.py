"""Batch-level expression evaluation: whole-stage XLA fusion.

The TPU path stages the ENTIRE projection/filter expression list into one
traced function and jits it per (expression-list, input schema, bucket) — so
XLA fuses every elementwise op, cast, and hash into a single kernel.  This is
the structural performance advantage over the reference, which dispatches one
cuDF kernel per operator node (GpuProjectExec.project -> columnarEval chain).

The CPU path evaluates the same trees with the numpy backend (fallback +
differential oracle).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn, HostColumn
from spark_rapids_tpu.expressions.base import (EvalContext, Expression, TCol,
                                               valid_array)


# ---------------------------------------------------------------------------
# batch <-> TCol bridges
# ---------------------------------------------------------------------------

def device_batch_tcols(batch: ColumnarBatch) -> List[TCol]:
    """Bridges a device batch into evaluation TCols.  Encoded columns
    (dictionary codes / RLE runs) materialize here — the transparent
    per-column fallback for every operator that is not encoding-aware
    (the fused-stage path consumes codes directly and never calls
    this on encoded columns it keeps)."""
    from spark_rapids_tpu.columnar.encoding import materialize_batch
    batch = materialize_batch(batch, site="operator")
    return [TCol(c.data, c.validity, c.data_type, lengths=c.lengths,
                 elem_valid=c.elem_valid)
            for c in batch.columns]


def host_batch_tcols(batch: HostColumnarBatch) -> List[TCol]:
    out = []
    for c in batch.columns:
        dt = c.data_type
        valid = c.validity_np()
        if isinstance(dt, (T.StringType, T.BinaryType)) or dt.is_nested:
            # CPU backend: object array of python values (str / list / dict)
            data = np.empty(len(c), dtype=object)
            lst = c.to_pylist()
            for i, v in enumerate(lst):
                data[i] = v
            out.append(TCol(data, valid, dt))
        elif isinstance(dt, T.DecimalType) and dt.is_decimal128:
            # CPU backend: python-int object array of unscaled values
            raw = c.data_np()
            data = np.empty(len(c), dtype=object)
            for i in range(len(c)):
                data[i] = (int(raw[i, 0]) << 64) | (int(raw[i, 1])
                                                    & 0xFFFFFFFFFFFFFFFF)
            out.append(TCol(data, valid, dt))
        else:
            out.append(TCol(c.data_np(), valid, dt))
    return out


def tcol_to_device_column(tc: TCol, row_count: int, bucket: int,
                          xp) -> DeviceColumn:
    data, valid, lens = tc.data, tc.valid, tc.lengths
    if not tc.is_scalar and isinstance(tc.dtype, T.ArrayType):
        return DeviceColumn(data, valid, row_count, tc.dtype, lengths=lens,
                            elem_valid=tc.elem_valid)
    if tc.is_scalar:
        # densify a scalar result
        ctx = EvalContext([], "tpu", bucket)
        from spark_rapids_tpu.expressions.base import materialize
        if isinstance(tc.dtype, (T.StringType, T.BinaryType)):
            from spark_rapids_tpu.expressions.predicates import _densify_string
            d = _densify_string(tc, ctx, xp)
            data, valid, lens = d.data, valid_array(tc, ctx), d.lengths
        else:
            data = materialize(tc, ctx, tc.dtype.np_dtype)
            valid = valid_array(tc, ctx)
    return DeviceColumn(data, valid, row_count, tc.dtype, lengths=lens)


def tcol_to_host_column(tc: TCol, row_count: int) -> HostColumn:
    import pyarrow as pa
    dt = tc.dtype
    if tc.is_scalar:
        v = tc.data if tc.valid else None
        if isinstance(dt, T.DecimalType):
            import decimal
            vals = [None if v is None else decimal.Decimal(v)] * row_count
            return HostColumn(pa.array(vals, type=T.to_arrow(dt)), dt)
        return HostColumn(pa.array([_pyify(v, dt)] * row_count,
                                   type=T.to_arrow(dt)), dt)
    valid = np.asarray(tc.valid)
    if valid.ndim == 0:
        # all-literal expression trees keep scalar (0-d) planes through
        # binary kernels; broadcast to the logical row count
        valid = np.full(row_count, bool(valid))
    if not (isinstance(dt, (T.StringType, T.BinaryType)) or dt.is_nested):
        d = np.asarray(tc.data)
        if d.ndim == 0:
            tc = TCol(np.full(row_count, d[()]), valid, dt)
    if isinstance(dt, (T.StringType, T.BinaryType)) or dt.is_nested:
        vals = [tc.data[i] if valid[i] else None for i in range(row_count)]
        return HostColumn(pa.array(vals, type=T.to_arrow(dt)), dt)
    if isinstance(dt, T.DecimalType) and dt.is_decimal128:
        import decimal
        vals = [decimal.Decimal(int(tc.data[i])).scaleb(-dt.scale)
                if valid[i] else None for i in range(row_count)]
        return HostColumn(pa.array(vals, type=T.to_arrow(dt)), dt)
    return HostColumn.from_numpy(np.asarray(tc.data)[:row_count],
                                 valid[:row_count], dt)


def _pyify(v, dt):
    if v is None:
        return None
    if hasattr(v, "item"):
        return v.item()
    return v


# ---------------------------------------------------------------------------
# CPU evaluation (fallback + oracle)
# ---------------------------------------------------------------------------

def eval_exprs_cpu(exprs: Sequence[Expression],
                   batch: HostColumnarBatch,
                   names: Optional[List[str]] = None) -> HostColumnarBatch:
    cols = host_batch_tcols(batch)
    ctx = EvalContext(cols, "cpu", batch.row_count)
    outs = [e.eval_cpu(ctx) for e in exprs]
    host_cols = [tcol_to_host_column(tc, batch.row_count) for tc in outs]
    return HostColumnarBatch(host_cols, batch.row_count,
                             names or _out_names(exprs))


# ---------------------------------------------------------------------------
# TPU evaluation: one jitted XLA program per (plan signature, schema, bucket)
# (programs live in the process-wide StageCompiler cache, exec/stage_compiler)
# ---------------------------------------------------------------------------


def _signature(exprs, batch: ColumnarBatch) -> Tuple:
    shape_sig = tuple(
        (str(c.data_type), tuple(c.data.shape),
         None if c.lengths is None else True,
         None if c.elem_valid is None else True)
        for c in batch.columns)
    # sql() alone under-identifies (e.g. lit(1, INT) vs lit(1, LONG) both
    # render "1"), so the output dtype participates in the key
    return (tuple((e.sql(), str(e.data_type)) for e in exprs), shape_sig)


def eval_exprs_tpu(exprs: Sequence[Expression], batch: ColumnarBatch,
                   names: Optional[List[str]] = None) -> ColumnarBatch:
    from spark_rapids_tpu.columnar.column import _jnp
    from spark_rapids_tpu.columnar.encoding import (batch_has_encoded,
                                                    materialize_batch)
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    if batch_has_encoded(batch):
        # decode only the ordinals these expressions actually read; an
        # unreferenced encoded column would still flow into the program
        # below as raw codes, so it must decode too unless every
        # expression ignores it (projections list their inputs)
        from spark_rapids_tpu.expressions.base import BoundReference
        refs = set()
        for e in exprs:
            refs.update(b.ordinal for b in
                        e.collect(lambda n: isinstance(n, BoundReference)))
        batch = materialize_batch(batch, ordinals=sorted(refs),
                                  site="operator")
    xp = _jnp()
    key = _signature(exprs, batch)
    dtypes = [c.data_type for c in batch.columns]
    bucket = batch.bucket

    def build():
        def run(arrs):
            cols = [TCol(d, v, dt, lengths=ln, elem_valid=ev)
                    for (d, v, ln, ev), dt in zip(arrs, dtypes)]
            ctx = EvalContext(cols, "tpu", bucket)
            outs = []
            for e in exprs:
                tc = e.eval_tpu(ctx)
                dc = tcol_to_device_column(tc, 0, bucket, xp)
                outs.append((dc.data, dc.validity, dc.lengths,
                             dc.elem_valid))
            return outs
        return run

    fn = get_or_build("expr.project", key, build)

    arrs = [(c.data, c.validity, c.lengths, c.elem_valid)
            for c in batch.columns]
    results = fn(arrs)
    out_cols = []
    for (d, v, ln, ev), e in zip(results, exprs):
        out_cols.append(DeviceColumn(d, v, batch.row_count, e.data_type,
                                     lengths=ln, elem_valid=ev))
    return ColumnarBatch(out_cols, batch.row_count, names or _out_names(exprs))


def _out_names(exprs) -> List[str]:
    from spark_rapids_tpu.expressions.base import Alias, BoundReference
    names = []
    for i, e in enumerate(exprs):
        if isinstance(e, Alias):
            names.append(e.alias_name)
        elif isinstance(e, BoundReference) and e.ref_name:
            names.append(e.ref_name)
        else:
            names.append(f"col{i}")
    return names
