"""Hash expressions: Spark-compatible murmur3_x86_32 and xxhash64.

Reference: HashFunctions.scala + the ``Hash`` JNI kernels (SURVEY.md §2.16);
Spark's Murmur3Hash (seed 42) drives hash partitioning, so bit-exact parity
here is what makes our shuffle placement agree with Spark's.

Spark quirks implemented:
- murmur3 processes the byte tail ONE SIGNED BYTE at a time (unlike standard
  murmur3's little-endian tail accumulation).
- long/double hash as two 32-bit halves (low first); float/double normalize
  -0.0 to 0.0 and NaN to the canonical NaN bits.
- NULL fields leave the running hash unchanged.

Device kernels: statically-unrolled masked loops over the padded string
rectangle — each step is a full-width vector op, fusable by XLA.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               jnp, materialize, valid_array)

_U32 = np.uint32
_C1 = _U32(0xCC9E2D51)
_C2 = _U32(0x1B873593)


def _bitcast(x, target, xp):
    """Bit-reinterpret (numpy .view / jax.lax.bitcast_convert_type)."""
    if xp is np:
        return np.asarray(x).view(target)
    import jax
    return jax.lax.bitcast_convert_type(x, target)


def _rotl32(x, r, xp):
    r = _U32(r)
    return ((x << r) | (x >> _U32(32 - r))).astype(_U32)


def _mix_k1(k1, xp):
    k1 = (k1 * _C1).astype(_U32)
    k1 = _rotl32(k1, 15, xp)
    return (k1 * _C2).astype(_U32)


def _mix_h1(h1, k1, xp):
    h1 = (h1 ^ k1).astype(_U32)
    h1 = _rotl32(h1, 13, xp)
    return (h1 * _U32(5) + _U32(0xE6546B64)).astype(_U32)


def _fmix(h1, length, xp):
    h1 = (h1 ^ length).astype(_U32)
    h1 = h1 ^ (h1 >> _U32(16))
    h1 = (h1 * _U32(0x85EBCA6B)).astype(_U32)
    h1 = h1 ^ (h1 >> _U32(13))
    h1 = (h1 * _U32(0xC2B2AE35)).astype(_U32)
    return h1 ^ (h1 >> _U32(16))


def _hash_int(values_u32, seed_u32, xp):
    k1 = _mix_k1(values_u32.astype(_U32), xp)
    h1 = _mix_h1(seed_u32, k1, xp)
    return _fmix(h1, _U32(4), xp)


def _hash_long(values_i64, seed_u32, xp):
    v = values_i64.astype(np.uint64)
    low = (v & np.uint64(0xFFFFFFFF)).astype(_U32)
    high = (v >> np.uint64(32)).astype(_U32)
    h1 = _mix_h1(seed_u32, _mix_k1(low, xp), xp)
    h1 = _mix_h1(h1, _mix_k1(high, xp), xp)
    return _fmix(h1, _U32(8), xp)


def _normalize_float_bits(data, xp, double: bool):
    if double:
        d = data.astype(np.float64)
        d = xp.where(d == 0.0, 0.0, d)          # -0.0 -> 0.0
        d = xp.where(xp.isnan(d), np.float64("nan"), d)  # canonical NaN
        if xp is np:
            return _bitcast(d, np.int64, xp)
        # device: f64_ieee_bits picks the exact bitcast where supported
        # and the arithmetic dd reconstruction on TPU (no f64 bitcast)
        from spark_rapids_tpu.ops.f64bits import f64_ieee_bits
        return f64_ieee_bits(d, xp)
    f = data.astype(np.float32)
    f = xp.where(f == 0.0, np.float32(0.0), f)
    f = xp.where(xp.isnan(f), np.float32("nan"), f)
    return _bitcast(f, np.int32, xp)


def _hash_string_murmur(chars, lens, seed_u32, xp):
    """Spark hashUnsafeBytes over the padded rectangle.

    Blocks of 4 bytes little-endian for the aligned prefix, then each tail
    byte hashed individually as a SIGNED int (the Spark quirk).
    """
    n, w = chars.shape
    h1 = xp.broadcast_to(seed_u32, (n,)).astype(_U32) if np.ndim(seed_u32) == 0 \
        else seed_u32.astype(_U32)
    nblocks = lens // 4
    max_blocks = w // 4
    c = chars.astype(_U32)
    for b in range(max_blocks):
        k = (c[:, 4 * b] | (c[:, 4 * b + 1] << _U32(8)) |
             (c[:, 4 * b + 2] << _U32(16)) | (c[:, 4 * b + 3] << _U32(24)))
        nh = _mix_h1(h1, _mix_k1(k, xp), xp)
        h1 = xp.where(b < nblocks, nh, h1)
    # tail: at most 3 bytes, each as signed int
    signed = chars.astype(np.int8).astype(np.int32).astype(_U32)
    base = (nblocks * 4).astype(np.int32)
    for t in range(3):
        pos = base + t
        idx = xp.clip(pos, 0, w - 1)
        byte = xp.take_along_axis(signed, idx[:, None], axis=1)[:, 0]
        nh = _mix_h1(h1, _mix_k1(byte, xp), xp)
        h1 = xp.where(pos < lens, nh, h1)
    return _fmix(h1, lens.astype(_U32), xp)


def murmur3_col(c: TCol, dtype: T.DataType, seed, ctx: EvalContext, xp):
    """Running murmur3 update for one column; returns uint32 array."""
    seed = seed.astype(_U32) if hasattr(seed, "astype") else _U32(seed)
    valid = valid_array(c, ctx)
    if isinstance(dtype, T.ArrayType):
        # Spark hashes arrays by folding element hashes: h = hash(e, h)
        # per element in order (host path; device taggers keep arrays off
        # the hash kernels)
        if ctx.backend != "cpu":
            raise NotImplementedError(
                "array hashing runs on the host tier")
        data = materialize(c, ctx, np.dtype(object))
        out = np.broadcast_to(np.asarray(seed, dtype=_U32),
                              (len(data),)).copy()
        for i in range(len(data)):
            if not valid[i] or data[i] is None:
                continue
            for e in data[i]:
                etc = TCol(np.array([e] if e is not None else [None],
                                    dtype=object)
                           if dtype.element_type.np_dtype is None
                           else np.array([0 if e is None else e],
                                         dtype=dtype.element_type.np_dtype),
                           np.array([e is not None]), dtype.element_type)
                sub = EvalContext([], "cpu", 1)
                out[i] = murmur3_col(etc, dtype.element_type,
                                     _U32(int(out[i])), sub, np)[0]
        return out
    if isinstance(dtype, (T.StringType, T.BinaryType)):
        if ctx.backend == "cpu":
            data = materialize(c, ctx, np.dtype(object))
            out = np.broadcast_to(np.asarray(seed, dtype=_U32),
                              (len(data),)).copy()
            for i in range(len(data)):
                if valid[i] and data[i] is not None:
                    raw = data[i].encode() if isinstance(data[i], str) else data[i]
                    out[i] = _murmur_bytes_py(raw, int(out[i]))
            return out
        from spark_rapids_tpu.expressions.predicates import _densify_string
        c = _densify_string(c, ctx, xp)
        h = _hash_string_murmur(c.data, c.lengths, seed, xp)
    elif isinstance(dtype, T.BooleanType):
        d = materialize(c, ctx, np.dtype(bool))
        h = _hash_int(d.astype(np.int32).astype(_U32), seed, xp)
    elif isinstance(dtype, (T.LongType, T.TimestampType)):
        h = _hash_long(materialize(c, ctx, np.dtype(np.int64)), seed, xp)
    elif isinstance(dtype, T.DoubleType):
        bits = _normalize_float_bits(materialize(c, ctx, np.dtype(np.float64)),
                                     xp, True)
        h = _hash_long(bits, seed, xp)
    elif isinstance(dtype, T.FloatType):
        bits = _normalize_float_bits(materialize(c, ctx, np.dtype(np.float32)),
                                     xp, False)
        h = _hash_int(bits.astype(np.int64).astype(_U32), seed, xp)
    elif isinstance(dtype, T.DecimalType) and not dtype.is_decimal128:
        h = _hash_long(materialize(c, ctx, np.dtype(np.int64)), seed, xp)
    else:  # byte/short/int/date
        d = materialize(c, ctx, np.dtype(np.int32))
        h = _hash_int(d.astype(np.int64).astype(_U32), seed, xp)
    seed_arr = xp.broadcast_to(seed, h.shape) if np.ndim(seed) == 0 else seed
    return xp.where(valid, h, seed_arr).astype(_U32)


def _murmur_bytes_py(raw: bytes, seed: int) -> int:
    """Reference scalar implementation (CPU oracle for strings)."""

    def mixk1(k1):
        k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        return (k1 * 0x1B873593) & 0xFFFFFFFF

    def mixh1(h1, k1):
        h1 ^= k1
        h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
        return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF

    h1 = seed & 0xFFFFFFFF
    nblocks = len(raw) // 4
    for b in range(nblocks):
        k = int.from_bytes(raw[4 * b:4 * b + 4], "little")
        h1 = mixh1(h1, mixk1(k))
    for t in range(nblocks * 4, len(raw)):
        byte = raw[t] - 256 if raw[t] >= 128 else raw[t]  # signed
        h1 = mixh1(h1, mixk1(byte & 0xFFFFFFFF))
    h1 ^= len(raw)
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


def murmur3_hash_cols(cols: Sequence[TCol], dtypes: Sequence[T.DataType],
                      seed: int, ctx: EvalContext, xp):
    """Chained multi-column murmur3 (Spark Murmur3Hash of a struct)."""
    h = _U32(seed)
    for c, dt in zip(cols, dtypes):
        h = murmur3_col(c, dt, h, ctx, xp)
    return h


class Murmur3Hash(Expression):
    """hash(cols...) -> int32, seed 42 (Spark `hash` function)."""

    def __init__(self, *exprs, seed: int = 42):
        super().__init__(list(exprs))
        self.seed = seed

    @property
    def data_type(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def _eval(self, ctx, xp):
        cols = [c.eval(ctx) for c in self.children]
        dtypes = [c.data_type for c in self.children]
        h = murmur3_hash_cols(cols, dtypes, self.seed, ctx, xp)
        n = ctx.row_count
        ones = xp.ones(n, dtype=bool)
        return TCol(_bitcast(h, np.int32, xp), ones, T.INT)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


# ---------------------------------------------------------------------------
# xxhash64 (seed 42) — Spark XxHash64
# ---------------------------------------------------------------------------

_U64 = np.uint64
_P1 = _U64(0x9E3779B185EBCA87)
_P2 = _U64(0xC2B2AE3D27D4EB4F)
_P3 = _U64(0x165667B19E3779F9)
_P4 = _U64(0x85EBCA77C2B2AE63)
_P5 = _U64(0x27D4EB2F165667C5)


def _rotl64(x, r, xp):
    r = _U64(r)
    return ((x << r) | (x >> _U64(64 - r))).astype(_U64)


def _xx_round(acc, inp, xp):
    acc = (acc + inp * _P2).astype(_U64)
    acc = _rotl64(acc, 31, xp)
    return (acc * _P1).astype(_U64)


def _xx_fmix(h, xp):
    h = h ^ (h >> _U64(33))
    h = (h * _P2).astype(_U64)
    h = h ^ (h >> _U64(29))
    h = (h * _P3).astype(_U64)
    return h ^ (h >> _U64(32))


def _xx_hash_long(v_u64, seed_u64, xp):
    h = (seed_u64 + _P5 + _U64(8)).astype(_U64)
    h = (h ^ _xx_round(xp.zeros_like(v_u64), v_u64, xp)).astype(_U64)
    h = (_rotl64(h, 27, xp) * _P1 + _P4).astype(_U64)
    return _xx_fmix(h, xp)


def xxhash64_col(c: TCol, dtype: T.DataType, seed, ctx: EvalContext, xp):
    seed = seed.astype(_U64) if hasattr(seed, "astype") else _U64(seed)
    valid = valid_array(c, ctx)
    if isinstance(dtype, (T.StringType, T.BinaryType)):
        # string xxhash on device: later milestone; CPU scalar loop here
        data = materialize(c, ctx, np.dtype(object))
        out = np.broadcast_to(np.asarray(seed, dtype=_U64),
                              (len(data),)).copy()
        for i in range(len(data)):
            if valid[i] and data[i] is not None:
                raw = data[i].encode() if isinstance(data[i], str) else data[i]
                out[i] = _xx_bytes_py(raw, int(out[i]))
        return xp.asarray(out) if ctx.backend == "tpu" else out
    if isinstance(dtype, T.DoubleType):
        bits = _normalize_float_bits(materialize(c, ctx, np.dtype(np.float64)),
                                     xp, True)
        v = bits.astype(_U64)
    elif isinstance(dtype, T.FloatType):
        bits = _normalize_float_bits(materialize(c, ctx, np.dtype(np.float32)),
                                     xp, False)
        v = bits.astype(np.int64).astype(np.uint64)
    elif isinstance(dtype, T.BooleanType):
        v = materialize(c, ctx, np.dtype(bool)).astype(np.uint64)
    else:
        v = materialize(c, ctx, np.dtype(np.int64)).astype(np.uint64)
    h = _xx_hash_long(v, seed, xp)
    seed_arr = xp.broadcast_to(seed, h.shape) if np.ndim(seed) == 0 else seed
    return xp.where(valid, h, seed_arr).astype(_U64)


def _xx_bytes_py(raw: bytes, seed: int) -> int:
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def rnd(acc, inp):
        acc = (acc + inp * int(_P2)) & M
        return (rotl(acc, 31) * int(_P1)) & M

    n = len(raw)
    if n >= 32:
        v1 = (seed + int(_P1) + int(_P2)) & M
        v2 = (seed + int(_P2)) & M
        v3 = seed
        v4 = (seed - int(_P1)) & M
        i = 0
        while i <= n - 32:
            v1 = rnd(v1, int.from_bytes(raw[i:i + 8], "little"))
            v2 = rnd(v2, int.from_bytes(raw[i + 8:i + 16], "little"))
            v3 = rnd(v3, int.from_bytes(raw[i + 16:i + 24], "little"))
            v4 = rnd(v4, int.from_bytes(raw[i + 24:i + 32], "little"))
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            h = ((h ^ rnd(0, v)) * int(_P1) + int(_P4)) & M
    else:
        h = (seed + int(_P5)) & M
        i = 0
    h = (h + n) & M
    while i <= n - 8:
        h = ((rotl(h ^ rnd(0, int.from_bytes(raw[i:i + 8], "little")), 27)
              * int(_P1)) + int(_P4)) & M
        i += 8
    if i <= n - 4:
        k = int.from_bytes(raw[i:i + 4], "little")
        h = ((rotl(h ^ ((k * int(_P1)) & M), 23) * int(_P2)) + int(_P3)) & M
        i += 4
    while i < n:
        h = (rotl(h ^ ((raw[i] * int(_P5)) & M), 11) * int(_P1)) & M
        i += 1
    h ^= h >> 33
    h = (h * int(_P2)) & M
    h ^= h >> 29
    h = (h * int(_P3)) & M
    h ^= h >> 32
    return h


class XxHash64(Expression):
    """xxhash64(cols...) -> long, seed 42."""

    def __init__(self, *exprs, seed: int = 42):
        super().__init__(list(exprs))
        self.seed = seed

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def _eval(self, ctx, xp):
        h = _U64(self.seed)
        for c, dt in zip([c.eval(ctx) for c in self.children],
                         [c.data_type for c in self.children]):
            h = xxhash64_col(c, dt, h, ctx, xp)
        ones = xp.ones(ctx.row_count, dtype=bool)
        return TCol(_bitcast(h, np.int64, xp), ones, T.LONG)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)
