"""JSON and URL expressions.

Reference: GpuGetJsonObject / GpuJsonToStructs / GpuStructsToJson /
GpuJsonTuple (JNI ``JSONUtils``/``MapUtils``, SURVEY.md §2.16) and
GpuParseUrl (JNI ``ParseURI``).

TPU stance: byte-level JSON/URL parsing is TPU-hostile (irregular control
flow, no fixed-width lanes), so these run on the host tier with honest
fallback tagging — exactly the contract the reference applies to ops cuDF
cannot run (SURVEY.md §7 hard-parts #4).  The expressions still exist as
first-class components: they plan, tag, and execute through the same
pipeline, just on the CPU engine.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (EvalContext, Expression, TCol,
                                               materialize, valid_array)


class _HostStringExpr(Expression):
    """Host-tier expression over string inputs."""

    host_reason = "byte-level parsing is host tier on TPU"

    def tpu_supported(self, conf):
        return self.host_reason

    def eval_tpu(self, ctx):
        return self.eval_cpu(ctx)


# ---------------------------------------------------------------------------
# JSON path (reference: JSONUtils.getJsonObject; Spark JsonPathParser)
# ---------------------------------------------------------------------------

class _PathStep:
    __slots__ = ("kind", "value")

    def __init__(self, kind, value=None):
        self.kind = kind       # "field" | "index" | "wild"
        self.value = value


def parse_json_path(path: str) -> Optional[List[_PathStep]]:
    """Parses Spark's get_json_object path dialect: $, .name, ['name'],
    [index], [*].  Returns None for an invalid path (Spark -> null)."""
    if not path or not path.startswith("$"):
        return None
    steps: List[_PathStep] = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            j = i + 1
            # unquoted field name: up to next '.' or '['
            k = j
            while k < n and path[k] not in ".[":
                k += 1
            if k == j:
                return None
            steps.append(_PathStep("field", path[j:k]))
            i = k
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            token = path[i + 1:j].strip()
            if token == "*":
                steps.append(_PathStep("wild"))
            elif token[:1] in ("'", '"') and token[-1:] == token[:1]:
                steps.append(_PathStep("field", token[1:-1]))
            else:
                try:
                    steps.append(_PathStep("index", int(token)))
                except ValueError:
                    return None
            i = j + 1
        else:
            return None
    return steps


def _walk(value, steps: List[_PathStep], idx: int):
    """Returns list of matches (wildcards can fan out)."""
    if idx == len(steps):
        return [value]
    step = steps[idx]
    if step.kind == "field":
        if isinstance(value, dict) and step.value in value:
            return _walk(value[step.value], steps, idx + 1)
        return []
    if step.kind == "index":
        if isinstance(value, list) and 0 <= step.value < len(value):
            return _walk(value[step.value], steps, idx + 1)
        return []
    # wildcard
    if isinstance(value, list):
        out = []
        for v in value:
            out.extend(_walk(v, steps, idx + 1))
        return out
    return []


def _render(matches, had_wildcard: bool) -> Optional[str]:
    """Spark rendering: scalars unquoted; objects/arrays as JSON; multiple
    wildcard matches wrapped in a JSON array."""
    if not matches:
        return None
    if len(matches) == 1 and not had_wildcard:
        v = matches[0]
        if v is None:
            return None
        if isinstance(v, str):
            return v
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (int, float)):
            return json.dumps(v)
        return json.dumps(v, separators=(",", ":"))
    if len(matches) == 1:
        v = matches[0]
        return json.dumps(v, separators=(",", ":")) \
            if not isinstance(v, str) else v
    return json.dumps(matches, separators=(",", ":"))


class GetJsonObject(_HostStringExpr):
    """get_json_object(json, path) (reference GpuGetJsonObject)."""

    def __init__(self, child, path):
        super().__init__([child, path])

    @property
    def data_type(self):
        return T.STRING

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import Literal
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        p = self.children[1]
        if isinstance(p, Literal):
            paths = [p.value] * ctx.row_count
            pvalid = np.full(ctx.row_count, p.value is not None)
        else:
            ptc = p.eval(ctx)
            paths = materialize(ptc, ctx, np.dtype(object))
            pvalid = valid_array(ptc, ctx)
        out = np.empty(ctx.row_count, dtype=object)
        ok = np.zeros(ctx.row_count, dtype=bool)
        path_cache = {}
        for i in range(ctx.row_count):
            out[i] = None
            if not (valid[i] and pvalid[i]) or data[i] is None \
                    or paths[i] is None:
                continue
            pth = paths[i]
            if pth not in path_cache:
                path_cache[pth] = parse_json_path(pth)
            steps = path_cache[pth]
            if steps is None:
                continue
            try:
                doc = json.loads(data[i])
            except (ValueError, TypeError):
                continue
            wild = any(s.kind == "wild" for s in steps)
            r = _render(_walk(doc, steps, 0), wild)
            out[i] = r
            ok[i] = r is not None
        return TCol(out, ok, T.STRING)


class JsonTuple(_HostStringExpr):
    """json_tuple(json, f1, ..., fn) -> struct of n string fields
    (reference GpuJsonTuple; Spark's generator form is a projection of
    this struct)."""

    def __init__(self, child, *fields: str):
        super().__init__([child])
        if not fields:
            raise ValueError("json_tuple needs at least one field")
        self.fields = list(fields)

    @property
    def data_type(self):
        return T.StructType([T.StructField(f, T.STRING) for f in self.fields])

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        out = np.empty(ctx.row_count, dtype=object)
        for i in range(ctx.row_count):
            row = {f: None for f in self.fields}
            if valid[i] and data[i] is not None:
                try:
                    doc = json.loads(data[i])
                    if isinstance(doc, dict):
                        for f in self.fields:
                            v = doc.get(f)
                            if v is not None:
                                row[f] = v if isinstance(v, str) else \
                                    json.dumps(v, separators=(",", ":"))
                except (ValueError, TypeError):
                    pass
            out[i] = row
        return TCol(out, np.ones(ctx.row_count, dtype=bool), self.data_type)


class JsonToStructs(_HostStringExpr):
    """from_json(json, schema) (reference GpuJsonToStructs via JSONUtils).
    Malformed rows -> null (PERMISSIVE-lite)."""

    def __init__(self, child, schema: T.DataType):
        super().__init__([child])
        if not isinstance(schema, (T.StructType, T.ArrayType, T.MapType)):
            raise TypeError("from_json needs a struct/array/map schema")
        self._schema = schema

    @property
    def data_type(self):
        return self._schema

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        out = np.empty(ctx.row_count, dtype=object)
        ok = np.zeros(ctx.row_count, dtype=bool)
        for i in range(ctx.row_count):
            out[i] = None
            if not valid[i] or data[i] is None:
                continue
            try:
                doc = json.loads(data[i])
            except (ValueError, TypeError):
                continue
            v = _coerce_json(doc, self._schema)
            if v is not None:
                out[i] = v
                ok[i] = True
        return TCol(out, ok, self._schema)


def _coerce_json(v, dt: T.DataType):
    """Coerces a parsed JSON value to the target type; None on mismatch."""
    if v is None:
        return None
    if isinstance(dt, T.StructType):
        if not isinstance(v, dict):
            return None
        return {f.name: _coerce_json(v.get(f.name), f.data_type)
                for f in dt.fields}
    if isinstance(dt, T.ArrayType):
        if not isinstance(v, list):
            return None
        return [_coerce_json(x, dt.element_type) for x in v]
    if isinstance(dt, T.MapType):
        if not isinstance(v, dict):
            return None
        return [(k, _coerce_json(x, dt.value_type)) for k, x in v.items()]
    if isinstance(dt, T.StringType):
        return v if isinstance(v, str) else \
            json.dumps(v, separators=(",", ":"))
    if isinstance(dt, T.BooleanType):
        return v if isinstance(v, bool) else None
    if isinstance(dt, (T.DoubleType, T.FloatType)):
        return float(v) if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else None
    if dt.is_integral:
        if isinstance(v, bool) or not isinstance(v, int):
            return None
        return v
    if isinstance(dt, T.DecimalType):
        import decimal
        if isinstance(v, bool) or not isinstance(v, (int, float, str)):
            return None
        try:
            return decimal.Decimal(str(v)).quantize(
                decimal.Decimal(1).scaleb(-dt.scale))
        except decimal.InvalidOperation:
            return None
    return None


class StructsToJson(_HostStringExpr):
    """to_json(struct/array/map) (reference GpuStructsToJson)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def data_type(self):
        return T.STRING

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        if not c.dtype.is_nested:
            raise TypeError(f"to_json needs a nested input, got "
                            f"{c.dtype.simple_name}")
        valid = valid_array(c, ctx)
        out = np.empty(ctx.row_count, dtype=object)
        ok = np.zeros(ctx.row_count, dtype=bool)
        for i in range(ctx.row_count):
            out[i] = None
            if not valid[i] or (not c.is_scalar and c.data[i] is None):
                continue
            v = c.data if c.is_scalar else c.data[i]
            out[i] = json.dumps(_jsonable(v, c.dtype),
                                separators=(",", ":"), default=str)
            ok[i] = True
        return TCol(out, ok, T.STRING)


def _jsonable(v, dt: T.DataType):
    import datetime
    import decimal
    if v is None:
        return None
    if isinstance(dt, T.StructType):
        return {f.name: _jsonable(v.get(f.name), f.data_type)
                for f in dt.fields}
    if isinstance(dt, T.ArrayType):
        return [_jsonable(x, dt.element_type) for x in v]
    if isinstance(dt, T.MapType):
        entries = v.items() if isinstance(v, dict) else v
        return {str(k): _jsonable(x, dt.value_type) for k, x in entries}
    if isinstance(v, decimal.Decimal):
        return float(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return str(v)
    if hasattr(v, "item"):
        return v.item()
    return v


# ---------------------------------------------------------------------------
# parse_url (reference: GpuParseUrl via JNI ParseURI)
# ---------------------------------------------------------------------------

_URL_PARTS = {"HOST", "PATH", "QUERY", "REF", "PROTOCOL", "FILE",
              "AUTHORITY", "USERINFO"}


class ParseUrl(_HostStringExpr):
    """parse_url(url, part [, key]) — Spark semantics (java.net.URI-style
    extraction; QUERY with key returns that parameter's value)."""

    def __init__(self, url, part, key=None):
        children = [url, part] + ([key] if key is not None else [])
        super().__init__(children)

    @property
    def data_type(self):
        return T.STRING

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        part_tc = self.children[1].eval(ctx)
        parts = materialize(part_tc, ctx, np.dtype(object))
        pvalid = valid_array(part_tc, ctx)
        if len(self.children) > 2:
            key_tc = self.children[2].eval(ctx)
            keys = materialize(key_tc, ctx, np.dtype(object))
            kvalid = valid_array(key_tc, ctx)
        else:
            keys = [None] * ctx.row_count
            kvalid = np.ones(ctx.row_count, dtype=bool)
        out = np.empty(ctx.row_count, dtype=object)
        ok = np.zeros(ctx.row_count, dtype=bool)
        for i in range(ctx.row_count):
            out[i] = None
            if not (valid[i] and pvalid[i] and kvalid[i]) \
                    or data[i] is None or parts[i] is None:
                continue
            r = _parse_url_one(data[i], parts[i], keys[i])
            out[i] = r
            ok[i] = r is not None
        return TCol(out, ok, T.STRING)


def _parse_url_one(url: str, part: str, key: Optional[str]) -> Optional[str]:
    from urllib.parse import urlsplit
    if part not in _URL_PARTS:
        return None
    try:
        sp = urlsplit(url)
    except ValueError:
        return None
    if not sp.scheme:
        return None   # Spark returns null for non-absolute URIs
    if part == "PROTOCOL":
        return sp.scheme or None
    if part == "HOST":
        return sp.hostname
    if part == "PATH":
        return sp.path
    if part == "QUERY":
        q = sp.query or None
        if q is None:
            return None
        if key is None:
            return q
        # Spark matches the raw key=value pair via regex, no decoding
        for pair in q.split("&"):
            if pair.startswith(key + "="):
                return pair[len(key) + 1:]
        return None
    if part == "REF":
        return sp.fragment or None
    if part == "FILE":
        return sp.path + ("?" + sp.query if sp.query else "")
    if part == "AUTHORITY":
        return sp.netloc or None
    if part == "USERINFO":
        if "@" in sp.netloc:
            return sp.netloc.rsplit("@", 1)[0]
        return None
    return None


# plan-rewrite registrations (host tier: exist in the registry so tagging
# reports "host tier" instead of "no TPU implementation")
from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_expr  # noqa: E402

for _cls in (GetJsonObject, JsonTuple, JsonToStructs, StructsToJson,
             ParseUrl):
    register_expr(_cls, TS.BASIC_WITH_ARRAYS)
