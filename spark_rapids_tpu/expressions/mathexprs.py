"""Math expressions (reference: mathExpressions.scala — GpuSqrt, GpuExp,
GpuLog variants, trig family, GpuFloor/GpuCeil, GpuRound/GpuBRound, GpuSignum,
GpuAtan2, GpuHypot, GpuPow...).

Spark deviations followed: log of non-positive returns NULL (Hive semantics);
round uses HALF_UP, bround HALF_EVEN.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, TCol, jnp,
                                               materialize, valid_array)
from spark_rapids_tpu.expressions.arithmetic import BinaryExpr, UnaryExpr


class UnaryMath(UnaryExpr):
    """double -> double elementwise math with null propagation."""

    null_on_domain_error = False  # e.g. log(-1) -> NULL per Spark/Hive

    @property
    def data_type(self):
        return T.DOUBLE

    def _fn(self, x, xp):
        raise NotImplementedError

    def _domain_ok(self, x, xp):
        return None

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            if not c.valid or c.data is None:
                return TCol.scalar(None, T.DOUBLE)
            x = np.float64(c.data)
            ok = self._domain_ok(np.asarray(x), np)
            if ok is not None and not bool(ok[()]):
                return TCol.scalar(None, T.DOUBLE)
            with np.errstate(all="ignore"):
                return TCol.scalar(float(self._fn(np.asarray(x), np)[()]),
                                   T.DOUBLE)
        data = c.data.astype(np.float64)
        valid = c.valid
        ok = self._domain_ok(data, xp)
        if ok is not None:
            valid = valid & ok
        out = self._fn(data, xp)
        return TCol(out, valid, T.DOUBLE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)


def _unary(name, fn, domain=None, doc=""):
    cls = type(name, (UnaryMath,), {
        "_fn": staticmethod(lambda x, xp, _f=fn: _f(x, xp)),
        "_domain_ok": (staticmethod(lambda x, xp, _d=domain: _d(x, xp))
                       if domain else UnaryMath._domain_ok),
        "__doc__": doc,
    })
    # staticmethod wrappers lose `self`; rebind as plain methods
    cls._fn = lambda self, x, xp, _f=fn: _f(x, xp)
    if domain:
        cls._domain_ok = lambda self, x, xp, _d=domain: _d(x, xp)
    return cls


Sqrt = _unary("Sqrt", lambda x, xp: xp.sqrt(xp.where(x < 0, xp.nan, x)))
Exp = _unary("Exp", lambda x, xp: xp.exp(x))
Expm1 = _unary("Expm1", lambda x, xp: xp.expm1(x))
Log = _unary("Log", lambda x, xp: xp.log(x), domain=lambda x, xp: x > 0)
Log2 = _unary("Log2", lambda x, xp: xp.log2(x), domain=lambda x, xp: x > 0)
Log10 = _unary("Log10", lambda x, xp: xp.log10(x), domain=lambda x, xp: x > 0)
Log1p = _unary("Log1p", lambda x, xp: xp.log1p(x), domain=lambda x, xp: x > -1)
Sin = _unary("Sin", lambda x, xp: xp.sin(x))
Cos = _unary("Cos", lambda x, xp: xp.cos(x))
Tan = _unary("Tan", lambda x, xp: xp.tan(x))
Asin = _unary("Asin", lambda x, xp: xp.arcsin(x))
Acos = _unary("Acos", lambda x, xp: xp.arccos(x))
Atan = _unary("Atan", lambda x, xp: xp.arctan(x))
Sinh = _unary("Sinh", lambda x, xp: xp.sinh(x))
Cosh = _unary("Cosh", lambda x, xp: xp.cosh(x))
Tanh = _unary("Tanh", lambda x, xp: xp.tanh(x))
Asinh = _unary("Asinh", lambda x, xp: xp.arcsinh(x))
Acosh = _unary("Acosh", lambda x, xp: xp.arccosh(x))
Atanh = _unary("Atanh", lambda x, xp: xp.arctanh(x))
Cbrt = _unary("Cbrt", lambda x, xp: xp.cbrt(x))
Rint = _unary("Rint", lambda x, xp: xp.rint(x))
ToRadians = _unary("ToRadians", lambda x, xp: x * (np.pi / 180.0))
ToDegrees = _unary("ToDegrees", lambda x, xp: x * (180.0 / np.pi))


class Signum(UnaryMath):
    def _fn(self, x, xp):
        return xp.sign(x)


class Floor(UnaryExpr):
    @property
    def data_type(self):
        dt = self.child.data_type
        return dt if dt.is_integral else T.LONG

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if self.child.data_type.is_integral:
            return c
        if c.is_scalar:
            import math
            v = c.data if c.valid else None
            return TCol.scalar(None if v is None else math.floor(v), T.LONG)
        return TCol(xp.floor(c.data).astype(np.int64), c.valid, T.LONG)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Ceil(UnaryExpr):
    @property
    def data_type(self):
        dt = self.child.data_type
        return dt if dt.is_integral else T.LONG

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if self.child.data_type.is_integral:
            return c
        if c.is_scalar:
            import math
            v = c.data if c.valid else None
            return TCol.scalar(None if v is None else math.ceil(v), T.LONG)
        return TCol(xp.ceil(c.data).astype(np.int64), c.valid, T.LONG)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class Round(Expression):
    """round(x, d): HALF_UP (away from zero at .5), Spark default."""

    half_even = False

    def __init__(self, child, scale=0):
        from spark_rapids_tpu.expressions.base import Literal
        if not isinstance(scale, Expression):
            scale = Literal(int(scale))
        super().__init__([child, scale])

    @property
    def data_type(self):
        return self.children[0].data_type

    def _eval(self, ctx, xp):
        c = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        assert s.is_scalar, "round scale must be a literal"
        d = int(s.data)
        dt = self.data_type
        factor = 10.0 ** d
        if c.is_scalar:
            v = c.data if c.valid else None
            if v is None:
                return TCol.scalar(None, dt)
            arr = np.asarray(float(v))
            out = self._round(arr * factor, np) / factor
            if dt.is_integral:
                return TCol.scalar(int(out[()]), dt)
            return TCol.scalar(float(out[()]), dt)
        if dt.is_integral and d >= 0:
            return c
        data = c.data.astype(np.float64) * factor
        out = self._round(data, xp) / factor
        if dt.is_integral:
            out = out.astype(dt.np_dtype)
        elif dt.np_dtype is not None:
            out = out.astype(dt.np_dtype)
        return TCol(out, c.valid, dt)

    def _round(self, x, xp):
        if self.half_even:
            return xp.rint(x)
        # HALF_UP: away from zero
        return xp.sign(x) * xp.floor(xp.abs(x) + 0.5)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class BRound(Round):
    """bround: HALF_EVEN (banker's rounding)."""
    half_even = True


class BinaryMath(BinaryExpr):
    @property
    def data_type(self):
        return T.DOUBLE

    def _fn(self, a, b, xp):
        raise NotImplementedError

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.base import both_valid
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        if a.is_scalar and b.is_scalar:
            if not valid:
                return TCol.scalar(None, T.DOUBLE)
            out = self._fn(np.float64(a.data), np.float64(b.data), np)
            return TCol.scalar(float(out), T.DOUBLE)
        ad = materialize(a, ctx, np.dtype(np.float64))
        bd = materialize(b, ctx, np.dtype(np.float64))
        if hasattr(ad, "astype"):
            ad = ad.astype(np.float64)
        if hasattr(bd, "astype"):
            bd = bd.astype(np.float64)
        return TCol(self._fn(ad, bd, xp), valid, T.DOUBLE)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)


class Pow(BinaryMath):
    symbol = "pow"

    def _fn(self, a, b, xp):
        return xp.power(a, b)


class Atan2(BinaryMath):
    symbol = "atan2"

    def _fn(self, a, b, xp):
        return xp.arctan2(a, b)


class Hypot(BinaryMath):
    symbol = "hypot"

    def _fn(self, a, b, xp):
        return xp.hypot(a, b)
