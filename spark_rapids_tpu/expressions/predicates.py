"""Comparison and boolean predicates (reference: org/apache/spark/sql/rapids/
predicates.scala — GpuAnd/GpuOr/GpuNot; GpuEqualTo etc. in GpuOverrides
expr registrations; nullExpressions.scala — GpuIsNull/GpuIsNotNull/GpuCoalesce;
NormalizeFloatingNumbers handling of NaN comparisons).

Spark semantics implemented here:
- Comparisons propagate NULL; EqualNullSafe (<=>) never returns NULL.
- AND/OR use Kleene three-valued logic (FALSE AND NULL = FALSE).
- NaN: Spark treats NaN = NaN as TRUE and NaN greater than everything in
  comparisons (unlike IEEE); see docs/compatibility.md in the reference.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               both_valid, jnp, materialize,
                                               valid_array)
from spark_rapids_tpu.expressions.arithmetic import BinaryExpr, UnaryExpr


def _compare_dtype(left: Expression, right: Expression) -> T.DataType:
    lt, rt = left.data_type, right.data_type
    if lt == rt:
        return lt
    return T.common_type(lt, rt)


def _string_cmp_arrays(c: TCol, ctx: EvalContext, xp):
    """Device strings compare bytewise on the padded rectangle; padding is
    zero so prefix ordering matches byte-lexicographic ordering for UTF-8."""
    return c.data, c.lengths


class BinaryComparison(BinaryExpr):
    @property
    def data_type(self):
        return T.BOOLEAN

    def _cmp(self, a, b, xp):
        raise NotImplementedError

    # string comparison on device: compare padded byte rows lexicographically
    def _device_string_cmp(self, a: TCol, b: TCol, xp):
        ad, bd = a.data, b.data
        w = max(ad.shape[1], bd.shape[1])
        if ad.shape[1] < w:
            ad = xp.pad(ad, ((0, 0), (0, w - ad.shape[1])))
        if bd.shape[1] < w:
            bd = xp.pad(bd, ((0, 0), (0, w - bd.shape[1])))
        # first differing byte decides; equal prefixes decided by length
        diff = ad.astype(np.int16) - bd.astype(np.int16)
        nz = diff != 0
        first_idx = xp.argmax(nz, axis=1)
        any_nz = xp.any(nz, axis=1)
        first = xp.take_along_axis(diff, first_idx[:, None], axis=1)[:, 0]
        cmp = xp.where(any_nz, xp.sign(first),
                       xp.sign(a.lengths - b.lengths))
        return cmp  # -1/0/1 per row

    def tpu_supported(self, conf):
        from spark_rapids_tpu.expressions import decimal_math as DM
        lt, rt = self.left.data_type, self.right.data_type
        if DM.compare_involved(lt, rt):
            return DM.compare_supported(lt, rt)
        return None

    def _eval(self, ctx: EvalContext, xp) -> TCol:
        from spark_rapids_tpu.expressions import decimal_math as DM
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        # Spark promotes decimal-vs-fractional to double before comparing
        if isinstance(a.dtype, T.DecimalType) and b.dtype.is_floating:
            a = _decimal_side_to_double(a, ctx, xp)
        elif isinstance(b.dtype, T.DecimalType) and a.dtype.is_floating:
            b = _decimal_side_to_double(b, ctx, xp)
        valid = both_valid(a, b, ctx)
        if DM.compare_involved(a.dtype, b.dtype) and \
                not (a.is_scalar and b.is_scalar):
            cmp = DM.compare(a, b, ctx, xp)
            out = self._cmp(cmp, np.int8(0), xp)
            if isinstance(valid, bool):
                from spark_rapids_tpu.expressions.base import valid_array
                valid = valid_array(a, ctx) & valid_array(b, ctx)
            return TCol(out, valid, T.BOOLEAN)
        if a.is_scalar and b.is_scalar:
            if not valid:
                return TCol.scalar(None, T.BOOLEAN)
            if DM.compare_involved(a.dtype, b.dtype):
                x = DM._scalar_unscaled(a) * 10 ** max(
                    0, _dscale(b.dtype) - _dscale(a.dtype))
                y = DM._scalar_unscaled(b) * 10 ** max(
                    0, _dscale(a.dtype) - _dscale(b.dtype))
                order = (x > y) - (x < y)
                return TCol.scalar(bool(self._cmp(np.asarray(order),
                                                  np.asarray(0), np)[()]),
                                   T.BOOLEAN)
            return TCol.scalar(bool(self._cmp(np.asarray(a.data),
                                              np.asarray(b.data), np)[()]),
                               T.BOOLEAN)
        if ctx.backend == "tpu" and (a.is_string or b.is_string):
            a, b = _densify_string(a, ctx, xp), _densify_string(b, ctx, xp)
            cmp = self._device_string_cmp(a, b, xp)
            out = self._cmp(cmp, xp.zeros_like(cmp), xp)
            return TCol(out, valid, T.BOOLEAN)
        ad = materialize(a, ctx)
        bd = materialize(b, ctx)
        if ctx.backend == "cpu" and (a.is_string or b.is_string):
            # object arrays: python comparison row-wise, vectorized via numpy
            with np.errstate(all="ignore"):
                out = self._cmp_obj(ad, bd)
            return TCol(out, valid, T.BOOLEAN)
        ad, bd = _numeric_align(ad, bd, xp)
        out = self._cmp(ad, bd, xp)
        return TCol(out, valid, T.BOOLEAN)

    def _cmp_obj(self, ad, bd):
        n = len(ad)
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            x, y = ad[i], bd[i]
            if x is None or y is None:
                continue
            out[i] = bool(self._cmp(np.asarray(x), np.asarray(y), np)[()]) \
                if not isinstance(x, str) else self._py_cmp(x, y)
        return out

    def _py_cmp(self, x, y):
        order = (x > y) - (x < y)
        return bool(self._cmp(np.asarray(order), np.asarray(0), np)[()])

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        with np.errstate(all="ignore"):
            return self._eval(ctx, np)


def _dscale(dt) -> int:
    return dt.scale if isinstance(dt, T.DecimalType) else 0


def _decimal_side_to_double(c: TCol, ctx, xp) -> TCol:
    from spark_rapids_tpu.expressions import decimal_math as DM
    if c.is_scalar:
        v = None if c.data is None else \
            float(DM._scalar_unscaled(c)) / (10.0 ** c.dtype.scale)
        return TCol.scalar(v, T.DOUBLE)
    return DM.decimal_to_double(c, ctx, xp)


def _densify_string(c: TCol, ctx: EvalContext, xp):
    if not c.is_scalar:
        return c
    s = c.data or ""
    raw = np.frombuffer(s.encode() if isinstance(s, str) else s, dtype=np.uint8)
    from spark_rapids_tpu.columnar.column import bucket_strlen
    w = bucket_strlen(max(1, len(raw)))
    chars = np.zeros((ctx.row_count, w), dtype=np.uint8)
    chars[:, :len(raw)] = raw
    lens = np.full(ctx.row_count, len(raw), dtype=np.int32)
    return TCol(xp.asarray(chars), valid_array(c, ctx), c.dtype,
                lengths=xp.asarray(lens))


def _numeric_align(ad, bd, xp):
    """Promotes both arrays to a common numeric dtype for comparison."""
    if ad.dtype == bd.dtype:
        return ad, bd
    common = np.promote_types(ad.dtype, bd.dtype)
    return ad.astype(common), bd.astype(common)


class EqualTo(BinaryComparison):
    symbol = "="

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            # Spark: NaN = NaN is TRUE
            return (a == b) | (xp.isnan(a) & xp.isnan(b))
        return a == b


class LessThan(BinaryComparison):
    symbol = "<"

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            # Spark: NaN is greater than everything
            return (a < b) | (xp.isnan(b) & ~xp.isnan(a))
        return a < b


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            return (a <= b) | xp.isnan(b)
        return a <= b


class GreaterThan(BinaryComparison):
    symbol = ">"

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            return (a > b) | (xp.isnan(a) & ~xp.isnan(b))
        return a > b


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            return (a >= b) | xp.isnan(a)
        return a >= b


class NotEqual(BinaryComparison):
    symbol = "!="

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            return ~((a == b) | (xp.isnan(a) & xp.isnan(b)))
        return a != b


class EqualNullSafe(BinaryComparison):
    """<=> : nulls compare equal; never returns NULL."""
    symbol = "<=>"

    def _cmp(self, a, b, xp):
        if a.dtype.kind == "f":
            return (a == b) | (xp.isnan(a) & xp.isnan(b))
        return a == b

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        if a.is_scalar and b.is_scalar:
            an, bn = a.data is None, b.data is None
            if an or bn:
                return TCol.scalar(an and bn, T.BOOLEAN)
            return super()._eval(ctx, xp)
        base = super()._eval(ctx, xp)
        av = valid_array(a, ctx)
        bv = valid_array(b, ctx)
        eq = xp.asarray(base.data) & av & bv
        both_null = ~av & ~bv
        return TCol(eq | both_null, xp.ones_like(av), T.BOOLEAN)


class And(BinaryExpr):
    """Kleene AND: F&x=F, T&N=N."""
    symbol = "AND"

    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        if a.is_scalar and b.is_scalar:
            av = a.data if a.valid else None
            bv = b.data if b.valid else None
            if av is False or bv is False:
                return TCol.scalar(False, T.BOOLEAN)
            if av is None or bv is None:
                return TCol.scalar(None, T.BOOLEAN)
            return TCol.scalar(True, T.BOOLEAN)
        ad = materialize(a, ctx, np.dtype(bool))
        bd = materialize(b, ctx, np.dtype(bool))
        av = valid_array(a, ctx)
        bv = valid_array(b, ctx)
        at = ad & av  # definitely true
        bt = bd & bv
        af = ~ad & av  # definitely false
        bf = ~bd & bv
        out = at & bt
        valid = (at & bt) | af | bf
        return TCol(out, valid, T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu


class Or(BinaryExpr):
    """Kleene OR: T|x=T, F|N=N."""
    symbol = "OR"

    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx, xp):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        if a.is_scalar and b.is_scalar:
            av = a.data if a.valid else None
            bv = b.data if b.valid else None
            if av is True or bv is True:
                return TCol.scalar(True, T.BOOLEAN)
            if av is None or bv is None:
                return TCol.scalar(None, T.BOOLEAN)
            return TCol.scalar(False, T.BOOLEAN)
        ad = materialize(a, ctx, np.dtype(bool))
        bd = materialize(b, ctx, np.dtype(bool))
        av = valid_array(a, ctx)
        bv = valid_array(b, ctx)
        at = ad & av
        bt = bd & bv
        out = at | bt
        valid = at | bt | (av & bv)
        return TCol(out, valid, T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu


class Not(UnaryExpr):
    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            v = c.data if c.valid else None
            return TCol.scalar(None if v is None else not v, T.BOOLEAN)
        return TCol(~c.data, c.valid, T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu


class IsNull(UnaryExpr):
    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            return TCol.scalar(not bool(c.valid) or c.data is None, T.BOOLEAN)
        ones = xp.ones_like(c.valid)
        return TCol(~c.valid, ones, T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu


class IsNotNull(UnaryExpr):
    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            return TCol.scalar(bool(c.valid) and c.data is not None, T.BOOLEAN)
        ones = xp.ones_like(c.valid)
        return TCol(c.valid, ones, T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu


class IsNan(UnaryExpr):
    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx, xp):
        c = self.child.eval(ctx)
        if c.is_scalar:
            import math
            v = c.data if c.valid else None
            return TCol.scalar(False if v is None else math.isnan(v), T.BOOLEAN)
        if c.data.dtype.kind != "f":
            return TCol(xp.zeros_like(c.valid), c.valid, T.BOOLEAN)
        return TCol(xp.isnan(c.data) & c.valid, xp.ones_like(c.valid), T.BOOLEAN)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu


class In(Expression):
    """value IN (literals...) — device impl is an OR-reduction of equality
    against each list element (reference GpuInSet uses a cuDF table lookup;
    an OR chain fuses fine in XLA for modest list sizes)."""

    def __init__(self, value: Expression, options):
        super().__init__([value])
        self.options = list(options)

    @property
    def data_type(self):
        return T.BOOLEAN

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.base import Literal
        c = self.children[0]
        acc = None
        for opt in self.options:
            eq = EqualTo(c, opt if isinstance(opt, Expression) else Literal(opt))
            acc = eq if acc is None else Or(acc, eq)
        if acc is None:
            return TCol.scalar(False, T.BOOLEAN)
        return acc.eval(ctx)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    eval_cpu = eval_tpu
