"""Scalar pandas UDF expression surface.

Reference: Spark's ``PythonUDF`` expression + the reference's
``GpuArrowEvalPythonExec`` (execution/python/GpuArrowEvalPythonExec.scala):
a projection containing python UDFs is split — the UDFs evaluate in an
ArrowEvalPython exec (arrow hand-off to python), the projection then
references their output columns.  The DataFrame layer performs the same
extraction (session.DataFrame._plan_pandas_udfs).
"""

from __future__ import annotations

from typing import Callable, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Expression


class PandasUDFCall(Expression):
    """``pandas_udf(fn, dtype)(*cols)`` — evaluated only via
    CpuArrowEvalPythonExec, never inline."""

    foldable = False          # python fns are opaque: never constant-fold
    deterministic = False

    def __init__(self, fn: Callable, dtype: T.DataType,
                 children: Sequence[Expression]):
        super().__init__(children)
        self.fn = fn
        self._dtype = dtype

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return True

    def sql(self) -> str:
        name = getattr(self.fn, "__name__", "pandas_udf")
        return f"{name}({', '.join(c.sql() for c in self.children)})"

    def eval_cpu(self, ctx):
        raise NotImplementedError(
            "PandasUDFCall must be extracted into ArrowEvalPython "
            "(use it inside select()/with_column())")

    eval_tpu = eval_cpu


def pandas_udf(fn: Callable, return_type) -> Callable:
    """pyspark-style: ``my = pandas_udf(lambda s: s * 2, T.DOUBLE);
    df.select(my(col("a")).alias("x"))`` — ``fn(*pandas.Series) ->
    pandas.Series``."""
    dtype = return_type

    def call(*cols) -> PandasUDFCall:
        from spark_rapids_tpu.functions import _expr
        return PandasUDFCall(fn, dtype, [_expr(c) for c in cols])

    return call
